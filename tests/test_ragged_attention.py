"""Ragged paged attention: ops-level parity and fused-scheduler parity.

The ragged path replaces the alternating admit-then-step dispatches with
one fused mixed prefill/decode batch per engine step, so the whole
contract is token-exactness vs the dense reference: the pure-jnp
fallback must match the gathered ``_gqa_decode_attention`` rule row for
row, the Pallas kernel (interpret mode on CPU) must match the fallback,
and ``PagedBatcher(ragged=True)`` / ``ContinuousBatcher(ragged=True)``
must emit the same greedy tokens as their legacy alternating paths.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.continuous import ContinuousBatcher
from kubeflow_tpu.models.llama import _gqa_decode_attention, _kv_quantize
from kubeflow_tpu.models.paged import PagedBatcher
from kubeflow_tpu.models.serving import GenerationConfig, batch_generate
from kubeflow_tpu.ops.ragged_attention import (
    ragged_attention_reference,
    ragged_paged_attention,
)

from tests.test_continuous import _assert_greedy_consistent, _prompts


# ---------------------------------------------------------------------------
# Ops level: reference vs dense rule, kernel vs reference


def _setup(s=3, hq=8, hkv=4, d=128, bs=16, maxb=6, nb=32, t=24, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    q = jax.random.normal(ks[0], (t, hq, d), jnp.bfloat16)
    kp = jax.random.normal(ks[1], (nb, hkv, bs, d), jnp.bfloat16)
    vp = jax.random.normal(ks[2], (nb, hkv, bs, d), jnp.bfloat16)
    tables = jax.random.permutation(ks[3], nb)[: s * maxb].reshape(
        s, maxb
    ).astype(jnp.int32)
    return q, kp, vp, tables


def _meta(spans, t, maxb, bs):
    """Build (starts, lens, kvls, kv_mask) from [(seq_len, kv_len)]."""
    starts, lens, kvls = [], [], []
    row = 0
    for n, kvl in spans:
        starts.append(row)
        lens.append(n)
        kvls.append(kvl)
        row += n
    assert row <= t
    kv_mask = jnp.arange(maxb * bs)[None, :] < jnp.asarray(kvls)[:, None]
    return (jnp.asarray(starts, jnp.int32), jnp.asarray(lens, jnp.int32),
            jnp.asarray(kvls, jnp.int32), kv_mask)


def _dense_rows(q, kp, vp, tables, kv_mask, starts, lens, kvls, bs):
    """Row-by-row dense reference through _gqa_decode_attention: query j
    of sequence s sits at absolute position kv_len - seq_len + j and
    attends the slot's gathered view at that position."""
    t, hq, d = q.shape
    hkv = kp.shape[1]
    maxb = tables.shape[1]
    out = np.zeros((t, hq, d), np.float32)
    for s in range(tables.shape[0]):
        n = int(lens[s])
        if n == 0:
            continue
        g = np.asarray(kp[tables[s]], np.float32).transpose(
            1, 0, 2, 3
        ).reshape(hkv, maxb * bs, d)
        gv = np.asarray(vp[tables[s]], np.float32).transpose(
            1, 0, 2, 3
        ).reshape(hkv, maxb * bs, d)
        for j in range(n):
            row = int(starts[s]) + j
            pos = int(kvls[s]) - n + j
            o = _gqa_decode_attention(
                jnp.asarray(q[row], jnp.float32)[None, :, None, :],
                jnp.asarray(g)[None], jnp.asarray(gv)[None],
                jnp.asarray([pos]), kv_mask=kv_mask[s][None],
                per_batch=True,
            )[0, :, 0]
            out[row] = np.asarray(o, np.float32)
    return out


def _assert_close(out, ref, owned_rows, tol=2e-2):
    err = float(jnp.max(jnp.abs(
        out.astype(jnp.float32)[owned_rows] - ref[owned_rows]
    )))
    assert err < tol, f"ragged path diverges from dense rule: {err}"


def _owned(starts, lens, t):
    rows = []
    for s, n in zip(np.asarray(starts), np.asarray(lens)):
        rows.extend(range(int(s), int(s) + int(n)))
    return np.asarray(rows, np.int64)


class TestReferenceVsDense:
    @pytest.mark.parametrize("spans", [
        [(1, 17), (1, 40), (1, 96)],          # decode-only
        [(8, 8), (12, 12), (4, 20)],          # prefill-only chunks
        [(1, 33), (10, 10), (1, 5)],          # mixed decode + prefill
        [(1, 64), (1, 96), (6, 22)],          # mixed, longer histories
        [(5, 30), (1, 1), (0, 0)],            # single-token tail + idle
    ])
    def test_rows_match_gathered_gqa_rule(self, spans):
        q, kp, vp, tables = _setup()
        starts, lens, kvls, kv_mask = _meta(spans, 24, 6, 16)
        out = ragged_attention_reference(
            q, kp, vp, tables, kv_mask, starts, lens, kvls, 16
        )
        ref = _dense_rows(q, kp, vp, tables, kv_mask, starts, lens, kvls, 16)
        _assert_close(out, ref, _owned(starts, lens, 24))

    def test_all_true_mask_relies_on_positional_bound(self):
        """Schedulers may mark future positions True and lean on the
        ``k_pos <= q_pos`` bound — the fallback must apply it."""
        q, kp, vp, tables = _setup(seed=3)
        starts, lens, kvls, _ = _meta(
            [(1, 25), (7, 18), (1, 90)], 24, 6, 16
        )
        kv_mask = jnp.ones((3, 6 * 16), bool)
        out = ragged_attention_reference(
            q, kp, vp, tables, kv_mask, starts, lens, kvls, 16
        )
        ref = _dense_rows(q, kp, vp, tables, kv_mask, starts, lens, kvls, 16)
        _assert_close(out, ref, _owned(starts, lens, 24))


class TestKernelVsReference:
    @pytest.mark.parametrize("spans,q_tile", [
        ([(1, 17), (1, 40), (1, 96)], 8),      # decode-only
        ([(8, 8), (12, 12), (4, 20)], 8),      # prefill-only
        ([(1, 33), (20, 20), (1, 5)], 8),      # chunk spans 3 q-tiles
        # Non-default q_tiles each pay their own interpret-mode compile;
        # tier-1's wall budget keeps them in the full suite only.
        pytest.param([(5, 30), (1, 1), (0, 0)], 16,
                     marks=pytest.mark.slow),  # partial tile + idle slot
        pytest.param([(3, 19), (9, 41), (12, 12)], 4,
                     marks=pytest.mark.slow),  # every span spills a tile
    ])
    def test_kernel_matches_reference(self, spans, q_tile):
        q, kp, vp, tables = _setup(seed=1)
        starts, lens, kvls, kv_mask = _meta(spans, 24, 6, 16)
        out = ragged_paged_attention(
            q, kp, vp, tables, kv_mask, starts, lens, kvls, 16,
            q_tile=q_tile, interpret=True,
        )
        ref = ragged_attention_reference(
            q, kp, vp, tables, kv_mask, starts, lens, kvls, 16
        ).astype(jnp.float32)
        _assert_close(out, np.asarray(ref), _owned(starts, lens, 24))

    def test_spill_rows_are_overwritten_not_leaked(self):
        """A partial last q-tile writes whole tiles: its spill rows land
        on the NEXT sequence's span and must be overwritten by the later
        program — every owned row must still be exact."""
        q, kp, vp, tables = _setup(seed=2)
        # 5 rows then 7 rows: with q_tile=4 the first sequence's second
        # tile covers rows 4..7, clobbering rows 5..7 of sequence 1.
        starts, lens, kvls, kv_mask = _meta(
            [(5, 21), (7, 39), (1, 64)], 24, 6, 16
        )
        out = ragged_paged_attention(
            q, kp, vp, tables, kv_mask, starts, lens, kvls, 16,
            q_tile=4, interpret=True,
        )
        ref = ragged_attention_reference(
            q, kp, vp, tables, kv_mask, starts, lens, kvls, 16
        ).astype(jnp.float32)
        _assert_close(out, np.asarray(ref), _owned(starts, lens, 24))

    def test_kv_mask_shape_validated(self):
        q, kp, vp, tables = _setup()
        starts, lens, kvls, _ = _meta([(1, 4), (1, 4), (1, 4)], 24, 6, 16)
        with pytest.raises(ValueError, match="kv_mask shape"):
            ragged_paged_attention(
                q, kp, vp, tables, jnp.ones((3, 7), bool), starts, lens,
                kvls, 16, interpret=True,
            )


# ---------------------------------------------------------------------------
# int8 KV × ragged: the fused path over quantized block pools


def _quantize_pools(kp, vp):
    """(NB, Hkv, BS, D) bf16 pools → int8 values + (NB, Hkv, BS) bf16
    scales, the same per-(block, head, slot) amax scheme the paged
    engine's quantize-on-write scatter uses."""
    kq, ks = _kv_quantize(kp)
    vq, vs = _kv_quantize(vp)
    return kq, ks, vq, vs


class TestInt8Ragged:
    # Pinned parity gate: int8 storage error through softmax on normal
    # random pools. A wiring bug (wrong scale axis, mask, pointer) shows
    # up orders of magnitude larger.
    INT8_TOL = 8e-2

    @pytest.mark.parametrize("spans", [
        [(1, 17), (1, 40), (1, 96)],          # decode-only
        [(8, 8), (12, 12), (4, 20)],          # prefill-only chunks
        [(1, 33), (10, 10), (1, 5)],          # mixed decode + prefill
    ])
    def test_reference_dequant_within_quantization_error(self, spans):
        """jnp fallback over an int8+scale pool vs the dense bf16 rule:
        differences must be bounded by quantization error."""
        q, kp, vp, tables = _setup(seed=4)
        starts, lens, kvls, kv_mask = _meta(spans, 24, 6, 16)
        kq, ks, vq, vs = _quantize_pools(kp, vp)
        out = ragged_attention_reference(
            q, kq, vq, tables, kv_mask, starts, lens, kvls, 16,
            k_scale_pool=ks, v_scale_pool=vs,
        )
        ref = _dense_rows(q, kp, vp, tables, kv_mask, starts, lens, kvls, 16)
        _assert_close(out, ref, _owned(starts, lens, 24), tol=self.INT8_TOL)

    def test_kernel_matches_reference_on_int8_pool(self):
        """Kernel and fallback dequantize the SAME stored values, so
        they must agree to normal fp tolerance, not quantization
        tolerance."""
        q, kp, vp, tables = _setup(seed=5)
        starts, lens, kvls, kv_mask = _meta(
            [(1, 33), (10, 10), (1, 5)], 24, 6, 16
        )
        kq, ks, vq, vs = _quantize_pools(kp, vp)
        out = ragged_paged_attention(
            q, kq, vq, tables, kv_mask, starts, lens, kvls, 16,
            q_tile=8, interpret=True,
            k_scale_pool=ks, v_scale_pool=vs,
        )
        ref = ragged_attention_reference(
            q, kq, vq, tables, kv_mask, starts, lens, kvls, 16,
            k_scale_pool=ks, v_scale_pool=vs,
        ).astype(jnp.float32)
        _assert_close(out, np.asarray(ref), _owned(starts, lens, 24))

    def test_scale_pools_are_both_or_neither(self):
        q, kp, vp, tables = _setup()
        starts, lens, kvls, kv_mask = _meta(
            [(1, 4), (1, 4), (1, 4)], 24, 6, 16
        )
        kq, ks, vq, vs = _quantize_pools(kp, vp)
        with pytest.raises(ValueError, match="scale"):
            ragged_paged_attention(
                q, kq, vq, tables, kv_mask, starts, lens, kvls, 16,
                interpret=True, k_scale_pool=ks,
            )
        with pytest.raises(ValueError, match="scale"):
            ragged_attention_reference(
                q, kq, vq, tables, kv_mask, starts, lens, kvls, 16,
                v_scale_pool=vs,
            )


# ---------------------------------------------------------------------------
# Scheduler level: fused ragged batches vs the legacy alternating path


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _run(batcher, prompts):
    rids = [batcher.submit(p) for p in prompts]
    out = batcher.run()
    return [out[r] for r in rids]


class TestPagedRagged:
    def test_single_request_matches_fused_batch_path(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        prompt = [5, 9, 17, 33]
        ref = batch_generate(params, cfg, [prompt], gen=gen, pad_to=16)[0]
        pb = PagedBatcher(params, cfg, gen=gen, slots=1, num_blocks=16,
                          block_size=8, prompt_bucket=16,
                          attn_kernel=False, ragged=True, token_budget=8)
        rid = pb.submit(prompt)
        assert pb.run()[rid] == [int(t) for t in ref]

    @pytest.mark.slow
    def test_mixed_batch_token_parity_with_legacy(self, tiny):
        """The headline invariant: fusing decode rows and prefill chunks
        into one dispatch must not move any request off the greedy path
        the alternating scheduler produced."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        prompts = _prompts(cfg, 6)
        legacy = _run(
            PagedBatcher(params, cfg, gen=gen, slots=3, num_blocks=24,
                         block_size=8, prompt_bucket=16,
                         attn_kernel=False),
            prompts,
        )
        ragged = _run(
            PagedBatcher(params, cfg, gen=gen, slots=3, num_blocks=24,
                         block_size=8, prompt_bucket=16,
                         attn_kernel=False, ragged=True, token_budget=12),
            prompts,
        )
        assert legacy == ragged
        for prompt, toks in zip(prompts, ragged):
            _assert_greedy_consistent(params, cfg, prompt, toks)

    @pytest.mark.slow
    def test_starved_budget_still_completes_admissions(self, tiny):
        """token_budget == slots leaves at most zero prefill rows on a
        full step — admissions must still make progress on idle steps
        and complete exactly."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=4, eos_id=-1)
        prompts = _prompts(cfg, 4)
        legacy = _run(
            PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=24,
                         block_size=8, prompt_bucket=16,
                         attn_kernel=False),
            prompts,
        )
        ragged = _run(
            PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=24,
                         block_size=8, prompt_bucket=16,
                         attn_kernel=False, ragged=True, token_budget=2),
            prompts,
        )
        assert legacy == ragged

    @pytest.mark.slow
    def test_preemption_mid_batch_token_parity(self, tiny):
        """Pool pressure preempts mid-run (including mid-prefill
        admissions holding their bucket) — both schedulers must converge
        to the same greedy tokens and return every block."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=10, eos_id=-1)
        prompts = _prompts(cfg, 4, key=11)
        legacy = _run(
            PagedBatcher(params, cfg, gen=gen, slots=3, num_blocks=10,
                         block_size=8, prompt_bucket=16,
                         attn_kernel=False),
            prompts,
        )
        pb = PagedBatcher(params, cfg, gen=gen, slots=3, num_blocks=10,
                          block_size=8, prompt_bucket=16,
                          attn_kernel=False, ragged=True, token_budget=24)
        ragged = _run(pb, prompts)
        assert legacy == ragged
        assert pb.free_blocks == 9  # everything released (block 0 null)

    def test_mid_prefill_cancel_frees_blocks(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=16,
                          block_size=8, prompt_bucket=16,
                          attn_kernel=False, ragged=True, token_budget=4)
        p = _prompts(cfg, 2, key=3)
        r1, r2 = pb.submit(p[0]), pb.submit(p[1])
        pb._admit_free_slots()
        pb._step()  # partial prefill in flight
        assert pb._ragged_admit
        assert pb.cancel(r1)
        out = pb.run()
        assert len(out[r2]) == 6
        assert pb.run_aborted() == {r1: "cancelled"}
        assert pb.free_blocks == 15

    def test_fill_stats_populate(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=4, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=24,
                          block_size=8, prompt_bucket=16,
                          attn_kernel=False, ragged=True, token_budget=8)
        _run(pb, _prompts(cfg, 3))
        assert pb.ragged_steps > 0
        assert pb.ragged_tokens > pb.ragged_steps  # prefill rows counted
        assert 0.0 < pb.ragged_fill <= 1.0

    @pytest.mark.slow
    def test_kernel_smoke_end_to_end(self, tiny):
        """attn_kernel=True off-TPU runs the Pallas kernel interpreted
        through the full engine loop — slow, so one short request."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=2, eos_id=-1)
        prompts = [[5, 9, 17, 33]]
        ref = _run(
            PagedBatcher(params, cfg, gen=gen, slots=1, num_blocks=16,
                         block_size=8, prompt_bucket=16,
                         attn_kernel=False, ragged=True, token_budget=8),
            prompts,
        )
        out = _run(
            PagedBatcher(params, cfg, gen=gen, slots=1, num_blocks=16,
                         block_size=8, prompt_bucket=16,
                         attn_kernel=True, ragged=True, token_budget=8),
            prompts,
        )
        assert out == ref

    def test_composition_rejections(self, tiny):
        cfg, params = tiny
        for kw in (
            {"prompt_cache": True},
            {"prefix_cache": True},
        ):
            with pytest.raises(ValueError, match="ragged"):
                PagedBatcher(params, cfg, slots=2, num_blocks=16,
                             block_size=8, prompt_bucket=16,
                             attn_kernel=False, ragged=True, **kw)
        with pytest.raises(ValueError, match="token_budget"):
            PagedBatcher(params, cfg, slots=4, num_blocks=16, block_size=8,
                         prompt_bucket=16, attn_kernel=False, ragged=True,
                         token_budget=2)
        # int8 + fused kernel exists ONLY through the ragged path — the
        # decode-step kernel still has no dequant epilogue.
        with pytest.raises(ValueError, match="kv_bits"):
            PagedBatcher(params, cfg, slots=2, num_blocks=16, block_size=8,
                         prompt_bucket=16, attn_kernel=True, kv_bits=8)

    def test_int8_ragged_constructs_and_serves(self, tiny):
        """The PR 14 headline: ragged=True composes with kv_bits=8. The
        fused jnp path reads the int8 pool and stays token-exact vs the
        legacy alternating scheduler over the SAME quantized format
        (identical stored values → identical greedy tokens)."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        prompts = _prompts(cfg, 3)
        legacy = _run(
            PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=24,
                         block_size=8, prompt_bucket=16,
                         attn_kernel=False, kv_bits=8),
            prompts,
        )
        ragged = _run(
            PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=24,
                         block_size=8, prompt_bucket=16,
                         attn_kernel=False, ragged=True, token_budget=12,
                         kv_bits=8),
            prompts,
        )
        assert legacy == ragged

    def test_int8_ragged_greedy_matches_bf16(self, tiny):
        """Token-exact greedy parity vs the bf16 ragged path on the tiny
        model: prefill logits never read quantized storage and the decode
        drift stays below the greedy margin at this depth."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        prompt = [5, 9, 17, 33, 41, 2, 77, 13]
        mk = lambda bits: PagedBatcher(  # noqa: E731
            params, cfg, gen=gen, slots=1, num_blocks=16, block_size=8,
            prompt_bucket=16, attn_kernel=False, ragged=True,
            token_budget=8, kv_bits=bits,
        )
        assert _run(mk(8), [prompt]) == _run(mk(0), [prompt])

    @pytest.mark.slow
    def test_int8_ragged_kernel_smoke_end_to_end(self, tiny):
        """attn_kernel=True + kv_bits=8 + ragged=True runs the quantized
        Pallas variant interpreted through the full engine loop; tokens
        must match the jnp-fallback int8 path exactly."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=2, eos_id=-1)
        prompts = [[5, 9, 17, 33]]
        ref = _run(
            PagedBatcher(params, cfg, gen=gen, slots=1, num_blocks=16,
                         block_size=8, prompt_bucket=16, attn_kernel=False,
                         ragged=True, token_budget=8, kv_bits=8),
            prompts,
        )
        out = _run(
            PagedBatcher(params, cfg, gen=gen, slots=1, num_blocks=16,
                         block_size=8, prompt_bucket=16, attn_kernel=True,
                         ragged=True, token_budget=8, kv_bits=8),
            prompts,
        )
        assert out == ref


class TestContinuousRagged:
    @pytest.mark.slow
    def test_fused_admission_token_parity(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        prompts = _prompts(cfg, 5)
        legacy = _run(
            ContinuousBatcher(params, cfg, gen=gen, slots=3, cache_len=64,
                              prompt_bucket=16, attn_kernel=False),
            prompts,
        )
        ragged = _run(
            ContinuousBatcher(params, cfg, gen=gen, slots=3, cache_len=64,
                              prompt_bucket=16, attn_kernel=False,
                              admit_chunk=8, ragged=True),
            prompts,
        )
        assert legacy == ragged
        for prompt, toks in zip(prompts, ragged):
            _assert_greedy_consistent(params, cfg, prompt, toks)

    def test_requires_admit_chunk(self, tiny):
        cfg, params = tiny
        with pytest.raises(ValueError, match="admit_chunk"):
            ContinuousBatcher(params, cfg, slots=2, cache_len=64,
                              prompt_bucket=16, attn_kernel=False,
                              ragged=True)
