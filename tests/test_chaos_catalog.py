"""Chaos catalog: schema validation + in-process execution of every
experiment, plus knowledge-model drift checks against the code.

Reference analog: operator_chaos_validation.yaml schema-validates the
catalog per PR; here the catalog additionally *runs* (the envtest-style
cluster makes the injections executable).
"""

from __future__ import annotations

from pathlib import Path

import pytest

from kubeflow_tpu.k8s import chaos_catalog as cat

from tests.harness import make_env, tpu_notebook

CHAOS_DIR = Path(__file__).resolve().parent.parent / "chaos"


def _experiments():
    return cat.load_experiments(CHAOS_DIR / "experiments")


def test_catalog_has_reference_parity_experiments():
    names = {d["metadata"]["name"] for d in _experiments()}
    assert names == {
        "slice-pod-kill",
        "culler-network-partition",
        "controller-scale-zero",
        "rbac-revoke",
        "webhook-disrupt",
        # Beyond reference: the warm-capacity subsystem gets chaos coverage.
        "slicepool-placeholder-kill",
        # Recovery escalation state machine (controller/preemption.py):
        # storm, withheld capacity (both escalation outcomes), and an
        # apiserver flap mid-ladder.
        "slice-preemption-storm",
        "capacity-withheld-warm-pool",
        "capacity-withheld-no-pool",
        "apiserver-flap-mid-escalation",
        # Serving request-lifecycle (models/server.py): dead clients,
        # overload shedding, and engine-thread crash containment.
        "serving-disconnect-storm",
        "serving-overload",
        "serving-engine-stall",
        # Checkpoint durability (runtime/checkpoint.py): SIGKILL mid-save,
        # on-disk corruption at restore, and ENOSPC during the save loop.
        "checkpoint-kill-mid-save",
        "checkpoint-restore-corrupt",
        "checkpoint-disk-full",
        # Fleet gateway (models/gateway.py): replica death mid-stream —
        # bounded error burst, ring heals, throughput recovers.
        "gateway-replica-kill",
        # Disaggregated serving: prefill pod death mid-KV-export — the
        # handoff re-routes within budget, never silent truncation, and
        # the decode tier stays healthy.
        "serving-kv-handoff-loss",
        # Fleet KV tier (models/gateway.py peer prefix fetch): the
        # probed peer dies mid-export — the fetch degrades to
        # re-prefill, the corpse is negative-cached, no client notices.
        "serving-kv-peer-loss",
        # Fleet autoscaler (models/autoscaler.py): scale-down under
        # stream churn — drain before release, never kill a stream.
        "autoscaler-scaledown-storm",
        # Live slice migration (runtime/migration.py): preemption-notice
        # storm — every migration resumes loss-exact, throughput never
        # zeroes, one complete trace per migration.
        "migration-storm",
    }


@pytest.mark.parametrize("doc", _experiments(), ids=lambda d: d["metadata"]["name"])
def test_experiment_schema_valid(doc):
    cat.validate_experiment(doc)


def test_validation_rejects_bad_docs():
    good = _experiments()[0]
    bad = {**good, "spec": {**good["spec"], "injection": {"type": "meteor-strike"}}}
    with pytest.raises(cat.ValidationError):
        cat.validate_experiment(bad)
    with pytest.raises(cat.ValidationError):
        cat.validate_experiment({**good, "spec": {**good["spec"], "steadyState": []}})


def test_knowledge_model_valid_and_matches_code():
    (doc,) = cat.load_documents(CHAOS_DIR / "knowledge" / "workbenches.yaml")
    cat.validate_knowledge(doc)

    # Cross-check the inventory against code truth so it cannot drift.
    from kubeflow_tpu.api import annotations as ann
    from kubeflow_tpu.deploy import manifests as m

    controllers = {c["name"]: c for c in doc["spec"]["controllers"]}
    core = controllers["notebook-controller"]
    assert ann.STOP in core["annotationsOwned"]
    assert ann.LAST_ACTIVITY in core["annotationsOwned"]
    assert ann.TPU_SLICE_INTERRUPTED in core["annotationsOwned"]
    # The recovery escalation state machine's annotations are inventoried.
    assert ann.TPU_RECOVERY_STARTED in core["annotationsOwned"]
    assert ann.TPU_RECOVERY_ESCALATIONS in core["annotationsOwned"]
    assert ann.TPU_RECOVERY_LAST_ESCALATION in core["annotationsOwned"]
    assert ann.TPU_LAST_INTERRUPTION_DURATION in core["annotationsOwned"]
    # The warm-capacity subsystem is inventoried: SlicePool watched, and a
    # managedResources entry names the placeholder StatefulSets with the
    # naming scheme the code actually uses.
    from kubeflow_tpu.controller.slicepool import warm_sts_name

    assert "SlicePool" in core["watches"]
    placeholder_notes = [
        r.get("note", "")
        for r in core["managedResources"]
        if r["kind"] == "StatefulSet"
    ]
    pattern = warm_sts_name("{pool}", 0).replace("-0", "-{gen}")
    assert any(pattern in n for n in placeholder_notes), (
        f"no StatefulSet managedResource mentions {pattern!r}"
    )

    platform_kinds = {
        r["kind"] for r in controllers["platform-notebook-controller"]["managedResources"]
    }
    # Everything the platform reconciler Owns (platform.py register()) must
    # be inventoried.
    for kind in (
        "ServiceAccount",
        "Service",
        "ConfigMap",
        "Secret",
        "NetworkPolicy",
        "RoleBinding",
        "HTTPRoute",
        "ReferenceGrant",
    ):
        assert kind in platform_kinds, kind

    paths = {w["path"] for w in doc["spec"]["webhooks"]}
    mutating, validating = m.webhook_configurations()
    assert mutating["webhooks"][0]["clientConfig"]["service"]["path"] in paths
    assert validating["webhooks"][0]["clientConfig"]["service"]["path"] in paths


@pytest.mark.parametrize("doc", _experiments(), ids=lambda d: d["metadata"]["name"])
def test_experiment_executes_and_hypothesis_holds(doc):
    runner = cat.ExperimentRunner(make_env, tpu_notebook)
    result = runner.run(doc)
    assert result.passed, f"{result.name}: {result.detail}"


def test_checkpoint_experiments_wired_and_faithful():
    """The three durability experiments are first-class catalog members:
    a registered handler each, YAML that survives a round-trip (so the
    catalog can be applied by external chaos tooling), the checkpoint
    steady-state checks, and hypotheses that actually promise what
    tests/test_checkpoint.py proves (exact resume, zero divergence)."""
    import yaml

    checkpoint_names = {
        "checkpoint-kill-mid-save",
        "checkpoint-restore-corrupt",
        "checkpoint-disk-full",
    }
    docs = {
        d["metadata"]["name"]: d
        for d in _experiments()
        if d["metadata"]["name"] in checkpoint_names
    }
    assert set(docs) == checkpoint_names

    runner = cat.ExperimentRunner(make_env, tpu_notebook)
    for name, doc in docs.items():
        injection = doc["spec"]["injection"]["type"]
        assert injection in runner._handlers, name
        assert injection in cat.INJECTION_TYPES, name
        assert cat.TARGET_KIND_FOR_INJECTION[injection] == "CheckpointManager"
        assert doc["spec"]["target"]["kind"] == "CheckpointManager", name
        assert yaml.safe_load(yaml.safe_dump(doc)) == doc, name
        checks = {s["check"] for s in doc["spec"]["steadyState"]}
        assert {"checkpointValid", "trainingResumed"} <= checks, name
        hypothesis = doc["spec"]["hypothesis"]
        assert "zero" in hypothesis and "divergence" in hypothesis, name
        assert "resume" in hypothesis, name
