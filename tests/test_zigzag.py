"""Zigzag (balanced causal) ring attention: parity with the dense
reference, gradients, odd mesh sizes, train-step integration, and the
causal-only contract.

The contiguous ring masks away ~half its causal FLOPs; zigzag pairs each
device with a front+back chunk so every ring step is fully visible —
same numbers, about half the attention compute (parallel/
zigzag_attention.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.train import make_train_step, shard_state
from kubeflow_tpu.ops.attention import flash_attention
from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh
from kubeflow_tpu.parallel.zigzag_attention import (
    make_sharded_zigzag_attention,
)

from tests.test_sp_attention import _close, _qkv


class TestZigzagParity:
    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_matches_dense_causal(self, sp):
        mesh = make_mesh(dp=2 if sp <= 4 else 1, sp=sp,
                         devices=jax.devices()[: 2 * sp if sp <= 4 else 8])
        q, k, v = _qkv(heads=4, sq=128)
        zz = make_sharded_zigzag_attention(mesh)
        got = zz(q, k, v, causal=True)
        ref = flash_attention(q, k, v, causal=True, impl="xla")
        _close(got, ref)

    def test_odd_device_count(self):
        """The owner permutations must hold for odd n too (parity-based
        slot selection is per-chunk, not per-mesh-half)."""
        mesh = make_mesh(sp=3, devices=jax.devices()[:3])
        q, k, v = _qkv(heads=2, sq=96)  # 32 per shard, C=16
        zz = make_sharded_zigzag_attention(mesh)
        got = zz(q, k, v, causal=True)
        ref = flash_attention(q, k, v, causal=True, impl="xla")
        _close(got, ref)

    def test_sub_block_scan_matches(self, monkeypatch):
        import importlib

        R = importlib.import_module("kubeflow_tpu.parallel.ring_attention")
        monkeypatch.setattr(R, "_RING_BLOCK", 8)  # C=16 → 2 sub-blocks
        mesh = make_mesh(sp=4, devices=jax.devices()[:4])
        q, k, v = _qkv(heads=2, sq=128)
        got = make_sharded_zigzag_attention(mesh)(q, k, v, causal=True)
        ref = flash_attention(q, k, v, causal=True, impl="xla")
        _close(got, ref)

    def test_gradients_match_dense(self):
        mesh = make_mesh(sp=4, devices=jax.devices()[:4])
        q, k, v = _qkv(heads=2, sq=64)
        zz = make_sharded_zigzag_attention(mesh)

        def loss_zz(q, k, v):
            return jnp.sum(zz(q, k, v, causal=True).astype(jnp.float32) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=True, impl="xla").astype(
                    jnp.float32
                ) ** 2
            )

        g_zz = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_zz, g_ref):
            _close(a, b, tol=5e-4)

    def test_masked_options_rejected(self):
        mesh = make_mesh(sp=2, devices=jax.devices()[:2])
        q, k, v = _qkv(heads=2, sq=32)
        zz = make_sharded_zigzag_attention(mesh)
        with pytest.raises(ValueError, match="causal-only"):
            zz(q, k, v, causal=True, window=16)
        with pytest.raises(ValueError, match="causal-only"):
            zz(q, k, v, causal=False)
        with pytest.raises(ValueError, match="causal-only"):
            zz(q, k, v, causal=True, kv_mask=jnp.ones((2, 32), bool))


class TestZigzagTrainStep:
    def test_loss_matches_ring(self):
        """One full train step under sp_impl='zigzag' produces the same
        loss as 'ring' (same math, balanced schedule) and composes with
        dp/tp on the same mesh."""
        cfg = L.LLAMA_CONFIGS["tiny"]
        mesh = make_mesh(dp=2, sp=2, tp=2)
        plan = MeshPlan(mesh)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size
        )

        def run(sp_impl):
            # Fresh params per run: the step donates its state buffers.
            params = L.init_params(cfg, jax.random.PRNGKey(0))
            init, step = make_train_step(cfg, plan, sp_impl=sp_impl)
            state = shard_state(plan, init(params))
            _, loss = step(state, tokens)
            return float(loss)

        assert abs(run("zigzag") - run("ring")) < 1e-4

    def test_unknown_impl_message_lists_zigzag(self):
        cfg = L.LLAMA_CONFIGS["tiny"]
        plan = MeshPlan(make_mesh(sp=2, devices=jax.devices()[:2]))
        with pytest.raises(ValueError, match="zigzag"):
            make_train_step(cfg, plan, sp_impl="nope")
