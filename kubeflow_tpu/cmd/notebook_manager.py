"""Core notebook-controller manager entrypoint.

Reference parity — components/notebook-controller/main.go (148 LoC):
- flag parsing: metrics-addr, probe-addr, leader-election, burst, qps
  (main.go:65-72),
- scheme registration for all three API versions (main.go:48-56) — here the
  conversion-aware API layer (kubeflow_tpu.api.notebook) is version-complete
  by construction,
- NotebookReconciler always; CullingReconciler iff ENABLE_CULLING=true
  (main.go:111-123),
- healthz/readyz checks (main.go:125-133),
- leader election gating the reconcile loop (main.go:87-94).
"""

from __future__ import annotations

import argparse
import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Optional

from kubeflow_tpu.controller.culling import CullerConfig, CullingReconciler
from kubeflow_tpu.controller.notebook import ControllerConfig, NotebookReconciler
from kubeflow_tpu.controller.preemption import RecoveryConfig, SliceHealthReconciler
from kubeflow_tpu.controller.prepull import PrePullConfig, PrePullReconciler
from kubeflow_tpu.controller.slicepool import SlicePoolReconciler
from kubeflow_tpu.k8s.client import Client
from kubeflow_tpu.k8s.health import HealthChecks, HealthServer, ping
from kubeflow_tpu.k8s.leader import UPSTREAM_LEASE, LeaderElector
from kubeflow_tpu.k8s.manager import FakeClock, Manager, RealClock
from kubeflow_tpu.k8s.serve import install_signal_handlers, serve, split_addr
from kubeflow_tpu.metrics.metrics import Metrics


@dataclass
class Options:
    """CLI flags (reference main.go:65-72)."""

    metrics_addr: str = ":8080"
    probe_addr: str = ":8081"
    enable_leader_election: bool = False
    burst: int = 0
    qps: int = 0


def parse_args(argv: Optional[list[str]] = None) -> Options:
    parser = argparse.ArgumentParser(prog="notebook-controller")
    parser.add_argument("--metrics-addr", default=":8080")
    parser.add_argument("--probe-addr", default=":8081")
    parser.add_argument("--enable-leader-election", action="store_true")
    parser.add_argument("--burst", type=int, default=0)
    parser.add_argument("--qps", type=int, default=0)
    ns = parser.parse_args(argv or [])
    return Options(
        metrics_addr=ns.metrics_addr,
        probe_addr=ns.probe_addr,
        enable_leader_election=ns.enable_leader_election,
        burst=ns.burst,
        qps=ns.qps,
    )


@dataclass
class ManagerBundle:
    """Everything main() wires together, exposed for tests/e2e drivers."""

    manager: Manager
    options: Options
    health: HealthChecks
    metrics: Metrics
    notebook_reconciler: NotebookReconciler
    culling_reconciler: Optional[CullingReconciler]
    preemption_reconciler: SliceHealthReconciler
    slicepool_reconciler: Optional[SlicePoolReconciler] = None
    prepull_reconciler: Optional["PrePullReconciler"] = None
    elector: Optional[LeaderElector] = None
    extra: dict = field(default_factory=dict)

    def run_until_idle(self, max_cycles: int = 200) -> int:
        """Reconcile loop, gated on leadership as mgr.Start is."""
        if self.elector and not self.elector.try_acquire():
            return 0
        return self.manager.run_until_idle(max_cycles)

    def tick(self, seconds: float) -> int:
        if self.elector and not self.elector.try_acquire():
            self.manager.clock.advance(seconds)
            return 0
        return self.manager.tick(seconds)


def build(
    cluster: Client,
    env: Optional[dict] = None,
    argv: Optional[list[str]] = None,
    clock: Optional[FakeClock] = None,
    identity: str = "notebook-controller-0",
    prober=None,
) -> ManagerBundle:
    """Assemble the manager exactly as main() does, against any cluster."""
    env = env or {}
    opts = parse_args(argv)
    manager = Manager(cluster, clock)

    metrics = Metrics(cluster)
    nb = NotebookReconciler(
        cluster,
        config=ControllerConfig.from_env(env),
        metrics=metrics,
        clock=manager.clock,
    )
    nb.register(manager)

    preemption = SliceHealthReconciler(
        cluster,
        metrics=metrics,
        clock=manager.clock,
        config=RecoveryConfig.from_env(env),
    )
    preemption.register(manager)

    # Warm slice pools: inert without SlicePool CRs, so always registered
    # (mirrors how Owns-watches cost nothing until objects exist).
    pools = SlicePoolReconciler(cluster, metrics=metrics, clock=manager.clock)
    pools.register(manager)

    # Gate style as culling (reference main.go:111-123), but the
    # reconciler ALWAYS registers: when the gate is off it reconciles an
    # empty desired set, so pods created by a previously-enabled run are
    # GC'd instead of orphaned (they carry no ownerReferences).
    prepull = PrePullReconciler(
        cluster, config=PrePullConfig.from_env(env), metrics=metrics,
        clock=manager.clock,
        enabled=env.get("ENABLE_IMAGE_PREPULL", "").lower() == "true",
    )
    prepull.register(manager)

    culler: Optional[CullingReconciler] = None
    culler_cfg = CullerConfig.from_env(env)
    # Reference main.go:111-123: culling controller only exists when enabled.
    if culler_cfg.enable_culling:
        if prober is None:
            # Native concurrent fan-out when built, Python prober otherwise;
            # DEV mode keeps the localhost-proxy path.
            from kubeflow_tpu.controller.prober import make_prober

            prober = make_prober(
                dev_proxy="http://localhost:8001" if culler_cfg.dev_mode else None
            )
        culler = CullingReconciler(
            cluster,
            config=culler_cfg,
            prober=prober,
            metrics=metrics,
            clock=manager.clock,
        )
        culler.register(manager)

    health = HealthChecks()
    health.add_healthz_check("healthz", ping)
    health.add_readyz_check("readyz", ping)

    elector = None
    if opts.enable_leader_election:
        elector = LeaderElector(
            cluster,
            UPSTREAM_LEASE,
            env.get("K8S_NAMESPACE", "kubeflow"),
            identity,
            clock=manager.clock,
        )

    return ManagerBundle(
        manager=manager,
        options=opts,
        health=health,
        metrics=metrics,
        notebook_reconciler=nb,
        culling_reconciler=culler,
        preemption_reconciler=preemption,
        slicepool_reconciler=pools,
        prepull_reconciler=prepull,
        elector=elector,
    )


def main(argv: Optional[list[str]] = None) -> int:
    """Process entrypoint (reference main.go:58-148): connect to the real
    apiserver, assemble the manager, serve probes, run until SIGTERM."""
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    from kubeflow_tpu.k8s.real import ClusterConfig, RealClient

    import sys

    if argv is None:
        argv = sys.argv[1:]
    env = dict(os.environ)
    opts = parse_args(argv)
    client = RealClient(ClusterConfig.from_env(env))
    bundle = build(
        client,
        env=env,
        argv=argv,
        clock=RealClock(),
        identity=env.get("HOSTNAME", "notebook-controller-0"),
    )

    host, port = split_addr(opts.probe_addr)
    health_server = HealthServer(bundle.health, host=host, port=port)
    health_server.start()
    logging.getLogger(__name__).info(
        "notebook-controller up: probes on %s:%d", host, health_server.port
    )

    metrics_server = None
    if opts.metrics_addr and opts.metrics_addr != "0":
        from kubeflow_tpu.metrics.server import MetricsServer

        mhost, mport = split_addr(opts.metrics_addr)
        metrics_server = MetricsServer(bundle.metrics, host=mhost, port=mport)
        metrics_server.start()

    stop = threading.Event()
    install_signal_handlers(stop)
    try:
        serve(bundle, client, stop)
    finally:
        health_server.stop()
        if metrics_server is not None:
            metrics_server.stop()
        client.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess e2e
    raise SystemExit(main())
