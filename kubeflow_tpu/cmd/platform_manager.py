"""Platform (ODH-equivalent) manager entrypoint.

Reference parity — components/odh-notebook-controller/main.go (374 LoC):
- required ``--kube-rbac-proxy-image`` flag, validated before anything else
  (main.go:149-150,172-176),
- TLS security-profile fetch from the cluster APIServer CR with hardened
  fallback ciphers (main.go:71-78,183-234),
- cache transforms stripping ConfigMap/Secret payloads (main.go:95-125),
- controller-namespace detection (main.go:127-139),
- MLflow env config (main.go:286-289),
- platform reconciler + mutating + validating webhook registration
  (main.go:291-331),
- SecurityProfileWatcher restarting the process on TLS change
  (main.go:344-367).
"""

from __future__ import annotations

import argparse
import logging
import os
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Optional

from kubeflow_tpu.controller.platform import PlatformConfig, PlatformReconciler
from kubeflow_tpu.controller.tls import (
    SecurityProfileWatcher,
    TLSProfile,
    fetch_tls_profile,
)
from kubeflow_tpu.k8s.cache import TransformingClient
from kubeflow_tpu.k8s.client import Client
from kubeflow_tpu.k8s.health import HealthChecks, HealthServer, ping
from kubeflow_tpu.k8s.leader import PLATFORM_LEASE, LeaderElector
from kubeflow_tpu.k8s.manager import FakeClock, Manager, RealClock
from kubeflow_tpu.k8s.serve import install_signal_handlers, serve, split_addr
from kubeflow_tpu.webhook.mutating import NotebookMutatingWebhook, WebhookConfig
from kubeflow_tpu.webhook.validating import NotebookValidatingWebhook

IN_CLUSTER_NAMESPACE_FILE = "/var/run/secrets/kubernetes.io/serviceaccount/namespace"


class FlagError(ValueError):
    """Invalid CLI flags (the reference exits 1 — main.go:172-176)."""


@dataclass
class Options:
    kube_rbac_proxy_image: str = ""
    metrics_addr: str = ":8080"
    probe_addr: str = ":8081"
    webhook_port: int = 8443
    cert_dir: str = ""
    enable_leader_election: bool = False


def parse_args(argv: Optional[list[str]] = None) -> Options:
    parser = argparse.ArgumentParser(prog="platform-notebook-controller")
    parser.add_argument("--kube-rbac-proxy-image", default="")
    parser.add_argument("--metrics-addr", default=":8080")
    parser.add_argument("--probe-addr", default=":8081")
    parser.add_argument("--webhook-port", type=int, default=8443)
    parser.add_argument("--cert-dir", default="")
    parser.add_argument("--enable-leader-election", action="store_true")
    ns = parser.parse_args(argv or [])
    opts = Options(
        kube_rbac_proxy_image=ns.kube_rbac_proxy_image,
        metrics_addr=ns.metrics_addr,
        probe_addr=ns.probe_addr,
        webhook_port=ns.webhook_port,
        cert_dir=ns.cert_dir,
        enable_leader_election=ns.enable_leader_election,
    )
    # Reference main.go:172-176: the image flag is mandatory — fail fast at
    # boot rather than inject an empty sidecar image later.
    if not opts.kube_rbac_proxy_image:
        raise FlagError("--kube-rbac-proxy-image is required")
    return opts


def detect_namespace(env: dict, namespace_file: Optional[str] = None) -> str:
    """Controller-namespace detection (reference main.go:127-139):
    explicit env wins, then the in-cluster serviceaccount namespace file,
    then the development default."""
    if env.get("K8S_NAMESPACE"):
        return env["K8S_NAMESPACE"]
    path = Path(namespace_file or IN_CLUSTER_NAMESPACE_FILE)
    try:
        text = path.read_text().strip()
        if text:
            return text
    except OSError:
        pass
    return "opendatahub"


@dataclass
class PlatformBundle:
    manager: Manager
    options: Options
    health: HealthChecks
    platform_reconciler: PlatformReconciler
    mutating_webhook: NotebookMutatingWebhook
    validating_webhook: NotebookValidatingWebhook
    tls_profile: TLSProfile
    tls_watcher: SecurityProfileWatcher
    cache_client: TransformingClient
    elector: Optional[LeaderElector] = None
    restart_requested: list = field(default_factory=list)

    def run_until_idle(self, max_cycles: int = 200) -> int:
        if self.elector and not self.elector.try_acquire():
            return 0
        return self.manager.run_until_idle(max_cycles)

    def tick(self, seconds: float) -> int:
        if self.elector and not self.elector.try_acquire():
            self.manager.clock.advance(seconds)
            return 0
        return self.manager.tick(seconds)


def build(
    cluster: Client,
    env: Optional[dict] = None,
    argv: Optional[list[str]] = None,
    clock: Optional[FakeClock] = None,
    namespace_file: Optional[str] = None,
    identity: str = "platform-controller-0",
    on_tls_change: Optional[Callable[[TLSProfile], None]] = None,
) -> PlatformBundle:
    env = env or {}
    opts = parse_args(argv if argv is not None else ["--kube-rbac-proxy-image", "x"])

    namespace = detect_namespace(env, namespace_file)
    env = {**env, "K8S_NAMESPACE": namespace}

    manager = Manager(cluster, clock)

    # TLS profile at boot + restart-on-change watcher.
    tls_profile = fetch_tls_profile(cluster)
    restart_requested: list = []

    def _restart(profile: TLSProfile) -> None:
        restart_requested.append(profile)
        if on_tls_change:
            on_tls_change(profile)

    tls_watcher = SecurityProfileWatcher(cluster, tls_profile, _restart)
    tls_watcher.register(manager)

    # Informer-cache transform client (used for bulk reads; the reconciler
    # keeps the raw client for payload-bearing objects, as the reference's
    # transform allowlist does).
    cache_client = TransformingClient(cluster)

    platform_cfg = PlatformConfig.from_env(env)
    platform = PlatformReconciler(cluster, config=platform_cfg)
    platform.register(manager)

    webhook_cfg = WebhookConfig.from_env(
        {**env, "KUBE_RBAC_PROXY_IMAGE": opts.kube_rbac_proxy_image}
    )
    mutating = NotebookMutatingWebhook(cluster, config=webhook_cfg)
    validating = NotebookValidatingWebhook(cluster)
    if hasattr(cluster, "register_mutating_webhook"):
        # In-process admission chain (FakeCluster / envtest tier). Against
        # a real apiserver, admission arrives over HTTPS instead — main()
        # serves the same handler objects via WebhookServer.
        mutating.register(cluster)
        validating.register(cluster)

    health = HealthChecks()
    health.add_healthz_check("healthz", ping)
    health.add_readyz_check("readyz", ping)

    elector = None
    if opts.enable_leader_election:
        elector = LeaderElector(
            cluster, PLATFORM_LEASE, namespace, identity, clock=manager.clock
        )

    return PlatformBundle(
        manager=manager,
        options=opts,
        health=health,
        platform_reconciler=platform,
        mutating_webhook=mutating,
        validating_webhook=validating,
        tls_profile=tls_profile,
        tls_watcher=tls_watcher,
        cache_client=cache_client,
        elector=elector,
        restart_requested=restart_requested,
    )


def main(argv: Optional[list[str]] = None) -> int:
    """Process entrypoint (reference odh main.go:141-374): real apiserver
    client, TLS profile at boot, manager + HTTPS admission server, probes,
    run until SIGTERM — or until the cluster TLS profile changes, which
    exits 0 so the pod restarts with the new profile (main.go:344-367)."""
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    from kubeflow_tpu.k8s.real import ClusterConfig, RealClient
    from kubeflow_tpu.webhook.server import WebhookServer

    import sys

    if argv is None:
        argv = sys.argv[1:]
    env = dict(os.environ)
    opts = parse_args(argv)
    client = RealClient(ClusterConfig.from_env(env))

    stop = threading.Event()
    bundle = build(
        client,
        env=env,
        argv=argv,
        clock=RealClock(),
        identity=env.get("HOSTNAME", "platform-controller-0"),
        on_tls_change=lambda profile: stop.set(),
    )

    host, port = split_addr(opts.probe_addr)
    health_server = HealthServer(bundle.health, host=host, port=port)
    health_server.start()

    webhook_server = WebhookServer(
        mutating_handler=bundle.mutating_webhook.handle,
        validating_handler=bundle.validating_webhook.handle,
        host="0.0.0.0",
        port=opts.webhook_port,
        cert_dir=opts.cert_dir or None,
        tls_profile=bundle.tls_profile,
    )
    webhook_server.start()
    logging.getLogger(__name__).info(
        "platform-controller up: probes on %s:%d, webhooks on :%d (%s)",
        host, health_server.port, webhook_server.port,
        "https" if webhook_server.tls_enabled else "http",
    )

    metrics_server = None
    if opts.metrics_addr and opts.metrics_addr != "0":
        from kubeflow_tpu.metrics.metrics import Metrics
        from kubeflow_tpu.metrics.server import MetricsServer

        mhost, mport = split_addr(opts.metrics_addr)
        metrics_server = MetricsServer(Metrics(client), host=mhost, port=mport)
        metrics_server.start()

    install_signal_handlers(stop)
    try:
        serve(bundle, client, stop)
    finally:
        health_server.stop()
        webhook_server.stop()
        if metrics_server is not None:
            metrics_server.stop()
        client.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess e2e
    raise SystemExit(main())
