"""Manager process entrypoints (the reference's two ``main.go`` files)."""
