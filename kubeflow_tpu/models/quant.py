"""Weight-only int8 / int4 quantization for the Llama family.

Decode at batch 1 is HBM-bandwidth-bound: every generated token reads all
~13.5 GB of bf16 weights on a 7B model. Storing the big projections in
fewer bits cuts the bytes read — XLA fuses the dequant into the matmul
loop, so the quantized tensors are what actually crosses HBM.

Two schemes:
- **int8, per-output-channel** (symmetric, scale over the contraction
  axis): dequant is a broadcast multiply on the OUTPUT side of the
  matmul — 2× fewer weight bytes, <0.5% logit error.
- **int4, group-wise** (symmetric, one scale per ``group`` contraction
  elements per output channel): int4's 15 levels are too coarse for a
  whole channel, so scales live at group granularity and dequant happens
  on the INPUT side (fused elementwise on the weight operand). ~4× fewer
  weight bytes (int4 packs two values per byte on TPU); expect a further
  ~1.5-1.8× decode over int8 at a small accuracy cost.

The quantized tree mirrors the bf16 tree: each targeted weight becomes
{"q", "s"} — pure arrays in both schemes (the int4 grouping is encoded in
the scale tensor's SHAPE, keeping the tree pytree/jit safe). llama.py's
matmul helper (_mm / _lm_head_logits) consumes any representation, so
forward/prefill/decode/generate work unchanged.

Embeddings stay bf16 (a gather, not a matmul: per-channel scales don't
fold, and it is read once per token, not per layer).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# Stacked (L, in, out) projections plus the (V, D) lm_head.
_LAYER_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@partial(jax.jit, static_argnames=("axis",))
def quantize_weight(w: jax.Array, axis: int) -> dict:
    """Symmetric per-channel int8: scale = max|w| / 127 over ``axis``
    (the contraction axis), so dequant is a broadcast multiply on the
    OUTPUT side of the matmul.

    Jitted so the f32 upcast stays fused into the reduce/round kernels —
    eager mode would materialize a full f32 copy (2× the bf16 tensor),
    which OOMs a 16 GB chip mid-way through quantizing a 7B model."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale.astype(jnp.float32)}


def _check_int4_shape(w, axis: int, group: int) -> None:
    """Validate one target's (shape, axis, group) BEFORE any quantization
    side effects — quantize_params calls this for every target up front so
    free_source never deletes half a tree and then fails."""
    if w.shape[axis] % group:
        raise ValueError(
            f"contraction dim {w.shape[axis]} not divisible by group {group}"
        )
    if not 2 <= group < w.shape[axis]:
        raise ValueError(
            f"group {group} must be in [2, {w.shape[axis]}) — the grouping "
            "is encoded in the scale tensor's shape, which needs "
            "n_groups != contraction dim and != group count of 1"
        )


@partial(jax.jit, static_argnames=("axis", "group"))
def quantize_weight_int4(w: jax.Array, axis: int, group: int = 128) -> dict:
    """Symmetric group-wise int4: the contraction axis is split into
    groups of ``group``; each (group, output-channel) pair gets its own
    scale = max|w| / 7. Returns {"q": int4 (original shape), "s": f32
    per-group scales} — dequantized on the weight-operand side by the
    consumer; axis/group are recovered from the shapes (int4_axis_group).
    """
    _check_int4_shape(w, axis, group)
    wf = w.astype(jnp.float32)
    # Split the contraction axis into (n_groups, group).
    shape = list(wf.shape)
    shape[axis:axis + 1] = [shape[axis] // group, group]
    grouped = wf.reshape(shape)
    amax = jnp.max(jnp.abs(grouped), axis=axis + 1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(grouped / scale), -7, 7).astype(jnp.int4)
    # The representation is {"q": int4 (original shape), "s": f32 with
    # n_groups replacing the contraction dim}: axis and group are
    # recoverable from the STATIC shapes (the one dim where they differ),
    # so the tree stays pure-array — pytree/jit safe.
    return {"q": q.reshape(w.shape), "s": jnp.squeeze(scale, axis=axis + 1)}


def int4_axis_group(q: jax.Array, s: jax.Array) -> tuple[int, int]:
    """Recover (contraction axis, group size) from an int4 pair's shapes."""
    for i, (qd, sd) in enumerate(zip(q.shape, s.shape)):
        if qd != sd:
            return i, qd // sd
    raise ValueError(f"no grouped axis between shapes {q.shape} / {s.shape}")


def dequantize_weight(qw: dict, dtype=jnp.bfloat16) -> jax.Array:
    q = qw["q"]
    if q.dtype == jnp.int4:
        axis, g = int4_axis_group(q, qw["s"])
        shape = list(q.shape)
        shape[axis:axis + 1] = [shape[axis] // g, g]
        grouped = q.astype(jnp.float32).reshape(shape)
        scale = jnp.expand_dims(qw["s"], axis + 1)
        return (grouped * scale).reshape(q.shape).astype(dtype)
    return (q.astype(jnp.float32) * qw["s"]).astype(dtype)


def quantize_params(params: dict, targets=_LAYER_TARGETS,
                    quantize_lm_head: bool = True,
                    free_source: bool = False,
                    bits: int = 8, group: int = 128) -> dict:
    """bf16 param tree → mixed tree with int8 (``bits=8``, per-channel),
    int4 (``bits=4``, group-wise), or fp8 (``bits="fp8"``, per-channel
    e4m3 — models/fp8.py) projections.

    Stacked layer weights (L, in, out) contract over axis 1; lm_head
    (V, D) contracts over axis 1 (used as x @ lm_head.T).

    ``free_source=True`` DELETES each bf16 source buffer as soon as its
    quantized copy exists — required to quantize a 7B model in place on a
    16 GB chip (13.5 GB bf16 + 7 GB int8 would not coexist). The input
    tree's projection leaves are invalid afterwards."""
    if bits == "fp8":
        from kubeflow_tpu.models.fp8 import quantize_weight_fp8

        quantize = lambda w, axis: quantize_weight_fp8(w, axis=axis)  # noqa: E731
    elif bits == 8:
        quantize = lambda w, axis: quantize_weight(w, axis=axis)  # noqa: E731
    elif bits == 4:
        quantize = lambda w, axis: quantize_weight_int4(  # noqa: E731
            w, axis=axis, group=group
        )
        # Validate EVERY target up front: with free_source, a mid-loop
        # shape error after earlier delete()s would leave neither a usable
        # bf16 tree nor a quantized one.
        for t in targets:
            _check_int4_shape(params["layers"][t], 1, group)
        if quantize_lm_head and "lm_head" in params:
            _check_int4_shape(params["lm_head"], 1, group)
    else:
        raise ValueError(f"bits must be 8, 4, or 'fp8', got {bits}")
    layers = dict(params["layers"])
    for t in targets:
        src = layers[t]
        layers[t] = jax.block_until_ready(quantize(src, axis=1))
        if free_source:
            src.delete()
    out = {**params, "layers": layers}
    # Tied trees have no lm_head leaf; the projection then goes through
    # the (unquantized) embedding, which is also the gather table.
    if quantize_lm_head and "lm_head" in params:
        out["lm_head"] = jax.block_until_ready(
            quantize(params["lm_head"], axis=1)
        )
        if free_source:
            params["lm_head"].delete()
    return out


def quantized_bytes(params: dict) -> int:
    """HBM bytes of a (possibly mixed) param tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total


def quant_bits_from_env():
    """Serving-side half of the notebook runtime option: the webhook
    projects the ``notebooks.kubeflow.org/tpu-quantization`` annotation
    into KUBEFLOW_TPU_QUANT ("int8"|"int4"|"fp8"; absent/"bf16" = 0).
    Returns the ``bits`` argument for quantize_params (0 = stay bf16;
    "fp8" passes through as the string quantize_params dispatches on).
    Raises on values the validating webhook would have denied — a
    hand-set env var must not silently serve full precision."""
    import os

    from kubeflow_tpu.api.annotations import QUANT_ENV_NAME

    value = os.environ.get(QUANT_ENV_NAME, "")
    if value in ("", "bf16"):
        return 0
    if value == "int8":
        return 8
    if value == "int4":
        return 4
    if value == "fp8":
        return "fp8"
    raise ValueError(
        f"{QUANT_ENV_NAME}={value!r}: want 'int8', 'int4', 'fp8', or 'bf16'"
    )
