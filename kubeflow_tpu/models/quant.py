"""Weight-only int8 quantization for the Llama family.

Decode at batch 1 is HBM-bandwidth-bound: every generated token reads all
~13.5 GB of bf16 weights on a 7B model. Storing the big projections as
int8 with a per-output-channel bf16 scale halves the bytes read — XLA
fuses the dequant (cast + scale multiply) into the matmul loop, so the
int8 tensors are what actually crosses HBM. Expected decode speedup at
bs=1 approaches 2× with <0.5% logit error (symmetric per-channel).

The quantized tree mirrors the bf16 tree: each targeted weight becomes
{"q": int8, "s": f32 scale broadcast over the input axis}. llama.py's
matmul helper (_mm / _lm_head_logits) consumes either representation, so
forward/prefill/decode/generate work unchanged.

Embeddings stay bf16 (a gather, not a matmul: per-channel scales don't
fold, and it is read once per token, not per layer).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# Stacked (L, in, out) projections plus the (V, D) lm_head.
_LAYER_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@partial(jax.jit, static_argnames=("axis",))
def quantize_weight(w: jax.Array, axis: int) -> dict:
    """Symmetric per-channel int8: scale = max|w| / 127 over ``axis``
    (the contraction axis), so dequant is a broadcast multiply on the
    OUTPUT side of the matmul.

    Jitted so the f32 upcast stays fused into the reduce/round kernels —
    eager mode would materialize a full f32 copy (2× the bf16 tensor),
    which OOMs a 16 GB chip mid-way through quantizing a 7B model."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale.astype(jnp.float32)}


def dequantize_weight(qw: dict, dtype=jnp.bfloat16) -> jax.Array:
    return (qw["q"].astype(jnp.float32) * qw["s"]).astype(dtype)


def quantize_params(params: dict, targets=_LAYER_TARGETS,
                    quantize_lm_head: bool = True,
                    free_source: bool = False) -> dict:
    """bf16 param tree → mixed tree with int8 projections.

    Stacked layer weights (L, in, out) contract over axis 1; lm_head
    (V, D) contracts over axis 1 (used as x @ lm_head.T).

    ``free_source=True`` DELETES each bf16 source buffer as soon as its
    int8 copy exists — required to quantize a 7B model in place on a
    16 GB chip (13.5 GB bf16 + 7 GB int8 would not coexist). The input
    tree's projection leaves are invalid afterwards."""
    layers = dict(params["layers"])
    for t in targets:
        src = layers[t]
        layers[t] = jax.block_until_ready(quantize_weight(src, axis=1))
        if free_source:
            src.delete()
    out = {**params, "layers": layers}
    # Tied trees have no lm_head leaf; the projection then goes through
    # the (unquantized) embedding, which is also the gather table.
    if quantize_lm_head and "lm_head" in params:
        out["lm_head"] = jax.block_until_ready(
            quantize_weight(params["lm_head"], axis=1)
        )
        if free_source:
            params["lm_head"].delete()
    return out


def quantized_bytes(params: dict) -> int:
    """HBM bytes of a (possibly mixed) param tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
