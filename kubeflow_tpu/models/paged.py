"""Paged KV cache: block-pool serving memory with on-demand allocation.

``ContinuousBatcher`` (models/continuous.py) reserves ``cache_len`` rows
per slot for the slot's whole lifetime — a request that stops after 10
tokens still held memory for 1024. Paged serving (the vLLM insight)
carves the cache into fixed-size BLOCKS shared by all slots through
per-slot block TABLES: a request holds exactly the blocks its tokens
occupy, blocks return to the pool at retirement, and total memory is
sized to the *expected* load, not slots × worst case.

TPU-first shape discipline (all static shapes, one compiled step):
- the pool is ``(L, num_blocks, Hkv, block_size, D)`` per k/v; the decode
  step gathers each slot's table → ``(B, Hkv, MAXB·BS, D)`` logical cache
  view and reuses the same GQA decode attention as the dense path, with
  the same ``(B, C)`` validity mask — correctness is inherited, only the
  storage changed;
- per-token writes are an advanced-indexing scatter at
  ``(block, :, offset)`` — requests own disjoint blocks, so rows never
  conflict;
- block tables/positions are host numpy, uploaded once per step; the
  block ALLOCATOR is plain host Python between steps (a free list), the
  exact split the reference architecture uses for its control planes:
  device for math, host for bookkeeping.

Allocation is on demand: a request takes ``prompt_bucket/BS`` blocks at
admit and one more each time generation crosses a block boundary. When
the pool runs dry the YOUNGEST active request is preempted — its blocks
freed, its prompt+generated tokens re-queued as a continuation prompt —
which is also vLLM's recovery mechanism. Greedy continuations are
byte-identical after re-prefill; sampled ones resume with a fresh key
stream (documented, matching vLLM's recompute semantics).

Two opt-in prompt-reuse tiers:
- ``prompt_cache=True`` — whole-prompt: identical padded prompts share
  refcounted blocks + cached first logits (left-padded layout kept);
- ``prefix_cache=True`` — per-block: position-0-ANCHORED admission (no
  left-padding; token i at logical position i) makes common PREFIXES
  across different-length prompts content-addressable block-by-block
  via a vLLM-style chain hash; only the unmatched tail is prefilled,
  through the block tables (``_paged_prefix_admit``).

No reference counterpart (control plane only); sits with serving/
continuous/speculative as the in-notebook inference surface.
"""

from __future__ import annotations

import base64
import hashlib
from collections import OrderedDict
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.llama import (
    LlamaConfig,
    _embed,
    _gqa_decode_attention,
    _kv_cache_leaves,
    _kv_quantize,
    _lm_head_logits,
    _merge_heads,
    _mlp,
    _mm,
    _norm,
    _prefill_impl,
    _qkv,
    _split_heads,
    apply_rope,
    init_kv_cache,
    rope_frequencies,
    sample_logits,
    sample_logits_per_row,
)
from kubeflow_tpu.models.continuous import (
    _AdmissionCursor,
    _BatcherBase,
    _Request,
)
from kubeflow_tpu.models.serving import GenerationConfig, left_pad


def init_block_pool(
    cfg: LlamaConfig, num_blocks: int, block_size: int, kv_bits: int = 0
) -> dict:
    """k/v block pools, (L, NB, Hkv, BS, D).

    ``kv_bits=8`` stores int8 values + per-(block-row, head, offset) bf16
    scale leaves — same structure-keyed format as models.llama
    init_kv_cache (shared leaf constructor), so the step/admit programs
    dispatch off the pytree."""
    shape = (cfg.n_layers, num_blocks, cfg.n_kv_heads, block_size, cfg.head_dim)
    return _kv_cache_leaves(shape, cfg.dtype, kv_bits)


def _kv_block_bytes(cfg: LlamaConfig, block_size: int, kv_bits: int = 0,
                    tp: int = 1) -> int:
    """Raw bytes ONE pool block occupies across every leaf (k + v, plus
    the bf16 scale leaves under kv_bits=8).

    ``tp`` > 1 returns the PER-SHARD cost under a head-sharded pool
    (parallel.mesh.MeshPlan.shard_kv_cache puts the kv-head axis over
    tp): each shard holds n_kv_heads/tp heads' rows, so per-chip pool
    bytes drop by exactly the TP degree."""
    if tp < 1 or cfg.n_kv_heads % tp:
        raise ValueError(
            f"tp={tp} must be >= 1 and divide n_kv_heads={cfg.n_kv_heads}"
        )
    rows = cfg.n_layers * (cfg.n_kv_heads // tp) * block_size
    if kv_bits == 8:
        # int8 values + one bf16 scale per (layer, head, offset) row.
        return 2 * rows * cfg.head_dim + 2 * rows * 2
    return 2 * rows * cfg.head_dim * np.dtype(jnp.bfloat16).itemsize


def pool_blocks_from_hbm(
    cfg: LlamaConfig,
    block_size: int,
    kv_bits: int = 0,
    *,
    fraction: float = 0.5,
    fallback: int = 64,
    device=None,
    with_source: bool = False,
    tp: int = 1,
):
    """Size a block pool from the accelerator's live memory stats: spend
    ``fraction`` of the device's free HBM (bytes_limit - bytes_in_use) on
    KV blocks. Backends without memory_stats (CPU, some plugins) return
    ``fallback`` — today's constant block counts keep working there, so
    notebooks stay runnable off-TPU while TPU pools scale with the chip.

    ``tp`` > 1 sizes off PER-SHARD headroom: a head-sharded pool costs
    each chip only 1/tp of a block's bytes, so the same free HBM holds
    tp× the blocks — the capacity win of tensor-parallel serving.

    ``with_source`` returns ``(blocks, source)`` with source ``"hbm"``
    (sized from live memory stats) or ``"fallback"`` — the /stats
    pool-sizing record, so operators can see which branch actually ran.
    """
    def _ret(blocks: int, source: str):
        return (blocks, source) if with_source else blocks

    if not 0.0 < fraction <= 1.0:
        raise ValueError(f"fraction must be in (0, 1], got {fraction!r}")
    if device is None:
        devices = jax.local_devices()
        if not devices:
            return _ret(fallback, "fallback")
        device = devices[0]
    stats_fn = getattr(device, "memory_stats", None)
    if stats_fn is None:
        return _ret(fallback, "fallback")
    try:
        stats = stats_fn()
    except Exception:
        stats = None
    if not stats:
        return _ret(fallback, "fallback")
    limit = int(stats.get("bytes_limit")
                or stats.get("bytes_reservable_limit") or 0)
    in_use = int(stats.get("bytes_in_use") or 0)
    budget = int((limit - in_use) * fraction)
    per_block = _kv_block_bytes(cfg, block_size, kv_bits, tp=tp)
    if budget <= 0 or per_block <= 0:
        return _ret(fallback, "fallback")
    # Block 0 is the null block; 2 is the smallest pool with a usable one.
    return _ret(max(2, budget // per_block), "hbm")


def _np_leaf_dtype(name: str) -> np.dtype:
    """numpy dtype for a serialized pool-leaf dtype name. bf16 resolves
    through ml_dtypes (a jax dependency): np.dtype("bfloat16") raises
    TypeError while the registered scalar type works."""
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


@partial(jax.jit, static_argnames=("cfg", "block_size"), donate_argnums=(3,))
def _paged_admit(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,  # (1, Lb) left-padded prompt
    pool: dict,
    prompt_mask: Optional[jax.Array],  # (1, Lb) or None
    blocks: jax.Array,  # (Lb // BS,) int32 — this slot's prompt blocks
    block_size: int,
) -> tuple[jax.Array, dict]:
    """Prefill one prompt into its allocated blocks; first logits (V,)."""
    lb = tokens.shape[1]
    # Temp cache mirrors the pool's storage format (structure-keyed int8);
    # scale leaves are one rank lower, with the sequence axis at -1.
    temp = init_kv_cache(cfg, 1, lb, kv_bits=8 if "k_scale" in pool else 0)
    logits, temp = _prefill_impl(params, cfg, tokens, temp, kv_mask=prompt_mask)
    new_pool = dict(pool)
    for name in pool:
        buf = new_pool[name]
        # temp[name][:, 0] is (L, Hkv, S, D) for values, (L, Hkv, S) for
        # scale leaves — the sequence axis is 2 in both.
        # kftpu-lint: disable=kftpu-host-sync-in-hot-path — per-BLOCK relayout of one prompt's kv at admission time (bounded by prompt length / block_size), not a per-token decode readback
        for j in range(lb // block_size):
            chunk = jax.lax.dynamic_slice_in_dim(
                temp[name][:, 0], j * block_size, block_size, axis=2
            )  # (L, Hkv, BS[, D])
            buf = jax.lax.dynamic_update_slice(
                buf, chunk[:, None], (0, blocks[j]) + (0,) * (buf.ndim - 2)
            )
        new_pool[name] = buf
    return logits[0], new_pool


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "block_size", "top_k", "top_p", "attn_kernel",
    ),
    donate_argnums=(3,),
)
def _paged_step(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,  # (B, 1)
    pool: dict,
    tables: jax.Array,  # (B, MAXB) int32
    positions: jax.Array,  # (B,)
    kv_mask: jax.Array,  # (B, MAXB * BS)
    key: jax.Array,
    block_size: int,
    temps: jax.Array,  # (B,) per-slot sampling temperature (0 = greedy)
    top_k: int,
    top_p: float,
    bias=None,  # (B, V) per-slot logit bias, or None (bias-free program)
    attn_kernel: bool = False,
) -> tuple[jax.Array, dict]:
    """One decode step across every slot, reading/writing through tables."""
    cos, sin = rope_frequencies(cfg, positions)
    blks = jnp.take_along_axis(
        tables, (positions // block_size)[:, None], axis=1
    )  # (B, 1) physical block for this step's token
    offs = (positions % block_size)[:, None]
    x, new_pool = _paged_chunk_scan(
        params, cfg, tokens, pool, tables, kv_mask, cos, sin, blks, offs,
        positions, block_size, attn_kernel=attn_kernel,
    )
    logits = _lm_head_logits(_norm(x[:, 0], params["final_norm"], cfg), params)
    if bias is not None:
        logits = logits + bias
    nxt = sample_logits_per_row(logits, key, temps, top_k, top_p)
    lp = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), nxt[:, None], axis=-1
    )[:, 0]
    return nxt, lp, new_pool


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "block_size", "top_k", "top_p", "attn_kernel",
    ),
    donate_argnums=(3,),
)
def _paged_ragged_step(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,  # (T, 1) flattened mixed batch, tail-padded
    pool: dict,
    tables: jax.Array,  # (S, MAXB) int32 per-SLOT block tables
    kv_mask: jax.Array,  # (S, MAXB * BS) per-slot validity
    tok_pos: jax.Array,  # (T,) absolute kv position per token
    tok_seq: jax.Array,  # (T,) owning slot per token (pads: 0)
    n_tokens: jax.Array,  # scalar int32 — real rows; pads sit at the tail
    seq_starts: jax.Array,  # (S,) first row of each slot's span
    seq_lens: jax.Array,    # (S,) rows this step (0 = not participating)
    kv_lens: jax.Array,     # (S,) kv length INCLUDING this step's span
    last_rows: jax.Array,   # (S,) row of each slot's LAST token (0 if idle)
    key: jax.Array,
    block_size: int,
    temps: jax.Array,  # (S,) per-slot sampling temperature
    top_k: int,
    top_p: float,
    bias=None,  # (S, V) per-slot logit bias, or None
    attn_kernel: bool = False,
    adapters=None,  # (stacked, ids (S,), scaling) → per-row LoRA deltas
) -> tuple[jax.Array, jax.Array, dict]:
    """ONE fused dispatch for a mixed decode/prefill batch (the ragged
    entry point, arXiv 2604.15464): every participating slot contributes
    a contiguous row span — one row for a decoding slot, its next prompt
    chunk for an admitting slot — and the whole flattened batch runs the
    SAME chunk body as plain paged decode (_paged_chunk_scan with T as
    the batch axis, K=1). Each token scatters at its own (block, offset)
    and attends its slot's view at its own absolute position, so chunk
    causality and cross-chunk isolation fall out of the existing masking
    rule; pads are routed to the null block and fenced by position.

    Returns per-SLOT (next_token, chosen logprob) sampled from each
    span's last row — a decoding slot's next token and an admission-
    completing slot's FIRST token come out of the same dispatch — plus
    the updated pool. Rows of mid-prefill or idle slots are sampled too
    (static shapes) and discarded by the scheduler.

    ``adapters`` = (stacked, ids (S,), scaling): every row rides its
    OWNING slot's LoRA adapter through the shared chunk body (multi-LoRA
    over the ragged dispatch) — decode rows and admission chunk rows
    alike, so prefill is adapter-aware for free."""
    posmat = tok_pos[:, None]
    tok_tables = tables[tok_seq]
    tok_mask = kv_mask[tok_seq]
    cos, sin, blks, offs = _chunk_coords(cfg, tok_tables, posmat, block_size)
    # Tail pads carry tok_seq 0 — their scatter targets must be forced to
    # the null block, or they would overwrite slot 0's live KV.
    tok_valid = jnp.arange(tokens.shape[0]) < n_tokens
    blks = jnp.where(tok_valid[:, None], blks, 0)
    x, new_pool = _paged_chunk_scan(
        params, cfg, tokens, pool, tok_tables, tok_mask, cos, sin, blks,
        offs, posmat, block_size, attn_kernel=attn_kernel,
        ragged=(seq_starts, seq_lens, kv_lens, tables, kv_mask),
        adapters=_row_adapters(adapters, tok_seq),
    )
    # Logits only at each slot's last row — the lm head runs S wide, not
    # T wide (the budget is several× the slot count under load).
    xs = x[last_rows, 0]  # (S, dim)
    logits = _lm_head_logits(_norm(xs, params["final_norm"], cfg), params)
    if bias is not None:
        logits = logits + bias
    nxt = sample_logits_per_row(logits, key, temps, top_k, top_p)
    lp = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), nxt[:, None], axis=-1
    )[:, 0]
    return nxt, lp, new_pool


def _row_adapters(adapters, tok_seq):
    """Per-SLOT adapter spec → per-ROW gathered selection for the chunk
    body: (stacked, ids (S,), scaling) becomes (sel, scaling) with sel's
    leaves (L, T, in, r) — each flattened row indexes its owning slot's
    adapter pair. None passes through (the base-only program)."""
    if adapters is None:
        return None
    from kubeflow_tpu.models.multilora import _gather_adapters

    stacked, ids, scaling = adapters
    return _gather_adapters(stacked, ids[tok_seq]), scaling


@partial(
    jax.jit,
    static_argnames=("cfg", "block_size", "attn_kernel"),
    donate_argnums=(3,),
)
def _paged_ragged_verify(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,  # (T, 1) flattened mixed batch, tail-padded
    pool: dict,
    tables: jax.Array,  # (S, MAXB)
    kv_mask: jax.Array,  # (S, MAXB * BS)
    tok_pos: jax.Array,  # (T,)
    tok_seq: jax.Array,  # (T,)
    n_tokens: jax.Array,  # scalar int32
    seq_starts: jax.Array,  # (S,)
    seq_lens: jax.Array,    # (S,)
    kv_lens: jax.Array,     # (S,)
    block_size: int,
    attn_kernel: bool = False,
    adapters=None,  # (stacked, ids (S,), scaling)
) -> tuple[jax.Array, dict]:
    """The ragged dispatch with a T-wide ARGMAX head — speculation as a
    scheduling mode of the fused step. Row metadata is identical to
    _paged_ragged_step; the difference is WHAT each span means: a
    speculating slot contributes a (1 + draft_len) verify span
    [last, d_1..d_k] whose row j is the target's prediction after
    ...[last, d_1..d_j], so the lm head must run at EVERY row, not just
    last_rows (greedy acceptance walks the whole span; an admission-
    completing span's first token is its last row's argmax). Returns
    (per-row argmax predictions (T,), updated pool)."""
    posmat = tok_pos[:, None]
    tok_tables = tables[tok_seq]
    tok_mask = kv_mask[tok_seq]
    cos, sin, blks, offs = _chunk_coords(cfg, tok_tables, posmat, block_size)
    tok_valid = jnp.arange(tokens.shape[0]) < n_tokens
    blks = jnp.where(tok_valid[:, None], blks, 0)
    x, new_pool = _paged_chunk_scan(
        params, cfg, tokens, pool, tok_tables, tok_mask, cos, sin, blks,
        offs, posmat, block_size, attn_kernel=attn_kernel,
        ragged=(seq_starts, seq_lens, kv_lens, tables, kv_mask),
        adapters=_row_adapters(adapters, tok_seq),
    )
    logits = _lm_head_logits(
        _norm(x[:, 0], params["final_norm"], cfg), params
    )
    return jnp.argmax(logits, axis=-1), new_pool  # (T,)


def _scatter_chunk(pool_l, k, v, blks, offs):
    """Scatter a (B, Hkv, K, D) chunk into (block, offset) per token —
    requests own disjoint blocks, so batch rows never collide; the small
    static K unrolls. The pool pytree's structure decides the storage
    format: scale leaves present → quantize on write (int8 KV,
    models.llama kv_bits=8)."""
    pool_l = dict(pool_l)
    if "k_scale" in pool_l:
        kq, ks = _kv_quantize(k)
        vq, vs = _kv_quantize(v)
        for j in range(blks.shape[1]):
            bj, oj = blks[:, j], offs[:, j]
            pool_l["k"] = pool_l["k"].at[bj, :, oj].set(kq[:, :, j])
            pool_l["v"] = pool_l["v"].at[bj, :, oj].set(vq[:, :, j])
            pool_l["k_scale"] = (
                pool_l["k_scale"].at[bj, :, oj].set(ks[:, :, j])
            )
            pool_l["v_scale"] = (
                pool_l["v_scale"].at[bj, :, oj].set(vs[:, :, j])
            )
    else:
        for j in range(blks.shape[1]):
            bj, oj = blks[:, j], offs[:, j]
            pool_l["k"] = pool_l["k"].at[bj, :, oj].set(k[:, :, j])
            pool_l["v"] = pool_l["v"].at[bj, :, oj].set(v[:, :, j])
    return pool_l


def _paged_chunk_scan(params, cfg, tokens, pool, tables, kv_mask, cos, sin,
                      blks, offs, attn_positions, block_size,
                      attn_kernel=False, ragged=None, adapters=None):
    """The ONE paged decode body (scan over layers), shared by the
    ordinary decode step (K=1) and the speculative verify chunk (K>1) —
    same discipline as llama._chunk_decode_scan: a single body means a
    future change (norm placement, window semantics, int8
    quantize-on-write) cannot diverge plain paged decode from
    speculative verification.

    ``attn_kernel``: read the cache THROUGH the tables with the pallas
    paged-attention kernel (ops/paged_attention.py) instead of
    materializing the gathered logical view — one read of the live
    blocks per step instead of gather-write-reread of all MAXB slots.
    Applies to the single-token path (K=1, no sliding window); the
    per-token decode kernel additionally requires a bf16 pool, while
    the ragged kernel also reads int8-value + bf16-scale pools
    (dequantized per streamed block). Everything else keeps the
    gathered view, whose masking the kernel is tested to match
    bit-for-bit in intent and to bf16 tolerance in value.

    ``ragged``: ``(seq_starts, seq_lens, kv_lens, seq_tables, seq_mask)``
    per-SEQUENCE metadata for a flattened mixed batch (the ragged entry
    point, _paged_ragged_step). With ``attn_kernel`` it swaps the
    per-token decode kernel for ops/ragged_attention.py's per-sequence
    kernel — each slot's blocks are read ONCE and amortized over its
    whole chunk instead of once per token. Without the kernel the
    gathered per-token path below already handles the ragged layout
    (``tables``/``kv_mask`` arrive pre-indexed per token), which is the
    CPU fallback tier-1 exercises.

    ``adapters``: ``(sel, scaling)`` — per-ROW LoRA selections with
    layer-leading leaves (L, B, in, r), already gathered by adapter id
    (_row_adapters). The deltas ride the base matmuls inside this ONE
    body (multilora's skinny-einsum scheme), so every caller — decode,
    ragged mixed batches, speculative verify — is adapter-correct
    without a second forward."""
    if adapters is not None:
        # Lazy: multilora subclasses PagedBatcher, so the module-level
        # import direction is multilora → paged.
        from kubeflow_tpu.models.multilora import (
            _adapted_mlp,
            _adapted_qkv,
            _delta,
        )

        sel_all, scaling = adapters
    x = _embed(params, cfg, tokens)
    use_kernel = (
        attn_kernel
        and tokens.shape[1] == 1
        and not cfg.sliding_window
        # int8 pools compose with the RAGGED kernel (it dequantizes per
        # block); the plain per-token decode kernel stays bf16-only.
        and (ragged is not None or "k_scale" not in pool)
    )

    def gathered(pool_l):
        return _gathered_view(
            pool_l, tables, cfg.n_kv_heads, block_size, cfg.head_dim
        )

    def body(x, scanned):
        if adapters is None:
            layer, pool_l = scanned  # per-layer pool dict, (NB, Hkv, …)
            sel = None
        else:
            layer, pool_l, sel = scanned
        h = _norm(x, layer["attn_norm"], cfg)
        if sel is None:
            hq, hk, hv = _qkv(h, layer)
        else:
            hq, hk, hv = _adapted_qkv(h, layer, sel, scaling)
        q = apply_rope(_split_heads(hq, cfg.n_heads), cos, sin,
                       per_batch=True)
        k = apply_rope(_split_heads(hk, cfg.n_kv_heads), cos, sin,
                       per_batch=True)
        v = _split_heads(hv, cfg.n_kv_heads)
        pool_l = _scatter_chunk(pool_l, k, v, blks, offs)
        if use_kernel and ragged is not None:
            from kubeflow_tpu.ops.ragged_attention import (
                ragged_paged_attention,
            )

            seq_starts, seq_lens, kv_lens, seq_tables, seq_mask = ragged
            attn = ragged_paged_attention(
                q[:, :, 0, :], pool_l["k"], pool_l["v"], seq_tables,
                seq_mask, seq_starts, seq_lens, kv_lens, block_size,
                interpret=jax.default_backend() not in ("tpu", "axon"),
                k_scale_pool=pool_l.get("k_scale"),
                v_scale_pool=pool_l.get("v_scale"),
            )[:, :, None, :]
        elif use_kernel:
            from kubeflow_tpu.ops.paged_attention import (
                paged_decode_attention,
            )

            attn = paged_decode_attention(
                q[:, :, 0, :], pool_l["k"], pool_l["v"], tables, kv_mask,
                attn_positions + 1, block_size,
                interpret=jax.default_backend() not in ("tpu", "axon"),
            )[:, :, None, :]
        else:
            attn = _gqa_decode_attention(
                q, gathered(pool_l["k"]), gathered(pool_l["v"]),
                attn_positions,
                window=cfg.sliding_window, kv_mask=kv_mask, per_batch=True,
                k_scale=(gathered(pool_l["k_scale"])
                         if "k_scale" in pool_l else None),
                v_scale=(gathered(pool_l["v_scale"])
                         if "v_scale" in pool_l else None),
            )
        merged = _merge_heads(attn)
        o = _mm(merged, layer["wo"])
        if sel is not None and "wo" in sel:
            o = o + _delta(merged, sel, "wo", scaling)
        x = x + o
        h = _norm(x, layer["mlp_norm"], cfg)
        x = x + (_mlp(layer, h, cfg) if sel is None
                 else _adapted_mlp(layer, h, cfg, sel, scaling))
        return x, pool_l

    if adapters is None:
        return jax.lax.scan(body, x, (params["layers"], pool))
    return jax.lax.scan(body, x, (params["layers"], pool, sel_all))


def _chunk_coords(cfg, tables, posmat, block_size):
    """Per-token (cos, sin, blks, offs) for a (B, K) chunk decoded at
    absolute positions ``posmat`` through ``tables`` — the ONE home for
    the chunk coordinate math (rope batching, block index, offset),
    shared by the speculative verify and the prefix-admit wrappers so
    it cannot drift between them."""
    b, k_len = posmat.shape
    cos, sin = rope_frequencies(cfg, posmat.reshape(-1))
    cos = cos.reshape(b, k_len, -1)
    sin = sin.reshape(b, k_len, -1)
    blks = jnp.take_along_axis(tables, posmat // block_size, axis=1)
    offs = posmat % block_size
    return cos, sin, blks, offs


def _gathered_view(pool_l, tables, n_kv_heads, block_size, head_dim):
    """(NB, Hkv, BS[, D])[tables] → logical per-slot view
    (B, Hkv, MAXB·BS[, D]). Shared by the decode step and the speculative
    verify chunk; handles value leaves and (one rank lower) int8 scale
    leaves alike."""
    b, maxb = tables.shape
    g = pool_l[tables]
    perm = (0, 2, 1, 3) + ((4,) if g.ndim == 5 else ())
    shape = (b, n_kv_heads, maxb * block_size)
    if g.ndim == 5:
        shape += (head_dim,)
    return g.transpose(perm).reshape(shape)


def _gather_cells(pool: dict, blks, offs) -> dict:
    """Snapshot the pool cells addressed by parallel (block, offset)
    lists — the read half of speculative rollback. Generic over the
    storage format: value leaves (L, NB, Hkv, BS, D) gather to
    (N, L, Hkv, D), int8 scale leaves (one rank lower) to (N, L, Hkv) —
    mixed basic/advanced indexing moves the advanced axes to the
    front."""
    bi = jnp.asarray(blks, jnp.int32)
    oi = jnp.asarray(offs, jnp.int32)
    return {name: leaf[:, bi, :, oi] for name, leaf in pool.items()}


def _restore_cells(pool: dict, snap: dict, blks, offs) -> dict:
    """Write a _gather_cells snapshot back — the unwind half of
    speculative rollback: a rejected-suffix cell returns to its exact
    pre-dispatch bytes, so the pool is byte-identical to a
    never-speculated run (pinned by tests)."""
    bi = jnp.asarray(blks, jnp.int32)
    oi = jnp.asarray(offs, jnp.int32)
    return {name: pool[name].at[:, bi, :, oi].set(snap[name])
            for name in pool}


@partial(
    jax.jit, static_argnames=("cfg", "block_size"), donate_argnums=(3,)
)
def _paged_verify(
    params: dict,
    cfg: LlamaConfig,
    chunk: jax.Array,  # (B, K) — [last, d_1..d_{K-1}] per row
    pool: dict,
    tables: jax.Array,  # (B, MAXB)
    positions: jax.Array,  # (B,) per-row write offsets
    kv_mask: jax.Array,  # (B, MAXB * BS)
    block_size: int,
) -> tuple[jax.Array, dict]:
    """Target verification through the BLOCK POOL: decode a (B, K) chunk
    at per-row offsets — row b's token j writes block
    tables[b, (positions[b]+j) // BS] offset (positions[b]+j) % BS, and
    query j attends logical slots <= positions[b]+j (chunk causality).
    The paged analog of llama._decode_chunk_batch_impl; returns the
    target's argmax predictions (B, K) + updated pool."""
    k_len = chunk.shape[1]
    posmat = positions[:, None] + jnp.arange(k_len)[None, :]  # (B, K)
    cos, sin, blks, offs = _chunk_coords(cfg, tables, posmat, block_size)
    x, new_pool = _paged_chunk_scan(
        params, cfg, chunk, pool, tables, kv_mask, cos, sin, blks, offs,
        posmat, block_size,
    )
    logits = _lm_head_logits(_norm(x, params["final_norm"], cfg), params)
    return jnp.argmax(logits, axis=-1), new_pool  # (B, K)


@partial(
    jax.jit, static_argnames=("cfg", "block_size"), donate_argnums=(3,)
)
def _paged_prefix_admit(
    params: dict,
    cfg: LlamaConfig,
    chunk: jax.Array,  # (1, Kp) tail tokens, right-padded to block multiple
    pool: dict,
    table: jax.Array,  # (1, MAXB) — the slot's table, prefix blocks filled
    pos0: jax.Array,  # scalar int32 — first tail position (m * BS)
    kv_mask: jax.Array,  # (1, MAXB * BS)
    last_idx: jax.Array,  # scalar int32 — last REAL token's chunk index
    block_size: int,
) -> tuple[jax.Array, dict]:
    """Tail prefill THROUGH the block tables (prefix-cached admission).

    Position-0-anchored layout: the prompt's token i lives at logical
    position i, so a prefix shared with a cached chain occupies the SAME
    blocks with the SAME rope rotations regardless of total prompt
    length. Only the tail past the matched chain is computed — a (1, Kp)
    chunk decoded at positions ``pos0..pos0+Kp-1`` that attends the
    shared prefix blocks through the table (the same chunk-causal body
    the speculative verify uses, so storage format and window semantics
    cannot diverge).

    Right-padding needs no mask: pad slots sit at positions ``> L-1`` —
    FUTURE positions, causally invisible to every real query, and decode
    overwrites each one before any query can reach it (scatter runs
    before attention in the chunk body). Returns the logits at
    ``last_idx`` (the real last token) + the updated pool."""
    posmat = pos0 + jnp.arange(chunk.shape[1])[None, :]  # (1, Kp)
    cos, sin, blks, offs = _chunk_coords(cfg, table, posmat, block_size)
    x, new_pool = _paged_chunk_scan(
        params, cfg, chunk, pool, table, kv_mask, cos, sin, blks, offs,
        posmat, block_size,
    )
    x_last = _norm(x[0, last_idx], params["final_norm"], cfg)
    return _lm_head_logits(x_last[None], params)[0], new_pool


class PagedBatcher(_BatcherBase):
    """Continuous batching over a shared block pool.

    >>> pb = PagedBatcher(params, cfg, slots=4, num_blocks=32, block_size=16)
    >>> ids = [pb.submit(p) for p in prompts]
    >>> results = pb.run()          # {rid: tokens}, EOS-truncated

    ``num_blocks`` sizes total KV memory independently of ``slots`` —
    the paged advantage. When it is too small for the moment's live
    tokens, the youngest active request is preempted and re-queued.
    """

    def __init__(
        self,
        params: dict,
        cfg: LlamaConfig,
        gen: Optional[GenerationConfig] = None,
        slots: int = 4,
        num_blocks: int = 64,
        block_size: int = 16,
        prompt_bucket: int = 64,
        key: Optional[jax.Array] = None,
        plan=None,  # parallel.mesh.MeshPlan → tp-sharded serving
        kv_bits: int = 0,  # 8 → int8 block pool (halved KV HBM)
        headroom_tokens: int = 0,  # extra per-slot span (speculative rounds)
        prompt_cache: bool = False,  # share identical prompts' blocks
        prefix_cache: bool = False,  # share common PREFIXES block-by-block
        admit_chunk: Optional[int] = None,  # prefix-admission piece width
        attn_kernel: Optional[bool] = None,  # pallas paged attention
        ragged: bool = False,  # fused mixed prefill/decode batches
        token_budget: Optional[int] = None,  # ragged rows per step
        hbm_fraction: Optional[float] = None,  # size pool from device HBM
        swap_bytes: int = 0,  # host-RAM swap tier for cold prefix chains
    ):
        self.gen = gen or GenerationConfig()
        # Decode attention THROUGH the tables (ops/paged_attention.py):
        # default on where the pallas TPU backend exists; CPU runs the
        # kernel interpreted (slow — tests opt in explicitly). Applies to
        # the bf16 K=1 step; int8/window/verify keep the gathered path.
        # A tp plan keeps the gathered path too: pallas_call does not
        # auto-partition under GSPMD, so running it over a kv-head-
        # sharded pool would silently gather the shards.
        if attn_kernel and plan is not None:
            raise ValueError(
                "attn_kernel=True does not compose with plan= (the paged "
                "kernel is single-device; a tp-sharded pool would be "
                "gathered) — drop one of the two"
            )
        if attn_kernel and kv_bits and not ragged:
            raise ValueError(
                "attn_kernel=True does not compose with kv_bits on the "
                "per-token decode kernel (it reads bf16 pools; an int8 "
                "pool would silently run the gathered path) — the RAGGED "
                "kernel dequantizes int8 pools: add ragged=True or drop "
                "one of the two"
            )
        if attn_kernel and cfg.sliding_window:
            raise ValueError(
                "attn_kernel=True does not support sliding-window "
                "configs (the window bound lives in the gathered path) "
                "— drop attn_kernel for this model"
            )
        self.attn_kernel = (
            jax.default_backend() in ("tpu", "axon") and plan is None
            and (not kv_bits or ragged) and not cfg.sliding_window
            if attn_kernel is None else attn_kernel
        )
        if prompt_bucket % block_size:
            raise ValueError(
                f"prompt_bucket {prompt_bucket} must be a multiple of "
                f"block_size {block_size}"
            )
        if admit_chunk is None:
            # ~256 tokens, rounded up to a block multiple so the default
            # is valid for ANY block_size (admit_chunk only matters on
            # the prefix_cache admission path).
            admit_chunk = max(block_size, -(-256 // block_size) * block_size)
        elif prefix_cache and (admit_chunk % block_size or admit_chunk <= 0):
            raise ValueError(
                f"admit_chunk {admit_chunk} must be a positive multiple "
                f"of block_size {block_size}"
            )
        if prompt_cache and prefix_cache:
            raise ValueError(
                "prompt_cache and prefix_cache are mutually exclusive: "
                "prefix_cache subsumes whole-prompt sharing (identical "
                "prompts share all their full blocks) under the "
                "position-0-anchored layout"
            )
        # Ragged scheduling (arXiv 2604.15464): admission stops being a
        # separate (1, Lb) prefill dispatch that stalls every in-flight
        # decode. _admit_free_slots only ALLOCATES (blocks + cursor);
        # _step assembles one flattened batch per engine step — every
        # decoding slot's token plus each admitting slot's next prompt
        # chunk, bounded by token_budget — and runs ONE fused dispatch
        # (_paged_ragged_step). Sharing tiers keep the legacy alternating
        # path. A tp plan composes with ragged: the gathered ragged body
        # is pure jnp, so GSPMD runs it identically on every shard with
        # the tp psums inserted inside the jitted step (the single-device
        # pallas kernel stays rejected by the attn_kernel guard above).
        if ragged:
            if prompt_cache or prefix_cache:
                raise ValueError(
                    "ragged=True does not compose with prompt_cache/"
                    "prefix_cache yet — the sharing tiers admit through "
                    "their own prefill programs; drop one of the two"
                )
            if token_budget is None:
                token_budget = 512
            if token_budget < slots:
                raise ValueError(
                    f"token_budget {token_budget} < slots {slots}: every "
                    "decoding slot needs one row per step"
                )
        self.ragged = bool(ragged)
        self.token_budget = int(token_budget) if ragged else 0
        self._ragged_admit: dict[int, dict] = {}
        # Batch-fill observability (models/server.py mirrors the gauge):
        # fraction of the last step's budget carrying real tokens, plus
        # lifetime token/step counters for bench.py's mixed mode.
        self.ragged_fill = 0.0
        self.ragged_steps = 0
        self.ragged_tokens = 0
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.block_size = block_size
        tp_degree = (int(plan.mesh.shape.get("tp", 1))
                     if plan is not None else 1)
        if hbm_fraction is not None:
            # Satellite of the paged pool: size from the accelerator's
            # live memory stats, with num_blocks as the CPU fallback.
            # Under a tp plan the pool is head-sharded, so sizing runs
            # off PER-SHARD headroom: each chip pays 1/tp of a block.
            num_blocks, self.pool_source = pool_blocks_from_hbm(
                cfg, block_size, kv_bits,
                fraction=hbm_fraction, fallback=num_blocks,
                with_source=True, tp=tp_degree,
                device=(plan.mesh.devices.flat[0]
                        if plan is not None else None),
            )
        else:
            self.pool_source = "config"
        self.num_blocks = num_blocks
        self.prompt_bucket = prompt_bucket
        # Capacity (in blocks) one request can ever hold; fixes MAXB so the
        # step compiles once.
        # +1: a preempted continuation re-admits at a block-aligned padded
        # length, which can overhang the nominal span by up to one block.
        # ``headroom_tokens``: a speculative round writes up to k_spec+1
        # slots past the pointer before rewinding — the tables must be
        # wide enough for those dead-by-rewind writes too.
        self.max_blocks = (
            prompt_bucket + self.gen.max_new_tokens + headroom_tokens
            + block_size - 1
        ) // block_size + 1
        self.key = jax.random.PRNGKey(0) if key is None else key
        self.pool = init_block_pool(cfg, num_blocks, block_size,
                                    kv_bits=kv_bits)
        if plan is not None:
            # tp-sharded paged serving: params per the model-wide plan,
            # the pool's kv-head axis over tp; GSPMD propagates through
            # the unchanged jitted step (psum for tp matmuls). Sequence
            # sharding (sp) is NOT supported here — a paged pool shards
            # by BLOCK ownership, not by contiguous sequence ranges, so
            # the split-KV sp merge does not apply; use ContinuousBatcher
            # for sp-sharded caches.
            if plan.mesh.shape.get("sp", 1) > 1:
                raise ValueError(
                    "PagedBatcher does not support sp-sharded meshes; "
                    "the block pool has no contiguous sequence axis to "
                    "shard (use ContinuousBatcher for sp)"
                )
            # Pool first: shard_kv_cache owns the tp-divides-kv-heads
            # validation, and must fire before params are placed.
            self.pool = plan.shard_kv_cache(self.pool)
            self.params = plan.shard_params(params)
        self.plan = plan
        # Mesh observability (/stats `mesh` block + bench provenance):
        # the non-trivial axes this engine's replica spans. None for the
        # classic one-chip engine, so stats stay byte-identical there.
        self.mesh_axes = plan.axes if plan is not None else None
        # Committed per-leaf pool shardings: host-side pool WRITES
        # (swap promotion, KV import) rebuild a leaf from numpy and must
        # re-pin it, or one import would silently gather the pool onto
        # a single device. device_put is a no-op when already placed.
        self._pool_shardings = (
            {name: leaf.sharding for name, leaf in self.pool.items()}
            if plan is not None else None
        )
        self.kv_mask = jnp.zeros((slots, self.max_blocks * block_size), bool)
        self.tables = np.zeros((slots, self.max_blocks), np.int32)
        self.positions = np.zeros((slots,), np.int32)
        self.tokens = np.full((slots, 1), self.gen.pad_id, np.int32)
        # Block 0 is the NULL block, never allocated: inactive slots keep
        # tables=0/positions=0, so their (ignored) per-step writes land in
        # block 0 instead of corrupting a block someone else reallocated —
        # the shared pool's analog of the dense batcher's harmless
        # stale-slot writes.
        self._free = list(range(1, num_blocks))
        # Prompt cache (opt-in): identical PADDED prompts produce
        # byte-identical block contents at identical logical positions
        # (absolute-position rope over the same left-padded layout), so
        # their prompt blocks — and last-position logits — are shared
        # instead of re-prefilled. Decode writes only ever land at
        # positions >= bucket (a fresh owned block: bucket is
        # block-aligned), so shared blocks are never mutated and no
        # copy-on-write is needed. Entries are evicted (their blocks
        # returned) before the allocator resorts to preemption.
        self._prompt_cache_enabled = prompt_cache
        self._prompt_cache: dict = {}  # padded-bytes -> {blocks, logits}
        self._shared_refs: dict = {}  # block -> cache ref + active users
        # Prefix cache (opt-in): position-0-ANCHORED admission (prompts
        # live unpadded at positions 0..L-1, decode continues at L) makes
        # a common prefix occupy byte-identical blocks at identical
        # logical positions across prompts of ANY length — so full prompt
        # blocks are content-addressed by a vLLM-style chain hash
        # h_j = H(h_{j-1}, tokens_j) and shared block-by-block. The block
        # holding the LAST prompt token is never registered (it is the
        # one decode mutates, and a full-chain hit would otherwise leave
        # no tail to recompute logits from). Entries form chains; only
        # chain LEAVES are evictable (a broken chain's tail could never
        # be matched again).
        self._prefix_cache_enabled = prefix_cache
        self._prefix_entries: dict = {}  # chain hash -> block/parent/children
        # Prefix-cache observability (host-side, O(1) per admission):
        # hits/misses count REGISTRABLE prompt blocks at successful
        # admission (a hit is a block whose prefill was skipped), so
        # hits/(hits+misses) is exactly the fraction of prefill compute
        # the cache saved. Mirrored into tpu_serving_prefix_cache_* by
        # the InferenceServer and scraped by the fleet gateway.
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_evictions = 0
        self.admit_chunk = admit_chunk
        # Host-RAM block swap (opt-in via swap_bytes > 0): instead of
        # LOSING a demoted prefix leaf's KV, its block's leaves are
        # copied to host numpy keyed by the SAME chain hash, bounded by
        # a byte budget with LRU demotion inside the tier. A returning
        # request whose chain walk misses the device cache but hits the
        # swap tier promotes the block back (device write + re-register)
        # instead of re-prefilling — the admission path counts that as a
        # prefix-cache hit, because the prefill compute is skipped
        # either way. Entries store the parent chain key so promotion
        # can refuse a stale/mismatched chain.
        if swap_bytes < 0:
            raise ValueError(f"swap_bytes must be >= 0, got {swap_bytes}")
        self.swap_bytes_limit = int(swap_bytes)
        self._swap: "OrderedDict[bytes, dict]" = OrderedDict()
        self.swap_bytes_used = 0
        self.kv_swap_out = 0
        self.kv_swap_in = 0
        self.kv_swap_restored_tokens = 0
        # Paged-KV handoff (disaggregated serving): lifetime counters
        # mirrored into /stats by the serving frontend, plus the deferred
        # first-token queue import_blocks feeds (delivered at the next
        # drive quantum so the frontend can register per-request state
        # between import returning and on_token firing).
        self.kv_exports = 0
        self.kv_imports = 0
        self.kv_import_blocks_reused = 0
        self.kv_import_blocks_written = 0
        self._kv_pending_first: list[tuple] = []
        # Fleet KV tier (peer prefix fetch): cache-chain export/import
        # counters, kept separate from the live-request handoff above so
        # the two transfer paths stay individually observable.
        self.kv_chain_exports = 0
        self.kv_chain_imports = 0
        self.kv_chain_blocks_sent = 0
        self.kv_chain_blocks_written = 0
        self._init_base(self.gen, slots, prompt_bucket)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    # -- allocator ---------------------------------------------------------

    def _take_blocks(self, n: int, preempt: bool = True) -> Optional[list[int]]:
        """n blocks off the free list. With ``preempt`` (the DECODE path:
        a running request needs its next block), the youngest active
        request is evicted until the pool can supply n. The ADMISSION path
        passes preempt=False and waits for retirements instead: admitting
        a queued request by evicting a running one degenerates into
        preempt → full re-prefill → one decode step → preempt again,
        O(max_new_tokens) prefills per request, exactly when the pool is
        under pressure — vLLM's policy split. None when the pool cannot
        supply n under the given policy."""
        while len(self._free) < n:
            # Idle cached prompts are the cheapest capacity: evicting one
            # costs a future re-prefill, preempting a RUNNING request
            # costs a re-prefill NOW plus its lost decode progress.
            if self._evict_cached():
                continue
            if not preempt:
                return None
            victim = self._youngest_active()
            if victim is None:
                return None
            self._preempt(victim)
        taken, self._free = self._free[:n], self._free[n:]
        return taken

    def _evict_cached(self) -> bool:
        """Free one cache-held block set, whichever cache is on."""
        if self._prompt_cache_enabled:
            return self._evict_cached_prompt()
        if self._prefix_cache_enabled:
            return self._evict_prefix_leaf()
        return False

    def _evict_prefix_leaf(self) -> bool:
        """Drop one prefix-chain LEAF no active request references
        (refcount 1 — the cache's own hold), returning its block.
        Leaf-only: evicting a middle link would orphan the chain's tail
        (matching walks parent→child). Insertion order ≈ LRU (hits
        re-append their matched chain). With a swap budget the leaf is
        DEMOTED to host RAM first, so its prefill survives eviction."""
        for key, ent in self._prefix_entries.items():
            if (ent["children"] == 0
                    and self._shared_refs.get(ent["block"], 0) == 1):
                if self.swap_bytes_limit:
                    self._swap_out(key, ent)
                del self._prefix_entries[key]
                del self._shared_refs[ent["block"]]
                self._free.append(ent["block"])
                if ent["parent"] is not None:
                    self._prefix_entries[ent["parent"]]["children"] -= 1
                self.prefix_evictions += 1
                return True
        return False

    # -- host-RAM block swap ----------------------------------------------

    def _pin_pool_leaf(self, name: str, leaf):
        """Re-commit one pool leaf to its plan sharding after a
        host-sourced write (.at[].set of numpy data). The update op's
        output sharding follows GSPMD propagation, which may differ from
        the pool's committed head-sharded layout; an unpinned leaf would
        gather the whole pool onto one chip at the next step. No-op
        (and identity) without a plan or when already placed."""
        if self._pool_shardings is None:
            return leaf
        return jax.device_put(leaf, self._pool_shardings[name])

    def _swap_out(self, key: bytes, ent: dict) -> None:
        """Demote one prefix-chain leaf's block to the host-RAM tier:
        copy every pool leaf's rows for the block to numpy, keyed by the
        SAME chain hash the device cache used, bounded by
        ``swap_bytes_limit`` with LRU eviction inside the tier. The
        parent key rides along so promotion can refuse a chain that no
        longer matches."""
        leaves = {
            name: np.asarray(leaf[:, ent["block"]])
            for name, leaf in self.pool.items()
        }
        nbytes = sum(a.nbytes for a in leaves.values())
        if nbytes > self.swap_bytes_limit:
            return  # a single block over budget: plain eviction
        old = self._swap.pop(key, None)
        if old is not None:
            self.swap_bytes_used -= old["bytes"]
        self._swap[key] = {
            "leaves": leaves, "parent": ent["parent"], "bytes": nbytes,
        }
        self.swap_bytes_used += nbytes
        self.kv_swap_out += 1
        while self.swap_bytes_used > self.swap_bytes_limit:
            _, victim = self._swap.popitem(last=False)  # LRU
            self.swap_bytes_used -= victim["bytes"]

    def _swap_promote(self, key: bytes, parent: Optional[bytes]):
        """Promote a swap-resident block back into the device pool and
        re-register it on the prefix chain (cache hold, refcount 1).
        Returns the fresh ``_prefix_entries`` record, or None when the
        key is not swap-resident, its recorded parent does not match the
        caller's chain walk, or the pool cannot spare a block under the
        admission watermark (caller treats all three as a miss)."""
        entry = self._swap.get(key)
        if entry is None or entry["parent"] != parent:
            return None
        blocks = self._reserve_take(1)
        if blocks is None:
            return None
        (blk,) = blocks
        for name, host in entry["leaves"].items():
            self.pool[name] = self._pin_pool_leaf(
                name, self.pool[name].at[:, blk].set(jnp.asarray(host))
            )
        del self._swap[key]
        self.swap_bytes_used -= entry["bytes"]
        ent = {"block": blk, "parent": parent, "children": 0}
        self._prefix_entries[key] = ent
        if parent is not None:
            self._prefix_entries[parent]["children"] += 1
        self._shared_refs[blk] = 1
        self.kv_swap_in += 1
        self.kv_swap_restored_tokens += self.block_size
        return ent

    def swap_contains(self, key: bytes) -> bool:
        """True when a chain key's block is resident in the host-RAM
        swap tier (the /kv/probe advisory: a hit here is restorable
        without re-prefill, it just needs a promotion on import)."""
        return key in self._swap

    @property
    def swap_blocks(self) -> int:
        """Blocks currently parked in the host-RAM swap tier."""
        return len(self._swap)

    @property
    def prefix_cached_blocks(self) -> int:
        """Blocks currently registered on warm prefix chains."""
        return len(self._prefix_entries)

    @staticmethod
    def _chain_key(parent: Optional[bytes], tokens,
                   adapter: Optional[int] = None) -> bytes:
        """Content address of one full block GIVEN its prefix chain.
        ``adapter`` salts the ROOT: a LoRA adapter changes every K/V the
        same tokens produce, so chains must never cross-hit between
        adapters — the whole chain forks at its first block. None keeps
        the legacy base-model root byte-for-byte (gateway.chain_key
        mirrors this exactly; parity is pinned by tests)."""
        if parent is None:
            parent = (b"root" if adapter is None
                      else b"root|adapter:%d" % int(adapter))
        h = hashlib.sha1(parent)
        h.update(np.asarray(tokens, np.int32).tobytes())
        return h.digest()

    def _evict_cached_prompt(self) -> bool:
        """Drop one cached prompt no active request references (every
        block at refcount 1 — the cache's own hold), returning its blocks
        to the pool. Insertion order ≈ LRU-enough for a serving cache."""
        for key, entry in self._prompt_cache.items():
            if all(self._shared_refs.get(b, 0) == 1 for b in entry["blocks"]):
                del self._prompt_cache[key]
                for b in entry["blocks"]:
                    del self._shared_refs[b]
                    self._free.append(b)
                return True
        return False

    def _reserve_take(self, need: int) -> Optional[list[int]]:
        """Watermark-guarded admission allocation (vLLM's admission
        reserve, shared by both admission layouts): keep one free block
        per RUNNING request on top of the admit cost — otherwise
        admission grabs exactly the blocks running slots need at their
        next boundary and the decode path immediately evicts the fresh
        admit (one-step-removed thrash). Cached prompts yield first;
        never preempts. None = stall (or pool-too-small if idle —
        caller distinguishes)."""
        reserve = sum(1 for r in self._by_slot if r is not None)
        while len(self._free) < need + reserve and self._evict_cached():
            pass
        if len(self._free) < need + reserve:
            return None
        return self._take_blocks(need, preempt=False)

    def _finish_admit(self, slot: int, req: _Request, logits,
                      draft_tokens, draft_mask) -> None:
        """Shared admission tail: sample the first token off the
        admission logits, install the request, prime any lockstep draft
        cache (_post_admit), and feed the token through retirement."""
        self.key, sub = jax.random.split(self.key)
        temp = (self.gen.temperature if req.temperature is None
                else req.temperature)
        bias_row = self._install_bias(slot, req)
        if bias_row is not None:
            logits = logits + bias_row
        first = int(
            sample_logits(
                logits[None], sub, temp, self.gen.top_k,
                self.gen.top_p,
            )[0]
        )
        first_lp = float(
            jax.nn.log_softmax(logits.astype(jnp.float32))[first]
        )
        req.budget = self._initial_budget(req) - len(req.tokens)
        self.temps[slot] = temp
        self._by_slot[slot] = req
        self._post_admit(slot, draft_tokens, draft_mask)
        self._note_token(slot, first, first_lp)

    def _youngest_active(self) -> Optional[int]:
        slots = [
            (req.rid, slot)
            for slot, req in enumerate(self._by_slot)
            if req is not None
        ]
        # Mid-prefill ragged admissions hold their full bucket of blocks
        # — they must be preemptable, or a decode-path allocation could
        # dead-end while admissions sit on the whole pool.
        slots += [
            (a["req"].rid, slot) for slot, a in self._ragged_admit.items()
        ]
        return max(slots)[1] if slots else None

    def _preempt(self, slot: int) -> None:
        """Free the slot and re-queue prompt+generated as a continuation
        (greedy continuations are identical after re-prefill; it re-admits
        at a block-aligned padded length, so it may exceed prompt_bucket)."""
        if slot in self._ragged_admit:
            # A mid-prefill ragged admission: nothing was sampled yet, so
            # the continuation is simply the original request re-queued
            # (its partial KV is discarded with the blocks).
            req = self._ragged_admit.pop(slot)["req"]
            self._clear_slot_storage(slot, req)
        else:
            req = self._by_slot[slot]
            self._release_slot(slot)
        # Front of the queue: a preempted request outranks new arrivals.
        cont = _Request(req.rid, req.prompt, req.tokens, max_new=req.max_new,
                        temperature=req.temperature, stop=req.stop,
                        logit_bias=req.logit_bias,
                        logprobs=req.logprobs, deadline=req.deadline,
                        adapter_id=req.adapter_id)
        self._queue.insert(0, cont)

    def _clear_slot_storage(self, slot: int, req: _Request) -> None:
        """Return a request's blocks and fence the slot's device state —
        shared by normal release and mid-prefill (ragged) teardown."""
        for blk in req.blocks:
            if blk in req.shared:
                self._shared_refs[blk] -= 1
                if self._shared_refs[blk] == 0:
                    # Cache entry already evicted; last user frees it.
                    del self._shared_refs[blk]
                    self._free.append(blk)
            else:
                self._free.append(blk)
        req.blocks = []
        req.shared = frozenset()
        self.kv_mask = self.kv_mask.at[slot].set(False)
        self.tables[slot] = 0  # dead writes go to the null block
        self.positions[slot] = 0

    def _release_slot(self, slot: int) -> None:
        req = self._by_slot[slot]
        self._clear_slot_storage(slot, req)
        self._by_slot[slot] = None

    # -- paged-KV handoff (disaggregated prefill/decode tiers) -------------

    def export_blocks(self, rid: int, skip_keys=()) -> dict:
        """Serialize a live request's prompt-KV blocks for a cross-replica
        handoff. Called at FIRST-token time (on_token for a
        max_new_tokens=1 prefill-tier request): positions[slot] still
        equals the prompt KV length and the sampled token's KV is
        unwritten, so the payload is exactly the prefill state a decode
        replica needs plus the pending first token to deliver.

        Full blocks are chain-keyed exactly like prefix admission
        (``_chain_key`` / gateway.chain_key); a key listed in
        ``skip_keys`` (hex) ships as a data-less stub — the suffix-only
        transfer for a decode replica that already holds the prefix
        chain. The tail block (last prompt token's block, never
        registered) always ships data.

        Requires prefix_cache=True: the position-0-anchored layout IS
        the transfer wire format."""
        if not self._prefix_cache_enabled:
            raise RuntimeError(
                "export_blocks requires prefix_cache=True (the anchored "
                "admission layout is the transfer wire format)"
            )
        slot = None
        for i, r in enumerate(self._by_slot):
            if r is not None and r.rid == rid:
                slot = i
                break
        if slot is None:
            raise KeyError(
                f"rid {rid} holds no slot — export at first-token time, "
                "while the request is still installed"
            )
        req = self._by_slot[slot]
        if not req.tokens:
            raise RuntimeError(
                "export_blocks before the first sampled token: the "
                "pending token is part of the payload"
            )
        bs = self.block_size
        lng = int(self.positions[slot])  # prompt KV length; pending unwritten
        nblocks = -(-lng // bs)
        kv_tokens = (req.prompt + req.tokens)[:lng]
        registrable = (lng - 1) // bs  # == nblocks - 1: exactly one tail
        skip = {k if isinstance(k, str) else bytes(k).hex()
                for k in skip_keys}
        keys: list[str] = []
        parent: Optional[bytes] = None
        for j in range(registrable):
            parent = self._chain_key(parent, kv_tokens[j * bs:(j + 1) * bs],
                                     adapter=req.adapter_id)
            keys.append(parent.hex())
        send = [j for j in range(nblocks)
                if j >= registrable or keys[j] not in skip]
        blk_ids = np.asarray([req.blocks[j] for j in send], np.int32)
        # One device gather per leaf for the blocks that actually ship.
        leaf_rows = {
            name: np.asarray(self.pool[name][:, jnp.asarray(blk_ids)])
            for name in self.pool
        }
        at = {j: i for i, j in enumerate(send)}
        blocks = []
        for j in range(nblocks):
            ent: dict = {"key": keys[j] if j < registrable else None}
            i = at.get(j)
            if i is not None:
                ent["data"] = {
                    name: base64.b64encode(
                        np.ascontiguousarray(rows[:, i]).tobytes()
                    ).decode("ascii")
                    for name, rows in leaf_rows.items()
                }
            blocks.append(ent)
        self.kv_exports += 1
        return {
            "version": 1,
            "block_size": bs,
            "kv_bits": 8 if "k_scale" in self.pool else 0,
            "adapter": req.adapter_id,
            "tokens": [int(t) for t in kv_tokens],
            "pending_token": int(req.tokens[-1]),
            "pending_logprob": (
                float(req.logprobs[-1]) if req.logprobs else None
            ),
            "leaves": {
                name: {
                    "dtype": str(self.pool[name].dtype),
                    "shape": list(self.pool[name].shape[:1]
                                  + self.pool[name].shape[2:]),
                }
                for name in self.pool
            },
            "blocks": blocks,
        }

    def import_blocks(self, payload: dict,
                      max_new_tokens: Optional[int] = None,
                      temperature: Optional[float] = None,
                      stop=None, logit_bias: Optional[dict] = None,
                      deadline_s: Optional[float] = None) -> Optional[int]:
        """Admit a request DIRECTLY into a free slot from an exported
        KV payload, skipping re-prefill. Chain keys are recomputed
        locally and checked against the payload (a mismatch means the
        two replicas' chain hashing diverged — refused loudly, which is
        what pins cross-host chain-key parity at runtime). With
        prefix_cache on, the longest locally-cached chain is reused and
        only the remainder is written; stub blocks past the local chain
        raise KeyError (suffix-only transfer raced an eviction — the
        caller retries with full data or falls back to fused routing).

        Returns the new rid, or None when no slot/blocks are free under
        the admission watermark (caller sheds or retries elsewhere).
        The pending first token is delivered through the normal
        retirement path at the next drive quantum."""
        if not isinstance(payload, dict) or payload.get("version") != 1:
            raise ValueError("kv payload: missing or unsupported version")
        if int(payload.get("block_size", -1)) != self.block_size:
            raise ValueError(
                f"kv payload block_size {payload.get('block_size')!r} != "
                f"engine block_size {self.block_size}"
            )
        kv_bits = 8 if "k_scale" in self.pool else 0
        if int(payload.get("kv_bits", -1)) != kv_bits:
            raise ValueError(
                f"kv payload kv_bits {payload.get('kv_bits')!r} does not "
                f"match this pool's storage format (kv_bits={kv_bits})"
            )
        leaves = payload.get("leaves") or {}
        if set(leaves) != set(self.pool):
            raise ValueError("kv payload leaves do not match this pool")
        shapes: dict[str, tuple] = {}
        for name, spec in leaves.items():
            want = self.pool[name].shape[:1] + self.pool[name].shape[2:]
            if (tuple(spec.get("shape") or ()) != want
                    or spec.get("dtype") != str(self.pool[name].dtype)):
                raise ValueError(
                    f"kv payload leaf {name!r}: shape/dtype "
                    f"{spec.get('shape')}/{spec.get('dtype')} != local "
                    f"{list(want)}/{self.pool[name].dtype}"
                )
            shapes[name] = want
        tokens = [int(t) for t in payload.get("tokens") or []]
        bs = self.block_size
        lng = len(tokens)
        nblocks = -(-lng // bs)
        entries = payload.get("blocks") or []
        if lng == 0 or len(entries) != nblocks:
            raise ValueError(
                f"kv payload carries {len(entries)} blocks for a "
                f"{lng}-token prompt (want {nblocks})"
            )
        adapter = payload.get("adapter")
        if adapter is not None and (
                not isinstance(adapter, int) or isinstance(adapter, bool)):
            raise ValueError(
                f"kv payload adapter must be an int or null, "
                f"got {adapter!r}"
            )
        # Validation (and rid mint) via the shared request builder.
        req = self._build_request(
            tokens, max_new_tokens=max_new_tokens, temperature=temperature,
            stop=stop, logit_bias=logit_bias, deadline_s=deadline_s,
        )
        req.adapter_id = adapter
        slot = None
        for i, r in enumerate(self._by_slot):
            if r is None and i not in self._ragged_admit:
                slot = i
                break
        if slot is None:
            return None
        registrable = (lng - 1) // bs
        keys: list[bytes] = []
        parent: Optional[bytes] = None
        for j in range(registrable):
            parent = self._chain_key(parent, tokens[j * bs:(j + 1) * bs],
                                     adapter=adapter)
            sent = entries[j].get("key")
            if sent is not None and sent != parent.hex():
                raise ValueError(
                    f"kv payload chain-key mismatch at block {j}: the "
                    "exporting replica's chain hashing diverged from ours"
                )
            keys.append(parent)
        # Longest local chain match (empty when prefix_cache is off —
        # import still works, it just writes every block). A device miss
        # falls through to the host-RAM swap tier: a swap-resident key
        # is promoted back into the pool, so a /kv/probe advisory hit on
        # swapped-out blocks is honored instead of raising on the stub.
        # Matched blocks are pinned AS the walk advances — promotion
        # allocates under the watermark and may evict unpinned leaves.
        m = 0
        shared_blocks: list[int] = []
        if self._prefix_cache_enabled:
            walk_parent: Optional[bytes] = None
            for j in range(registrable):
                ent = self._prefix_entries.get(keys[j])
                if ent is None and self._swap:
                    ent = self._swap_promote(keys[j], walk_parent)
                if ent is None:
                    break
                shared_blocks.append(ent["block"])
                self._shared_refs[ent["block"]] += 1
                m += 1
                walk_parent = keys[j]
        bad = next((j for j in range(nblocks)
                    if j >= m and "data" not in entries[j]), None)
        if bad is not None:
            for blk in shared_blocks:  # un-pin; promoted blocks stay warm
                self._shared_refs[blk] -= 1
            raise KeyError(
                f"kv payload block {bad} is a stub but its chain is "
                "not cached here (suffix-only transfer raced an "
                "eviction) — resend with full block data"
            )
        for k in keys[:m]:  # hit refreshes recency (LRU-ish order)
            self._prefix_entries[k] = self._prefix_entries.pop(k)
        need = nblocks - m
        blocks = self._reserve_take(need)
        if blocks is None:
            for blk in shared_blocks:
                self._shared_refs[blk] -= 1
            return None
        all_blocks = shared_blocks + blocks
        # Batched per-leaf pool write of the shipped blocks.
        idxs = jnp.asarray(all_blocks[m:], jnp.int32)
        for name in self.pool:
            dtype = _np_leaf_dtype(leaves[name]["dtype"])
            stacked = np.stack(
                [
                    np.frombuffer(
                        base64.b64decode(entries[j]["data"][name]),
                        dtype=dtype,
                    ).reshape(shapes[name])
                    for j in range(m, nblocks)
                ],
                axis=1,
            )
            self.pool[name] = self._pin_pool_leaf(
                name, self.pool[name].at[:, idxs].set(jnp.asarray(stacked))
            )
        # Register the imported FULL blocks on the chain (same refcount
        # convention as prefix admission: cache ref + this request).
        if self._prefix_cache_enabled:
            chain_parent = keys[m - 1] if m else None
            for j in range(m, registrable):
                self._prefix_entries[keys[j]] = {
                    "block": all_blocks[j], "parent": chain_parent,
                    "children": 0,
                }
                if chain_parent is not None:
                    self._prefix_entries[chain_parent]["children"] += 1
                self._shared_refs[all_blocks[j]] = 2
                chain_parent = keys[j]
            req.shared = frozenset(all_blocks[:registrable])
            self.prefix_hits += m
            self.prefix_misses += registrable - m
        # Install as a DECODING slot — table/positions/mask exactly as
        # anchored admission leaves them, decode continues at lng.
        req.blocks = all_blocks
        self.tables[slot] = 0
        self.tables[slot, :nblocks] = all_blocks
        self.positions[slot] = lng
        self.kv_mask = self.kv_mask.at[slot].set(True)
        temp = (self.gen.temperature if req.temperature is None
                else req.temperature)
        self.temps[slot] = temp
        self._install_bias(slot, req)
        req.budget = self._initial_budget(req)
        self._by_slot[slot] = req
        self.kv_imports += 1
        self.kv_import_blocks_reused += m
        self.kv_import_blocks_written += need
        self._kv_pending_first.append((
            slot, req.rid, int(payload["pending_token"]),
            payload.get("pending_logprob"),
        ))
        return req.rid

    # -- fleet KV tier (peer prefix fetch) ---------------------------------

    def chain_block_bytes(self) -> int:
        """Wire-format bytes ONE full block costs in an exported chain
        payload: base64 of every pool leaf's rows for a single block
        plus a small JSON envelope. The /kv/probe byte advisory — a
        fetcher multiplies by the matched chain length to enforce its
        max-bytes cap BEFORE pulling a transfer."""
        raw = sum(
            int(leaf.nbytes) // int(leaf.shape[1])
            for leaf in self.pool.values()
        )
        return 4 * ((raw + 2) // 3) + 96

    def export_chain(self, keys) -> Optional[dict]:
        """Serialize the longest held prefix of a chain-key walk straight
        from the PREFIX CACHE — no live request involved. Swap-resident
        links are promoted into the device pool first (the host-RAM tier
        is part of the advertised chain, and promotion leaves this
        replica warm too). Returns the version-1 wire format minus the
        live-request fields — no tokens or pending token: the importing
        side validates the keys against its own prompt — or None when
        not even the first requested key is held."""
        if not self._prefix_cache_enabled:
            raise RuntimeError(
                "export_chain requires prefix_cache=True (the chain "
                "registry is the export source)"
            )
        raw = [k if isinstance(k, bytes) else bytes.fromhex(k)
               for k in keys]
        ents: list[tuple] = []
        parent: Optional[bytes] = None
        for key in raw:
            ent = self._prefix_entries.get(key)
            if ent is None and self._swap:
                ent = self._swap_promote(key, parent)
            if ent is None:
                break
            ents.append((key, ent))
            parent = key
        if not ents:
            return None
        blk_ids = np.asarray([e["block"] for _, e in ents], np.int32)
        leaf_rows = {
            name: np.asarray(self.pool[name][:, jnp.asarray(blk_ids)])
            for name in self.pool
        }
        blocks = []
        for i, (key, _) in enumerate(ents):
            blocks.append({
                "key": key.hex(),
                "data": {
                    name: base64.b64encode(
                        np.ascontiguousarray(rows[:, i]).tobytes()
                    ).decode("ascii")
                    for name, rows in leaf_rows.items()
                },
            })
        for key, _ in ents:  # an export is a hit: refresh recency
            self._prefix_entries[key] = self._prefix_entries.pop(key)
        self.kv_chain_exports += 1
        self.kv_chain_blocks_sent += len(blocks)
        return {
            "version": 1,
            "block_size": self.block_size,
            "kv_bits": 8 if "k_scale" in self.pool else 0,
            "adapter": None,
            "leaves": {
                name: {
                    "dtype": str(self.pool[name].dtype),
                    "shape": list(self.pool[name].shape[:1]
                                  + self.pool[name].shape[2:]),
                }
                for name in self.pool
            },
            "blocks": blocks,
        }

    def import_chain(self, payload: dict, tokens) -> int:
        """Register an exported cache chain into THIS engine's prefix
        cache without installing a request — the peer-fetch import.
        Chain keys are recomputed from the fetching request's own prompt
        tokens (base-model salt) and checked positionally against the
        payload; version skew, geometry skew, or a key mismatch raise
        ValueError so the fetcher quarantines the payload and falls
        through to re-prefill. Registration is best-effort under the
        admission watermark: the walk stops at the first block the pool
        cannot spare. Returns how many leading chain keys are now
        resident — a subsequent submit() of the same prompt counts them
        as prefix hits."""
        if not self._prefix_cache_enabled:
            raise ValueError(
                "import_chain requires prefix_cache=True (there is no "
                "chain registry to import into)"
            )
        if not isinstance(payload, dict) or payload.get("version") != 1:
            raise ValueError(
                "kv chain payload: missing or unsupported version"
            )
        if int(payload.get("block_size", -1)) != self.block_size:
            raise ValueError(
                f"kv chain payload block_size "
                f"{payload.get('block_size')!r} != engine block_size "
                f"{self.block_size}"
            )
        kv_bits = 8 if "k_scale" in self.pool else 0
        if int(payload.get("kv_bits", -1)) != kv_bits:
            raise ValueError(
                f"kv chain payload kv_bits {payload.get('kv_bits')!r} "
                f"does not match this pool's storage format "
                f"(kv_bits={kv_bits})"
            )
        leaves = payload.get("leaves") or {}
        if set(leaves) != set(self.pool):
            raise ValueError(
                "kv chain payload leaves do not match this pool"
            )
        shapes: dict[str, tuple] = {}
        for name, spec in leaves.items():
            want = self.pool[name].shape[:1] + self.pool[name].shape[2:]
            if (tuple(spec.get("shape") or ()) != want
                    or spec.get("dtype") != str(self.pool[name].dtype)):
                raise ValueError(
                    f"kv chain payload leaf {name!r}: shape/dtype "
                    f"{spec.get('shape')}/{spec.get('dtype')} != local "
                    f"{list(want)}/{self.pool[name].dtype}"
                )
            shapes[name] = want
        toks = [int(t) for t in tokens]
        bs = self.block_size
        registrable = max(0, (len(toks) - 1) // bs)
        entries = payload.get("blocks") or []
        if not entries or len(entries) > registrable:
            raise ValueError(
                f"kv chain payload carries {len(entries)} blocks for a "
                f"prompt with {registrable} registrable blocks"
            )
        keys: list[bytes] = []
        parent: Optional[bytes] = None
        for j in range(len(entries)):
            parent = self._chain_key(parent, toks[j * bs:(j + 1) * bs])
            sent = entries[j].get("key")
            if sent != parent.hex():
                raise ValueError(
                    f"kv chain payload chain-key mismatch at block {j}: "
                    "the exporting peer's chain diverged from this "
                    "prompt's"
                )
            if "data" not in entries[j]:
                raise ValueError(
                    f"kv chain payload block {j} is a stub — chain "
                    "exports always carry full block data"
                )
            keys.append(parent)
        # Pin each link as the walk advances: _reserve_take may evict
        # unreferenced leaves to make room, and the block registered one
        # iteration ago is exactly such a leaf until its child links in.
        resident = 0
        written = 0
        pinned: list[int] = []
        chain_parent: Optional[bytes] = None
        try:
            for j, key in enumerate(keys):
                ent = self._prefix_entries.get(key)
                if ent is None and self._swap:
                    ent = self._swap_promote(key, chain_parent)
                if ent is None:
                    take = self._reserve_take(1)
                    if take is None:
                        break  # pool pressure: keep what landed
                    (blk,) = take
                    for name in self.pool:
                        dtype = _np_leaf_dtype(leaves[name]["dtype"])
                        row = np.frombuffer(
                            base64.b64decode(entries[j]["data"][name]),
                            dtype=dtype,
                        ).reshape(shapes[name])
                        self.pool[name] = self._pin_pool_leaf(
                            name,
                            self.pool[name].at[:, blk].set(jnp.asarray(row)),
                        )
                    self._prefix_entries[key] = {
                        "block": blk, "parent": chain_parent,
                        "children": 0,
                    }
                    if chain_parent is not None:
                        self._prefix_entries[chain_parent]["children"] += 1
                    self._shared_refs[blk] = 1
                    written += 1
                    ent = self._prefix_entries[key]
                else:
                    # Reuse refreshes recency, like any chain hit.
                    self._prefix_entries[key] = self._prefix_entries.pop(
                        key
                    )
                self._shared_refs[ent["block"]] += 1
                pinned.append(ent["block"])
                resident += 1
                chain_parent = key
        finally:
            for blk in pinned:
                self._shared_refs[blk] -= 1
        self.kv_chain_imports += 1
        self.kv_chain_blocks_written += written
        return resident

    def _deliver_imported(self) -> None:
        """Feed imported requests' pending first tokens through the
        normal retirement path (EOS/stop/budget/cancel semantics apply
        verbatim). Runs at the top of every admission pass — i.e. the
        first drive quantum after import_blocks() returned, once the
        serving frontend has registered its per-request state."""
        while self._kv_pending_first:
            slot, rid, token, lp = self._kv_pending_first.pop(0)
            req = self._by_slot[slot]
            if req is None or req.rid != rid:
                continue  # preempted/cancelled before delivery
            self._note_token(slot, token, lp)

    # -- internals ---------------------------------------------------------

    def _admit_free_slots(self) -> None:
        if self._kv_pending_first:
            self._deliver_imported()
        if self.ragged:
            self._admit_free_slots_ragged()
            return
        if self._prefix_cache_enabled:
            self._admit_free_slots_prefix()
            return
        # kftpu-lint: disable=kftpu-host-sync-in-hot-path — bounded per-slot admission host->device upload (at most `slots` iterations), not a per-token readback
        for slot in range(self.slots):
            if self._by_slot[slot] is not None:
                continue
            # Admission never preempts (decode-path eviction may still
            # push a continuation to the queue FRONT between steps, so the
            # head is re-read per attempt).
            while self._queue:
                head = self._queue[0]
                effective = head.prompt + head.tokens
                # Block-aligned admit bucket: prompt_bucket normally;
                # larger for a preempted continuation that outgrew it
                # (bounded variants → bounded compiles of the admit
                # program).
                bucket = max(
                    self.prompt_bucket,
                    -(-len(effective) // self.block_size) * self.block_size,
                )
                # Prompt-cache hit (pure prompts only — a preempted
                # continuation's effective tokens are request-unique):
                # reuse the shared blocks + cached last-position logits,
                # no allocation, no prefill. The key carries the validity
                # MASK as well as the tokens: a prompt whose leading
                # token equals pad_id pads to the same bytes as the
                # shorter prompt without it, but their masks (and so
                # their attention, KV, and logits) differ. Padding and
                # key are computed here only when the cache is on — the
                # default path pays nothing before allocation succeeds.
                padded = mask = cache_key = cache_hit = None
                if self._prompt_cache_enabled and not head.tokens:
                    padded, mask = left_pad(
                        [effective], self.gen.pad_id, bucket
                    )
                    cache_key = (padded.tobytes(), mask.tobytes())
                    cache_hit = self._prompt_cache.get(cache_key)
                if cache_hit is not None:
                    # Move-to-end: eviction scans insertion order, so a
                    # hit must refresh recency or the hottest prompt is
                    # evicted first (FIFO, not LRU).
                    self._prompt_cache[cache_key] = self._prompt_cache.pop(
                        cache_key
                    )
                    blocks = list(cache_hit["blocks"])
                    break
                need = bucket // self.block_size
                blocks = self._reserve_take(need)
                if blocks is None:
                    if not any(r is not None for r in self._by_slot):
                        # Nothing running to wait on and still short: the
                        # pool cannot EVER host this prompt — fail, don't
                        # spin.
                        raise RuntimeError(
                            f"block pool too small: {bucket // self.block_size}"
                            f" blocks needed for a {len(effective)}-token "
                            f"prompt, pool has {self.num_blocks - 1} usable; "
                            "raise num_blocks"
                        )
                    return  # pool busy; retry after in-flight slots retire
                break
            else:
                continue  # queue drained for this slot
            req = self._pop_queue()
            generated = list(req.tokens)
            if padded is None:
                padded, mask = left_pad([effective], self.gen.pad_id, bucket)
            prompt_mask = None if mask.all() else jnp.asarray(mask)
            shared: frozenset = frozenset()
            if cache_hit is not None:
                for blk in blocks:
                    self._shared_refs[blk] += 1
                logits = cache_hit["logits"]
                shared = frozenset(blocks)
            else:
                logits, self.pool = _paged_admit(
                    self.params, self.cfg, jnp.asarray(padded), self.pool,
                    prompt_mask, jnp.asarray(blocks, jnp.int32),
                    self.block_size,
                )
                if cache_key is not None:
                    # Retain: one ref for the cache + one for this
                    # request; the blocks are shared from here on.
                    self._prompt_cache[cache_key] = {
                        "blocks": list(blocks), "logits": logits,
                    }
                    for blk in blocks:
                        self._shared_refs[blk] = (
                            self._shared_refs.get(blk, 0) + 2
                        )
                    shared = frozenset(blocks)
            self.tables[slot] = 0  # stale entries never alias freed blocks
            self.tables[slot, :len(blocks)] = blocks
            self.positions[slot] = bucket
            # Same convention as the dense continuous batcher: the mask
            # carries PADDING validity only; future positions stay True
            # because causality (k_pos <= position) already hides them, and
            # the current step's freshly-written row must be attendable by
            # its own query.
            row = np.ones((self.max_blocks * self.block_size,), bool)
            row[:bucket] = np.asarray(mask)[0]
            self.kv_mask = self.kv_mask.at[slot].set(jnp.asarray(row))
            self._finish_admit(
                slot,
                _Request(req.rid, req.prompt, generated, blocks=blocks,
                         shared=shared, max_new=req.max_new,
                         temperature=req.temperature, stop=req.stop,
                         logit_bias=req.logit_bias,
                         logprobs=req.logprobs,
                         adapter_id=req.adapter_id),
                logits, jnp.asarray(padded), prompt_mask,
            )

    def _admit_free_slots_ragged(self) -> None:
        """Ragged admission ALLOCATES only — blocks, table row, validity
        mask, sampling state, and a prompt cursor. The prefill itself
        rides the next _step_ragged dispatches as chunk rows under the
        token budget, so admission never stalls in-flight decodes and a
        short prompt's first token can arrive with the SAME dispatch
        that finishes its prefill."""
        # kftpu-lint: disable=kftpu-host-sync-in-hot-path — bounded per-slot admission host->device upload feeding ragged chunk rows, not a per-token readback
        for slot in range(self.slots):
            if (self._by_slot[slot] is not None
                    or slot in self._ragged_admit):
                continue
            if not self._queue:
                return
            head = self._queue[0]
            effective = head.prompt + head.tokens
            bucket = max(
                self.prompt_bucket,
                -(-len(effective) // self.block_size) * self.block_size,
            )
            need = bucket // self.block_size
            blocks = self._reserve_take(need)
            if blocks is None:
                if (not any(r is not None for r in self._by_slot)
                        and not self._ragged_admit):
                    raise RuntimeError(
                        f"block pool too small: {need} blocks needed for "
                        f"a {len(effective)}-token prompt, pool has "
                        f"{self.num_blocks - 1} usable; raise num_blocks"
                    )
                return  # pool busy; retry after in-flight slots retire
            req = self._pop_queue()
            padded, mask = left_pad([effective], self.gen.pad_id, bucket)
            self.tables[slot] = 0  # stale entries never alias freed blocks
            self.tables[slot, :len(blocks)] = blocks
            # Decode continues at the bucket once installed; the cursor
            # (not ``positions``) tracks mid-prefill progress.
            self.positions[slot] = bucket
            row = np.ones((self.max_blocks * self.block_size,), bool)
            row[:bucket] = np.asarray(mask)[0]
            self.kv_mask = self.kv_mask.at[slot].set(jnp.asarray(row))
            installed = _Request(
                req.rid, req.prompt, list(req.tokens), blocks=blocks,
                max_new=req.max_new, temperature=req.temperature,
                stop=req.stop, logit_bias=req.logit_bias,
                logprobs=req.logprobs, deadline=req.deadline,
                adapter_id=req.adapter_id,
            )
            # Sampling state goes live NOW: the chunk that completes this
            # prefill samples the first token inside its own dispatch.
            self.temps[slot] = (self.gen.temperature
                                if req.temperature is None
                                else req.temperature)
            self._install_bias(slot, installed)
            self._ragged_admit[slot] = {
                "req": installed,
                "padded": np.array(padded),
                "prompt_mask": None if mask.all() else jnp.asarray(mask),
                "cursor": _AdmissionCursor(np.asarray(mask)[0], bucket),
            }

    def _admit_free_slots_prefix(self) -> None:
        """Admission under the position-0-anchored layout (prefix_cache):
        match the longest cached block chain, allocate only the tail,
        prefill the tail THROUGH the table, register fresh full blocks.

        Anchoring removes padding entirely — token i sits at logical
        position i, decode continues at position L — so the kv_mask row
        is simply all-True (pad slots would be future positions, which
        causality already hides; see _paged_prefix_admit)."""
        bs = self.block_size
        # kftpu-lint: disable=kftpu-host-sync-in-hot-path — bounded per-slot admission host->device upload on the prefix-cache path, not a per-token readback
        for slot in range(self.slots):
            if self._by_slot[slot] is not None:
                continue
            while self._queue:
                head = self._queue[0]
                effective = head.prompt + head.tokens
                lng = len(effective)
                nblocks = -(-lng // bs)
                # Longest cached chain over FULL blocks, excluding the
                # last token's block (kept mutable + recomputable).
                registrable = (lng - 1) // bs
                keys: list[bytes] = []
                shared_blocks: list[int] = []
                parent: Optional[bytes] = None
                for j in range(registrable):
                    key = self._chain_key(
                        parent, effective[j * bs:(j + 1) * bs],
                        adapter=head.adapter_id,
                    )
                    ent = self._prefix_entries.get(key)
                    if ent is None and self._swap:
                        # Device miss, swap tier next: a promoted block
                        # is a HIT (its prefill is skipped either way).
                        ent = self._swap_promote(key, parent)
                    if ent is None:
                        break
                    keys.append(key)
                    shared_blocks.append(ent["block"])
                    # Pin NOW, not after the walk: promotion allocates
                    # under the watermark and may evict unpinned leaves
                    # — including chain links this walk already matched.
                    self._shared_refs[ent["block"]] += 1
                    parent = key
                m = len(shared_blocks)
                for key in keys:  # hit refreshes recency (LRU-ish order)
                    self._prefix_entries[key] = self._prefix_entries.pop(key)
                need = nblocks - m
                blocks = self._reserve_take(need)
                if blocks is not None:
                    break
                for blk in shared_blocks:  # un-pin; admission stalled
                    self._shared_refs[blk] -= 1
                if not any(r is not None for r in self._by_slot):
                    raise RuntimeError(
                        f"block pool too small: {need} blocks needed for "
                        f"a {lng}-token prompt ({m} matched cached), pool "
                        f"has {self.num_blocks - 1} usable; raise "
                        "num_blocks"
                    )
                return  # pool busy; retry after in-flight slots retire
            else:
                continue  # queue drained for this slot
            req = self._pop_queue()
            # Counted only once allocation committed: a pool-stall retry
            # re-walks the chain and must not double-count its blocks.
            self.prefix_hits += m
            self.prefix_misses += registrable - m
            generated = list(req.tokens)
            all_blocks = shared_blocks + blocks
            self.tables[slot] = 0  # stale entries never alias freed blocks
            self.tables[slot, :len(all_blocks)] = all_blocks
            self.positions[slot] = lng
            self.kv_mask = self.kv_mask.at[slot].set(True)
            # Tail tokens right-padded to the owned blocks' span; every
            # pad write lands at a future position inside an OWNED block.
            start = m * bs
            padded_len = (nblocks - m) * bs
            chunk = np.full((1, padded_len), self.gen.pad_id, np.int32)
            chunk[0, :lng - start] = effective[start:]
            # Fixed-width pieces (the paged analog of prefill_chunked):
            # admission compiles O(1) programs regardless of prompt
            # length — every piece is admit_chunk wide except the final
            # remainder (a block multiple < admit_chunk, so at most
            # admit_chunk/BS distinct widths ever compile) — and score
            # memory is bounded at O(admit_chunk · span) instead of
            # O(tail · span). The final piece always holds the last real
            # token (right-padding is < one block), so only its logits
            # survive; earlier pieces' last_idx is clamped in-range and
            # their logits row discarded.
            off = 0
            while off < padded_len:
                width = min(self.admit_chunk, padded_len - off)
                last_idx = min(max(lng - 1 - start - off, 0), width - 1)
                logits, self.pool = _paged_prefix_admit(
                    self.params, self.cfg,
                    jnp.asarray(chunk[:, off:off + width]), self.pool,
                    jnp.asarray(self.tables[slot:slot + 1]),
                    jnp.asarray(start + off, jnp.int32),
                    jnp.ones((1, self.max_blocks * bs), bool),
                    jnp.asarray(last_idx, jnp.int32), bs,
                )
                off += width
            # Register the NEW full blocks onto the chain (content-
            # addressed, so continuations' generated tokens are as
            # shareable as prompt text): cache ref + this request's ref.
            for j in range(m, registrable):
                key = self._chain_key(parent,
                                      effective[j * bs:(j + 1) * bs],
                                      adapter=req.adapter_id)
                self._prefix_entries[key] = {
                    "block": all_blocks[j], "parent": parent, "children": 0,
                }
                if parent is not None:
                    self._prefix_entries[parent]["children"] += 1
                self._shared_refs[all_blocks[j]] = 2
                parent = key
            # The spec draft primes right-anchored too: the full prompt
            # at positions 0..L-1, no mask (anchored padding is causally
            # invisible — same argument as the tail chunk above).
            bucket = max(self.prompt_bucket, nblocks * bs)
            dpad = np.full((1, bucket), self.gen.pad_id, np.int32)
            dpad[0, :lng] = effective
            self._finish_admit(
                slot,
                _Request(req.rid, req.prompt, generated,
                         blocks=all_blocks,
                         shared=frozenset(all_blocks[:registrable]),
                         max_new=req.max_new,
                         temperature=req.temperature, stop=req.stop,
                         logit_bias=req.logit_bias,
                         logprobs=req.logprobs,
                         adapter_id=req.adapter_id),
                logits, jnp.asarray(dpad), None,
            )

    def _ensure_step_blocks(self, span: int = 1) -> list[int]:
        """Every active slot whose next ``span`` writes reach an
        unallocated block gets one before the step dispatches (span=1:
        ordinary decode; span=k_spec+1: a speculative verify chunk). A
        slot's request holds its blocks in position order, so positions
        p..p+span-1 need coverage through (p+span-1) // block_size.
        Preemption inside _take_blocks may evict slots (including a
        needing one); loop until stable — multi-block deficits resolve
        one block per pass."""
        while True:
            active = [i for i, r in enumerate(self._by_slot) if r is not None]
            needing = [
                s for s in active
                if (int(self.positions[s]) + span - 1) // self.block_size
                >= len(self._by_slot[s].blocks)
            ]
            if not needing:
                return active
            blocks = self._take_blocks(len(needing))
            if blocks is None:
                raise RuntimeError(
                    "block pool exhausted with a single active request; "
                    "raise num_blocks"
                )
            for s, blk in zip(needing, blocks):
                req = self._by_slot[s]
                if req is None:  # evicted by the preemption above
                    self._free.append(blk)
                    continue
                self.tables[s, len(req.blocks)] = blk
                req.blocks.append(blk)

    def _step(self) -> None:
        if self.ragged:
            self._step_ragged()
            return
        active = self._ensure_step_blocks()
        if not active:
            return
        self.last_step = {
            "decode_rows": len(active),
            "prefill_rows": 0,
            "fill": len(active) / self.slots,
        }
        self.key, sub = jax.random.split(self.key)
        nxt, lps, self.pool = _paged_step(
            self.params, self.cfg, jnp.array(self.tokens), self.pool,
            jnp.array(self.tables), jnp.array(self.positions), self.kv_mask,
            sub, self.block_size, jnp.array(self.temps), self.gen.top_k,
            self.gen.top_p, bias=self._bias,
            attn_kernel=self.attn_kernel,
        )
        for slot in active:
            self.positions[slot] += 1
        host_next = np.asarray(nxt)
        host_lps = np.asarray(lps)
        for slot in active:
            self._note_token(slot, int(host_next[slot]),
                             float(host_lps[slot]))

    def _expire_ragged_admissions(self) -> None:
        """Cancelled or deadline-expired MID-PREFILL admissions retire
        before the step assembles: a dead request must not spend budget
        (slotted requests keep retiring through _note_token)."""
        for slot, a in list(self._ragged_admit.items()):
            req = a["req"]
            reason = self._cancelled.pop(req.rid, None)
            if reason is None and req.deadline is not None \
                    and self._clock() >= req.deadline:
                reason = "deadline"
            if reason is not None:
                del self._ragged_admit[slot]
                self._clear_slot_storage(slot, req)
                self._deliver_abort(req, reason)

    def _ragged_adapters(self):
        """Per-slot adapter spec for the fused dispatches, or None (the
        base-only program). Overridden by MultiLoraPagedBatcher with
        (stacked, ids (S,), scaling) — every scheduling mode (plain
        decode, admission chunks, speculative verify spans) routes
        through this ONE hook, so they cannot disagree about a slot's
        adapter."""
        return None

    def _assemble_ragged(self, spans: dict):
        """Lay out ONE flattened mixed batch under the token budget —
        every decode span in ``spans`` (slot → (token_list, pos0)) first,
        in slot order (never squeezed out; seq_starts stays
        non-decreasing, the kernel's spill-row contract), then each
        admitting slot's next prompt chunk rides whatever budget is
        left. A plain decode step passes 1-token spans; a speculative
        step passes (1 + draft_len) verify spans — span length is the
        ONLY difference between the two scheduling modes.

        Returns (tokens, tok_pos, tok_seq, seq_starts, seq_lens,
        kv_lens, last_rows, rows, completing)."""
        tb = self.token_budget
        tokens = np.full((tb, 1), self.gen.pad_id, np.int32)
        tok_pos = np.zeros((tb,), np.int32)
        tok_seq = np.zeros((tb,), np.int32)
        seq_starts = np.zeros((self.slots,), np.int32)
        seq_lens = np.zeros((self.slots,), np.int32)
        kv_lens = np.zeros((self.slots,), np.int32)
        last_rows = np.zeros((self.slots,), np.int32)
        budget = tb - sum(len(toks) for toks, _ in spans.values())
        rows = 0
        completing: list[int] = []
        for slot in range(self.slots):
            span = spans.get(slot)
            if span is not None:
                toks, pos0 = span
                n = len(toks)
                tokens[rows:rows + n, 0] = toks
                tok_pos[rows:rows + n] = np.arange(pos0, pos0 + n)
                tok_seq[rows:rows + n] = slot
                seq_starts[slot] = rows
                seq_lens[slot] = n
                kv_lens[slot] = pos0 + n
                last_rows[slot] = rows + n - 1
                rows += n
            elif slot in self._ragged_admit and budget > 0:
                a = self._ragged_admit[slot]
                start, n = a["cursor"].take(budget)
                if n == 0:
                    continue
                budget -= n
                tokens[rows:rows + n, 0] = a["padded"][0, start:start + n]
                tok_pos[rows:rows + n] = np.arange(start, start + n)
                tok_seq[rows:rows + n] = slot
                seq_starts[slot] = rows
                seq_lens[slot] = n
                kv_lens[slot] = start + n
                last_rows[slot] = rows + n - 1
                rows += n
                if a["cursor"].done:
                    completing.append(slot)
        return (tokens, tok_pos, tok_seq, seq_starts, seq_lens, kv_lens,
                last_rows, rows, completing)

    def _dispatch_width(self, rows: int) -> int:
        """Dispatch width: the smallest power-of-two bucket that holds
        the assembled rows (floor 8, cap token_budget). The budget is
        CAPACITY, not shape — a mostly-decode step must not pay a full
        512-row dispatch to carry 9 live rows; power-of-two buckets
        bound the compiled step variants at ~log2(budget)."""
        width = 8
        while width < rows:
            width *= 2
        return min(width, self.token_budget)

    def _stamp_ragged(self, rows: int, decode_rows: int) -> None:
        """Per-dispatch observability shared by both scheduling modes:
        lifetime ragged counters + the drive span's last_step record."""
        self.ragged_steps += 1
        self.ragged_tokens += rows
        self.ragged_fill = rows / self.token_budget
        self.last_step = {
            "decode_rows": decode_rows,
            "prefill_rows": rows - decode_rows,
            "fill": self.ragged_fill,
        }

    def _complete_ragged_admissions(self, completing, first_tok: dict,
                                    first_lp: dict) -> None:
        """Finish admissions whose last prompt chunk just dispatched:
        the SAME dispatch already produced each one's first token
        (``first_tok``/``first_lp`` per slot; lp None on argmax-only
        verify dispatches) — no separate prefill readback."""
        for slot in completing:
            a = self._ragged_admit.pop(slot)
            req = a["req"]
            req.budget = self._initial_budget(req) - len(req.tokens)
            self._by_slot[slot] = req
            self._post_admit(slot, jnp.asarray(a["padded"]),
                             a["prompt_mask"])
            self._note_token(slot, first_tok[slot], first_lp.get(slot))

    def _step_ragged(self) -> None:
        """One fused mixed prefill/decode dispatch: every decoding
        slot's next token plus admission chunks under the token budget
        (_assemble_ragged), sampled at each span's last row."""
        self._expire_ragged_admissions()
        active = self._ensure_step_blocks()
        if not active and not self._ragged_admit:
            return
        spans = {
            slot: ([int(self.tokens[slot, 0])], int(self.positions[slot]))
            for slot in active
        }
        (tokens, tok_pos, tok_seq, seq_starts, seq_lens, kv_lens,
         last_rows, rows, completing) = self._assemble_ragged(spans)
        if rows == 0:
            return
        width = self._dispatch_width(rows)
        self.key, sub = jax.random.split(self.key)
        nxt, lps, self.pool = _paged_ragged_step(
            self.params, self.cfg, jnp.array(tokens[:width]), self.pool,
            jnp.array(self.tables), self.kv_mask,
            jnp.array(tok_pos[:width]),
            jnp.array(tok_seq[:width]), jnp.asarray(rows, jnp.int32),
            jnp.array(seq_starts), jnp.array(seq_lens),
            jnp.array(kv_lens), jnp.array(last_rows), sub,
            self.block_size, jnp.array(self.temps), self.gen.top_k,
            self.gen.top_p, bias=self._bias,
            attn_kernel=self.attn_kernel,
            adapters=self._ragged_adapters(),
        )
        self._stamp_ragged(rows, decode_rows=len(active))
        host_next = np.asarray(nxt)
        host_lps = np.asarray(lps)
        for slot in active:
            self.positions[slot] += 1
        for slot in active:
            self._note_token(slot, int(host_next[slot]),
                             float(host_lps[slot]))
        # The completing chunk's dispatch already sampled the first
        # token (its span's last row).
        self._complete_ragged_admissions(
            completing,
            {s: int(host_next[s]) for s in completing},
            {s: float(host_lps[s]) for s in completing},
        )
