from kubeflow_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    LLAMA_CONFIGS,
    init_params,
    forward,
    decode_step,
    init_kv_cache,
    prefill,
    prefill_chunked,
    generate,
    sample,
)
