"""fp8 training and serving for the Llama family (v5p-class hardware).

Two independent capabilities, both built on the IEEE-754 fp8 formats XLA
ships (float8_e4m3fn for values, float8_e5m2 for gradients):

- **fp8 TRAINING** (TransformerEngine-style delayed scaling): master
  weights stay bf16/f32 and the optimizer is untouched, but every targeted
  matmul runs with fp8 operands — forward operands in e4m3 (more mantissa),
  gradients in e5m2 (more exponent range). Each weight carries an
  ``Fp8Meta`` of per-tensor amax HISTORIES; the scale used at step N is
  derived from the maxima observed at steps < N ("delayed scaling" — the
  cast needs no extra pass over the tensor), and the amax observed at step
  N is recorded for step N+1. On v5p-class MXUs the fp8 operands double
  matmul throughput and halve weight/activation bytes; on hardware without
  fp8 MXU lanes (v5e, CPU) XLA upcasts the operands, so the numerics are
  identical everywhere and only the speedup is hardware-gated.

  Meta updates ride the AUTODIFF pass ("overwrite with gradient", the
  flax fp8_ops pattern): the custom_vjp reports each meta's NEXT value as
  its cotangent, and ``fp8_meta_replace`` — wired automatically by
  ``train.make_train_step`` via ``optax.multi_transform`` — applies that
  "gradient" by replacement instead of gradient descent. This keeps the
  whole mechanism inside the functional (params, grads, updates) cycle:
  no mutable state, no side channels, shard_map/pjit-safe.

- **fp8 weight-only SERVING**: ``quantize_weight_fp8`` stores projections
  as e4m3 with a per-output-channel f32 scale — the same {"q", "s"} layout
  as int8 (llama._mm consumes it unchanged) and the same 2× HBM cut,
  which is the whole bandwidth-bound-decode win. Be precise about what it
  is NOT (yet): _mm upcasts the e4m3 operand to the activation dtype
  before the matmul, exactly like the int8 path, so no native-fp8 MXU
  instruction is emitted — on v5e this costs nothing (no fp8 MXU lanes
  exist), and on v5p wiring the operands through a true fp8 dot is a
  compile-path change the stored format keeps open. The per-element grid
  is COARSER than int8's (3 mantissa bits ≈ 6% relative error vs int8
  per-channel's ≤0.8%); fp8's draw today is format consistency with
  fp8-trained checkpoints, not accuracy.

Reference parity: the reference (opendatahub-io/kubeflow) has no in-
notebook ML runtime at all; this module is part of the added TPU-native
runtime scope (SURVEY.md §2.5, ROADMAP "fp8 training + serving").
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

E4M3_MAX = 448.0      # largest finite float8_e4m3fn
E5M2_MAX = 57344.0    # largest finite float8_e5m2

# Matmul targets: the stacked (L, in, out) layer projections. lm_head is
# deliberately excluded — logits are the classic fp8 casualty, and the
# head is read once per token (vs once per layer), so the bandwidth win
# is small relative to the accuracy risk.
_LAYER_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")

_HISTORY = 16  # amax history window (TransformerEngine's default order)


def init_meta(history: int = _HISTORY) -> dict:
    """Fresh per-weight fp8 metadata: amax histories for the forward
    activation (x), the weight (w), and the backward gradient (g).
    Zeros mean "nothing observed yet" → scale 1.0 on the first step."""
    z = jnp.zeros((history,), jnp.float32)
    return {"x_hist": z, "w_hist": z, "g_hist": z}


def _scale_from(hist: jax.Array, fmax: float, margin: float = 1.0) -> jax.Array:
    """Delayed scale: map the largest recently-observed amax to the fp8
    format's max (divided by ``margin`` headroom). An all-zero history
    (first step, or a dead tensor) scales by 1.0 rather than inf."""
    amax = jnp.max(hist)
    return jnp.where(amax > 0.0, fmax / (margin * amax), 1.0)


def _record(hist: jax.Array, x: jax.Array) -> jax.Array:
    """Roll the newest amax observation into the history window."""
    return jnp.roll(hist, 1).at[0].set(jnp.max(jnp.abs(x)).astype(jnp.float32))


def _cast(x: jax.Array, scale: jax.Array, dtype, fmax: float) -> jax.Array:
    """Scale into the representable range and saturate-cast. The clip
    matters: e4m3fn has no inf, and an overflow would become NaN."""
    return jnp.clip(x.astype(jnp.float32) * scale, -fmax, fmax).astype(dtype)


@jax.custom_vjp
def fp8_matmul(x: jax.Array, w: jax.Array, meta: dict) -> jax.Array:
    """``x @ w`` with fp8 operands and delayed scaling.

    x: (..., K), w: (K, N), meta: init_meta() pytree. Differentiable in x
    and w; meta's "gradient" is its next value (overwrite-with-gradient —
    pair with ``fp8_meta_replace`` in the optimizer, which
    train.make_train_step does automatically)."""
    y, _ = _fp8_fwd(x, w, meta)
    return y


def _fp8_fwd(x, w, meta):
    sx = _scale_from(meta["x_hist"], E4M3_MAX)
    sw = _scale_from(meta["w_hist"], E4M3_MAX)
    qx = _cast(x, sx, jnp.float8_e4m3fn, E4M3_MAX)
    qw = _cast(w, sw, jnp.float8_e4m3fn, E4M3_MAX)
    # f32 accumulation, then undo both operand scales in the epilogue.
    y = (
        jnp.matmul(qx, qw, preferred_element_type=jnp.float32)
        / (sx * sw)
    ).astype(x.dtype)
    res = (
        qx, qw, sx, sw,
        _record(meta["x_hist"], x),
        _record(meta["w_hist"], w),
        meta["g_hist"],
        # dtype carriers (a raw np.dtype is not a valid residual leaf)
        jnp.zeros((), x.dtype), jnp.zeros((), w.dtype),
    )
    return y, res


def _fp8_bwd(res, g):
    qx, qw, sx, sw, new_x_hist, new_w_hist, g_hist, x_proto, w_proto = res
    x_dtype, w_dtype = x_proto.dtype, w_proto.dtype
    sg = _scale_from(g_hist, E5M2_MAX)
    qg = _cast(g, sg, jnp.float8_e5m2, E5M2_MAX)
    # dx = g @ w.T ; dw = x.T @ g — both with fp8 operands, f32 accum.
    dx = (
        jnp.matmul(qg, qw.T, preferred_element_type=jnp.float32) / (sg * sw)
    ).astype(x_dtype)
    qg2 = qg.reshape(-1, qg.shape[-1])
    qx2 = qx.reshape(-1, qx.shape[-1])
    dw = (
        jnp.matmul(qx2.T, qg2, preferred_element_type=jnp.float32) / (sx * sg)
    ).astype(w_dtype)
    meta_next = {
        "x_hist": new_x_hist,
        "w_hist": new_w_hist,
        "g_hist": _record(g_hist, g),
    }
    return dx, dw, meta_next


fp8_matmul.defvjp(_fp8_fwd, _fp8_bwd)


def wrap_params_fp8(params: dict, targets=_LAYER_TARGETS,
                    history: int = _HISTORY) -> dict:
    """bf16 param tree → fp8-training tree: each targeted projection
    becomes {"hp": <master weight, unchanged>, "fp8": init_meta()}.
    llama's matmul helper dispatches on the "hp" key; everything else
    (embeddings, norms, lm_head, biases) is untouched. Stacked (L, ...)
    weights get per-LAYER metas (histories stacked on the layer axis) so
    each layer scales independently inside the lax.scan."""
    layers = dict(params["layers"])
    n_layers = None
    for t in targets:
        if t not in layers:
            continue
        w = layers[t]
        n_layers = w.shape[0]
        meta = init_meta(history)
        meta = jax.tree_util.tree_map(
            lambda h: jnp.broadcast_to(h, (n_layers,) + h.shape), meta
        )
        layers[t] = {"hp": w, "fp8": meta}
    return {**params, "layers": layers}


def unwrap_params_fp8(params: dict) -> dict:
    """fp8-training tree → plain tree (the master weights), e.g. for
    checkpoint export or switching to inference."""
    layers = {
        t: (w["hp"] if isinstance(w, dict) and "hp" in w else w)
        for t, w in params["layers"].items()
    }
    return {**params, "layers": layers}


def has_fp8_params(params: dict) -> bool:
    return any(
        isinstance(w, dict) and "hp" in w
        for w in params.get("layers", {}).values()
        if w is not None
    )


def fp8_meta_replace():
    """GradientTransformation for fp8 meta leaves: the incoming "gradient"
    IS the next meta value (overwrite-with-gradient), so the update is
    ``next - current`` and optax.apply_updates lands exactly on ``next``.

    NOTE on grad accumulation: summed-then-averaged microbatch "grads"
    average the histories — a mild underestimate of the true window max,
    covered by the delayed-scaling margin."""
    import optax

    def init(params):
        del params
        return optax.EmptyState()

    def update(updates, state, params=None):
        if params is None:
            raise ValueError("fp8_meta_replace requires params")
        return (
            jax.tree_util.tree_map(lambda g, p: g - p, updates, params),
            state,
        )

    return optax.GradientTransformation(init, update)


def fp8_partition_labels(params: dict) -> dict:
    """Label tree for optax.multi_transform: "fp8_meta" for every leaf
    under an {"hp", "fp8"} wrapper's meta, "default" elsewhere."""
    def label(path, leaf):
        del leaf
        return (
            "fp8_meta"
            if any(getattr(k, "key", None) == "fp8" for k in path)
            else "default"
        )

    return jax.tree_util.tree_map_with_path(label, params)


@partial(jax.jit, static_argnames=("axis",))
def quantize_weight_fp8(w: jax.Array, axis: int) -> dict:
    """Weight-only fp8 serving: per-output-channel scale maps each
    channel's amax to E4M3_MAX. Same {"q", "s"} layout as int8 — the
    dequant multiply rides the matmul epilogue unchanged (llama._mm),
    and dequantize_weight's generic branch already handles it."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / E4M3_MAX
    q = jnp.clip(wf / scale, -E4M3_MAX, E4M3_MAX).astype(jnp.float8_e4m3fn)
    return {"q": q, "s": scale.astype(jnp.float32)}
