"""HuggingFace checkpoint conversion for the Llama family.

A notebook user's first real act on a fresh TPU slice is loading weights;
this module turns a HuggingFace Llama checkpoint (``LlamaForCausalLM``
state dict, or a directory of ``*.safetensors`` shards) into the stacked
pytree kubeflow_tpu.models.llama consumes.

Layout notes (why each transform exists):

- torch ``nn.Linear.weight`` is (out, in); our matmuls are ``x @ w`` with
  w (in, out) → every projection transposes once at load time so the hot
  path never does.
- Our transformer layers are STACKED along a leading (n_layers, ...) axis
  for the lax.scan forward — per-layer HF tensors are stacked here, once.
- transformers stores q/k projections already permuted for its rotate-half
  RoPE convention, which is the same convention ``llama.apply_rope``
  implements, so weights load with no head-dim permutation.
- ``lm_head.weight`` is (vocab, dim) in both layouts (we compute
  ``x @ lm_head.T``): no transpose. Tied-embedding checkpoints
  (``tie_word_embeddings=true``) reuse the embedding matrix.

There is no counterpart in the reference (it has no ML runtime —
SURVEY.md §2.5); this is north-star tooling for the in-notebook Llama
benchmark (BASELINE.md).
"""

from __future__ import annotations

import json
import pathlib
from typing import Any, Mapping, Optional

import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.llama import LlamaConfig, RopeScaling


def _rope_scaling_from_hf(raw: Any) -> Optional[RopeScaling]:
    """Map HF's rope_scaling block; raise rather than silently drop it —
    ignoring e.g. Llama-3.1's "llama3" schedule would load cleanly and
    generate garbage past the scaling regime."""
    if raw is None:
        return None
    if not isinstance(raw, Mapping):
        raw = dict(raw)
    kind = raw.get("rope_type", raw.get("type", "default"))
    if kind == "default":
        return None
    if kind == "llama3":
        return RopeScaling(
            factor=float(raw["factor"]),
            low_freq_factor=float(raw["low_freq_factor"]),
            high_freq_factor=float(raw["high_freq_factor"]),
            original_max_position_embeddings=int(
                raw["original_max_position_embeddings"]
            ),
        )
    raise NotImplementedError(
        f"rope_scaling type {kind!r} is not supported (have: llama3); "
        "loading would produce wrong positions silently"
    )


def _sliding_window_from_hf(get, model_type: str) -> int:
    """Window semantics differ per family. Mistral windows every layer.
    Qwen2 windows only layers >= max_window_layers when use_sliding_window
    is set — with the HF default max_window_layers == n_layers, NO layer
    is windowed. A partial (per-layer) window split is unsupported: raise
    rather than silently windowing all layers (wrong long-context logits)."""
    if model_type == "mistral":
        return int(get("sliding_window") or 0)
    if model_type == "qwen2" and get("use_sliding_window", False):
        n_layers = get("num_hidden_layers")
        cutoff = get("max_window_layers", n_layers)
        if cutoff >= n_layers:
            return 0  # HF applies the window to no layer
        if cutoff == 0:
            return int(get("sliding_window") or 0)  # every layer windowed
        raise NotImplementedError(
            f"qwen2 max_window_layers={cutoff} < num_hidden_layers="
            f"{n_layers}: per-layer sliding-window splits are not "
            "supported (all-or-nothing only)"
        )
    return 0


def config_from_hf(hf_config: Any) -> LlamaConfig:
    """Map a transformers config (object or dict) to LlamaConfig.

    Handles the Llama-family variants that share the HF module layout:
    llama (+3.1 rope scaling), mistral (sliding window), gemma (GeGLU,
    1+w norms, scaled/tied embeddings, decoupled head_dim).
    """
    get = (
        hf_config.get
        if isinstance(hf_config, Mapping)
        else lambda k, d=None: getattr(hf_config, k, d)
    )
    model_type = get("model_type", "llama") or "llama"
    if model_type not in ("llama", "mistral", "gemma", "qwen2"):
        raise NotImplementedError(
            f"model_type {model_type!r} is not in the supported Llama "
            "family (llama, mistral, gemma, qwen2)"
        )
    n_heads = get("num_attention_heads")
    default_head_dim = get("hidden_size") // n_heads
    act = get("hidden_activation") or get("hidden_act") or "silu"
    is_gemma = model_type == "gemma"
    return LlamaConfig(
        vocab_size=get("vocab_size"),
        dim=get("hidden_size"),
        n_layers=get("num_hidden_layers"),
        n_heads=n_heads,
        n_kv_heads=get("num_key_value_heads", n_heads) or n_heads,
        ffn_hidden=get("intermediate_size"),
        rope_theta=float(get("rope_theta", 10000.0)),
        rope_scaling=_rope_scaling_from_hf(get("rope_scaling")),
        max_seq_len=get("max_position_embeddings", 4096),
        norm_eps=float(get("rms_norm_eps", 1e-5)),
        sliding_window=_sliding_window_from_hf(get, model_type),
        act="gelu" if act.startswith("gelu") else "silu",
        norm_add_unit=is_gemma,
        embed_scale=is_gemma,
        head_dim_override=(
            hd if (hd := get("head_dim", 0) or 0) != default_head_dim else 0
        ),
        tie_embeddings=bool(get("tie_word_embeddings", False)),
        attn_bias=model_type == "qwen2",
    )


def _to_np(t: Any) -> np.ndarray:
    """torch tensor / numpy array → numpy, without a torch import here."""
    if isinstance(t, np.ndarray):
        return t
    # torch.Tensor: bf16 has no numpy dtype; detach via float32.
    if hasattr(t, "detach"):
        t = t.detach()
        if str(t.dtype) == "torch.bfloat16":
            t = t.float()
        return t.cpu().numpy()
    return np.asarray(t)


def params_from_hf_state_dict(
    cfg: LlamaConfig,
    state_dict: Mapping[str, Any],
    dtype: Optional[Any] = None,
) -> dict:
    """HF LlamaForCausalLM state dict → stacked params pytree.

    Accepts torch tensors or numpy arrays as values. ``dtype`` defaults to
    ``cfg.dtype`` (bf16 — the MXU-native choice).
    """
    dtype = cfg.dtype if dtype is None else dtype
    sd = dict(state_dict)
    # Some exports prefix everything with "model." except lm_head.
    prefix = "model." if any(k.startswith("model.") for k in sd) else ""

    def take(name: str) -> jnp.ndarray:
        key = prefix + name if not name.startswith("lm_head") else name
        try:
            return jnp.asarray(_to_np(sd[key]), dtype)
        except KeyError:
            raise KeyError(
                f"checkpoint is missing '{key}' "
                f"(have {len(sd)} tensors; is this a Llama-family export?)"
            ) from None

    def stack_linear(fmt: str) -> jnp.ndarray:
        # (out, in) per layer → stacked (L, in, out).
        return jnp.stack(
            [take(fmt.format(i)).T for i in range(cfg.n_layers)]
        )

    def stack_norm(fmt: str) -> jnp.ndarray:
        return jnp.stack([take(fmt.format(i)) for i in range(cfg.n_layers)])

    layers = {
        "attn_norm": stack_norm("layers.{}.input_layernorm.weight"),
        "wq": stack_linear("layers.{}.self_attn.q_proj.weight"),
        "wk": stack_linear("layers.{}.self_attn.k_proj.weight"),
        "wv": stack_linear("layers.{}.self_attn.v_proj.weight"),
        "wo": stack_linear("layers.{}.self_attn.o_proj.weight"),
        "mlp_norm": stack_norm("layers.{}.post_attention_layernorm.weight"),
        "w_gate": stack_linear("layers.{}.mlp.gate_proj.weight"),
        "w_up": stack_linear("layers.{}.mlp.up_proj.weight"),
        "w_down": stack_linear("layers.{}.mlp.down_proj.weight"),
    }
    if cfg.attn_bias:
        layers["bq"] = stack_norm("layers.{}.self_attn.q_proj.bias")
        layers["bk"] = stack_norm("layers.{}.self_attn.k_proj.bias")
        layers["bv"] = stack_norm("layers.{}.self_attn.v_proj.bias")
    out = {
        "embed": take("embed_tokens.weight"),
        "final_norm": take("norm.weight"),
        "layers": layers,
    }
    # Tied configs carry no lm_head leaf (models/llama.py init_params:
    # one storage keeps gradients tied); untied checkpoints must have it.
    if not cfg.tie_embeddings:
        out["lm_head"] = take("lm_head.weight")
    return out


def params_to_hf_state_dict(cfg: LlamaConfig, params: dict) -> dict:
    """Inverse of params_from_hf_state_dict (numpy f32 values) — lets a
    notebook export back to the HF ecosystem after TPU fine-tuning."""
    out = {
        "model.embed_tokens.weight": _f32(params["embed"]),
        "model.norm.weight": _f32(params["final_norm"]),
    }
    if "lm_head" in params:
        out["lm_head.weight"] = _f32(params["lm_head"])
    names = {
        "attn_norm": ("input_layernorm.weight", False),
        **(
            {
                "bq": ("self_attn.q_proj.bias", False),
                "bk": ("self_attn.k_proj.bias", False),
                "bv": ("self_attn.v_proj.bias", False),
            }
            if "bq" in params["layers"]
            else {}
        ),
        "wq": ("self_attn.q_proj.weight", True),
        "wk": ("self_attn.k_proj.weight", True),
        "wv": ("self_attn.v_proj.weight", True),
        "wo": ("self_attn.o_proj.weight", True),
        "mlp_norm": ("post_attention_layernorm.weight", False),
        "w_gate": ("mlp.gate_proj.weight", True),
        "w_up": ("mlp.up_proj.weight", True),
        "w_down": ("mlp.down_proj.weight", True),
    }
    for ours, (theirs, is_linear) in names.items():
        stacked = params["layers"][ours]
        for i in range(cfg.n_layers):
            mat = _f32(stacked[i])
            out[f"model.layers.{i}.{theirs}"] = mat.T if is_linear else mat
    return out


def _f32(x: jnp.ndarray) -> np.ndarray:
    return np.asarray(x, np.float32)


def load_hf_checkpoint(
    path: str | pathlib.Path, dtype: Optional[Any] = None
) -> tuple[LlamaConfig, dict]:
    """Load (config, params) from an HF checkpoint directory.

    Reads ``config.json`` plus every ``*.safetensors`` shard (memory-mapped
    by safetensors, so a 7B load streams tensor-by-tensor instead of
    materializing the whole checkpoint twice).
    """
    path = pathlib.Path(path)
    cfg = config_from_hf(json.loads((path / "config.json").read_text()))
    shards = sorted(path.glob("*.safetensors"))
    if not shards:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    from safetensors import safe_open  # transformers dependency

    state: dict[str, np.ndarray] = {}
    for shard in shards:
        with safe_open(str(shard), framework="np") as f:
            for key in f.keys():
                state[key] = f.get_tensor(key)
    return cfg, params_from_hf_state_dict(cfg, state, dtype)
