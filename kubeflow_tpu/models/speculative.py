"""Greedy speculative decoding: a small draft model proposes, the target
verifies k tokens per step in ONE forward — batched.

Decode is HBM-bound on the TARGET's weights; verification reads them once
per k proposed tokens instead of once per token, so wall-clock approaches
(accepted+1)/k_spec × the plain decode cost when the draft agrees often
(same-family small model). Greedy acceptance makes the output EXACTLY the
target's greedy decoding — tested token-for-token — so speculation is a
pure latency optimization, never a quality trade.

Batched rounds (the cache-pointer discipline is the subtle part): after
round one every row has accepted a DIFFERENT prefix, so write pointers
diverge per row. Both models decode chunks at per-row offsets
(llama._decode_chunk_batch_impl: vmapped cache writes, (B, K) position
matrices through rope and the causal mask). Rows that reach ``steps``
freeze their pointer and keep riding the fixed-shape batch program —
their slots recompute harmlessly; one compiled shape for the whole run.

Per round:
- draft autoregressively proposes d_1..d_k from its own cache,
- target runs one chunked forward over [prev_token, d_1..d_k] (k+1 wide,
  so every proposal is acceptable) at each row's offset,
- per row: accept the longest prefix where target argmax matches the
  proposal, emit the target's own next token as the correction, advance
  that row's pointer by accepted+1 (rewinding past rejected slots).

No reference counterpart (control plane only — SURVEY.md §2.5).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.llama import (
    LlamaConfig,
    _decode_chunk_batch_impl,
    _prefill_impl,
    init_kv_cache,
)


@partial(jax.jit, static_argnames=("cfg", "k_spec"))
def _draft_propose(params, cfg, token, kv_cache, positions, k_spec,
                   kv_mask=None):
    """Draft k_spec greedy tokens autoregressively from ``token`` at
    per-row ``positions`` (B,). ``kv_mask`` (B, C) marks valid cache
    slots (serving: left-pad slots are False).

    Runs k_spec+1 decode steps: each step WRITES its input token's K/V,
    so the extra step is what lands d_k in the draft cache — on a fully
    accepted round the next round continues from position+k_spec+1 and a
    missing d_k entry would silently degrade later proposals (a hole the
    target's verification can't see)."""

    def step(carry, _):
        tok, cache, pos = carry
        logits, cache = _decode_chunk_batch_impl(
            params, cfg, tok, cache, pos, kv_mask=kv_mask
        )
        nxt = jnp.argmax(logits[:, 0], axis=-1)[:, None]
        return (nxt, cache, pos + 1), nxt[:, 0]

    (_, cache, _), sampled = jax.lax.scan(
        step, (token, kv_cache, positions), length=k_spec + 1
    )
    return sampled.T[:, :k_spec], cache  # (B, k_spec); last sample unused


@partial(jax.jit, static_argnames=("cfg",))
def _target_verify(params, cfg, chunk, kv_cache, positions, kv_mask=None):
    logits, cache = _decode_chunk_batch_impl(
        params, cfg, chunk, kv_cache, positions, kv_mask=kv_mask
    )
    return jnp.argmax(logits, axis=-1), cache  # (B, K)


def speculative_generate(
    target_params: dict,
    target_cfg: LlamaConfig,
    draft_params: dict,
    draft_cfg: LlamaConfig,
    prompt: jax.Array,  # (B, S)
    steps: int,
    cache_len: int,
    k_spec: int = 4,
) -> tuple[jax.Array, dict]:
    """Greedy speculative decoding. Returns (tokens (B, steps), stats).

    Output is IDENTICAL to target-only greedy decoding of each row; stats
    reports the acceptance rate that determines the speedup.

    The returned caches are valid only for rows still short of ``steps``
    at return (i.e. none): rows that completed keep riding the fixed-shape
    rounds with a clamped parked pointer, so their cache tails hold dead
    chunk writes. Callers continuing generation must re-prefill.
    """
    b, s_prompt = prompt.shape
    # Fixed-shape rounds need headroom for a full k_spec chunk even on
    # the last round; enforcing it up front keeps the (B, steps) output
    # contract AND pins every round to ONE compiled shape (a shrinking
    # tail k would retrace mid-decode).
    needed = s_prompt + steps + k_spec
    if cache_len < needed:
        raise ValueError(
            f"cache_len {cache_len} < prompt ({s_prompt}) + steps "
            f"({steps}) + k_spec ({k_spec}) = {needed}"
        )
    t_cache = init_kv_cache(target_cfg, b, cache_len)
    d_cache = init_kv_cache(draft_cfg, b, cache_len)

    t_logits, t_cache = _prefill_impl(target_params, target_cfg, prompt, t_cache)
    _, d_cache = _prefill_impl(draft_params, draft_cfg, prompt, d_cache)
    # np.array (not asarray): device arrays view as read-only numpy.
    last_np = np.array(jnp.argmax(t_logits, axis=-1))  # (B,) first tokens

    out: list[list[int]] = [[int(t)] for t in last_np]
    pos = np.full((b,), s_prompt, np.int64)  # per-row cache pointer
    proposed_total = accepted_total = 0

    while any(len(o) < steps for o in out):
        # Frozen rows (output complete) still ride the fixed-shape device
        # step with parked pointers; surplus acceptances can park one at
        # cache_len - 1, where the round's k_spec+1 chunk write would run
        # past the cache and dynamic_update_slice would silently CLAMP the
        # start — shifting the write onto the row's valid tail. Clamp the
        # pointer explicitly instead so the dead write stays in-bounds at
        # the cache's end. Consequence (documented contract): the returned
        # caches are NOT valid for rows that reached ``steps`` — their
        # tail slots hold dead chunk writes.
        positions = jnp.asarray(
            np.minimum(pos, cache_len - (k_spec + 1)), jnp.int32
        )
        last = jnp.asarray(last_np, jnp.int32)[:, None]
        proposals, d_cache = _draft_propose(
            draft_params, draft_cfg, last, d_cache, positions, k_spec
        )
        # Chunk is (k+1) wide so EVERY proposal is acceptable: pred i is
        # the target's next token after ...[last, d_1..d_i].
        chunk = jnp.concatenate([last, proposals], axis=1)
        preds, t_cache = _target_verify(
            target_params, target_cfg, chunk, t_cache, positions
        )
        preds_np = np.asarray(preds)
        props_np = np.asarray(proposals)
        for row in range(b):
            if len(out[row]) >= steps:
                continue  # frozen row: pointer parked, output complete
            n_accept = 0
            while (
                n_accept < k_spec
                and preds_np[row, n_accept] == props_np[row, n_accept]
            ):
                n_accept += 1
            # Emit accepted proposals + the target's own correction. When
            # all k were accepted the "correction" is the target's free
            # token for position pos+k (preds[k]).
            emitted = list(props_np[row, :n_accept]) + [
                int(preds_np[row, n_accept])
            ]
            out[row].extend(int(t) for t in emitted)
            proposed_total += k_spec
            accepted_total += n_accept
            pos[row] += n_accept + 1  # rewound past any rejected slots
            last_np[row] = out[row][-1] if len(out[row]) < steps else (
                out[row][steps - 1]
            )

    stats = {
        "proposed": proposed_total,
        "accepted": accepted_total,
        "acceptance_rate": (
            accepted_total / proposed_total if proposed_total else 0.0
        ),
    }
    return jnp.asarray([o[:steps] for o in out], jnp.int32), stats


def _apply_spec_round(outer, engine, active, preds_np, props_np,
                      k_spec=None) -> dict:
    """Accept/emit/rewind/stats for one SERVING speculative round — the
    ONE home for the per-slot acceptance walk, the retired-mid-round
    guard, and the consumed-proposals stat discipline, shared by the
    continuous and paged spec engines so their emission semantics and
    reported acceptance_rate cannot drift.

    ``outer`` carries k_spec/proposed/accepted; ``engine`` is the inner
    batcher (slots/positions/_note_token). ``k_spec`` overrides the
    round's draft length (adaptive ragged rounds propose
    outer.k_cur ≤ outer.k_spec); defaults to outer.k_spec. Returns
    {slot: n_accept} so paged callers can roll back the rejected
    suffix's block-pool writes."""
    k = outer.k_spec if k_spec is None else k_spec
    outer.rounds += 1
    accepts: dict[int, int] = {}
    for slot in active:
        n_accept = 0
        while (
            n_accept < k
            and preds_np[slot, n_accept] == props_np[slot, n_accept]
        ):
            n_accept += 1
        emitted = list(props_np[slot, :n_accept]) + [
            int(preds_np[slot, n_accept])
        ]
        consumed = 0
        for tok in emitted:
            if engine._by_slot[slot] is None:
                break  # retired mid-round (EOS/budget): drop the rest
            engine._note_token(slot, int(tok))
            consumed += 1
        # Rewind the pointer past any rejected slots; stale cache/pool
        # entries beyond it are causally invisible and overwritten next
        # round. A retired slot's position resets at its next admit.
        engine.positions[slot] += n_accept + 1
        # Stats count only what the request actually consumed: a slot
        # that retired mid-round discards its tail proposals, and
        # counting them would skew acceptance_rate low near retirements
        # (it is a REPORTED serving metric).
        if consumed == len(emitted):
            outer.proposed += k
            outer.accepted += n_accept
        else:
            outer.proposed += consumed
            outer.accepted += min(consumed, n_accept)
        accepts[slot] = n_accept
    return accepts


class _SpecServingBase:
    """Shared scaffolding for the speculative SERVING engines (continuous
    and paged): the greedy-only guard, the inner-engine subclass whose
    hooks keep the dense draft cache in lockstep, the draft state
    (+ optional tp sharding), the delegated public surface, and the
    proposed/accepted stats that _apply_spec_round updates. One home, so
    an edit to any of these cannot drift the engines apart."""

    @staticmethod
    def _require_greedy(gen) -> None:
        # Both greedy spellings pass: temperature=0.0 AND the
        # sampling-off default temperature=None (None != 0.0, so the
        # naive comparison used to reject the default config).
        if gen.temperature is not None and gen.temperature != 0.0:
            raise ValueError(
                "speculative serving is greedy-only (temperature must be 0: "
                "acceptance compares argmaxes, sampling would break the "
                "exactness guarantee)"
            )

    def _make_inner(self, engine_cls):
        """Subclass of the inner serving engine wired to this wrapper:
        admits prefill the draft, releases clear its mask rows, and the
        step IS the speculative round."""
        outer = self

        class _Inner(engine_cls):
            supports_logprobs = False  # verified tokens are argmax rounds

            def submit(self, prompt, max_new_tokens=None, temperature=None,
                       logit_bias=None, **kw):
                # Speculative serving is greedy-only (acceptance compares
                # argmaxes) — a sampled request would be silently served
                # greedy, so reject it where the engine-wide guard lives.
                if temperature:
                    raise ValueError(
                        "speculative serving is greedy-only; per-request "
                        f"temperature {temperature} is not supported"
                    )
                if logit_bias:
                    raise ValueError(
                        "speculative serving does not support logit_bias "
                        "(verification compares UNbiased argmaxes)"
                    )
                return super().submit(
                    prompt, max_new_tokens=max_new_tokens, **kw
                )

            def _post_admit(self, slot, padded, prompt_mask):
                outer._admit_draft(slot, padded, prompt_mask)

            def _release_slot(self, slot):
                super()._release_slot(slot)
                outer.draft_kv_mask = outer.draft_kv_mask.at[slot].set(False)

            def _step(self):
                outer._spec_step()

        return _Inner

    def _init_draft(self, draft_params, draft_cfg, slots, draft_len,
                    k_spec, plan, kv_bits) -> None:
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.k_spec = k_spec
        self.draft_cache = init_kv_cache(draft_cfg, slots, draft_len,
                                         kv_bits=kv_bits)
        self.draft_kv_mask = jnp.zeros((slots, draft_len), bool)
        if plan is not None:
            # The draft rides the same mesh: its params shard by the same
            # tp rules, its cache's kv-head axis over tp. GSPMD propagates
            # through _draft_propose and the verify program — psum for tp
            # matmuls, no code change. Cache first: shard_kv_cache owns
            # the tp-divides-kv-heads validation (the draft's head count
            # can differ from the target's), and must fire before params
            # are placed.
            self.draft_cache = plan.shard_kv_cache(self.draft_cache)
            self.draft_params = plan.shard_params(draft_params)
        self.proposed = 0
        self.accepted = 0
        self.rounds = 0
        # Adaptive ragged rounds move k_cur within 1..k_spec; every other
        # path proposes the full k_spec (it is a static program arg).
        self.k_cur = k_spec
        self._accept_ema = None  # EMA of per-round acceptance (adaptive)

    # -- public surface (delegated) ----------------------------------------

    def submit(self, prompt, max_new_tokens=None, temperature=None,
               stop=None, logit_bias=None, deadline_s=None) -> int:
        # Delegated verbatim: the inner engine owns the greedy-only
        # temperature/logit_bias rejections, so library and HTTP callers
        # get the same ValueError.
        return self._engine.submit(prompt, max_new_tokens=max_new_tokens,
                                   temperature=temperature, stop=stop,
                                   logit_bias=logit_bias,
                                   deadline_s=deadline_s)

    def run(self) -> dict:
        return self._engine.run()

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0

    def spec_stats(self) -> dict:
        """The /stats "speculative" block (server.py mirrors accepted/
        rounds deltas into the metric registry; signals.py windows them
        into fleet rates)."""
        return {
            "rounds": self.rounds,
            "proposed": self.proposed,
            "accepted": self.accepted,
            "acceptance_rate": self.acceptance_rate,
            "draft_len": self.k_cur,
        }

    # -- internals ---------------------------------------------------------

    def _adapt_draft_len(self, n_proposed: int, n_accepted: int) -> None:
        """Acceptance-rate-adaptive draft length (EMA-smoothed): a draft
        that keeps getting rejected wastes verify rows in the shared
        ragged token budget, so k_cur shrinks toward 1; sustained high
        acceptance grows it back toward k_spec. Only the ragged
        scheduling mode consults k_cur per round — the fixed-slot
        programs bake k_spec in as a static arg."""
        if not n_proposed:
            return
        r = n_accepted / n_proposed
        self._accept_ema = (
            r if self._accept_ema is None
            else 0.8 * self._accept_ema + 0.2 * r
        )
        if self._accept_ema >= 0.8 and self.k_cur < self.k_spec:
            self.k_cur += 1
        elif self._accept_ema < 0.4 and self.k_cur > 1:
            self.k_cur -= 1

    def _admit_draft(self, slot, padded, prompt_mask) -> None:
        from kubeflow_tpu.models.continuous import _admit_slot

        _, self.draft_cache, self.draft_kv_mask = _admit_slot(
            self.draft_params, self.draft_cfg, padded, prompt_mask,
            self.draft_cache, self.draft_kv_mask,
            jnp.asarray(slot, jnp.int32),
        )


class SpeculativeContinuousBatcher(_SpecServingBase):
    """Continuous batching with speculative decoding as the STEP engine:
    every serving round, the draft proposes k tokens per slot and the
    target verifies them in one (B, k+1) forward at per-slot offsets —
    the per-row cache-pointer machinery above, applied to the fixed-slot
    server's persistent caches. Every request's output follows the greedy
    path of its own prompt (tie-tolerant: the verify chunk computes
    logits in a different shape than single-token decode, so bf16
    NEAR-TIES may break differently — same caveat as every cross-shape
    greedy comparison in this stack); throughput multiplies by
    ~(accepted+1) per target read when the draft agrees often.

    Greedy-only: acceptance compares argmaxes, so a sampling temperature
    would break the exactness guarantee — rejected at construction.

    >>> sb = SpeculativeContinuousBatcher(params, cfg, dparams, dcfg,
    ...                                   slots=4, cache_len=256)
    >>> rids = [sb.submit(p) for p in prompts]
    >>> results = sb.run()                  # {rid: tokens}
    >>> sb.acceptance_rate                  # serving-level stat
    """

    def __init__(
        self,
        params: dict,
        target_cfg: LlamaConfig,
        draft_params: dict,
        draft_cfg: LlamaConfig,
        gen=None,
        slots: int = 8,
        cache_len: int = 1024,
        prompt_bucket: int = 64,
        key=None,
        k_spec: int = 4,
        plan=None,  # parallel.mesh.MeshPlan → tp-sharded spec serving
        kv_bits: int = 0,  # 8 → int8 KV for BOTH target and draft caches
    ):
        from kubeflow_tpu.models.continuous import ContinuousBatcher
        from kubeflow_tpu.models.serving import GenerationConfig

        gen = gen or GenerationConfig()
        self._require_greedy(gen)
        if plan is not None and plan.mesh.shape.get("sp", 1) > 1:
            raise ValueError(
                "SpeculativeContinuousBatcher does not support sp-sharded "
                "meshes: draft-propose and target-verify run the chunked "
                "decode (K>1 tokens per step), which has no split-KV sp "
                "merge; use tp (and dp/fsdp) axes, or ContinuousBatcher "
                "for sp-sharded caches"
            )
        # Spec rounds write up to k_spec+1 slots beyond the pointer before
        # rewinding; the cache needs that headroom past the nominal span.
        if prompt_bucket + gen.max_new_tokens + k_spec + 1 > cache_len:
            raise ValueError(
                f"cache_len {cache_len} too small for prompt_bucket "
                f"{prompt_bucket} + max_new_tokens {gen.max_new_tokens} + "
                f"k_spec {k_spec} + 1 speculative headroom"
            )

        self._engine = self._cb = self._make_inner(ContinuousBatcher)(
            params, target_cfg, gen=gen, slots=slots, cache_len=cache_len,
            prompt_bucket=prompt_bucket, key=key, plan=plan, kv_bits=kv_bits,
        )
        self._init_draft(draft_params, draft_cfg, slots, cache_len,
                         k_spec, plan, kv_bits)

    def _spec_step(self) -> None:
        cb = self._cb
        active = [i for i, r in enumerate(cb._by_slot) if r is not None]
        if not active:
            return
        positions = jnp.asarray(cb.positions, jnp.int32)
        last = jnp.asarray(cb.tokens, jnp.int32)  # (B, 1) per-slot input
        proposals, self.draft_cache = _draft_propose(
            self.draft_params, self.draft_cfg, last, self.draft_cache,
            positions, self.k_spec, kv_mask=self.draft_kv_mask,
        )
        chunk = jnp.concatenate([last, proposals], axis=1)
        preds, cb.cache = _target_verify(
            cb.params, cb.cfg, chunk, cb.cache, positions,
            kv_mask=cb.kv_mask,
        )
        _apply_spec_round(self, cb, active, np.asarray(preds),
                          np.asarray(proposals))


class SpeculativePagedBatcher(_SpecServingBase):
    """Speculative decoding over the PAGED block pool: the draft proposes
    k tokens per slot from a dense side cache, and the target verifies
    them in one (B, k+1) forward that reads/writes THROUGH the block
    tables (models.paged._paged_verify) — vLLM's spec-over-paged
    composition. Memory stays pool-sized (the paged advantage) while
    throughput multiplies by the acceptance rate; the greedy invariant is
    the same as every spec engine here (tie-tolerant across chunk
    shapes).

    The draft cache is DENSE per slot: the draft is small by design, so
    paging it would spend table-gather overhead to save little memory;
    the pool pays for the big target cache, which is the one that
    matters.

    >>> sb = SpeculativePagedBatcher(params, cfg, dparams, dcfg,
    ...                              slots=4, num_blocks=64)
    >>> rids = [sb.submit(p) for p in prompts]
    >>> results = sb.run()
    >>> sb.acceptance_rate
    """

    def __init__(
        self,
        params: dict,
        target_cfg: LlamaConfig,
        draft_params: dict,
        draft_cfg: LlamaConfig,
        gen=None,
        slots: int = 4,
        num_blocks: int = 64,
        block_size: int = 16,
        prompt_bucket: int = 64,
        key=None,
        k_spec: int = 4,
        plan=None,  # parallel.mesh.MeshPlan → tp-sharded spec serving
        kv_bits: int = 0,  # 8 → int8 pool AND draft cache
        headroom_tokens: int = 0,  # extra table span beyond k_spec+1
        prompt_cache: bool = False,  # share identical prompts' TARGET blocks
        prefix_cache: bool = False,  # share common-prefix TARGET blocks
        admit_chunk=None,  # prefix-admission piece width (PagedBatcher)
        ragged: bool = False,  # speculation as a ragged scheduling mode
        token_budget=None,  # ragged: verify+prefill rows per fused step
        adaptive: bool = False,  # ragged: acceptance-adaptive draft len
        attn_kernel=None,  # forwarded to PagedBatcher (ragged verify)
    ):
        from kubeflow_tpu.models.paged import PagedBatcher
        from kubeflow_tpu.models.serving import GenerationConfig

        gen = gen or GenerationConfig()
        self._require_greedy(gen)
        if adaptive and not ragged:
            raise ValueError(
                "adaptive=True requires ragged=True: the fixed-slot "
                "verify program bakes k_spec in as a static shape; only "
                "ragged rounds can vary the span length per step"
            )
        if ragged:
            # Every decoding slot contributes 1+k_spec verify rows to the
            # fused dispatch; the budget must hold a full-house round
            # (admission chunks ride whatever is left).
            if token_budget is None:
                token_budget = max(512, slots * (k_spec + 1))
            if token_budget < slots * (k_spec + 1):
                raise ValueError(
                    f"token_budget {token_budget} < slots*(k_spec+1) = "
                    f"{slots * (k_spec + 1)}: every decoding slot "
                    "contributes 1+k_spec verify rows per ragged step"
                )
        self.adaptive = bool(adaptive)
        self._engine = self._pb = self._make_inner(PagedBatcher)(
            params, target_cfg, gen=gen, slots=slots, num_blocks=num_blocks,
            block_size=block_size, prompt_bucket=prompt_bucket, key=key,
            plan=plan, kv_bits=kv_bits,
            # A spec round writes up to k_spec+1 slots past the pointer
            # before rewinding; the block tables must span those too.
            # Caller ``headroom_tokens`` adds on top — e.g. to pin
            # max_blocks (and so every compiled shape) constant across
            # configs with different max_new_tokens.
            headroom_tokens=k_spec + 1 + headroom_tokens,
            # A hit skips only the TARGET prefill (whole-prompt or
            # per-block prefix); the dense draft cache is per-slot state
            # and re-prefills through _post_admit.
            prompt_cache=prompt_cache,
            prefix_cache=prefix_cache,
            admit_chunk=admit_chunk,
            ragged=ragged, token_budget=token_budget,
            attn_kernel=attn_kernel,
        )
        # Dense draft cache spanning the pool's logical window (bucket
        # overhang on preempted continuations included — max_blocks
        # already accounts for it). sp is rejected by PagedBatcher itself
        # (no contiguous sequence axis).
        self._init_draft(draft_params, draft_cfg, slots,
                         self._pb.max_blocks * block_size, k_spec, plan,
                         kv_bits)

    @property
    def free_blocks(self) -> int:
        return self._pb.free_blocks

    def _spec_step(self) -> None:
        if self._pb.ragged:
            self._spec_step_ragged()
            return
        from kubeflow_tpu.models.paged import _paged_verify

        pb = self._pb
        # Allocate blocks covering the whole verify chunk up front (the
        # call may preempt; it returns the post-preemption active set).
        active = pb._ensure_step_blocks(span=self.k_spec + 1)
        if not active:
            return
        positions = jnp.asarray(pb.positions, jnp.int32)
        last = jnp.asarray(pb.tokens, jnp.int32)  # (B, 1) per-slot input
        proposals, self.draft_cache = _draft_propose(
            self.draft_params, self.draft_cfg, last, self.draft_cache,
            positions, self.k_spec, kv_mask=self.draft_kv_mask,
        )
        chunk = jnp.concatenate([last, proposals], axis=1)
        preds, pb.pool = _paged_verify(
            pb.params, pb.cfg, chunk, pb.pool, jnp.array(pb.tables),
            positions, pb.kv_mask, pb.block_size,
        )
        _apply_spec_round(self, pb, active, np.asarray(preds),
                          np.asarray(proposals))

    def _spec_step_ragged(self) -> None:
        """One speculative round as a RAGGED scheduling mode: each
        decoding slot contributes a (1 + k) verify span — its last
        token plus the draft's k proposals — to the SAME fused dispatch
        that carries admission prefill chunks; the verify rows land
        in the paged blocks through the tables exactly like decode
        rows (span causality comes from the kernel's position bound,
        so a span never sees its own later rows' writes).

        Rollback protocol: the (1+k) cells each span will write are
        snapshotted BEFORE the dispatch; after the acceptance walk the
        rejected suffix's cells are restored byte-identical and any
        trailing blocks the rewound pointer no longer covers are freed
        — the pool ends every round exactly as if the accepted tokens
        had been decoded one at a time."""
        from kubeflow_tpu.models.paged import (
            _gather_cells,
            _paged_ragged_verify,
            _restore_cells,
        )

        pb = self._pb
        pb._expire_ragged_admissions()
        k = self.k_cur if self.adaptive else self.k_spec
        # Allocate blocks covering every slot's whole verify span up
        # front (may preempt; returns the post-preemption active set).
        active = pb._ensure_step_blocks(span=k + 1)
        if not active and not pb._ragged_admit:
            return
        props_np = None
        if active:
            positions = jnp.asarray(pb.positions, jnp.int32)
            last = jnp.asarray(pb.tokens, jnp.int32)  # (B, 1) inputs
            proposals, self.draft_cache = _draft_propose(
                self.draft_params, self.draft_cfg, last, self.draft_cache,
                positions, k, kv_mask=self.draft_kv_mask,
            )
            props_np = np.asarray(proposals)
        spans = {
            slot: (
                [int(pb.tokens[slot, 0])]
                + [int(t) for t in props_np[slot]],
                int(pb.positions[slot]),
            )
            for slot in active
        }
        (tokens, tok_pos, tok_seq, seq_starts, seq_lens, kv_lens,
         last_rows, rows, completing) = pb._assemble_ragged(spans)
        if rows == 0:
            return
        # Snapshot the cells every verify span will write (positions
        # p0..p0+k per slot) so the rejected suffix can be rolled back
        # byte-identical. Cell lists are ordered [slot-major, offset-
        # minor]: span i's offset j lives at index i*(k+1)+j.
        cell_blks: list[int] = []
        cell_offs: list[int] = []
        for slot in active:
            req = pb._by_slot[slot]
            p0 = int(pb.positions[slot])
            for j in range(k + 1):
                pos = p0 + j
                cell_blks.append(req.blocks[pos // pb.block_size])
                cell_offs.append(pos % pb.block_size)
        snap = (_gather_cells(pb.pool, cell_blks, cell_offs)
                if cell_blks else None)
        width = pb._dispatch_width(rows)
        preds, pb.pool = _paged_ragged_verify(
            pb.params, pb.cfg, jnp.array(tokens[:width]), pb.pool,
            jnp.array(pb.tables), pb.kv_mask,
            jnp.array(tok_pos[:width]), jnp.array(tok_seq[:width]),
            jnp.asarray(rows, jnp.int32), jnp.array(seq_starts),
            jnp.array(seq_lens), jnp.array(kv_lens), pb.block_size,
            attn_kernel=pb.attn_kernel, adapters=pb._ragged_adapters(),
        )
        pb._stamp_ragged(rows, decode_rows=(k + 1) * len(active))
        host_preds = np.asarray(preds)
        if active:
            # Per-slot verdicts, indexed by SLOT like the fixed-slot
            # path so _apply_spec_round is shared verbatim.
            preds_mat = np.zeros((pb.slots, k + 1), host_preds.dtype)
            for slot in active:
                s0 = int(seq_starts[slot])
                preds_mat[slot] = host_preds[s0:s0 + k + 1]
            p_before, a_before = self.proposed, self.accepted
            accepts = _apply_spec_round(self, pb, active, preds_mat,
                                        props_np, k_spec=k)
            # Roll back the rejected suffix: restore its cells to the
            # pre-dispatch bytes in ONE scatter. Restores into blocks a
            # retired slot already freed are harmless (the cells are
            # re-zeroed or rewritten at the block's next allocation).
            idx = [
                j
                for i, slot in enumerate(active)
                for j in range(i * (k + 1) + accepts[slot] + 1,
                               (i + 1) * (k + 1))
            ]
            if idx:
                pb.pool = _restore_cells(
                    pb.pool,
                    {name: leaf[np.asarray(idx)]
                     for name, leaf in snap.items()},
                    [cell_blks[j] for j in idx],
                    [cell_offs[j] for j in idx],
                )
            # Free trailing blocks the rewound pointer no longer covers,
            # leaving each live slot with exactly the lazily-grown block
            # count the never-speculated path would hold.
            for slot in active:
                req = pb._by_slot[slot]
                if req is None:
                    continue  # retired mid-round: blocks already freed
                keep = (int(pb.positions[slot]) - 1) // pb.block_size + 1
                while len(req.blocks) > max(keep, 1):
                    blk = req.blocks.pop()
                    pb.tables[slot, len(req.blocks)] = 0
                    pb._free.append(blk)
            if self.adaptive:
                self._adapt_draft_len(self.proposed - p_before,
                                      self.accepted - a_before)
        # Admissions whose last prompt chunk rode this dispatch: their
        # first token is the argmax at their span's last row (no
        # logprob — verify dispatches are argmax-only).
        pb._complete_ragged_admissions(
            completing,
            {s: int(host_preds[int(last_rows[s])]) for s in completing},
            {},
        )


def truncated_draft(params: dict, cfg: LlamaConfig,
                    n_layers: int) -> tuple[dict, LlamaConfig]:
    """A zero-training draft from the TARGET's own weights: keep the
    first ``n_layers`` of the stacked layer axis, share embed/final-norm/
    lm-head. Early layers carry most next-token signal on trained
    models, so this gives a usable acceptance rate with no second
    checkpoint and no extra HBM beyond the sliced layer stack — the
    standard self-speculative deployment shortcut.

    Returns (draft_params, draft_cfg) ready for any spec engine."""
    import dataclasses

    if not 1 <= n_layers < cfg.n_layers:
        raise ValueError(
            f"draft n_layers must be in 1..{cfg.n_layers - 1}, "
            f"got {n_layers}"
        )
    draft = dict(params)
    draft["layers"] = jax.tree.map(lambda w: w[:n_layers], params["layers"])
    return draft, dataclasses.replace(cfg, n_layers=n_layers)
