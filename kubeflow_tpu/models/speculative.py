"""Greedy speculative decoding: a small draft model proposes, the target
verifies k tokens per step in ONE forward.

Decode at bs=1 is HBM-bound on the TARGET's weights; verification reads
them once per k proposed tokens instead of once per token, so wall-clock
approaches (accepted+1)/k_spec × the plain decode cost when the draft
agrees often (same-family small model). Greedy acceptance makes the
output EXACTLY the target's greedy decoding — tested token-for-token —
so speculation is a pure latency optimization, never a quality trade.

Mechanics per round (cache-pointer discipline is the subtle part):
- draft autoregressively proposes d_1..d_k from its own cache,
- target runs one chunked forward over [prev_token, d_1..d_k] (k+1 wide,
  so every proposal is acceptable) at the current cache offset via
  llama._decode_chunk_impl — the same body ordinary decode uses, with
  vector positions; stale slots beyond the pointer are overwritten next
  round and causally masked meanwhile,
- accept the longest prefix where target argmax matches the proposal,
  emit the target's own next token as the correction, and REWIND both
  caches' write pointers to the accepted length.

No reference counterpart (control plane only — SURVEY.md §2.5).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.llama import (
    LlamaConfig,
    _decode_chunk_impl,
    _decode_impl,
    _prefill_impl,
    init_kv_cache,
)


@partial(jax.jit, static_argnames=("cfg", "k_spec"))
def _draft_propose(params, cfg, token, kv_cache, position, k_spec):
    """Draft k_spec greedy tokens autoregressively from ``token``.

    Runs k_spec+1 decode steps: each step WRITES its input token's K/V,
    so the extra step is what lands d_k in the draft cache — on a fully
    accepted round the next round continues from position+k_spec+1 and a
    missing d_k entry would silently degrade later proposals (a hole the
    target's verification can't see)."""

    def step(carry, _):
        tok, cache, pos = carry
        logits, cache = _decode_impl(params, cfg, tok, cache, pos)
        nxt = jnp.argmax(logits, axis=-1)[:, None]
        return (nxt, cache, pos + 1), nxt[:, 0]

    (_, cache, _), sampled = jax.lax.scan(
        step, (token, kv_cache, position), length=k_spec + 1
    )
    return sampled.T[:, :k_spec], cache  # (B, k_spec); last sample unused


@partial(jax.jit, static_argnames=("cfg",))
def _target_verify(params, cfg, chunk, kv_cache, start_pos):
    logits, cache = _decode_chunk_impl(params, cfg, chunk, kv_cache, start_pos)
    return jnp.argmax(logits, axis=-1), cache  # (B, K)


def speculative_generate(
    target_params: dict,
    target_cfg: LlamaConfig,
    draft_params: dict,
    draft_cfg: LlamaConfig,
    prompt: jax.Array,  # (1, S) — bs=1, the latency-bound case
    steps: int,
    cache_len: int,
    k_spec: int = 4,
) -> tuple[jax.Array, dict]:
    """Greedy speculative decoding. Returns (tokens (1, steps), stats).

    Output is IDENTICAL to target-only greedy decoding; stats reports the
    acceptance rate that determines the speedup.
    """
    if prompt.shape[0] != 1:
        raise NotImplementedError("speculative decoding is bs=1 here")
    b, s_prompt = prompt.shape
    # Fixed-shape rounds need headroom for a full k_spec chunk even on
    # the last round; enforcing it up front keeps the (1, steps) output
    # contract AND pins every round to ONE compiled shape (a shrinking
    # tail k would retrace mid-decode).
    needed = s_prompt + steps + k_spec
    if cache_len < needed:
        raise ValueError(
            f"cache_len {cache_len} < prompt ({s_prompt}) + steps "
            f"({steps}) + k_spec ({k_spec}) = {needed}"
        )
    t_cache = init_kv_cache(target_cfg, b, cache_len)
    d_cache = init_kv_cache(draft_cfg, b, cache_len)

    t_logits, t_cache = _prefill_impl(target_params, target_cfg, prompt, t_cache)
    _, d_cache = _prefill_impl(draft_params, draft_cfg, prompt, d_cache)
    last = jnp.argmax(t_logits, axis=-1)[:, None]  # first generated token

    out: list[int] = [int(last[0, 0])]
    pos = s_prompt  # both caches hold [0, pos) real entries
    proposed_total = accepted_total = 0

    while len(out) < steps:
        # Always a FULL k_spec round (one compiled shape); surplus
        # acceptances past ``steps`` are trimmed host-side below.
        k = k_spec
        proposals, d_cache = _draft_propose(
            draft_params, draft_cfg, last, d_cache, jnp.asarray(pos, jnp.int32), k
        )
        # Chunk is (k+1) wide so EVERY proposal is acceptable: pred i is
        # the target's next token after ...[last, d_1..d_i].
        chunk = jnp.concatenate([last, proposals], axis=1)
        preds, t_cache = _target_verify(
            target_params, target_cfg, chunk, t_cache, jnp.asarray(pos, jnp.int32)
        )
        preds_np = np.asarray(preds[0])
        props_np = np.asarray(proposals[0])
        n_accept = 0
        while n_accept < k and preds_np[n_accept] == props_np[n_accept]:
            n_accept += 1
        # Emit accepted proposals + the target's own correction. When all
        # k were accepted the "correction" is the target's free token for
        # position pos+k (preds[k]).
        emitted = list(props_np[:n_accept]) + [int(preds_np[n_accept])]
        out.extend(int(t) for t in emitted)
        proposed_total += k
        accepted_total += n_accept
        pos += n_accept + 1  # rewound past any rejected slots
        last = jnp.asarray([[out[-1]]], jnp.int32)

    stats = {
        "proposed": proposed_total,
        "accepted": accepted_total,
        "acceptance_rate": (
            accepted_total / proposed_total if proposed_total else 0.0
        ),
    }
    return jnp.asarray([out[:steps]], jnp.int32), stats
