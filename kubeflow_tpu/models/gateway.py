"""Fleet-scale serving gateway: prefix-affinity routing over N replicas.

One engine per slice is the single-replica ceiling; this module is the
front door over a FLEET of ``models/server.py`` InferenceServer replicas.
The routing policy is the point: requests are placed by **consistent-hash
prefix affinity** — the gateway walks the request's prompt through the
same vLLM-style block chain hash the paged engine's prefix cache uses
(``PagedBatcher._chain_key``), finds the longest chain prefix any earlier
request shared, and hashes THAT key onto a virtual-node ring. Repeated
system prompts therefore land on the replica whose block-pool prefix
cache is already warm instead of re-prefilling cold on a random replica;
``loadtest/serve_fleet.py`` measures the difference against the
``random`` control arm on the same fleet.

Integration with the existing stack, layer by layer:

- **health/drain (PR-2 lifecycle):** a background probe loop GETs each
  replica's ``/healthz``; ``draining`` (503 the instant a drain starts)
  or an unreachable replica leaves the ring immediately — in-flight
  streams on it finish (the replica's own drain budget protects them),
  new work routes around it. A replica that comes back re-enters the
  ring with minimal key movement (virtual nodes).
- **bounded re-route:** a connect failure or a 503/429 answered BEFORE
  any byte was relayed walks to the next distinct ring node, at most
  ``reroute_budget`` alternates per request; the walk order is the ring
  successor order, so a key's traffic stays maximally stable.
- **tenant-fair load-shed:** when the whole fleet is at the gateway's
  in-flight capacity, tenants above their fair share
  (``ceil(capacity / active_tenants)``) are shed with 429 + Retry-After;
  a tenant under its share is never shed by a noisy neighbor.
- **streaming passthrough:** SSE bytes are relayed as they arrive; the
  client's per-request ``deadline_s`` is decremented by gateway queueing
  time before forwarding, and a client disconnect closes the upstream
  connection so the replica's own ``_client_gone`` peek cancels the
  request engine-side — cancellation is end-to-end.
- **elastic capacity (controller/slicepool.py):** ``WarmSliceReplicaSource``
  claims warm placeholder slices through the same ``claim_warm_slice``
  path notebook spawns use (misses stamp the demand annotations the pool
  autoscaler reads), so the fleet can follow load.

The gateway itself never imports the jax stack — it is pure stdlib +
numpy and can run on a CPU-only pod in front of TPU-backed replicas.
"""

from __future__ import annotations

import bisect
import hashlib
import http.client
import itertools
import json
import math
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from kubeflow_tpu.models.autoscaler import (
    FleetAutoscaler,
    autoscaler_from_env,
)
from kubeflow_tpu.models.server import BodyTooLarge, _client_gone, _read_body
from kubeflow_tpu.observability import tracing
from kubeflow_tpu.observability.signals import FleetTelemetry, TenantBuckets

AFFINITY_MODES = ("prefix", "random")


def chain_key(parent: Optional[bytes], tokens,
              adapter: Optional[int] = None) -> bytes:
    """Content address of one full prompt block given its prefix chain —
    byte-for-byte ``PagedBatcher._chain_key`` (tests assert the parity),
    duplicated here so routing never imports the jax stack. ``adapter``
    salts the ROOT (a LoRA adapter changes every K/V the same tokens
    produce, so chains fork at their first block); None keeps the legacy
    base-model root byte-for-byte."""
    if parent is None:
        parent = (b"root" if adapter is None
                  else b"root|adapter:%d" % int(adapter))
    h = hashlib.sha1(parent)
    h.update(np.asarray(tokens, np.int32).tobytes())
    return h.digest()


class HashRing:
    """Consistent-hash ring with virtual nodes.

    Each node owns ``vnodes`` pseudo-random positions; a key routes to
    the first node position clockwise from its own hash. Join/leave
    moves only the keys in the joining/leaving node's arcs (~1/N of the
    space), which is the property the prefix cache needs: a replica
    joining must not reshuffle every tenant's warm prefix to a cold
    replica. ``seed`` perturbs every position so parallel fleets don't
    co-shard the same hot prefixes. Not thread-safe — callers lock.
    """

    def __init__(self, vnodes: int = 64, seed: int = 0):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = vnodes
        self.seed = seed
        self._members: set = set()
        self._keys: list = []   # sorted vnode positions
        self._owners: list = []  # node owning _keys[i]

    def _pos(self, label) -> int:
        if isinstance(label, str):
            label = label.encode()
        h = hashlib.sha1(b"%d|" % self.seed + label).digest()
        return int.from_bytes(h[:8], "big")

    def _rebuild(self) -> None:
        pairs = sorted(
            (self._pos(f"{node}#{i}".encode()), node)
            for node in self._members
            for i in range(self.vnodes)
        )
        self._keys = [p for p, _ in pairs]
        self._owners = [n for _, n in pairs]

    def add(self, node: str) -> None:
        if node not in self._members:
            self._members.add(node)
            self._rebuild()

    def remove(self, node: str) -> None:
        if node in self._members:
            self._members.discard(node)
            self._rebuild()

    def nodes(self) -> frozenset:
        return frozenset(self._members)

    def __len__(self) -> int:
        return len(self._members)

    def lookup(self, key: bytes) -> Optional[str]:
        nodes = self.successors(key, 1)
        return nodes[0] if nodes else None

    def successors(self, key: bytes, limit: int) -> list:
        """Up to ``limit`` DISTINCT nodes clockwise from the key's
        position — the primary replica first, then the re-route walk."""
        if not self._keys or limit < 1:
            return []
        idx = bisect.bisect_right(self._keys, self._pos(key))
        out: list = []
        seen: set = set()
        for j in range(len(self._keys)):
            node = self._owners[(idx + j) % len(self._keys)]
            if node not in seen:
                seen.add(node)
                out.append(node)
                if len(out) >= limit:
                    break
        return out


class PrefixRouter:
    """Longest-shared-prefix routing keys over the block chain hash.

    Walks the prompt's full blocks through ``chain_key`` and returns the
    deepest chain key some earlier request already produced — all
    requests sharing that prefix compute the same key and co-locate on
    one replica, exactly where the paged engine's prefix chain is warm.
    A never-seen prefix routes by its FIRST block's key (deterministic,
    so the tenant's very next request converges); prompts shorter than
    one block hash whole. The seen-registry is a bounded LRU — stale
    entries only cost one extra cold route after re-learning.
    """

    def __init__(self, block_size: int = 16, max_entries: int = 65536):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = block_size
        self.max_entries = max_entries
        self._seen: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def route_key(self, prompt: list) -> bytes:
        bs = self.block_size
        keys: list = []
        parent: Optional[bytes] = None
        for j in range(len(prompt) // bs):
            parent = chain_key(parent, prompt[j * bs:(j + 1) * bs])
            keys.append(parent)
        if not keys:
            keys = [chain_key(None, prompt)]
        with self._lock:
            best = keys[0]
            for k in keys:
                if k not in self._seen:
                    # A chain's key is only ever registered together with
                    # its whole parent chain, so the first miss ends the
                    # longest shared prefix.
                    break
                best = k
            for k in keys:
                self._seen[k] = None
                self._seen.move_to_end(k)
            while len(self._seen) > self.max_entries:
                self._seen.popitem(last=False)
        return best


def prompt_chain_keys(prompt: list, block_size: int) -> list:
    """Chain keys of the prompt's REGISTRABLE full blocks — the first
    ``(len(prompt) - 1) // block_size`` blocks, excluding the tail block
    the decode path mutates. Byte-identical to the key walk
    ``PagedBatcher.export_blocks`` stamps into a KV payload, so the
    gateway can negotiate suffix-only transfers (/kv/probe) without
    importing jax."""
    keys: list = []
    parent: Optional[bytes] = None
    for j in range((len(prompt) - 1) // block_size):
        parent = chain_key(
            parent, prompt[j * block_size:(j + 1) * block_size]
        )
        keys.append(parent)
    return keys


def _parse_endpoint(endpoint: str) -> tuple:
    """``host:port`` → (host, port), raising on garbage — a mistyped
    replica list must not silently route into nothing."""
    host, sep, port_s = endpoint.rpartition(":")
    if not sep or not host:
        raise ValueError(f"replica endpoint {endpoint!r}: want host:port")
    try:
        port = int(port_s)
    except ValueError:
        port = -1
    if not 1 <= port <= 65535:
        raise ValueError(f"replica endpoint {endpoint!r}: bad port")
    return host, port


class _Replica:
    __slots__ = ("endpoint", "host", "port", "healthy", "draining", "stats",
                 "role", "drain_pinned")

    def __init__(self, endpoint: str, role: str = "fused"):
        self.endpoint = endpoint
        self.host, self.port = _parse_endpoint(endpoint)
        self.healthy = True   # optimistic: routable until a probe says no
        self.draining = False
        # Gateway-side drain pin (autoscaler scale-down): the replica is
        # held out of the ring even while its healthz still says ok —
        # its own drain flips that shortly, but new streams must stop
        # routing here the moment the decision lands, not a probe later.
        self.drain_pinned = False
        self.stats: Optional[dict] = None  # last /stats scrape (subset)
        # Disaggregated tier membership: "fused" (default), "prefill", or
        # "decode" — from gateway config (tier lists) or the replica's
        # own /stats tier_role advertisement (config wins).
        self.role = role


class GatewayOverloadedError(RuntimeError):
    """The fleet is at capacity and this tenant is over its fair share."""


class ServingGateway:
    """HTTP gateway fronting N InferenceServer replicas (see module doc).

    >>> gw = ServingGateway(["127.0.0.1:8001", "127.0.0.1:8002"], port=0)
    >>> gw.start()
    >>> # POST http://{gw.host}:{gw.port}/v1/completions  (same API shape)
    >>> gw.stop()
    """

    def __init__(self, replicas=(), host: str = "127.0.0.1", port: int = 0,
                 affinity: str = "prefix", block_size: int = 16,
                 vnodes: int = 64, hash_seed: int = 0,
                 reroute_budget: int = 2,
                 health_interval_s: float = 0.5,
                 health_timeout_s: float = 2.0,
                 upstream_timeout_s: float = 120.0,
                 max_inflight: Optional[int] = None,
                 max_body_bytes: int = 4 << 20,
                 metrics=None, replica_source=None,
                 telemetry: Optional[FleetTelemetry] = None,
                 tenant_top_k: int = 8,
                 tier_mode: str = "fused",
                 tier_roles: Optional[dict] = None,
                 kv_transfer_timeout_s: float = 30.0,
                 kv_transfer_max_bytes: int = 64 << 20,
                 kv_peer_fanout: int = 0,
                 kv_peer_timeout_s: float = 5.0,
                 kv_peer_max_bytes: int = 64 << 20,
                 adapter_affinity: bool = True,
                 autoscaler_config=None,
                 autoscaler_provisioner=None):
        if affinity not in AFFINITY_MODES:
            raise ValueError(
                f"affinity must be one of {AFFINITY_MODES}, got {affinity!r}"
            )
        if reroute_budget < 0:
            raise ValueError(
                f"reroute_budget must be >= 0, got {reroute_budget}"
            )
        if tier_mode not in ("fused", "disagg"):
            raise ValueError(
                f"tier_mode must be 'fused' or 'disagg', got {tier_mode!r}"
            )
        if kv_transfer_timeout_s <= 0:
            raise ValueError(
                f"kv_transfer_timeout_s must be > 0, got "
                f"{kv_transfer_timeout_s}"
            )
        if kv_transfer_max_bytes < 1:
            raise ValueError(
                f"kv_transfer_max_bytes must be >= 1, got "
                f"{kv_transfer_max_bytes}"
            )
        if kv_peer_fanout < 0:
            raise ValueError(
                f"kv_peer_fanout must be >= 0, got {kv_peer_fanout}"
            )
        if kv_peer_timeout_s <= 0:
            raise ValueError(
                f"kv_peer_timeout_s must be > 0, got {kv_peer_timeout_s}"
            )
        if kv_peer_max_bytes < 1:
            raise ValueError(
                f"kv_peer_max_bytes must be >= 1, got {kv_peer_max_bytes}"
            )
        # Same opt-in as the replicas: KUBEFLOW_TPU_TRACE_* switches the
        # process-wide provider on; default stays the no-op tracer.
        tracing.configure_from_env()
        self.affinity = affinity
        # (prefix, adapter) affinity: fold the request's "model" field
        # into the route key so one adapter's tenants co-locate — each
        # replica's bounded hot-adapter cache then sees ~n_adapters/N
        # distinct adapters instead of all of them. False = the
        # adapter-oblivious baseline the loadtest measures against.
        self.adapter_affinity = bool(adapter_affinity)
        self.reroute_budget = reroute_budget
        # Disaggregated prefill/decode serving: in "disagg" mode a
        # streaming token-id request prefills on the prefill tier, ships
        # its paged-KV payload to the decode tier, and streams from
        # there; everything else (and every transfer failure, within the
        # re-route budget) falls back to the fused path below.
        self.tier_mode = tier_mode
        self._tier_roles = dict(tier_roles or {})
        self.kv_transfer_timeout_s = kv_transfer_timeout_s
        self.kv_transfer_max_bytes = kv_transfer_max_bytes
        self._kv_transfers = 0
        self._kv_transfer_failures = 0
        self._kv_transfer_bytes = 0
        self._kv_transfer_last_s = 0.0
        # Fleet KV tier (peer prefix fetch): read-through fetch of warm
        # prefix chains from bounded ring successors. kv_peer_fanout=0
        # (the default, and FANOUT unset in gateway_from_env) keeps the
        # tier fully inert: the hot path never computes chain keys for
        # it and never opens a peer socket.
        self.kv_peer_fanout = int(kv_peer_fanout)
        self.kv_peer_timeout_s = float(kv_peer_timeout_s)
        self.kv_peer_max_bytes = int(kv_peer_max_bytes)
        self._kv_peer_fetches = 0
        self._kv_peer_fetch_failures = 0
        self._kv_peer_bytes = 0
        self._kv_peer_fetch_last_s = 0.0
        self._kv_peer_fail_reasons: dict = {}
        self._kv_peer_quarantined = 0
        self._kv_peer_quarantine: list = []  # bounded: last 8 refusals
        self._kv_peer_single_flight_skips = 0
        self._kv_peer_negative_hits = 0
        # endpoint -> (monotonic deadline, consecutive failures): the
        # per-peer negative cache with exponential backoff.
        self._kv_peer_negative: dict = {}
        # chain tail keys (hex) with a fetch in flight: single-flight.
        self._kv_peer_inflight: set = set()
        self.health_interval_s = health_interval_s
        self.health_timeout_s = health_timeout_s
        self.upstream_timeout_s = upstream_timeout_s
        self.max_inflight = max_inflight
        self.max_body_bytes = max_body_bytes
        self.metrics = metrics
        self.replica_source = replica_source
        # Fleet telemetry plane (observability/signals.py): None unless a
        # FleetTelemetry is passed in or KUBEFLOW_TPU_SIGNALS_ENABLE opts
        # in — every feed below checks `is not None` first, so the
        # request hot path does zero telemetry work when disabled.
        self.telemetry = (
            telemetry if telemetry is not None
            else FleetTelemetry.from_env(metrics=metrics)
        )
        # The shed counter's tenant label stays bounded even when the
        # telemetry plane is off; share its buckets when it is on so the
        # Prometheus label and the per-tenant series always agree.
        self._tenant_buckets = (
            self.telemetry.tenants if self.telemetry is not None
            else TenantBuckets(tenant_top_k)
        )
        self._lock = threading.Lock()
        self._ring = HashRing(vnodes=vnodes, seed=hash_seed)
        self._router = PrefixRouter(block_size=block_size)
        self._spread = itertools.count()  # "random" arm: uniform, RNG-free
        self._replicas: dict = {}
        # Live-migration restore targets (pin_for_migration): excluded
        # from autoscaler scale-down victim selection until unpinned.
        self._migration_pins: set = set()
        # Tenant-fair admission state + the routing-report counters.
        self._inflight: dict = {}
        self._total_inflight = 0
        self._requests = 0
        self._reroutes = 0
        self._shed = 0
        self._failed = 0          # exhausted budget / mid-stream loss
        self._stopped = False
        self._started = False
        self._stop_evt = threading.Event()
        self._httpd = ThreadingHTTPServer((host, port), self._handler_class())
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="gateway-http", daemon=True
        )
        self._health_thread = threading.Thread(
            target=self._health_loop, name="gateway-health", daemon=True
        )
        for ep in replicas:
            self.add_replica(ep)
        # Fleet autoscaler (models/autoscaler.py): same inert-by-default
        # stance as the telemetry plane — None unless a config is passed
        # or KUBEFLOW_TPU_AUTOSCALE_ENABLE opts in. Ticks ride probe
        # passes, so a disabled autoscaler costs literally nothing.
        scale_cfg = (autoscaler_config if autoscaler_config is not None
                     else autoscaler_from_env())
        self.autoscaler = (
            FleetAutoscaler(self, scale_cfg,
                            provisioner=autoscaler_provisioner,
                            metrics=metrics)
            if scale_cfg is not None else None
        )

    # -- fleet membership --------------------------------------------------

    def add_replica(self, endpoint: str) -> None:
        """Register a replica and route to it immediately (optimistic —
        the next probe pass demotes it if it is not actually healthy).
        Idempotent; loadtests and the chaos harness call this mid-run."""
        rep = _Replica(endpoint,
                       role=self._tier_roles.get(endpoint, "fused"))
        with self._lock:
            if endpoint not in self._replicas:
                self._replicas[endpoint] = rep
                self._ring.add(endpoint)
            self._mirror_ring_locked()

    def remove_replica(self, endpoint: str) -> None:
        with self._lock:
            self._replicas.pop(endpoint, None)
            self._ring.remove(endpoint)
            self._mirror_ring_locked()
        if self.telemetry is not None:
            # Drop the rebase state and scrape timestamp: a departed
            # replica's growing scrape age must not freeze the
            # autoscaler, and a re-add restarts its counter base.
            self.telemetry.forget_replica(endpoint)

    def begin_drain(self, endpoint: str) -> bool:
        """Autoscaler scale-down entry: pull the replica from the ring
        NOW and pin it out (in-flight streams keep flowing straight to
        it; new requests route elsewhere, before any probe runs). The
        pin survives probe passes until ``remove_replica``. Returns
        False for endpoints this gateway does not know."""
        with self._lock:
            rep = self._replicas.get(endpoint)
            if rep is None:
                return False
            rep.drain_pinned = True
            rep.draining = True
            rep.healthy = False
            if endpoint in self._ring.nodes():
                self._ring.remove(endpoint)
            self._mirror_ring_locked()
        return True

    def pin_for_migration(self, endpoint: str) -> bool:
        """Mark a replica as a live-migration restore target: the
        autoscaler must not pick it as a scale-down victim while a
        checkpoint is being rebuilt onto it (a drain mid-restore would
        release the very slice the migration is landing on). Idempotent;
        returns False for endpoints this gateway does not know."""
        with self._lock:
            if endpoint not in self._replicas:
                return False
            self._migration_pins.add(endpoint)
        return True

    def unpin_for_migration(self, endpoint: str) -> None:
        """Release the migration pin (flip done or migration fell back);
        the endpoint becomes an ordinary scale-down candidate again.
        Unknown endpoints are a no-op — the pin set is self-cleaning."""
        with self._lock:
            self._migration_pins.discard(endpoint)

    def migration_pinned(self) -> frozenset:
        """Endpoints currently pinned as migration restore targets."""
        with self._lock:
            # Pins for replicas that left the fleet entirely must not
            # accumulate: intersect with live membership on read.
            self._migration_pins &= set(self._replicas)
            return frozenset(self._migration_pins)

    def replica_endpoints(self) -> list:
        with self._lock:
            return sorted(self._replicas)

    def ring_nodes(self) -> frozenset:
        with self._lock:
            return self._ring.nodes()

    def scale_up(self, now: Optional[float] = None) -> Optional[str]:
        """One more slice from the warm pool via the replica source
        (None without one). Returns the pool name the claim came from;
        the caller registers the endpoint with ``add_replica`` once the
        replica's InferenceServer reports healthy."""
        if self.replica_source is None:
            return None
        return self.replica_source.acquire(now=now)

    def _mirror_ring_locked(self) -> None:
        if self.metrics is not None:
            self.metrics.gateway_replicas.set(len(self._ring))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ServingGateway":
        self._started = True
        self._http_thread.start()
        self._health_thread.start()
        return self

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        self._stop_evt.set()
        if self._started:
            # shutdown() handshakes with serve_forever; on a never-
            # started gateway it would wait forever for a loop that
            # never ran, so only the socket is closed in that case.
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._started:
            self._health_thread.join(timeout=10)

    # -- health / scrape loop ----------------------------------------------

    def _health_loop(self) -> None:
        while not self._stop_evt.wait(self.health_interval_s):
            self.probe_once()

    def probe_once(self) -> None:
        """One probe pass over every registered replica (public so tests
        and the chaos harness can force a pass instead of sleeping):
        healthz 200 → in the ring; draining/unreachable → out. In-ring
        replicas also get a /stats scrape for the routing report."""
        for rep in list(self._replicas.values()):
            state = self._probe(rep)
            with self._lock:
                if rep.endpoint not in self._replicas:
                    continue  # removed while we probed
                rep.healthy = state == "ok" and not rep.drain_pinned
                rep.draining = state == "draining" or rep.drain_pinned
                in_ring = rep.endpoint in self._ring.nodes()
                if rep.healthy and not in_ring:
                    self._ring.add(rep.endpoint)
                elif not rep.healthy and in_ring:
                    self._ring.remove(rep.endpoint)
                self._mirror_ring_locked()
            if rep.healthy:
                scraped = self._scrape_stats(rep)
                # _scrape_stats hands back the SAME object on a failed
                # scrape: only a genuinely fresh payload may feed the
                # telemetry plane, or a replica whose /stats endpoint
                # wedged would keep refreshing its scrape age and mask
                # the staleness the autoscaler freeze exists to catch.
                fresh = scraped is not rep.stats
                rep.stats = scraped
                if rep.endpoint not in self._tier_roles:
                    # Tier membership follows the replica's own /stats
                    # advertisement unless the gateway's config pinned it.
                    role = (rep.stats or {}).get("tier_role")
                    if role in ("fused", "prefill", "decode"):
                        rep.role = role
                if self.telemetry is not None and fresh:
                    self.telemetry.ingest_replica(rep.endpoint, rep.stats)
        if self.telemetry is not None:
            with self._lock:
                ring_size = len(self._ring)
            self.telemetry.ingest_ring(ring_size)
            # Burn rates ride the probe cadence: cheap dict math over the
            # signal rings, and the latch/metric/span emission lives in
            # the engine, not here.
            self.telemetry.evaluate_slo()
        if self.autoscaler is not None:
            # The control loop rides the same cadence, AFTER the scrape/
            # SLO pass so each tick sees this pass's fresh signals.
            self.autoscaler.tick()

    def _probe(self, rep: _Replica) -> str:
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.health_timeout_s
            )
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                body = resp.read()
            finally:
                conn.close()
        except OSError:
            return "down"
        if resp.status == 200:
            return "ok"
        try:
            status = json.loads(body).get("status", "")
        except (ValueError, AttributeError):
            status = ""
        return "draining" if status == "draining" else "down"

    def _scrape_stats(self, rep: _Replica) -> Optional[dict]:
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.health_timeout_s
            )
            try:
                conn.request("GET", "/stats")
                resp = conn.getresponse()
                body = resp.read()
            finally:
                conn.close()
            stats = json.loads(body)
        except (OSError, ValueError):
            return rep.stats  # keep the last good scrape
        keep = {k: stats.get(k) for k in
                ("active_slots", "queued", "slots", "served",
                 "requests_shed", "tokens_generated",
                 "engine_step_stalls")}
        # Optional sub-dicts the telemetry plane turns into per-replica
        # gauges (queue-wait/inter-token percentiles, ragged fill,
        # prefix hit ratio); absent on engines without the feature.
        keep["tier_role"] = stats.get("tier_role")
        for extra in ("prefix_cache", "queue_wait_s", "inter_token_s",
                      "ragged", "flight", "kv_handoff", "speculative",
                      "lora_cache"):
            if extra in stats:
                keep[extra] = stats[extra]
        return keep

    # -- admission (tenant-fair shed) --------------------------------------

    def _capacity_locked(self) -> int:
        if self.max_inflight is not None:
            return self.max_inflight
        # Heuristic fleet capacity: slots + a queue's worth per routable
        # replica, from the last scrape (default 16 when unscraped yet).
        cap = 0
        for ep in self._ring.nodes():
            rep = self._replicas.get(ep)
            slots = (rep.stats or {}).get("slots") if rep else None
            cap += 2 * int(slots) if slots else 16
        return max(cap, 1)

    def _admit(self, tenant: str) -> None:
        with self._lock:
            cap = self._capacity_locked()
            if self._total_inflight >= cap:
                active = len(self._inflight) + (
                    0 if tenant in self._inflight else 1
                )
                share = math.ceil(cap / max(active, 1))
                if self._inflight.get(tenant, 0) >= share:
                    # Over fair share while the fleet is saturated: shed.
                    # A tenant *under* its share is still admitted (the
                    # overshoot is bounded by one share per tenant), so a
                    # noisy neighbor can never starve a light one.
                    self._shed += 1
                    bucket = self._tenant_buckets.bucket(tenant)
                    if self.metrics is not None:
                        self.metrics.gateway_shed_total.labels(
                            tenant=bucket
                        ).inc()
                    if self.telemetry is not None:
                        self.telemetry.observe_shed(tenant)
                    raise GatewayOverloadedError(
                        f"fleet at capacity ({cap} in flight); tenant "
                        f"{tenant!r} is over its fair share ({share})"
                    )
            self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
            self._total_inflight += 1

    def _release(self, tenant: str) -> None:
        with self._lock:
            left = self._inflight.get(tenant, 0) - 1
            if left > 0:
                self._inflight[tenant] = left
            else:
                self._inflight.pop(tenant, None)
            self._total_inflight = max(0, self._total_inflight - 1)

    # -- routing -----------------------------------------------------------

    def _route_key(self, prompt, adapter=None) -> bytes:
        if self.affinity == "random":
            # Counter-hashed: uniform spread with zero RNG state, and the
            # ring seed still decorrelates parallel fleets.
            return next(self._spread).to_bytes(8, "big")
        if isinstance(prompt, list) and all(
            isinstance(t, int) and not isinstance(t, bool) for t in prompt
        ):
            key = self._router.route_key(prompt)
        else:
            # Text prompts (tokenizer lives replica-side): whole-string
            # affinity — identical notebooks still co-locate.
            key = hashlib.sha1(repr(prompt).encode()).digest()
        if self.adapter_affinity and adapter is not None:
            # Fold the adapter AFTER the prefix walk: same prefix + same
            # adapter co-locate (warm chain AND hot adapter), while a
            # different adapter lands elsewhere on the ring instead of
            # thrashing the first replica's bounded adapter cache.
            key = hashlib.sha1(
                b"adapter|" + repr(adapter).encode() + b"|" + key
            ).digest()
        return key

    def _candidates(self, key: bytes) -> list:
        with self._lock:
            return self._ring.successors(key, self.reroute_budget + 1)

    def _tier_candidates(self, role: str, key: bytes) -> list:
        """Ring-ordered healthy replicas of one tier role.

        The full successor walk keeps prefix affinity *within* the tier:
        the first decode replica after the key's ring position is stable
        for a given prompt prefix, so its chain cache warms exactly like
        a fused replica's would.
        """
        with self._lock:
            out = []
            for ep in self._ring.successors(key, len(self._ring)):
                rep = self._replicas.get(ep)
                if rep is None:
                    continue
                if (rep.role or "fused") == role:
                    out.append(ep)
                    if len(out) >= self.reroute_budget + 1:
                        break
            return out

    def _count_reroute(self) -> None:
        with self._lock:
            self._reroutes += 1
        if self.metrics is not None:
            self.metrics.gateway_reroutes_total.inc()
        if self.telemetry is not None:
            self.telemetry.observe_reroute()

    def _count_request(self) -> None:
        with self._lock:
            self._requests += 1
        if self.metrics is not None:
            self.metrics.gateway_requests_total.inc()

    def _count_failed(self) -> None:
        with self._lock:
            self._failed += 1

    def _count_kv_transfer(self, ok: bool, nbytes: int,
                           latency_s: float) -> None:
        with self._lock:
            if ok:
                self._kv_transfers += 1
                self._kv_transfer_bytes += nbytes
                self._kv_transfer_last_s = latency_s
            else:
                self._kv_transfer_failures += 1
        if self.metrics is not None:
            if ok:
                self.metrics.serving_kv_transfer_total.inc()
                self.metrics.serving_kv_transfer_bytes_total.inc(nbytes)
                self.metrics.serving_kv_transfer_latency_seconds.set(
                    latency_s
                )
            else:
                self.metrics.serving_kv_transfer_failures_total.inc()
        if self.telemetry is not None:
            self.telemetry.observe_kv_transfer(nbytes, latency_s, ok=ok)

    # -- fleet KV tier (peer prefix fetch) bookkeeping ---------------------

    def _count_kv_peer_fetch(self, ok: bool, nbytes: int, latency_s: float,
                             reason: Optional[str] = None) -> None:
        with self._lock:
            if ok:
                self._kv_peer_fetches += 1
                self._kv_peer_bytes += nbytes
                self._kv_peer_fetch_last_s = latency_s
            else:
                self._kv_peer_fetch_failures += 1
                if reason:
                    self._kv_peer_fail_reasons[reason] = (
                        self._kv_peer_fail_reasons.get(reason, 0) + 1
                    )
        if self.metrics is not None:
            if ok:
                self.metrics.serving_kv_peer_fetch_total.inc()
                self.metrics.serving_kv_peer_bytes_total.inc(nbytes)
                self.metrics.serving_kv_peer_fetch_latency_seconds.set(
                    latency_s
                )
            else:
                self.metrics.serving_kv_peer_fetch_failures_total.inc()
        if self.telemetry is not None:
            self.telemetry.observe_kv_peer_fetch(nbytes, latency_s, ok=ok)

    def _kv_peer_backoff(self, endpoint: str) -> None:
        """A dead/slow/refusing peer trips the negative cache with
        per-peer exponential backoff: the fetch ladder must never probe
        a corpse twice in a row, and a flapping peer earns a longer
        hold each consecutive failure."""
        with self._lock:
            _, fails = self._kv_peer_negative.get(endpoint, (0.0, 0))
            fails += 1
            hold = min(self.kv_peer_timeout_s * (2 ** (fails - 1)), 60.0)
            self._kv_peer_negative[endpoint] = (
                time.monotonic() + hold, fails
            )

    def _kv_peer_blocked(self, endpoint: str) -> bool:
        """True while the peer's negative-cache hold is live. An expired
        hold admits ONE fresh probe: success clears the entry, another
        failure doubles the hold."""
        with self._lock:
            entry = self._kv_peer_negative.get(endpoint)
            if entry is None:
                return False
            if time.monotonic() >= entry[0]:
                return False
            self._kv_peer_negative_hits += 1
            return True

    def _kv_peer_recovered(self, endpoint: str) -> None:
        with self._lock:
            self._kv_peer_negative.pop(endpoint, None)

    def _kv_peer_quarantine_payload(self, endpoint: str,
                                    error: str) -> None:
        """Import validation failure (geometry, chain-key, version):
        record the refusal so an operator can see WHICH peer ships
        incompatible payloads — the request itself just re-prefills."""
        with self._lock:
            self._kv_peer_quarantined += 1
            self._kv_peer_quarantine.append(
                {"endpoint": endpoint, "error": error[:200]}
            )
            del self._kv_peer_quarantine[:-8]

    def stats(self) -> dict:
        with self._lock:
            replicas = {
                ep: {
                    "in_ring": ep in self._ring.nodes(),
                    "healthy": rep.healthy,
                    "draining": rep.draining,
                    "role": rep.role,
                    **({"stats": rep.stats} if rep.stats else {}),
                }
                for ep, rep in sorted(self._replicas.items())
            }
            hits = misses = 0
            for rep in self._replicas.values():
                pc = (rep.stats or {}).get("prefix_cache") or {}
                hits += pc.get("hits", 0)
                misses += pc.get("misses", 0)
            out = {
                "affinity": self.affinity,
                "tier_mode": self.tier_mode,
                "ring_size": len(self._ring),
                "replicas": replicas,
                "requests": self._requests,
                "reroutes": self._reroutes,
                "shed": self._shed,
                "failed": self._failed,
                "kv_transfers": self._kv_transfers,
                "kv_transfer_failures": self._kv_transfer_failures,
                "kv_transfer_bytes": self._kv_transfer_bytes,
                "kv_transfer_latency_s": round(self._kv_transfer_last_s, 6),
                # Fleet KV tier (STATS_PARITY surface for the
                # tpu_serving_kv_peer_* families) + the robustness
                # ladder's own scoreboard.
                "kv_peer_fetches": self._kv_peer_fetches,
                "kv_peer_fetch_failures": self._kv_peer_fetch_failures,
                "kv_peer_bytes": self._kv_peer_bytes,
                "kv_peer_fetch_latency_s": round(
                    self._kv_peer_fetch_last_s, 6
                ),
                "kv_peer": {
                    "enabled": bool(self.kv_peer_fanout),
                    "fanout": self.kv_peer_fanout,
                    "timeout_s": self.kv_peer_timeout_s,
                    "max_bytes": self.kv_peer_max_bytes,
                    "quarantined": self._kv_peer_quarantined,
                    "quarantine": list(self._kv_peer_quarantine),
                    "single_flight_skips":
                        self._kv_peer_single_flight_skips,
                    "negative_hits": self._kv_peer_negative_hits,
                    "negative_cached": sorted(
                        ep for ep, (until, _)
                        in self._kv_peer_negative.items()
                        if until > time.monotonic()
                    ),
                    "failure_reasons": dict(self._kv_peer_fail_reasons),
                },
                "inflight": dict(self._inflight),
                # The fleet-level prefix-cache view, aggregated from the
                # per-replica /stats scrapes (satellite: the gateway's
                # routing report).
                "fleet_prefix_cache": {
                    "hits": hits,
                    "misses": misses,
                    "hit_ratio": round(hits / (hits + misses), 4)
                    if hits + misses else 0.0,
                },
            }
        # Assembled OUTSIDE self._lock: the autoscaler's stats() takes
        # its own lock, and its tick thread nests the locks the other
        # way around (autoscaler lock → gateway.stats → self._lock).
        if (self.replica_source is not None
                and hasattr(self.replica_source, "stats")):
            out["warm_claims"] = self.replica_source.stats()
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.stats()
        return out

    # -- HTTP --------------------------------------------------------------

    def _handler_class(self):
        gw = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            # Correlation id for the request being handled: the trace id
            # (caller's traceparent, or this gateway's fresh root trace).
            # Echoed to the client (X-Request-Id, SSE error payloads) and
            # forwarded to the replica so every layer logs the same id.
            _req_id = None

            def log_message(self, *args):
                pass

            def _json(self, code: int, payload: dict,
                      retry_after: Optional[int] = None) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                if self._req_id:
                    self.send_header("X-Request-Id", self._req_id)
                if retry_after is not None:
                    self.send_header("Retry-After", str(retry_after))
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("Connection", "close")
                self.end_headers()
                try:
                    self.wfile.write(body)
                except OSError:
                    pass  # client gone; nothing to tell it

            def do_GET(self):
                if self.path == "/healthz":
                    n = len(gw.ring_nodes())
                    if n > 0:
                        self._json(200, {"status": "ok", "replicas": n})
                    else:
                        self._json(503, {"status": "no healthy replicas"})
                elif self.path == "/stats":
                    self._json(200, gw.stats())
                elif self.path == "/debug/traces":
                    ring = tracing.trace_ring()
                    self._json(200, {
                        "traces": ring.snapshot() if ring else [],
                    })
                elif self.path == "/debug/signals":
                    tel = gw.telemetry
                    self._json(200, tel.snapshot() if tel is not None
                               else {"enabled": False})
                elif self.path == "/debug/slo":
                    tel = gw.telemetry
                    if tel is None:
                        self._json(200, {"enabled": False})
                    else:
                        self._json(200, {"enabled": True,
                                         **tel.evaluate_slo()})
                elif self.path == "/debug/autoscaler":
                    scaler = gw.autoscaler
                    self._json(200, scaler.debug() if scaler is not None
                               else {"enabled": False})
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/v1/completions":
                    self._json(404, {"error": "not found"})
                    return
                arrival = time.monotonic()
                # Root span of the whole distributed trace (unless the
                # caller already carries a traceparent, in which case the
                # gateway joins it); the replica hop continues the same
                # trace via the headers _proxy injects.
                with tracing.get_tracer("gateway").start_span(
                    "gateway.request",
                    traceparent=self.headers.get("traceparent"),
                ) as span:
                    self._req_id = (
                        self.headers.get("x-request-id")
                        or span.trace_id
                        or tracing.new_trace_id()
                    )
                    self._completions(arrival, span)

            def _completions(self, arrival: float, span) -> None:
                try:
                    body = _read_body(self, gw.max_body_bytes)
                except BodyTooLarge as err:
                    self._json(413, {"error": str(err)})
                    return
                except ValueError as err:
                    self._json(400, {"error": str(err)})
                    return
                try:
                    req = json.loads(body or b"{}")
                    if not isinstance(req, dict):
                        raise ValueError("request body must be an object")
                except ValueError as err:
                    self._json(400, {"error": str(err)})
                    return
                tenant = str(
                    self.headers.get("x-tenant")
                    or req.get("user") or "anonymous"
                )
                span.set_attribute("tenant", tenant)
                try:
                    gw._admit(tenant)
                except GatewayOverloadedError as err:
                    span.add_event("tenant_shed", {"tenant": tenant})
                    span.record_error(err)
                    self._json(429, {"error": str(err)}, retry_after=1)
                    return
                try:
                    self._route(req, arrival, tenant)
                finally:
                    gw._release(tenant)

            def _route(self, req: dict, arrival: float,
                       tenant: str) -> None:
                key = gw._route_key(req.get("prompt"),
                                    adapter=req.get("model"))
                counted = False
                if gw.tier_mode == "disagg":
                    outcome = self._route_disagg(req, arrival, tenant,
                                                 key)
                    if outcome == "done":
                        return
                    # "fallback-counted": the disagg attempt already
                    # counted the request (prefill ran; only the decode
                    # hop failed) — the fused retry must not double it.
                    counted = outcome == "fallback-counted"
                candidates = gw._candidates(key)
                if gw.kv_peer_fanout and candidates:
                    # Fleet KV tier (fused): warm the affinity target's
                    # prefix cache from a ring peer before routing.
                    # Base-model chains only — chain keys carry a
                    # replica-local adapter salt the gateway cannot
                    # recompute. Advisory and exception-contained: any
                    # failure means a plain local prefill.
                    prompt = req.get("prompt")
                    if not req.get("model") and isinstance(
                        prompt, list
                    ) and prompt and all(
                        isinstance(t, int) and not isinstance(t, bool)
                        for t in prompt
                    ):
                        try:
                            self._kv_peer_fetch(prompt, key,
                                                candidates[0])
                        except Exception:
                            pass
                # The routing decision is its own span: affinity mode,
                # candidate walk, and every re-route attempt (as events)
                # in one place.
                with tracing.get_tracer("gateway").start_span(
                    "gateway.route", affinity=gw.affinity,
                    candidates=len(candidates),
                ) as span:
                    self._route_span(req, arrival, candidates, span,
                                     tenant, counted=counted)

            def _route_span(self, req: dict, arrival: float,
                            candidates: list, span,
                            tenant: str, counted: bool = False) -> None:
                if not candidates:
                    span.record_error(
                        RuntimeError("no healthy replicas")
                    )
                    if gw.telemetry is not None:
                        gw.telemetry.observe_request(tenant, ok=False)
                    self._json(503, {"error": "no healthy replicas"},
                               retry_after=1)
                    return
                if not counted:
                    gw._count_request()
                deadline_s = req.get("deadline_s")
                stream = bool(req.get("stream", False))
                last = None
                for i, endpoint in enumerate(candidates):
                    if i:
                        gw._count_reroute()
                        span.add_event("reroute", {
                            "attempt": i, "endpoint": endpoint,
                            "prior": f"{last[0]}: {last[1]}"
                            if last else "unreachable",
                        })
                    span.set_attribute("endpoint", endpoint)
                    fwd = dict(req)
                    if isinstance(deadline_s, (int, float)) and not \
                            isinstance(deadline_s, bool):
                        # The client's budget covers the WHOLE request:
                        # forward only what gateway time left of it.
                        remaining = deadline_s - (time.monotonic() - arrival)
                        if remaining <= 0:
                            if gw.telemetry is not None:
                                gw.telemetry.observe_request(
                                    tenant, ok=False
                                )
                            self._json(504, {
                                "error": "deadline expired at the gateway",
                                "partial_tokens": [],
                            })
                            return
                        fwd["deadline_s"] = remaining
                    outcome, last = self._proxy(endpoint, fwd, stream,
                                                arrival, tenant)
                    if outcome == "done":
                        return
                # Budget exhausted: every candidate refused or was down.
                gw._count_failed()
                if gw.telemetry is not None:
                    gw.telemetry.observe_request(tenant, ok=False)
                code, detail = last if last else (503, "replicas unreachable")
                span.record_error(RuntimeError(
                    f"re-route budget exhausted: {detail}"
                ))
                self._json(code if code in (429, 503) else 503,
                           {"error": f"fleet exhausted re-route budget "
                                     f"({gw.reroute_budget}): {detail}"},
                           retry_after=1)

            # -- disaggregated prefill/decode tiers -----------------------

            def _route_disagg(self, req: dict, arrival: float,
                              tenant: str, key: bytes) -> str:
                """One disaggregated attempt: probe the decode tier's
                prefix chains, prefill on the prefill tier (suffix-only
                export), hand the paged-KV payload to a decode replica.

                Returns "done" when a response reached the client,
                "fallback" to run the fused path untouched, or
                "fallback-counted" when the request was already counted
                (prefill ran, decode hop failed)."""
                prompt = req.get("prompt")
                if not (isinstance(prompt, list) and prompt and all(
                    isinstance(t, int) and not isinstance(t, bool)
                    for t in prompt
                )):
                    return "fallback"  # text prompts tokenize replica-side
                if not req.get("stream"):
                    # Non-stream responses assemble replica-side; the
                    # handoff's first-token boundary only pays off for
                    # streamed decode.
                    return "fallback"
                if req.get("n", 1) != 1 or req.get("logprobs"):
                    return "fallback"
                if "kv_import" in req:
                    return "fallback"  # already a decode-tier hop
                mt = req.get("max_tokens")
                if mt is not None and (
                    not isinstance(mt, int) or isinstance(mt, bool)
                    or mt < 1
                ):
                    return "fallback"  # let the fused path 400 it
                prefills = gw._tier_candidates("prefill", key)
                decodes = gw._tier_candidates("decode", key)
                if not prefills or not decodes:
                    return "fallback"
                deadline_s = req.get("deadline_s")

                def remaining():
                    if isinstance(deadline_s, (int, float)) and not \
                            isinstance(deadline_s, bool):
                        return deadline_s - (time.monotonic() - arrival)
                    return None

                with tracing.get_tracer("gateway").start_span(
                    "gateway.route", affinity=gw.affinity,
                    tier_mode="disagg",
                    prefill_candidates=len(prefills),
                    decode_candidates=len(decodes),
                ) as span:
                    return self._disagg_span(
                        req, arrival, tenant, prompt, prefills, decodes,
                        remaining, span, key,
                    )

            def _disagg_span(self, req: dict, arrival: float,
                             tenant: str, prompt: list, prefills: list,
                             decodes: list, remaining, span,
                             key: bytes) -> str:
                # Probe the affinity-preferred decode replica for cached
                # prefix chains so the prefill tier exports only suffix
                # blocks — the same chain keys PagedBatcher stamps.
                keys_hex = [
                    k.hex() for k in prompt_chain_keys(
                        prompt, gw._router.block_size
                    )
                ]
                probe = self._kv_probe_replica(decodes[0], keys_hex) \
                    if keys_hex else None
                matched = probe[0] if probe else 0
                if (gw.kv_peer_fanout and keys_hex
                        and not req.get("model")
                        and matched < len(keys_hex)):
                    # Fleet KV tier (disagg): before the prefill tier
                    # re-computes the missing prefix, try to pull it
                    # from a ring peer into the decode replica so the
                    # prefill export shrinks to suffix blocks. Advisory:
                    # any failure leaves `matched` as probed.
                    try:
                        registered = self._kv_peer_fetch(
                            prompt, key, decodes[0], held=matched
                        )
                        if registered:
                            matched = max(matched, min(
                                int(registered), len(keys_hex)
                            ))
                    except Exception as exc:
                        span.add_event("kv_peer_fetch_error",
                                       {"error": str(exc)})
                skip = keys_hex[:matched]
                span.set_attribute("prefix_blocks_skipped", len(skip))
                result = None
                for i, endpoint in enumerate(prefills):
                    if i:
                        gw._count_reroute()
                        span.add_event("reroute", {
                            "attempt": i, "endpoint": endpoint,
                            "tier": "prefill",
                        })
                    rem = remaining()
                    if rem is not None and rem <= 0:
                        gw._count_request()
                        if gw.telemetry is not None:
                            gw.telemetry.observe_request(tenant, ok=False)
                        self._json(504, {
                            "error": "deadline expired at the gateway",
                            "partial_tokens": [],
                        })
                        return "done"
                    result = self._kv_prefill_replica(
                        endpoint, req, skip, rem
                    )
                    if result is not None:
                        span.set_attribute("prefill_endpoint", endpoint)
                        break
                if result is None:
                    gw._count_kv_transfer(False, 0, 0.0)
                    span.add_event("disagg_fallback", {"stage": "prefill"})
                    return "fallback"
                payload = result.get("payload")
                fin = result.get("finished") or {}
                mt = req.get("max_tokens")
                need_decode = (
                    payload is not None
                    and fin.get("finish_reason") == "length"
                    and (mt is None or mt > 1)
                )
                if not need_decode:
                    # The prefill token was the whole generation (EOS,
                    # stop sequence, or max_tokens == 1): answer from
                    # the prefill result, no transfer needed.
                    gw._count_request()
                    self._synthesize(result.get("id"), fin, tenant,
                                     arrival)
                    return "done"
                fwd = {k: v for k, v in req.items() if k != "prompt"}
                fwd["kv_import"] = payload
                rem = remaining()
                if rem is not None:
                    if rem <= 0:
                        gw._count_request()
                        if gw.telemetry is not None:
                            gw.telemetry.observe_request(tenant, ok=False)
                        self._json(504, {
                            "error": "deadline expired at the gateway",
                            "partial_tokens": [],
                        })
                        return "done"
                    fwd["deadline_s"] = rem
                body = json.dumps(fwd).encode()
                if len(body) > gw.kv_transfer_max_bytes:
                    gw._count_kv_transfer(False, len(body), 0.0)
                    span.add_event("disagg_fallback", {
                        "stage": "payload_size", "bytes": len(body),
                    })
                    return "fallback"
                gw._count_request()
                # A suffix-only payload binds to the probed replica (its
                # chain cache holds the skipped blocks); a full payload
                # may walk the decode tier.
                targets = decodes[:1] if skip else decodes
                last = None
                for i, endpoint in enumerate(targets):
                    if i:
                        gw._count_reroute()
                        span.add_event("reroute", {
                            "attempt": i, "endpoint": endpoint,
                            "tier": "decode",
                        })
                    outcome, last = self._kv_decode_hop(
                        endpoint, fwd, body, arrival, tenant
                    )
                    if outcome == "done":
                        span.set_attribute("decode_endpoint", endpoint)
                        return "done"
                span.add_event("disagg_fallback", {
                    "stage": "decode",
                    "prior": f"{last[0]}: {last[1]}" if last
                    else "unreachable",
                })
                return "fallback-counted"

            def _kv_trace_headers(self) -> dict:
                """Every /kv/* hop carries the trace: traceparent joins
                the replica-side span to this request's trace, and
                X-Request-Id survives even with tracing off."""
                headers = {"Content-Type": "application/json"}
                tp = tracing.format_traceparent(tracing.current_span())
                if tp:
                    headers["traceparent"] = tp
                if self._req_id:
                    headers["X-Request-Id"] = self._req_id
                return headers

            def _kv_probe_replica(self, endpoint: str, keys_hex: list,
                                  timeout: Optional[float] = None):
                """How many consecutive prompt chain keys the replica
                already holds, plus its per-chain payload byte estimate:
                ``(matched, payload_bytes)``, or None when the replica
                was unreachable/refused (a peer fetcher negative-caches
                that; a plain miss is ``(0, 0)``). Advisory only (no
                pinning): a racing eviction surfaces at import time and
                the request falls back."""
                rep = gw._replicas.get(endpoint)
                if rep is None:
                    return None
                try:
                    conn = http.client.HTTPConnection(
                        rep.host, rep.port,
                        timeout=(timeout if timeout is not None
                                 else gw.health_timeout_s),
                    )
                    try:
                        conn.request(
                            "POST", "/kv/probe",
                            json.dumps({"keys": keys_hex}).encode(),
                            self._kv_trace_headers(),
                        )
                        resp = conn.getresponse()
                        body = resp.read()
                    finally:
                        conn.close()
                    if resp.status != 200:
                        return None
                    out = json.loads(body)
                    matched = max(0, int(out.get("matched", 0)))
                    pbytes = max(0, int(out.get("payload_bytes", 0)))
                    return matched, pbytes
                except (OSError, ValueError, http.client.HTTPException):
                    return None

            # -- fleet KV tier (peer prefix fetch) -------------------------

            def _kv_peer_fetch(self, prompt: list, key: bytes,
                               target: str, held=None):
                """Read-through peer fetch: probe up to kv_peer_fanout
                ring successors for the prompt's chain keys, pick the
                longest matching chain (swap-resident links included —
                the peer promotes before export), pull it under the
                per-hop deadline + whole-fetch budget, and push it into
                ``target``'s prefix cache. Wholly advisory: every
                failure mode returns None and the request re-prefills
                locally. Concurrent fetches for the same chain are
                single-flighted. Returns the number of leading chain
                keys resident on the target after a successful import."""
                keys_hex = [
                    k.hex() for k in prompt_chain_keys(
                        prompt, gw._router.block_size
                    )
                ]
                if not keys_hex:
                    return None
                # Whole-fetch budget: one per-hop deadline for the
                # target probe plus one per probed peer. The ladder
                # stops wherever the budget runs out.
                deadline = time.monotonic() + gw.kv_peer_timeout_s * (
                    gw.kv_peer_fanout + 1
                )
                tail = keys_hex[-1]
                with gw._lock:
                    if tail in gw._kv_peer_inflight:
                        gw._kv_peer_single_flight_skips += 1
                        return None
                    gw._kv_peer_inflight.add(tail)
                try:
                    with tracing.get_tracer("gateway").start_span(
                        "kv_peer_fetch", target=target,
                        chain_blocks=len(keys_hex),
                    ) as span:
                        return self._kv_peer_fetch_span(
                            keys_hex, prompt, key, target, held,
                            deadline, span,
                        )
                finally:
                    with gw._lock:
                        gw._kv_peer_inflight.discard(tail)

            def _kv_peer_fetch_span(self, keys_hex: list, prompt: list,
                                    key: bytes, target: str, held,
                                    deadline: float, span):
                def rem():
                    return deadline - time.monotonic()

                def hop_timeout():
                    return max(0.001, min(gw.kv_peer_timeout_s, rem()))

                if held is None:
                    probe = self._kv_probe_replica(
                        target, keys_hex, timeout=hop_timeout()
                    )
                    held = probe[0] if probe else 0
                span.set_attribute("target_matched", held)
                if held >= len(keys_hex):
                    span.set_attribute("outcome", "already-warm")
                    return None
                # Bounded ring walk: at most kv_peer_fanout successors
                # of the route key, skipping the target itself and any
                # negative-cached peer.
                with gw._lock:
                    walk = gw._ring.successors(key, len(gw._ring))
                peers = []
                for ep in walk:
                    if ep == target:
                        continue
                    peers.append(ep)
                    if len(peers) >= gw.kv_peer_fanout:
                        break
                best = None  # (endpoint, matched, payload_bytes)
                for ep in peers:
                    if rem() <= 0:
                        gw._count_kv_peer_fetch(
                            False, 0, 0.0, reason="budget_exhausted"
                        )
                        span.set_attribute("outcome", "budget-exhausted")
                        return None
                    if gw._kv_peer_blocked(ep):
                        span.add_event("peer_skipped", {
                            "endpoint": ep, "reason": "negative-cache",
                        })
                        continue
                    probe = self._kv_probe_replica(
                        ep, keys_hex, timeout=hop_timeout()
                    )
                    if probe is None:
                        gw._kv_peer_backoff(ep)
                        gw._count_kv_peer_fetch(
                            False, 0, 0.0, reason="dead_peer"
                        )
                        span.add_event("peer_dead", {"endpoint": ep})
                        continue
                    matched, pbytes = probe
                    if matched <= held:
                        continue
                    if best is None or matched > best[1]:
                        best = (ep, matched, pbytes)
                    if matched >= len(keys_hex):
                        break
                if best is None:
                    span.set_attribute("outcome", "no-peer-chain")
                    return None
                ep, matched, pbytes = best
                span.set_attribute("peer", ep)
                span.set_attribute("peer_matched", matched)
                if pbytes > gw.kv_peer_max_bytes:
                    # The probe's byte advisory: refuse BEFORE pulling.
                    gw._count_kv_peer_fetch(
                        False, pbytes, 0.0, reason="oversized"
                    )
                    span.set_attribute("outcome", "oversized")
                    return None
                if rem() <= 0:
                    gw._count_kv_peer_fetch(
                        False, 0, 0.0, reason="budget_exhausted"
                    )
                    span.set_attribute("outcome", "budget-exhausted")
                    return None
                t0 = time.monotonic()
                pulled = self._kv_chain_pull(
                    ep, keys_hex[:matched], hop_timeout()
                )
                if pulled is None:
                    # Transport failure mid-export: the peer died or
                    # tore the response — a corpse is not re-probed.
                    gw._kv_peer_backoff(ep)
                    gw._count_kv_peer_fetch(
                        False, 0, time.monotonic() - t0,
                        reason="fetch_failed",
                    )
                    span.set_attribute("outcome", "fetch-failed")
                    return None
                nbytes, chain = pulled
                if chain is None:
                    reason = ("oversized"
                              if nbytes > gw.kv_peer_max_bytes
                              else "chain_gone")
                    gw._count_kv_peer_fetch(
                        False, nbytes, time.monotonic() - t0,
                        reason=reason,
                    )
                    span.set_attribute("outcome", reason)
                    return None
                status, registered = self._kv_chain_push(
                    target, prompt, chain, hop_timeout()
                )
                if status != 200:
                    if status == 400:
                        # Validation refusal (geometry/chain-key/
                        # version): quarantine, never retry the payload.
                        gw._kv_peer_quarantine_payload(
                            ep, registered if isinstance(registered, str)
                            else "validation refused"
                        )
                        reason = "quarantined"
                    else:
                        reason = "import_failed"
                    gw._count_kv_peer_fetch(
                        False, nbytes, time.monotonic() - t0,
                        reason=reason,
                    )
                    span.set_attribute("outcome", reason)
                    return None
                gw._kv_peer_recovered(ep)
                gw._count_kv_peer_fetch(
                    True, nbytes, time.monotonic() - t0
                )
                span.set_attribute("outcome", "imported")
                span.set_attribute("registered", registered)
                return registered

            def _kv_chain_pull(self, endpoint: str, keys_hex: list,
                               timeout: float):
                """POST /kv/chain to the chosen peer. Returns
                ``(nbytes, payload_dict)`` — payload None when the body
                blew the byte cap or the peer no longer holds the chain
                — or None on transport failure (caller backs off)."""
                rep = gw._replicas.get(endpoint)
                if rep is None:
                    return None
                try:
                    conn = http.client.HTTPConnection(
                        rep.host, rep.port, timeout=timeout
                    )
                    try:
                        conn.request(
                            "POST", "/kv/chain",
                            json.dumps({"keys": keys_hex}).encode(),
                            self._kv_trace_headers(),
                        )
                        resp = conn.getresponse()
                        # Cap enforcement while reading: one byte past
                        # the cap is enough to refuse the payload.
                        body = resp.read(gw.kv_peer_max_bytes + 1)
                    finally:
                        conn.close()
                    if resp.status != 200:
                        return None
                    if len(body) > gw.kv_peer_max_bytes:
                        return len(body), None
                    out = json.loads(body)
                    payload = (out.get("payload")
                               if isinstance(out, dict) else None)
                    return len(body), (payload if isinstance(
                        payload, dict) else None)
                except (OSError, ValueError, http.client.HTTPException):
                    return None

            def _kv_chain_push(self, endpoint: str, prompt: list,
                               payload: dict, timeout: float):
                """POST /kv/chain/import to the target replica. Returns
                ``(status, registered_count)`` on an answered hop —
                status 400 carries the validation error string instead
                of a count — or ``(None, 0)`` on transport failure."""
                rep = gw._replicas.get(endpoint)
                if rep is None:
                    return None, 0
                body = json.dumps({
                    "tokens": [int(t) for t in prompt],
                    "payload": payload,
                }).encode()
                try:
                    conn = http.client.HTTPConnection(
                        rep.host, rep.port, timeout=timeout
                    )
                    try:
                        conn.request("POST", "/kv/chain/import", body,
                                     self._kv_trace_headers())
                        resp = conn.getresponse()
                        rbody = resp.read()
                    finally:
                        conn.close()
                    if resp.status != 200:
                        detail = ""
                        try:
                            detail = str(
                                json.loads(rbody).get("error", "")
                            )
                        except ValueError:
                            pass
                        return resp.status, detail
                    return 200, max(
                        0, int(json.loads(rbody).get("registered", 0))
                    )
                except (OSError, ValueError, http.client.HTTPException):
                    return None, 0

            def _kv_prefill_replica(self, endpoint: str, req: dict,
                                    skip: list, rem):
                """One prefill-tier attempt. Returns the parsed
                ``/kv/prefill`` result (payload + finished tokens) or
                None when this replica refused or was unreachable."""
                rep = gw._replicas.get(endpoint)
                if rep is None:
                    return None
                fwd = {"prompt": req["prompt"], "skip_keys": skip}
                for k in ("temperature", "stop", "logit_bias", "model"):
                    if k in req:
                        fwd[k] = req[k]
                if rem is not None:
                    fwd["deadline_s"] = rem
                timeout = gw.upstream_timeout_s
                if rem is not None:
                    timeout = min(timeout, rem + 5.0)
                headers = {"Content-Type": "application/json"}
                tp = tracing.format_traceparent(tracing.current_span())
                if tp:
                    headers["traceparent"] = tp
                if self._req_id:
                    headers["X-Request-Id"] = self._req_id
                try:
                    conn = http.client.HTTPConnection(
                        rep.host, rep.port, timeout=timeout
                    )
                    try:
                        conn.request("POST", "/kv/prefill",
                                     json.dumps(fwd).encode(), headers)
                        resp = conn.getresponse()
                        body = resp.read()
                    finally:
                        conn.close()
                    if resp.status != 200:
                        return None
                    out = json.loads(body)
                    if isinstance(out, dict) and "finished" in out:
                        return out
                    return None
                except (OSError, ValueError, http.client.HTTPException):
                    # HTTPException covers the pod-death-mid-response
                    # shapes (IncompleteRead, BadStatusLine) a plain
                    # connection error never raises.
                    return None

            def _kv_decode_hop(self, endpoint: str, fwd: dict,
                               body: bytes, arrival: float, tenant: str):
                """POST the block payload to one decode replica and relay
                its stream. The ``kv_transfer`` span covers request →
                response headers — the wire hop plus the replica-side
                import, i.e. the gap between the prefill tier's
                ``prefill`` span and the decode tier's ``first_decode``."""
                rep = gw._replicas.get(endpoint)
                if rep is None:
                    return "retry", (503, f"{endpoint} left the fleet")
                timeout = gw.kv_transfer_timeout_s
                deadline_s = fwd.get("deadline_s")
                if isinstance(deadline_s, (int, float)):
                    timeout = min(timeout, float(deadline_s) + 5.0)
                headers = {"Content-Type": "application/json"}
                tp = tracing.format_traceparent(tracing.current_span())
                if tp:
                    headers["traceparent"] = tp
                if self._req_id:
                    headers["X-Request-Id"] = self._req_id
                t0 = time.monotonic()
                try:
                    with tracing.get_tracer("gateway").start_span(
                        "kv_transfer", endpoint=endpoint,
                        transfer_bytes=len(body),
                    ) as tspan:
                        try:
                            conn = http.client.HTTPConnection(
                                rep.host, rep.port, timeout=timeout
                            )
                            conn.request("POST", "/v1/completions",
                                         body, headers)
                            resp = conn.getresponse()
                        except (OSError,
                                http.client.HTTPException) as err:
                            tspan.record_error(err)
                            raise
                except (OSError, http.client.HTTPException):
                    gw._count_kv_transfer(False, len(body),
                                          time.monotonic() - t0)
                    return "retry", (503, f"{endpoint} unreachable")
                latency = time.monotonic() - t0
                ctype = resp.getheader("Content-Type", "")
                if resp.status != 200 or "text/event-stream" not in ctype:
                    try:
                        detail = json.loads(resp.read()).get(
                            "error", "refused")
                    except (OSError, ValueError):
                        detail = "refused"
                    conn.close()
                    gw._count_kv_transfer(False, len(body), latency)
                    return "retry", (resp.status,
                                     f"{endpoint}: {detail}")
                gw._count_kv_transfer(True, len(body), latency)
                if conn.sock is not None:
                    # The transfer deadline bounded the hop; the stream
                    # phase reverts to the ordinary upstream timeout.
                    conn.sock.settimeout(gw.upstream_timeout_s)
                return self._relay_stream(conn, resp, arrival, tenant)

            def _synthesize(self, rid, fin: dict, tenant: str,
                            arrival: float) -> None:
                """Answer a stream request straight from the prefill
                result (generation finished at the first token): same SSE
                shape a replica emits, so clients can't tell."""
                try:
                    self.send_response(200)
                    if self._req_id:
                        self.send_header("X-Request-Id", self._req_id)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    for tok in fin.get("tokens") or []:
                        self.wfile.write(
                            b"data: " + json.dumps(
                                {"id": rid, "token": tok}
                            ).encode() + b"\n\n"
                        )
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.wfile.flush()
                except OSError:
                    pass  # client gone mid-synthesis
                if gw.telemetry is not None:
                    gw.telemetry.observe_request(
                        tenant, ok=True,
                        ttft_s=time.monotonic() - arrival,
                        e2e_s=time.monotonic() - arrival,
                    )

            def _proxy(self, endpoint: str, req: dict, stream: bool,
                       arrival: float, tenant: str):
                """One attempt against one replica. Returns
                ("done", None) when a response (or a terminal error) was
                relayed, ("retry", (code, detail)) when the replica
                refused before any byte reached the client."""
                rep = gw._replicas.get(endpoint)
                if rep is None:
                    return "retry", (503, f"{endpoint} left the fleet")
                deadline_s = req.get("deadline_s")
                timeout = gw.upstream_timeout_s
                if isinstance(deadline_s, (int, float)):
                    timeout = min(timeout, float(deadline_s) + 5.0)
                # Propagate the trace across the HTTP hop: the replica's
                # server.request span joins this trace via the W3C
                # traceparent header; X-Request-Id rides along even when
                # tracing is off so the correlation id survives end to
                # end regardless.
                headers = {"Content-Type": "application/json"}
                tp = tracing.format_traceparent(tracing.current_span())
                if tp:
                    headers["traceparent"] = tp
                if self._req_id:
                    headers["X-Request-Id"] = self._req_id
                try:
                    conn = http.client.HTTPConnection(
                        rep.host, rep.port, timeout=timeout
                    )
                    conn.request(
                        "POST", "/v1/completions",
                        json.dumps(req).encode(),
                        headers,
                    )
                    resp = conn.getresponse()
                except OSError:
                    return "retry", (503, f"{endpoint} unreachable")
                if resp.status in (429, 503):
                    # Replica-side shed/drain answered before we relayed
                    # anything: eligible for the bounded re-route walk.
                    try:
                        detail = json.loads(resp.read()).get(
                            "error", "refused")
                    except (OSError, ValueError):
                        detail = "refused"
                    conn.close()
                    return "retry", (resp.status, f"{endpoint}: {detail}")
                ctype = resp.getheader("Content-Type", "")
                try:
                    if not stream or "text/event-stream" not in ctype:
                        # Errors (400/504/...) answer JSON even for
                        # stream requests — relay them as JSON too.
                        body = resp.read()
                        conn.close()
                        self._json(resp.status, json.loads(body))
                        if gw.telemetry is not None:
                            # Non-stream responses have no first-token
                            # boundary: e2e only, so the ttft_s series
                            # stays purely relay-measured.
                            gw.telemetry.observe_request(
                                tenant, ok=resp.status == 200,
                                e2e_s=time.monotonic() - arrival,
                            )
                        return "done", None
                    return self._relay_stream(conn, resp, arrival, tenant)
                except (OSError, ValueError):
                    # Replica died mid-body before ANY byte was relayed
                    # client-side (non-stream read) — safe to re-route;
                    # generation is idempotent.
                    conn.close()
                    if not stream:
                        return "retry", (503, f"{endpoint} died mid-read")
                    return "done", None

            def _relay_stream(self, conn, resp, arrival: float,
                              tenant: str):
                """SSE passthrough: relay lines as they arrive, peek for
                the client's FIN before each write (closing the upstream
                connection is the cancellation signal the replica's own
                _client_gone converts into an engine-side cancel).

                This is also where the telemetry plane's latencies come
                from: TTFT = arrival → first relayed data line and the
                gaps between data lines, measured at the point the bytes
                leave for the client — the fleet numbers are what a
                client actually experienced through the gateway."""
                started = False
                finished = False
                ttft = None
                last_data = None
                gaps: list = []
                try:
                    while True:
                        line = resp.fp.readline()
                        if not line:
                            break
                        if _client_gone(self.connection):
                            conn.close()  # upstream FIN → replica cancels
                            if finished:
                                # [DONE] already relayed: this is normal
                                # client teardown, not a cancel — the
                                # request completed.
                                self._observe_stream(tenant, True, ttft,
                                                     gaps, arrival)
                            return "done", None
                        if not started:
                            self.send_response(resp.status)
                            if self._req_id:
                                self.send_header("X-Request-Id",
                                                 self._req_id)
                            self.send_header("Content-Type",
                                             "text/event-stream")
                            self.send_header("Cache-Control", "no-cache")
                            self.send_header("Connection", "close")
                            self.end_headers()
                            started = True
                        self.wfile.write(line)
                        if line == b"data: [DONE]\n":
                            finished = True
                        elif line.startswith(b"data:"):
                            now_t = time.monotonic()
                            if ttft is None:
                                ttft = now_t - arrival
                            elif last_data is not None:
                                gaps.append(now_t - last_data)
                            last_data = now_t
                        if line == b"\n":
                            self.wfile.flush()
                            if finished:
                                # Terminator relayed: the stream is
                                # complete. Don't wait for upstream EOF —
                                # a client that hangs up right after
                                # [DONE] would race _client_gone and
                                # lose the completed request.
                                break
                    conn.close()
                    if not started:
                        # EOF before the first event: nothing reached the
                        # client, so the re-route walk may continue.
                        return "retry", (503, "empty replica response")
                    if not finished:
                        # A killed replica's socket often closes with a
                        # clean FIN, not a reset: EOF after bytes flowed
                        # but before [DONE] is the same mid-stream loss.
                        self._observe_stream(tenant, False, ttft, gaps,
                                             arrival)
                        return self._stream_lost()
                    self._observe_stream(tenant, True, ttft, gaps, arrival)
                    return "done", None
                except (BrokenPipeError, ConnectionResetError):
                    conn.close()  # client hung up; cancel upstream
                    if finished:
                        # The hangup came after the terminator: complete.
                        self._observe_stream(tenant, True, ttft, gaps,
                                             arrival)
                    return "done", None
                except OSError:
                    conn.close()
                    if started:
                        self._observe_stream(tenant, False, ttft, gaps,
                                             arrival)
                        return self._stream_lost()
                    # Nothing reached the client: the re-route walk may
                    # continue (budget exhaustion counts the failure).
                    return "retry", (503, "replica died before first byte")

            def _observe_stream(self, tenant: str, ok: bool, ttft,
                                gaps: list, arrival: float) -> None:
                if gw.telemetry is not None:
                    gw.telemetry.observe_request(
                        tenant, ok=ok, ttft_s=ttft, inter_token=gaps,
                        e2e_s=time.monotonic() - arrival,
                    )

            def _stream_lost(self):
                """UPSTREAM loss mid-stream: bytes already reached the
                client, so a re-route would splice two generations —
                terminate the stream distinguishably instead."""
                gw._count_failed()
                try:
                    # The error event carries the request id (the only
                    # correlation handle left once headers are gone —
                    # the chaos harness asserts it survives a replica
                    # kill). json.dumps keeps insertion order, so the
                    # "replica lost mid-stream" detail stays greppable.
                    self.wfile.write(
                        b"data: " + json.dumps({
                            "error": "replica lost mid-stream",
                            "request_id": self._req_id,
                        }).encode() + b"\n\ndata: [DONE]\n\n"
                    )
                    self.wfile.flush()
                except OSError:
                    pass
                return "done", None

        return Handler


class WarmSliceReplicaSource:
    """Elastic replica capacity through ``controller/slicepool.py``.

    ``acquire`` claims one warm all-Ready placeholder slice via the SAME
    ``claim_warm_slice`` path notebook spawns use: a hit deletes the
    placeholder StatefulSet (releasing its chips for the replica's pods)
    and stamps LAST_CLAIM on the owning pool; a miss stamps the
    LAST_MISS/MISS_COUNT demand annotations every matching autoscaled
    pool reads — so a gateway scaling up under load is itself the demand
    signal that grows the pool. The replica's lifecycle closes the loop
    the other way: draining flips its healthz, the gateway drops it from
    the ring, and the slice returns to the pool.

    Hardened for autoscaler claim storms: every ``acquire`` runs under
    a bounded wall-clock deadline (``claim_deadline_s`` — an apiserver
    crawling through conflict retries must not wedge the control loop),
    and attempts/failures/latency are counted for the gateway's /stats
    ``warm_claims`` block. The conflict-prone slicepool status writes
    themselves already go through ``retry_on_conflict``.
    """

    def __init__(self, client, namespace: str, topo,
                 recorder=None, notebook=None,
                 claim_deadline_s: float = 5.0):
        if claim_deadline_s <= 0:
            raise ValueError(
                f"claim_deadline_s must be > 0, got {claim_deadline_s}"
            )
        self.client = client
        self.namespace = namespace
        self.topo = topo
        self.recorder = recorder
        self.notebook = notebook
        self.claim_deadline_s = claim_deadline_s
        self._lock = threading.Lock()
        self._attempts = 0
        self._failures = 0
        self._last_latency_s = 0.0
        self._latency_total_s = 0.0

    def acquire(self, now: Optional[float] = None,
                pools: Optional[list] = None) -> Optional[str]:
        from kubeflow_tpu.controller.slicepool import claim_warm_slice

        with self._lock:
            self._attempts += 1
        t0 = time.perf_counter()
        try:
            pool = claim_warm_slice(
                self.client, self.namespace, self.topo,
                recorder=self.recorder, notebook=self.notebook,
                now=now if now is not None else time.time(), pools=pools,
                deadline=t0 + self.claim_deadline_s,
            )
        except Exception:
            with self._lock:
                self._failures += 1
                self._last_latency_s = time.perf_counter() - t0
                self._latency_total_s += self._last_latency_s
            raise
        with self._lock:
            self._last_latency_s = time.perf_counter() - t0
            self._latency_total_s += self._last_latency_s
            if pool is None:
                self._failures += 1
        return pool

    def stats(self) -> dict:
        """The gateway /stats ``warm_claims`` block (STATS_PARITY
        surface for the tpu_autoscaler_claim_* families)."""
        with self._lock:
            return {
                "claim_attempts": self._attempts,
                "claim_failures": self._failures,
                "claim_latency_s": round(self._last_latency_s, 6),
                "claim_latency_total_s": round(self._latency_total_s, 6),
                "claim_deadline_s": self.claim_deadline_s,
            }


def gateway_from_env(metrics=None, replica_source=None) -> ServingGateway:
    """Build an (unstarted) gateway from the KUBEFLOW_TPU_GATEWAY_* env
    contract (webhook/tpu_env.py ENV_CONTRACT). Raises on garbage — a
    hand-set env var must not silently fall back to defaults."""
    import os

    from kubeflow_tpu.webhook.tpu_env import (
        KUBEFLOW_TPU_GATEWAY_AFFINITY,
        KUBEFLOW_TPU_GATEWAY_HASH_SEED,
        KUBEFLOW_TPU_GATEWAY_PORT,
        KUBEFLOW_TPU_GATEWAY_REPLICAS,
        KUBEFLOW_TPU_GATEWAY_REROUTE_BUDGET,
        KUBEFLOW_TPU_GATEWAY_TIER_DECODE,
        KUBEFLOW_TPU_GATEWAY_TIER_MODE,
        KUBEFLOW_TPU_GATEWAY_TIER_PREFILL,
        KUBEFLOW_TPU_KV_PEER_FANOUT,
        KUBEFLOW_TPU_KV_PEER_MAX_BYTES,
        KUBEFLOW_TPU_KV_PEER_TIMEOUT_S,
        KUBEFLOW_TPU_KV_TRANSFER_MAX_BYTES,
        KUBEFLOW_TPU_KV_TRANSFER_TIMEOUT_S,
    )

    def _int(name: str, default: int, minimum: int) -> int:
        raw = os.environ.get(name, "").strip()
        if not raw:
            return default
        try:
            value = int(raw)
        except ValueError:
            value = minimum - 1
        if value < minimum:
            raise ValueError(
                f"{name}={raw!r}: want an integer >= {minimum}"
            )
        return value

    port = _int(KUBEFLOW_TPU_GATEWAY_PORT, 8080, 0)
    if port > 65535:
        raise ValueError(f"{KUBEFLOW_TPU_GATEWAY_PORT}={port}: want <= 65535")
    raw_replicas = os.environ.get(KUBEFLOW_TPU_GATEWAY_REPLICAS, "").strip()
    replicas = [r.strip() for r in raw_replicas.split(",") if r.strip()]
    for r in replicas:
        _parse_endpoint(r)  # fail loudly before serving into nothing
    affinity = os.environ.get(
        KUBEFLOW_TPU_GATEWAY_AFFINITY, "").strip().lower() or "prefix"
    if affinity not in AFFINITY_MODES:
        raise ValueError(
            f"{KUBEFLOW_TPU_GATEWAY_AFFINITY}={affinity!r}: want one of "
            f"{AFFINITY_MODES}"
        )
    raw_seed = os.environ.get(KUBEFLOW_TPU_GATEWAY_HASH_SEED, "").strip()
    try:
        seed = int(raw_seed) if raw_seed else 0
    except ValueError:
        raise ValueError(
            f"{KUBEFLOW_TPU_GATEWAY_HASH_SEED}={raw_seed!r}: want an integer"
        )
    budget = _int(KUBEFLOW_TPU_GATEWAY_REROUTE_BUDGET, 2, 0)
    tier_mode = os.environ.get(
        KUBEFLOW_TPU_GATEWAY_TIER_MODE, "").strip().lower() or "fused"
    if tier_mode not in ("fused", "disagg"):
        raise ValueError(
            f"{KUBEFLOW_TPU_GATEWAY_TIER_MODE}={tier_mode!r}: want "
            f"'fused' or 'disagg'"
        )
    tier_roles: dict = {}
    for env_name, role in ((KUBEFLOW_TPU_GATEWAY_TIER_PREFILL, "prefill"),
                           (KUBEFLOW_TPU_GATEWAY_TIER_DECODE, "decode")):
        raw = os.environ.get(env_name, "").strip()
        for ep in (r.strip() for r in raw.split(",") if r.strip()):
            _parse_endpoint(ep)
            if tier_roles.get(ep, role) != role:
                raise ValueError(
                    f"{env_name}: endpoint {ep!r} listed in both tiers"
                )
            tier_roles[ep] = role
            if ep not in replicas:
                replicas.append(ep)
    raw_timeout = os.environ.get(
        KUBEFLOW_TPU_KV_TRANSFER_TIMEOUT_S, "").strip()
    try:
        kv_timeout = float(raw_timeout) if raw_timeout else 30.0
    except ValueError:
        kv_timeout = 0.0
    if kv_timeout <= 0:
        raise ValueError(
            f"{KUBEFLOW_TPU_KV_TRANSFER_TIMEOUT_S}={raw_timeout!r}: "
            f"want a number > 0"
        )
    kv_max_bytes = _int(KUBEFLOW_TPU_KV_TRANSFER_MAX_BYTES, 64 << 20, 1)
    # Peer tier: unset fanout keeps it fully inert (zero hot-path cost,
    # zero new sockets); a set value must be a sane bound.
    kv_peer_fanout = _int(KUBEFLOW_TPU_KV_PEER_FANOUT, 0, 1)
    raw_peer_timeout = os.environ.get(
        KUBEFLOW_TPU_KV_PEER_TIMEOUT_S, "").strip()
    try:
        kv_peer_timeout = float(raw_peer_timeout) if raw_peer_timeout \
            else 5.0
    except ValueError:
        kv_peer_timeout = 0.0
    if kv_peer_timeout <= 0:
        raise ValueError(
            f"{KUBEFLOW_TPU_KV_PEER_TIMEOUT_S}={raw_peer_timeout!r}: "
            f"want a number > 0"
        )
    kv_peer_max_bytes = _int(KUBEFLOW_TPU_KV_PEER_MAX_BYTES, 64 << 20, 1)
    return ServingGateway(
        replicas=replicas, port=port, affinity=affinity, hash_seed=seed,
        reroute_budget=budget, metrics=metrics,
        replica_source=replica_source, tier_mode=tier_mode,
        tier_roles=tier_roles, kv_transfer_timeout_s=kv_timeout,
        kv_transfer_max_bytes=kv_max_bytes,
        kv_peer_fanout=kv_peer_fanout,
        kv_peer_timeout_s=kv_peer_timeout,
        kv_peer_max_bytes=kv_peer_max_bytes,
    )
