"""LoRA fine-tuning for the Llama family.

Low-rank adapters over the stacked layer weights: for a target weight
W (L, in, out), the adapter is a: (L, in, r), b: (L, r, out) with
W' = W + (alpha/r) · a@b. The merge is an einsum over the stacked layer
axis, so the adapted forward reuses llama.forward unchanged — XLA fuses
the merge into the surrounding graph, and only the (tiny) adapter tree
carries gradients/optimizer state.

TPU-first reasons this shape wins:
- base params stay frozen bf16 and are passed THROUGH the jitted step as
  an argument (never baked in as constants → no giant recompiles),
- gradient/optimizer memory is O(rank · dim) instead of O(dim²) — a 7B
  fine-tune fits on one v5e chip next to the bf16 base weights,
- the merged weight is rematerialized per use under jax.checkpoint-style
  remat if requested; by default XLA shares it across the layer scan.

No reference counterpart (control plane only, SURVEY.md §2.5): this is
in-notebook tooling for the flagship model family.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
import optax

from kubeflow_tpu.models.llama import LlamaConfig
from kubeflow_tpu.models.train import causal_lm_loss, make_optimizer
from kubeflow_tpu.parallel.mesh import MeshPlan

# Weights eligible for adapters: all stacked (L, in, out) projections.
_ADAPTABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    # Llama-paper default: attention q/v projections.
    targets: tuple = ("wq", "wv")

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def init_lora_params(
    cfg: LlamaConfig, lcfg: LoraConfig, key: jax.Array, dtype=None
) -> dict:
    """a ~ N(0, 1/in), b = 0 — the adapted model starts EXACTLY at the
    base model (b=0 ⇒ delta is zero), the standard LoRA init."""
    dtype = cfg.dtype if dtype is None else dtype
    bad = [t for t in lcfg.targets if t not in _ADAPTABLE]
    if bad:
        raise ValueError(f"unknown LoRA targets {bad}; valid: {_ADAPTABLE}")
    out: dict = {}
    keys = jax.random.split(key, len(lcfg.targets))
    shapes = _target_shapes(cfg)
    for k, target in zip(keys, lcfg.targets):
        d_in, d_out = shapes[target]
        a = jax.random.normal(k, (cfg.n_layers, d_in, lcfg.rank), dtype)
        a = a * jnp.asarray(1.0 / math.sqrt(d_in), dtype)
        b = jnp.zeros((cfg.n_layers, lcfg.rank, d_out), dtype)
        out[target] = {"a": a, "b": b}
    return out


def _target_shapes(cfg: LlamaConfig) -> dict:
    hd = cfg.head_dim
    return {
        "wq": (cfg.dim, cfg.n_heads * hd),
        "wk": (cfg.dim, cfg.n_kv_heads * hd),
        "wv": (cfg.dim, cfg.n_kv_heads * hd),
        "wo": (cfg.n_heads * hd, cfg.dim),
        "w_gate": (cfg.dim, cfg.ffn_hidden),
        "w_up": (cfg.dim, cfg.ffn_hidden),
        "w_down": (cfg.ffn_hidden, cfg.dim),
    }


def merge_lora(params: dict, lora: dict, lcfg: LoraConfig) -> dict:
    """Base params + scaled adapter deltas → effective params (same tree
    shape as the input, so every llama entry point works unchanged)."""
    layers = dict(params["layers"])
    for target, ab in lora.items():
        delta = jnp.einsum(
            "lir,lro->lio", ab["a"], ab["b"],
            preferred_element_type=jnp.float32,
        ) * lcfg.scaling
        layers[target] = (layers[target].astype(jnp.float32) + delta).astype(
            params["layers"][target].dtype
        )
    return {**params, "layers": layers}


def lora_param_count(cfg: LlamaConfig, lcfg: LoraConfig) -> int:
    shapes = _target_shapes(cfg)
    return sum(
        cfg.n_layers * lcfg.rank * (shapes[t][0] + shapes[t][1])
        for t in lcfg.targets
    )


def make_lora_train_step(
    cfg: LlamaConfig,
    lcfg: LoraConfig,
    plan: Optional[MeshPlan] = None,
    optimizer=None,
    learning_rate: float = 1e-4,
):
    """Build (init_state, step) where ONLY the adapters train.

    step(state, base_params, tokens) -> (state, loss). base_params flow
    through as a donat-able argument (frozen, never copied into the jit
    program as constants).

    With a ``plan``, the step jits over plan.mesh: the token batch is
    sharded over (dp, fsdp) × sp and base/adapter placement propagates
    from the caller's device_put (use plan.shard_params on the base tree)
    — same contract as train.make_train_step.
    """
    optimizer = optimizer or make_optimizer(lr=learning_rate, weight_decay=0.0)

    def init_state(lora_params):
        return {
            "lora": lora_params,
            "opt_state": optimizer.init(lora_params),
            "step": jnp.zeros((), jnp.int32),
        }

    def loss_fn(lora_params, base_params, tokens):
        merged = merge_lora(base_params, lora_params, lcfg)
        return causal_lm_loss(merged, cfg, tokens)

    def step(state, base_params, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(
            state["lora"], base_params, tokens
        )
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["lora"]
        )
        lora_params = optax.apply_updates(state["lora"], updates)
        return {
            "lora": lora_params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }, loss

    if plan is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        batch_sharding = NamedSharding(plan.mesh, P(("dp", "fsdp"), "sp"))
        jitted = jax.jit(
            step,
            in_shardings=(None, None, batch_sharding),
            donate_argnums=(0,),
        )
    else:
        jitted = jax.jit(step, donate_argnums=(0,))

    return init_state, jitted
