"""In-notebook HTTP inference server over the batching engines.

The serving stack's missing front door: the engines (ContinuousBatcher,
PagedBatcher, the speculative pair) are drive-to-completion batch APIs —
a notebook cell submits N prompts and collects N results. A live
endpoint needs the opposite shape: requests arrive whenever, responses
stream back while other slots keep decoding. ``InferenceServer`` puts a
stdlib ThreadingHTTPServer in front of ONE engine thread:

- HTTP handler threads ``submit()`` under the engine lock and block on
  (or stream from) a per-request queue;
- the engine thread loops admit → step while any work exists, sleeping
  on a condition variable when idle — continuous batching across
  requests that never saw each other;
- per-token delivery rides the engines' ``on_token``/``on_retire``
  hooks (models/continuous.py _BatcherBase), so all four engines serve
  unmodified.

Endpoints (OpenAI-completions-shaped, token-native):
- ``POST /v1/completions`` request fields:
  - ``prompt``: token-id list, or a string (needs a ``tokenizer``);
  - ``max_tokens``: per-request cap, clamped to the engine-wide budget;
  - ``temperature``: finite >= 0 (0 = greedy for this request; the
    batch freely mixes greedy and sampled rows);
  - ``n``: 1..64 choices decoded concurrently from one prompt;
  - ``stop``: string(s) via the tokenizer, or token-id list(s) —
    generation ends at (and excludes) the first match; streamed
    responses may still carry the stop tokens (documented divergence);
  - ``logit_bias``: {token id: bias}, clamped ±100 (force/ban);
  - ``logprobs``: true → per-choice ``logprobs.token_logprobs``
    (engines that compute them; rejected on speculative);
  - ``model``: adapter name for multi-LoRA engines;
  - ``stream``: true → ``text/event-stream`` lines
    ``data: {"token": id, "text"?: s}`` ending ``data: [DONE]``
    (requires n=1, no logprobs; error events precede [DONE] on abort).
  Response: ``{"id", "choices": [{"index", "tokens", "text"?,
  "logprobs"?, "finish_reason"}], "usage": {...}}``.
- ``GET /healthz`` — liveness (503 once the engine thread died);
  ``GET /v1/models`` — base + adapters; ``GET /stats`` — active slots,
  queue depth, served/token counts, lifetime tokens/sec, and p50/p95
  time-to-first-token + end-to-end latency over the last 256 requests.

Reference parity: the reference deploys notebook POD plumbing and leaves
what runs inside to the user (no serving stack at all — SURVEY.md §2.5);
this is added TPU-runtime scope, the consuming end of the controller's
NB_PREFIX/port wiring.
"""

from __future__ import annotations

import collections
import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

def _percentiles(window) -> dict:
    """{p50, p95} by nearest rank over one sort of the window."""
    if not window:
        return {"p50": None, "p95": None}
    xs = sorted(window)
    n = len(xs)

    def rank(q):
        return round(xs[min(n - 1, max(0, -(-q * n // 100) - 1))], 4)

    return {"p50": rank(50), "p95": rank(95)}


class _Final:
    """Success sentinel carrying the AUTHORITATIVE final token list (a
    stop-sequence match truncates tokens the per-token stream already
    delivered, so non-streaming responses must use the retire payload,
    not the accumulated stream) plus the chosen-token logprobs."""

    def __init__(self, tokens: list, logprobs: list):
        self.tokens = tokens
        self.logprobs = logprobs


class _Abort:
    """Queue sentinel for a request that did NOT complete (engine death,
    server shutdown) — per-queue, so a request that already finished
    normally can never be mislabeled by a later global failure."""

    def __init__(self, reason: str):
        self.reason = reason


class EngineFailedError(RuntimeError):
    """The engine thread is dead (or shutting down); submits are refused."""


def serving_port_from_env(default: int = 8000) -> int:
    """Consuming end of the ``tpu-serving-port`` annotation: the webhook
    projects it into KUBEFLOW_TPU_SERVING_PORT (api/annotations.py), the
    controller opens it in the ctrl NetworkPolicy and surfaces worker-0's
    address as status.tpu.servingEndpoint. Raises on garbage — a hand-set
    env var must not silently serve on the wrong port."""
    import os

    value = os.environ.get("KUBEFLOW_TPU_SERVING_PORT", "").strip()
    if not value:
        return default
    from kubeflow_tpu.api.annotations import parse_profiling_port

    port = parse_profiling_port(value)
    if port is None:
        raise ValueError(
            f"KUBEFLOW_TPU_SERVING_PORT={value!r}: want a port in "
            "1024..65535"
        )
    return port


class InferenceServer:
    """HTTP front-end driving one batching engine on a background thread.

    >>> engine = ContinuousBatcher(params, cfg, slots=4, cache_len=512)
    >>> srv = InferenceServer(engine, port=0)   # 0 = ephemeral
    >>> srv.start()
    >>> # POST http://127.0.0.1:{srv.port}/v1/completions
    >>> srv.stop()
    """

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 8000,
                 tokenizer=None, model_name: str = "kubeflow-tpu"):
        # The speculative engines are thin wrappers delegating to an
        # inner batcher (`_engine`) that owns the queue/slots/step loop —
        # hooks and the drive loop must target the inner one.
        self.engine = getattr(engine, "_engine", engine)
        if model_name in getattr(self.engine, "adapter_names", ()):
            # The "model == model_name → base" shortcut in _submit would
            # make that adapter silently unreachable.
            raise ValueError(
                f"model_name {model_name!r} collides with an adapter "
                "name — requests for the adapter would be routed to the "
                "base model"
            )
        self.tokenizer = tokenizer
        self.model_name = model_name
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queues: dict[int, queue.Queue] = {}
        self._shutdown = False
        self._served = 0
        self._engine_error: Optional[str] = None
        # Serving observability (host-side, O(1) per event): per-request
        # submit/first-token stamps plus sliding windows of time-to-first-
        # token and end-to-end latency, and a token counter for
        # throughput. All read under the lock by /stats.
        self._submit_ts: dict[int, float] = {}
        self._first_ts: dict[int, float] = {}
        self._ttft = collections.deque(maxlen=256)
        self._e2e = collections.deque(maxlen=256)
        self._tokens_out = 0
        self._started_at = None  # stamped in start(): uptime = serving time
        self._httpd = ThreadingHTTPServer((host, port), self._handler_class())
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._engine_thread = threading.Thread(
            target=self._drive, name="inference-engine", daemon=True
        )
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="inference-http",
            daemon=True,
        )
        # Hooks go on the RESOLVED engine — it is the object whose
        # _note_token/_retire read them; the spec wrappers forward nothing.
        self.engine.on_token = self._on_token
        self.engine.on_retire = self._on_retire

    # -- engine side (all under self._lock) --------------------------------

    def _on_token(self, rid: int, token: int) -> None:
        self._tokens_out += 1
        if rid not in self._first_ts and rid in self._submit_ts:
            now = time.monotonic()
            self._first_ts[rid] = now
            self._ttft.append(now - self._submit_ts[rid])
        q = self._queues.get(rid)
        if q is not None:
            q.put(token)

    def _on_retire(self, rid: int, tokens: list,
                   logprobs: list) -> None:
        self._served += 1
        t0 = self._submit_ts.pop(rid, None)
        self._first_ts.pop(rid, None)
        if t0 is not None:
            self._e2e.append(time.monotonic() - t0)
        q = self._queues.get(rid)
        if q is not None:
            q.put(_Final(list(tokens), list(logprobs)))

    def _drive(self) -> None:
        while True:
            with self._work:
                while not self._shutdown and not self._has_work():
                    self._work.wait(timeout=0.5)
                if self._shutdown:
                    return
                # Admit + one decode step under the lock: handler threads
                # only ever touch the engine between steps.
                try:
                    self.engine._admit_free_slots()
                    self.engine._step()
                except Exception as err:  # device OOM, preemption, ...
                    # The engine is in an unknown state: fail loudly —
                    # close every pending queue so no handler blocks
                    # forever, flip /healthz red, and stop driving. A
                    # silently-dead daemon thread would leave a hung
                    # server that health checks keep calling healthy.
                    # Queues that already received _Final completed
                    # normally; only still-open ones get the abort.
                    self._engine_error = f"{type(err).__name__}: {err}"
                    abort = _Abort(self._engine_error)
                    for q in self._queues.values():
                        q.put(abort)
                    return

    def _has_work(self) -> bool:
        return self.engine._pending()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "InferenceServer":
        self._started_at = time.monotonic()
        self._engine_thread.start()
        self._http_thread.start()
        return self

    def stop(self) -> None:
        with self._work:
            self._shutdown = True
            self._work.notify_all()
            # Unblock every in-flight handler: a request mid-decode would
            # otherwise hang its client past process exit. Shutdown
            # truncation is an ABORT — a partial answer must never read
            # as a completed generation (queues that already hold _Final
            # drain it first, FIFO, and complete normally).
            abort = _Abort("server shutdown before generation finished")
            for q in self._queues.values():
                q.put(abort)
        self._httpd.shutdown()
        self._httpd.server_close()  # release the listening socket NOW
        self._engine_thread.join(timeout=10)

    # -- HTTP side ---------------------------------------------------------

    def _decode_stop(self, stop):
        """OpenAI "stop": a string / list of strings (needs a tokenizer),
        or token-native: a list of ints (one sequence) / list of lists."""
        if stop is None:
            return None
        if isinstance(stop, str):
            stop = [stop]
        if not isinstance(stop, list) or not stop:
            raise ValueError("stop must be a string or a non-empty list")
        if all(isinstance(s, str) for s in stop):
            if self.tokenizer is None:
                raise ValueError(
                    "string stop sequences need a tokenizer; send token "
                    "id lists"
                )
            return [
                list(self.tokenizer(s, add_special_tokens=False)["input_ids"])
                for s in stop
            ]
        if all(isinstance(t, int) and not isinstance(t, bool)
               for t in stop):
            return [list(stop)]  # one token-id sequence
        if all(
            isinstance(s, list)
            and s
            and all(isinstance(t, int) and not isinstance(t, bool)
                    for t in s)
            for s in stop
        ):
            return [list(s) for s in stop]
        raise ValueError(
            "stop must be string(s), a token-id list, or a list of "
            "token-id lists"
        )

    def _submit(self, prompt: list[int], max_tokens: Optional[int],
                model: Optional[str] = None,
                temperature: Optional[float] = None,
                stop=None, logit_bias=None,
                ) -> tuple[int, queue.Queue]:
        q: queue.Queue = queue.Queue()
        with self._work:
            if self._engine_error is not None:
                # The drive thread is dead; a submit would register a
                # queue nothing will ever close.
                raise EngineFailedError(self._engine_error)
            if self._shutdown:
                raise EngineFailedError("server is shutting down")
            if model is not None and model == self.model_name:
                model = None  # the served base model, by its public name
            if model is not None:
                # Multi-LoRA routing (models/multilora.py): the request's
                # "model" selects the adapter; resolve_adapter raises
                # ValueError (→ 400) for unknown names.
                if not hasattr(self.engine, "resolve_adapter"):
                    raise ValueError(
                        f"unknown model {model!r} (this server serves "
                        f"{self.model_name!r})"
                    )
                rid = self.engine.submit(
                    prompt, max_new_tokens=max_tokens, adapter=model,
                    temperature=temperature, stop=stop,
                    logit_bias=logit_bias,
                )
            else:
                rid = self.engine.submit(prompt, max_new_tokens=max_tokens,
                                         temperature=temperature, stop=stop,
                                         logit_bias=logit_bias)
            self._queues[rid] = q
            self._submit_ts[rid] = time.monotonic()
            self._work.notify_all()
        return rid, q

    def _finish(self, rid: int) -> None:
        with self._lock:
            self._queues.pop(rid, None)
            # Aborted requests never retire: reap their stamps here so
            # the timing dicts stay bounded on a long-running server.
            self._submit_ts.pop(rid, None)
            self._first_ts.pop(rid, None)

    def _decode_prompt(self, prompt) -> list[int]:
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError(
                    "text prompt needs a tokenizer; send token ids"
                )
            return list(self.tokenizer(prompt)["input_ids"])
        if (isinstance(prompt, list)
                and all(isinstance(t, int) for t in prompt)):
            return prompt
        raise ValueError("prompt must be a string or a list of token ids")

    def _text(self, tokens: list[int]) -> Optional[str]:
        if self.tokenizer is None:
            return None
        return self.tokenizer.decode(tokens)

    def _handler_class(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 for chunk-free streaming semantics, but one
            # request per connection: an idle keep-alive connection would
            # pin a ThreadingHTTPServer handler thread per client with no
            # read timeout.
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet by default
                pass

            def _json(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                # send_header("Connection", "close") also sets
                # self.close_connection in stdlib http.server.
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    if server._engine_error is not None:
                        self._json(503, {"status": "engine failed",
                                         "error": server._engine_error})
                    else:
                        self._json(200, {"status": "ok"})
                elif self.path == "/v1/models":
                    ids = [server.model_name] + list(
                        getattr(server.engine, "adapter_names", [])
                    )
                    self._json(200, {
                        "object": "list",
                        "data": [{"id": i, "object": "model"} for i in ids],
                    })
                elif self.path == "/stats":
                    with server._lock:
                        active = sum(
                            r is not None for r in server.engine._by_slot
                        )
                        depth = len(server.engine._queue)
                        admitting = int(
                            getattr(server.engine, "_admitting", None)
                            is not None
                        )
                        ttft = list(server._ttft)
                        e2e = list(server._e2e)
                        tokens_out = server._tokens_out
                    up = (
                        time.monotonic() - server._started_at
                        if server._started_at is not None else 0.0
                    )
                    self._json(200, {
                        "active_slots": active,
                        "queued": depth,
                        # A chunked admission in flight is in neither
                        # queue nor slot — it must not vanish from the
                        # outstanding-work picture.
                        "admitting": admitting,
                        "slots": server.engine.slots,
                        "served": server._served,
                        "tokens_generated": tokens_out,
                        "tokens_per_sec_lifetime": round(
                            tokens_out / up, 2
                        ) if up > 0 else 0.0,
                        "ttft_s": _percentiles(ttft),
                        "e2e_latency_s": _percentiles(e2e),
                    })
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/v1/completions":
                    self._json(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    prompt = server._decode_prompt(req.get("prompt"))
                    max_tokens = req.get("max_tokens")
                    if max_tokens is not None and (
                        not isinstance(max_tokens, int)
                        or isinstance(max_tokens, bool)
                    ):
                        raise ValueError(
                            f"max_tokens must be an integer, got "
                            f"{max_tokens!r}"
                        )
                    # temperature is validated by the engine's submit()
                    # (isfinite incl. the JSON NaN/Infinity hole) — the
                    # ValueError it raises already becomes a 400 below;
                    # a second copy here could silently diverge.
                    temperature = req.get("temperature")
                    n = req.get("n", 1)
                    if not isinstance(n, int) or isinstance(n, bool) or (
                        not 1 <= n <= 64
                    ):
                        raise ValueError(
                            f"n must be an integer in [1, 64], got {n!r}"
                        )
                    stop = server._decode_stop(req.get("stop"))
                    logit_bias = req.get("logit_bias")
                    if logit_bias is not None and not isinstance(
                        logit_bias, dict
                    ):
                        raise ValueError(
                            "logit_bias must be an object mapping token "
                            "ids to biases"
                        )
                    stream = bool(req.get("stream", False))
                    if stream and n > 1:
                        raise ValueError("stream does not support n > 1")
                    want_logprobs = bool(req.get("logprobs", False))
                    if want_logprobs and stream:
                        raise ValueError(
                            "stream does not support logprobs"
                        )
                    if want_logprobs and not getattr(
                        server.engine, "supports_logprobs", False
                    ):
                        raise ValueError(
                            "this engine does not compute logprobs "
                            "(speculative serving verifies argmax rounds)"
                        )
                except (ValueError, TypeError, json.JSONDecodeError) as err:
                    self._json(400, {"error": str(err)})
                    return
                subs = []
                try:
                    try:
                        for _ in range(n):
                            subs.append(server._submit(
                                prompt, max_tokens, req.get("model"),
                                temperature, stop, logit_bias,
                            ))
                    except EngineFailedError as err:
                        self._json(503, {"error": str(err)})
                        return
                    except ValueError as err:  # over-bucket prompt etc.
                        self._json(400, {"error": str(err)})
                        return
                    if stream:
                        self._stream(*subs[0])
                    else:
                        self._complete(subs, len(prompt), want_logprobs)
                finally:
                    for rid, _ in subs:
                        server._finish(rid)

            def _complete(self, subs, prompt_len, want_logprobs=False):
                choices = []
                for idx, (rid, q) in enumerate(subs):
                    tokens = []
                    while True:
                        item = q.get()
                        if isinstance(item, (_Final, _Abort)):
                            break
                        tokens.append(item)
                    logprobs = []
                    if isinstance(item, _Final):
                        # Authoritative: a stop match truncated tokens
                        # the stream already delivered.
                        tokens = item.tokens
                        logprobs = item.logprobs
                    # Drop the queue BEFORE writing: a client that has
                    # seen the response must be able to observe the
                    # server state already cleaned up (the finally stays
                    # as a safety net).
                    server._finish(rid)
                    if isinstance(item, _Abort):
                        self._json(500, {"error": item.reason,
                                         "partial_tokens": tokens})
                        return
                    choice = {"index": idx, "tokens": tokens,
                              "finish_reason": "stop"}
                    if want_logprobs:
                        choice["logprobs"] = {
                            "tokens": tokens,
                            "token_logprobs": logprobs,
                        }
                    text = server._text(tokens)
                    if text is not None:
                        choice["text"] = text
                    choices.append(choice)
                total = sum(len(c["tokens"]) for c in choices)
                self._json(200, {
                    "id": f"cmpl-{subs[0][0]}",
                    "object": "text_completion",
                    "model": server.model_name,
                    "choices": choices,
                    "usage": {
                        "prompt_tokens": prompt_len,
                        "completion_tokens": total,
                        "total_tokens": prompt_len + total,
                    },
                })

            def _stream(self, rid, q):
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                # Length-unknown: close delimits the body.
                self.send_header("Connection", "close")
                self.end_headers()
                while True:
                    item = q.get()
                    if isinstance(item, (_Final, _Abort)):
                        server._finish(rid)
                        # An abort-truncated stream must be
                        # distinguishable from a completed one.
                        if isinstance(item, _Abort):
                            self.wfile.write(
                                b"data: " + json.dumps(
                                    {"error": item.reason}
                                ).encode() + b"\n\n"
                            )
                        self.wfile.write(b"data: [DONE]\n\n")
                        self.wfile.flush()
                        return
                    payload = {"id": f"cmpl-{rid}", "token": item}
                    text = server._text([item])
                    if text is not None:
                        payload["text"] = text
                    self.wfile.write(
                        b"data: " + json.dumps(payload).encode() + b"\n\n"
                    )
                    self.wfile.flush()

        return Handler
