"""In-notebook HTTP inference server over the batching engines.

The serving stack's missing front door: the engines (ContinuousBatcher,
PagedBatcher, the speculative pair) are drive-to-completion batch APIs —
a notebook cell submits N prompts and collects N results. A live
endpoint needs the opposite shape: requests arrive whenever, responses
stream back while other slots keep decoding. ``InferenceServer`` puts a
stdlib ThreadingHTTPServer in front of ONE engine thread:

- HTTP handler threads ``submit()`` under the engine lock and block on
  (or stream from) a per-request queue;
- the engine thread loops admit → step while any work exists, sleeping
  on a condition variable when idle — continuous batching across
  requests that never saw each other;
- per-token delivery rides the engines' ``on_token``/``on_retire``
  hooks (models/continuous.py _BatcherBase), so all four engines serve
  unmodified.

Endpoints (OpenAI-completions-shaped, token-native):
- ``POST /v1/completions`` request fields:
  - ``prompt``: token-id list, or a string (needs a ``tokenizer``);
  - ``max_tokens``: per-request cap, clamped to the engine-wide budget;
  - ``temperature``: finite >= 0 (0 = greedy for this request; the
    batch freely mixes greedy and sampled rows);
  - ``n``: 1..64 choices decoded concurrently from one prompt;
  - ``stop``: string(s) via the tokenizer, or token-id list(s) —
    generation ends at (and excludes) the first match; streamed
    responses may still carry the stop tokens (documented divergence);
  - ``logit_bias``: {token id: bias}, clamped ±100 (force/ban);
  - ``logprobs``: true → per-choice ``logprobs.token_logprobs``
    (engines that compute them; rejected on speculative);
  - ``model``: adapter name for multi-LoRA engines;
  - ``stream``: true → ``text/event-stream`` lines
    ``data: {"token": id, "text"?: s}`` ending ``data: [DONE]``
    (requires n=1, no logprobs; error events precede [DONE] on abort).
  Response: ``{"id", "choices": [{"index", "tokens", "text"?,
  "logprobs"?, "finish_reason"}], "usage": {...}}``.
- ``GET /healthz`` — liveness (503 once the engine thread died, or the
  moment a drain starts);
  ``GET /v1/models`` — base + adapters; ``GET /stats`` — active slots,
  queue depth, served/token counts, lifetime tokens/sec, p50/p95
  time-to-first-token + end-to-end latency over the last 256 requests,
  and the lifecycle counters (shed / cancelled / deadline-expired /
  drain duration).

Request lifecycle (overload protection — see ARCHITECTURE.md "Serving
overload protection & request lifecycle"):
- admission control: ``max_queue_depth`` bounds the pending queue;
  full → 429 + Retry-After without touching the engine lock;
  ``max_body_bytes`` caps Content-Length (413 past it);
- deadlines: per-request ``deadline_s`` (server default/ceiling via
  ``default_deadline_s``/``max_deadline_s``); expiry retires the slot
  engine-side at the next _note_token → 504 with partial tokens;
- disconnect cancellation: a broken stream pipe or a gone non-stream
  client cancels its rids; the engine reclaims the slot within one step;
- graceful drain: ``stop()`` rejects new submits (503 + Retry-After),
  waits up to ``drain_s`` for in-flight work, force-aborts stragglers;
- engine failure: a crashed drive loop aborts every waiting queue and
  flips /healthz red with the cause.

Reference parity: the reference deploys notebook POD plumbing and leaves
what runs inside to the user (no serving stack at all — SURVEY.md §2.5);
this is added TPU-runtime scope, the consuming end of the controller's
NB_PREFIX/port wiring.
"""

from __future__ import annotations

import collections
import json
import math
import queue
import select
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kubeflow_tpu.observability import tracing
from kubeflow_tpu.observability.flight import (
    FlightRecorder,
    stall_profiler_from_env,
)


def _percentiles(window) -> dict:
    """{p50, p95} by nearest rank over one sort of the window."""
    if not window:
        return {"p50": None, "p95": None}
    xs = sorted(window)
    n = len(xs)

    def rank(q):
        return round(xs[min(n - 1, max(0, -(-q * n // 100) - 1))], 4)

    return {"p50": rank(50), "p95": rank(95)}


class _Final:
    """Success sentinel carrying the AUTHORITATIVE final token list (a
    stop-sequence match truncates tokens the per-token stream already
    delivered, so non-streaming responses must use the retire payload,
    not the accumulated stream) plus the chosen-token logprobs and the
    finish reason ("stop" for EOS/stop-sequence, "length" for budget
    truncation — OpenAI semantics)."""

    def __init__(self, tokens: list, logprobs: list,
                 finish_reason: str = "stop"):
        self.tokens = tokens
        self.logprobs = logprobs
        self.finish_reason = finish_reason


class _Abort:
    """Queue sentinel for a request that did NOT complete (engine death,
    server shutdown, deadline, cancellation) — per-queue, so a request
    that already finished normally can never be mislabeled by a later
    global failure."""

    def __init__(self, reason: str):
        self.reason = reason


class EngineFailedError(RuntimeError):
    """The engine thread is dead; submits are refused (503)."""


class OverloadedError(RuntimeError):
    """The pending queue is at max_queue_depth: the request is SHED
    (429 + Retry-After) instead of parking a handler thread on a queue
    the engine will not reach for a long time."""


class DrainingError(RuntimeError):
    """The server is draining (stop()/SIGTERM): new submits are refused
    (503 + Retry-After) while in-flight requests finish."""


def _client_gone(conn) -> bool:
    """True when the peer has closed its end: the socket selects
    readable but a MSG_PEEK read returns b"" (EOF) or errors. A client
    that is merely slow selects NOT-readable (it sent its whole request)
    and is left alone."""
    try:
        r, _, _ = select.select([conn], [], [], 0)
        if not r:
            return False
        return conn.recv(1, socket.MSG_PEEK) == b""
    except (OSError, ValueError):
        return True


def _read_body(handler, limit: int) -> bytes:
    """THE body read for handler threads: refuses Content-Length past
    ``limit`` BEFORE reading a byte (the kftpu-unbounded-handler-read
    semgrep rule forbids bare rfile.read in serving/webhook handlers —
    an attacker-sized body must never be buffered whole into host
    memory). Raises ValueError on garbage lengths."""
    length = int(handler.headers.get("Content-Length", 0))
    if length < 0:
        raise ValueError(f"invalid Content-Length {length}")
    if length > limit:
        raise BodyTooLarge(length, limit)
    return handler.rfile.read(length)


class BodyTooLarge(ValueError):
    def __init__(self, length: int, limit: int):
        super().__init__(
            f"request body {length} bytes exceeds the {limit}-byte limit"
        )
        self.length = length
        self.limit = limit


def serving_port_from_env(default: int = 8000) -> int:
    """Consuming end of the ``tpu-serving-port`` annotation: the webhook
    projects it into KUBEFLOW_TPU_SERVING_PORT (api/annotations.py), the
    controller opens it in the ctrl NetworkPolicy and surfaces worker-0's
    address as status.tpu.servingEndpoint. Raises on garbage — a hand-set
    env var must not silently serve on the wrong port."""
    import os

    from kubeflow_tpu.api.annotations import SERVING_ENV_NAME

    value = os.environ.get(SERVING_ENV_NAME, "").strip()
    if not value:
        return default
    from kubeflow_tpu.api.annotations import parse_profiling_port

    port = parse_profiling_port(value)
    if port is None:
        raise ValueError(
            f"{SERVING_ENV_NAME}={value!r}: want a port in "
            "1024..65535"
        )
    return port


def ragged_from_env() -> tuple[bool, Optional[int]]:
    """Consuming end of the serving-engine ragged knobs: the
    ``(ragged, token_budget)`` pair for engine construction
    (``PagedBatcher(ragged=..., token_budget=...)``), with None budget
    meaning the engine default. Raises on garbage — a hand-set env var
    must not silently fall back to defaults."""
    import os

    from kubeflow_tpu.webhook.tpu_env import (
        KUBEFLOW_TPU_RAGGED_TOKEN_BUDGET,
        KUBEFLOW_TPU_SERVING_RAGGED,
    )

    raw = os.environ.get(KUBEFLOW_TPU_SERVING_RAGGED, "").strip().lower()
    if raw not in ("", "0", "1", "true", "false"):
        raise ValueError(
            f"{KUBEFLOW_TPU_SERVING_RAGGED}={raw!r}: want 0/1/true/false"
        )
    ragged = raw in ("1", "true")
    budget: Optional[int] = None
    raw_b = os.environ.get(KUBEFLOW_TPU_RAGGED_TOKEN_BUDGET, "").strip()
    if raw_b:
        try:
            budget = int(raw_b)
        except ValueError:
            budget = 0
        if budget <= 0:
            raise ValueError(
                f"{KUBEFLOW_TPU_RAGGED_TOKEN_BUDGET}={raw_b!r}: want a "
                "positive integer"
            )
    return ragged, budget


def kv_pool_from_env() -> dict:
    """Consuming end of the HBM-economy knobs: the ``kv_bits`` /
    ``hbm_fraction`` / ``swap_bytes`` keyword dict for PagedBatcher
    construction, so a replica runs a quantized, HBM-sized, swap-enabled
    pool purely from env (examples/serve_http.py consumes this next to
    ``ragged_from_env``). Unset vars keep the engine defaults. Raises on
    garbage — a hand-set env var must not silently fall back."""
    import os

    from kubeflow_tpu.webhook.tpu_env import (
        KUBEFLOW_TPU_HBM_FRACTION,
        KUBEFLOW_TPU_KV_BITS,
        KUBEFLOW_TPU_KV_SWAP_BYTES,
    )

    kw: dict = {}
    raw = os.environ.get(KUBEFLOW_TPU_KV_BITS, "").strip()
    if raw:
        if raw not in ("0", "8"):
            raise ValueError(
                f"{KUBEFLOW_TPU_KV_BITS}={raw!r}: want 0 (bf16) or 8 "
                "(int8 values + bf16 scales)"
            )
        kw["kv_bits"] = int(raw)
    raw = os.environ.get(KUBEFLOW_TPU_HBM_FRACTION, "").strip()
    if raw:
        try:
            fraction = float(raw)
        except ValueError:
            fraction = 0.0
        if not 0.0 < fraction <= 1.0:
            raise ValueError(
                f"{KUBEFLOW_TPU_HBM_FRACTION}={raw!r}: want a float in "
                "(0, 1]"
            )
        kw["hbm_fraction"] = fraction
    raw = os.environ.get(KUBEFLOW_TPU_KV_SWAP_BYTES, "").strip()
    if raw:
        try:
            swap = int(raw)
        except ValueError:
            swap = -1
        if swap < 0:
            raise ValueError(
                f"{KUBEFLOW_TPU_KV_SWAP_BYTES}={raw!r}: want a "
                "non-negative byte count"
            )
        kw["swap_bytes"] = swap
    return kw


def tier_role_from_env() -> str:
    """Consuming end of the disaggregated-serving role knob: what this
    replica advertises on /stats (the gateway's tier membership signal).
    Raises on garbage — a hand-set env var must not silently fall back."""
    import os

    from kubeflow_tpu.webhook.tpu_env import KUBEFLOW_TPU_GATEWAY_TIER_ROLE

    raw = os.environ.get(KUBEFLOW_TPU_GATEWAY_TIER_ROLE, "").strip().lower()
    if raw not in ("", "fused", "prefill", "decode"):
        raise ValueError(
            f"{KUBEFLOW_TPU_GATEWAY_TIER_ROLE}={raw!r}: want "
            "fused/prefill/decode"
        )
    return raw or "fused"


def spec_from_env() -> tuple[int, bool]:
    """Consuming end of the speculative-decoding knobs: the
    ``(draft_len, adaptive)`` pair for engine construction — draft_len 0
    means speculation off, otherwise
    ``SpeculativePagedBatcher(k_spec=draft_len, adaptive=...)``
    (examples/serve_http.py consumes this next to ``ragged_from_env``).
    Raises on garbage — a hand-set env var must not silently fall
    back."""
    import os

    from kubeflow_tpu.webhook.tpu_env import (
        KUBEFLOW_TPU_SPEC_ADAPTIVE,
        KUBEFLOW_TPU_SPEC_DRAFT_LEN,
    )

    draft_len = 0
    raw = os.environ.get(KUBEFLOW_TPU_SPEC_DRAFT_LEN, "").strip()
    if raw:
        try:
            draft_len = int(raw)
        except ValueError:
            draft_len = -1
        if draft_len < 0:
            raise ValueError(
                f"{KUBEFLOW_TPU_SPEC_DRAFT_LEN}={raw!r}: want a "
                "non-negative draft length (0 disables speculation)"
            )
    raw = os.environ.get(KUBEFLOW_TPU_SPEC_ADAPTIVE, "").strip().lower()
    if raw not in ("", "0", "1", "true", "false"):
        raise ValueError(
            f"{KUBEFLOW_TPU_SPEC_ADAPTIVE}={raw!r}: want 0/1/true/false"
        )
    adaptive = raw in ("1", "true")
    if adaptive and not draft_len:
        raise ValueError(
            f"{KUBEFLOW_TPU_SPEC_ADAPTIVE}=1 without "
            f"{KUBEFLOW_TPU_SPEC_DRAFT_LEN}: the adaptive range is "
            "[1, draft_len], so a draft length must be set"
        )
    return draft_len, adaptive


def serving_tp_from_env() -> int:
    """Consuming end of the tensor-parallel serving knob: the tp degree
    for ``models/tp_serving.serving_plan`` — the replica's engine spans
    a tp-device mesh (weights model-sharded, paged KV head-sharded)
    while staying one HTTP endpoint. Unset/1 keeps the classic
    single-chip engine. Raises on garbage — a hand-set env var must not
    silently fall back to one chip; model-shape and device-count
    validation happens at plan construction (fail-fast at startup)."""
    import os

    from kubeflow_tpu.webhook.tpu_env import KUBEFLOW_TPU_SERVING_TP

    raw = os.environ.get(KUBEFLOW_TPU_SERVING_TP, "").strip()
    if not raw:
        return 1
    try:
        tp = int(raw)
    except ValueError:
        tp = 0
    if tp < 1:
        raise ValueError(
            f"{KUBEFLOW_TPU_SERVING_TP}={raw!r}: want an integer >= 1 "
            "(1 keeps the single-chip engine)"
        )
    return tp


def lora_cache_from_env() -> int:
    """Consuming end of the hot-adapter cache bound: slots for
    ``MultiLoraPagedBatcher(lora_cache_slots=...)`` (0 = uncapped
    residency, counters off). Raises on garbage — a hand-set env var
    must not silently fall back."""
    import os

    from kubeflow_tpu.webhook.tpu_env import KUBEFLOW_TPU_LORA_CACHE_SLOTS

    raw = os.environ.get(KUBEFLOW_TPU_LORA_CACHE_SLOTS, "").strip()
    if not raw:
        return 0
    try:
        slots = int(raw)
    except ValueError:
        slots = -1
    if slots < 0:
        raise ValueError(
            f"{KUBEFLOW_TPU_LORA_CACHE_SLOTS}={raw!r}: want a "
            "non-negative slot count (0 leaves residency uncapped)"
        )
    return slots


class InferenceServer:
    """HTTP front-end driving one batching engine on a background thread.

    >>> engine = ContinuousBatcher(params, cfg, slots=4, cache_len=512)
    >>> srv = InferenceServer(engine, port=0)   # 0 = ephemeral
    >>> srv.start()
    >>> # POST http://127.0.0.1:{srv.port}/v1/completions
    >>> srv.stop()
    """

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 8000,
                 tokenizer=None, model_name: str = "kubeflow-tpu",
                 max_queue_depth: int = 64,
                 max_body_bytes: int = 4 << 20,
                 default_deadline_s: Optional[float] = None,
                 max_deadline_s: Optional[float] = None,
                 drain_s: float = 5.0,
                 metrics=None,
                 tier_role: str = "fused"):
        # Request-lifecycle knobs (all overload protection):
        # - max_queue_depth: pending (unslotted) requests beyond this are
        #   shed with 429 + Retry-After instead of parking handler
        #   threads — NotebookOS-style bounded queueing;
        # - max_body_bytes: Content-Length cap (413 past it);
        # - default_deadline_s / max_deadline_s: per-request TTL applied
        #   when the client sends none / ceiling on what it may ask for;
        # - drain_s: stop()/SIGTERM lets in-flight requests finish this
        #   long before force-aborting stragglers;
        # - metrics: optional metrics.Metrics bundle mirroring the
        #   /stats counters into Prometheus.
        if max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1, got "
                             f"{max_queue_depth}")
        if tier_role not in ("fused", "prefill", "decode"):
            raise ValueError(
                f"tier_role must be fused/prefill/decode, got {tier_role!r}"
            )
        # Disaggregated serving: the role this replica ADVERTISES on
        # /stats ("prefill" runs chunked prefill + exports paged KV,
        # "decode" imports payloads and streams tokens, "fused" does
        # both). The role is advisory — the engine serves whatever
        # arrives — tier membership is the gateway's routing decision.
        self.tier_role = tier_role
        # Env-gated tracing (no-op unless KUBEFLOW_TPU_TRACE_* is set, and
        # never clobbers a provider a test already installed).
        tracing.configure_from_env()
        self.max_queue_depth = max_queue_depth
        self.max_body_bytes = max_body_bytes
        self.default_deadline_s = default_deadline_s
        self.max_deadline_s = max_deadline_s
        self.drain_s = drain_s
        self.metrics = metrics
        # The speculative engines are thin wrappers delegating to an
        # inner batcher (`_engine`) that owns the queue/slots/step loop —
        # hooks and the drive loop must target the inner one. The WRAPPER
        # owns the acceptance stats, so keep a ref for /stats + metrics.
        self.engine = getattr(engine, "_engine", engine)
        self._spec = engine if hasattr(engine, "spec_stats") else None
        if model_name in getattr(self.engine, "adapter_names", ()):
            # The "model == model_name → base" shortcut in _submit would
            # make that adapter silently unreachable.
            raise ValueError(
                f"model_name {model_name!r} collides with an adapter "
                "name — requests for the adapter would be routed to the "
                "base model"
            )
        self.tokenizer = tokenizer
        self.model_name = model_name
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queues: dict[int, queue.Queue] = {}
        self._shutdown = False
        self._draining = False
        self._stopped = False
        self._served = 0
        self._engine_error: Optional[str] = None
        # Lifecycle counters. _shed has its OWN lock: the shed fast path
        # must not wait on the engine lock (held for whole decode steps)
        # — a full queue answers 429 in milliseconds, and the counter
        # still has to be exact under concurrent submits.
        self._shed = 0
        self._shed_lock = threading.Lock()
        self._cancelled = 0          # disconnects + explicit cancels
        self._deadline_expired = 0
        self._drain_duration: Optional[float] = None
        self._drain_started: Optional[float] = None
        # Serving observability (host-side, O(1) per event): per-request
        # submit/first-token stamps plus sliding windows of time-to-first-
        # token and end-to-end latency, and a token counter for
        # throughput. All read under the lock by /stats.
        self._submit_ts: dict[int, float] = {}
        self._first_ts: dict[int, float] = {}
        self._last_tok_ts: dict[int, float] = {}
        self._ttft = collections.deque(maxlen=256)
        self._e2e = collections.deque(maxlen=256)
        # Telemetry-plane inputs (the gateway scrapes these off /stats):
        # submit→batcher-pickup wait and the gap between consecutive
        # tokens of one stream — the queue_wait_p95 / inter_token_p95
        # SLO objectives replica-side.
        self._queue_wait = collections.deque(maxlen=256)
        self._itl = collections.deque(maxlen=256)
        self._tokens_out = 0
        self._started_at = None  # stamped in start(): uptime = serving time
        # Prometheus Counters only inc(): mirror the engine's monotonic
        # prefix-cache tallies by delta, last-mirrored snapshot here.
        self._prefix_mirrored = (0, 0, 0)
        self._swap_mirrored = (0, 0, 0)
        self._spec_mirrored = (0, 0)
        self._lora_mirrored = (0, 0, 0)
        self._stalls_mirrored = 0
        # Per-request span registry for the TTFT decomposition: rid →
        # {"root", "queue_wait", "prefill"} spans. queue_wait starts at
        # submit (handler thread) and ends at batcher pickup (engine
        # thread, via on_admit); prefill ends at the first token. All
        # mutations happen under self._lock.
        self._req_spans: dict[int, dict] = {}
        self._admit_ts: dict[int, float] = {}
        # Pending KV exports (disaggregated prefill tier): rid →
        # {"skip", "payload", "error"}. Registered at submit under the
        # engine lock; _on_token serializes the blocks at first-token
        # time (the only moment the slot still holds them AND the
        # sampled token is known); the /kv/prefill handler reads the
        # result after _Final arrives. Reaped in _finish.
        self._kv_exports: dict[int, dict] = {}
        # Flight recorder: always on (a deque append per step), sharing
        # the engine's injectable clock so stall tests can drive it.
        self.flight = FlightRecorder(
            clock=getattr(self.engine, "_clock", None)
        )
        # Stall→profile capture: armed only when the env names a log dir
        # (see flight.StallProfiler); the hook fires outside the
        # recorder's lock, so the drive loop never waits on jax.profiler.
        self._stall_profiler = stall_profiler_from_env()
        if self._stall_profiler is not None:
            self.flight.on_stall = self._stall_profiler.on_stall
        self._httpd = ThreadingHTTPServer((host, port), self._handler_class())
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._engine_thread = threading.Thread(
            target=self._drive, name="inference-engine", daemon=True
        )
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="inference-http",
            daemon=True,
        )
        # Hooks go on the RESOLVED engine — it is the object whose
        # _note_token/_retire read them; the spec wrappers forward nothing.
        self.engine.on_token = self._on_token
        self.engine.on_retire = self._on_retire
        self.engine.on_abort = self._on_abort
        self.engine.on_admit = self._on_admit
        self.engine.flight = self.flight

    # -- engine side (all under self._lock) --------------------------------

    def _on_admit(self, rid: int) -> None:
        """Batcher pickup (engine thread): the queue-wait phase ends here
        and the prefill phase begins — the span boundary that lets TTFT
        decompose into queue_wait + prefill + first_decode."""
        now = time.monotonic()
        self._admit_ts[rid] = now
        t0 = self._submit_ts.get(rid)
        if t0 is not None:
            self._queue_wait.append(now - t0)
        spans = self._req_spans.get(rid)
        if spans is None:
            return
        qs = spans.pop("queue_wait", None)
        if qs is not None:
            qs.end()
        spans["prefill"] = tracing.get_tracer("server").begin_span(
            "prefill", parent=spans.get("root"), rid=rid
        )

    def _end_request_spans(self, rid: int, error: str = "") -> None:
        """Close any still-open per-request child spans (the root span is
        owned by the handler's with-block). Abort paths pass the reason so
        a truncated request's spans read as errors."""
        spans = self._req_spans.pop(rid, None)
        if not spans:
            return
        for name, span in spans.items():
            if name == "root" or span is None:
                continue
            if error:
                span.record_error(RuntimeError(error))
            span.end()

    def _on_token(self, rid: int, token: int) -> None:
        exp = self._kv_exports.get(rid)
        if exp is not None and exp["payload"] is None and exp["error"] is None:
            # First token of a prefill-tier request: the slot still holds
            # its blocks and positions[slot] == prompt KV length, so this
            # is the one moment the handoff payload can be cut. The
            # request retires right after (max_new_tokens=1) — its prefix
            # chains stay registered, warming this replica's cache.
            try:
                exp["payload"] = self.engine.export_blocks(
                    rid, skip_keys=exp["skip"]
                )
            except Exception as err:  # surfaced to the gateway as failure
                exp["error"] = str(err)
        self._tokens_out += 1
        if rid in self._submit_ts:
            now_t = time.monotonic()
            prev = self._last_tok_ts.get(rid)
            if prev is not None:
                self._itl.append(now_t - prev)
            self._last_tok_ts[rid] = now_t
        if rid not in self._first_ts and rid in self._submit_ts:
            now = time.monotonic()
            self._first_ts[rid] = now
            self._ttft.append(now - self._submit_ts[rid])
            spans = self._req_spans.get(rid)
            if spans is not None:
                ps = spans.pop("prefill", None)
                root = spans.get("root")
                if ps is not None:
                    ps.end()
                    # First-token sampling is fused into the dispatch that
                    # completes the prefill (PR 6), so first_decode is the
                    # (≈0) tail between prefill end and token delivery:
                    # queue_wait + prefill + first_decode sums exactly to
                    # the submit→first-token wall clock.
                    fd = tracing.get_tracer("server").begin_span(
                        "first_decode", parent=root, rid=rid, fused=True
                    )
                    fd.start_time = ps.end_time
                    fd.end()
                if root is not None:
                    root.add_event("first_token", {
                        "rid": rid,
                        "ttft_s": round(now - self._submit_ts[rid], 6),
                    })
        q = self._queues.get(rid)
        if q is not None:
            q.put(token)

    def _on_retire(self, rid: int, tokens: list,
                   logprobs: list, finish_reason: str = "stop") -> None:
        self._served += 1
        t0 = self._submit_ts.pop(rid, None)
        self._first_ts.pop(rid, None)
        self._admit_ts.pop(rid, None)
        self._last_tok_ts.pop(rid, None)
        if t0 is not None:
            self._e2e.append(time.monotonic() - t0)
        self._end_request_spans(rid)
        q = self._queues.get(rid)
        if q is not None:
            q.put(_Final(list(tokens), list(logprobs), finish_reason))

    def _on_abort(self, rid: int, tokens: list, reason: str) -> None:
        """Engine-side abort (cancel/deadline): the request retired
        WITHOUT completing. Called under the engine lock, from cancel()
        (queued requests) or _note_token (slotted ones)."""
        if reason == "deadline":
            self._deadline_expired += 1
            if self.metrics is not None:
                self.metrics.serving_deadline_expired_total.inc()
        else:
            self._cancelled += 1
            if self.metrics is not None:
                self.metrics.serving_requests_cancelled_total.inc()
        self._submit_ts.pop(rid, None)
        self._first_ts.pop(rid, None)
        self._admit_ts.pop(rid, None)
        self._last_tok_ts.pop(rid, None)
        self._end_request_spans(rid, error=reason)
        q = self._queues.get(rid)
        if q is not None:
            q.put(_Abort(reason))

    def _drive(self) -> None:
        while True:
            with self._work:
                while not self._shutdown and not self._has_work():
                    self._work.wait(timeout=0.5)
                if self._shutdown:
                    return
                # Admit + one decode step under the lock: handler threads
                # only ever touch the engine between steps.
                try:
                    # drive_once = admit + step, timed: feeds the flight
                    # recorder and the per-step engine span. Engines
                    # without it (test fakes) get the raw pair.
                    drive = getattr(self.engine, "drive_once", None)
                    if drive is not None:
                        drive()
                    else:
                        t0 = time.monotonic()
                        self.engine._admit_free_slots()
                        self.engine._step()
                        self.flight.record_step(time.monotonic() - t0)
                    if self.metrics is not None:
                        stalls = self.flight.stalls
                        self.metrics.engine_step_stall_total.inc(
                            stalls - self._stalls_mirrored
                        )
                        self._stalls_mirrored = stalls
                    if (self.metrics is not None
                            and getattr(self.engine, "ragged", False)):
                        self.metrics.serving_ragged_batch_fill.set(
                            self.engine.ragged_fill
                        )
                    if (self.metrics is not None and getattr(
                            self.engine, "_prefix_cache_enabled", False)):
                        h = self.engine.prefix_hits
                        ms = self.engine.prefix_misses
                        ev = self.engine.prefix_evictions
                        ph, pm, pe = self._prefix_mirrored
                        self.metrics.serving_prefix_cache_hits_total.inc(
                            h - ph)
                        self.metrics.serving_prefix_cache_misses_total.inc(
                            ms - pm)
                        self.metrics.serving_prefix_cache_evictions_total \
                            .inc(ev - pe)
                        self._prefix_mirrored = (h, ms, ev)
                        self.metrics.serving_prefix_cached_blocks.set(
                            self.engine.prefix_cached_blocks
                        )
                    if (self.metrics is not None and getattr(
                            self.engine, "swap_bytes_limit", 0)):
                        so = self.engine.kv_swap_out
                        si = self.engine.kv_swap_in
                        rt = self.engine.kv_swap_restored_tokens
                        po, pi, pt = self._swap_mirrored
                        self.metrics.serving_kv_swap_out_total.inc(so - po)
                        self.metrics.serving_kv_swap_in_total.inc(si - pi)
                        self.metrics.serving_kv_swap_restored_tokens_total \
                            .inc(rt - pt)
                        self._swap_mirrored = (so, si, rt)
                        self.metrics.serving_kv_swap_bytes.set(
                            self.engine.swap_bytes_used
                        )
                    if self.metrics is not None and self._spec is not None:
                        st = self._spec.spec_stats()
                        acc, rnd = st["accepted"], st["rounds"]
                        pa, pr = self._spec_mirrored
                        self.metrics.serving_spec_accept_total.inc(acc - pa)
                        self.metrics.serving_spec_rounds_total.inc(rnd - pr)
                        self._spec_mirrored = (acc, rnd)
                    lc_fn = getattr(self.engine, "lora_cache_stats", None)
                    if self.metrics is not None and lc_fn is not None:
                        lc = lc_fn()
                        if lc is not None:
                            h, ms, ev = (lc["hits"], lc["misses"],
                                         lc["evictions"])
                            ph, pm, pe = self._lora_mirrored
                            self.metrics.serving_lora_cache_hits_total \
                                .inc(h - ph)
                            self.metrics.serving_lora_cache_misses_total \
                                .inc(ms - pm)
                            self.metrics \
                                .serving_lora_cache_evictions_total \
                                .inc(ev - pe)
                            self._lora_mirrored = (h, ms, ev)
                except Exception as err:  # device OOM, preemption, ...
                    # The engine is in an unknown state: fail loudly —
                    # close every pending queue so no handler blocks
                    # forever, flip /healthz red, and stop driving. A
                    # silently-dead daemon thread would leave a hung
                    # server that health checks keep calling healthy.
                    # Queues that already received _Final completed
                    # normally; only still-open ones get the abort.
                    self._engine_error = f"{type(err).__name__}: {err}"
                    abort = _Abort(self._engine_error)
                    for q in self._queues.values():
                        q.put(abort)
                    return

    def _has_work(self) -> bool:
        return self.engine._pending()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "InferenceServer":
        self._started_at = time.monotonic()
        self._engine_thread.start()
        self._http_thread.start()
        return self

    def stop(self) -> None:
        """Graceful drain, then hard stop. Phases:

        1. flip ``_draining`` — new submits get 503 + Retry-After and
           /healthz goes unready immediately (a load balancer must stop
           routing here the moment drain starts, not when it ends);
        2. wait up to ``drain_s`` for in-flight work to finish (the
           engine thread keeps driving; queues empty out as requests
           retire normally);
        3. force-abort stragglers and shut the engine thread + listener
           down. Shutdown truncation is an ABORT — a partial answer must
           never read as a completed generation (queues already holding
           _Final drain it first, FIFO, and complete normally).

        Idempotent: a second call returns once the first finished."""
        with self._work:
            if self._stopped:
                return
            if not self._draining:
                self._draining = True
            if self._drain_started is None:
                self._drain_started = time.monotonic()
            drain_started = self._drain_started
            self._work.notify_all()
        deadline = drain_started + self.drain_s
        while time.monotonic() < deadline:
            with self._lock:
                idle = (not self._queues
                        and not self.engine._pending())
                if self._engine_error is not None:
                    idle = True  # nothing will ever finish; stop waiting
            if idle:
                break
            time.sleep(min(0.05, self.drain_s))
        with self._work:
            if self._stopped:
                return
            self._stopped = True
            self._shutdown = True
            self._work.notify_all()
            # Unblock every straggler: a request mid-decode would
            # otherwise hang its client past process exit.
            abort = _Abort("server shutdown before generation finished")
            for q in self._queues.values():
                q.put(abort)
            self._drain_duration = time.monotonic() - drain_started
            if self.metrics is not None:
                self.metrics.serving_drain_seconds.set(self._drain_duration)
        self._httpd.shutdown()
        self._httpd.server_close()  # release the listening socket NOW
        self._engine_thread.join(timeout=10)

    # -- HTTP side ---------------------------------------------------------

    def _decode_stop(self, stop):
        """OpenAI "stop": a string / list of strings (needs a tokenizer),
        or token-native: a list of ints (one sequence) / list of lists."""
        if stop is None:
            return None
        if isinstance(stop, str):
            stop = [stop]
        if not isinstance(stop, list) or not stop:
            raise ValueError("stop must be a string or a non-empty list")
        if all(isinstance(s, str) for s in stop):
            if self.tokenizer is None:
                raise ValueError(
                    "string stop sequences need a tokenizer; send token "
                    "id lists"
                )
            return [
                list(self.tokenizer(s, add_special_tokens=False)["input_ids"])
                for s in stop
            ]
        if all(isinstance(t, int) and not isinstance(t, bool)
               for t in stop):
            return [list(stop)]  # one token-id sequence
        if all(
            isinstance(s, list)
            and s
            and all(isinstance(t, int) and not isinstance(t, bool)
                    for t in s)
            for s in stop
        ):
            return [list(s) for s in stop]
        raise ValueError(
            "stop must be string(s), a token-id list, or a list of "
            "token-id lists"
        )

    def _shed_check(self) -> None:
        """Admission control WITHOUT the engine lock. The drive thread
        holds self._lock for whole admit+step cycles (a JAX compile can
        take seconds), so a shed decision that waited on it would block
        exactly when the server is busiest — the opposite of shedding.
        len() on the engine deque and the flag reads are GIL-atomic;
        the worst race is admitting one request past the cap or shedding
        one early during a step boundary, both acceptable. The counter
        itself is exact (own lock)."""
        if self._draining or self._shutdown:
            raise DrainingError("server is draining; retry elsewhere")
        if self._engine_error is not None:
            raise EngineFailedError(self._engine_error)
        if len(self.engine._queue) >= self.max_queue_depth:
            with self._shed_lock:
                self._shed += 1
            if self.metrics is not None:
                self.metrics.serving_requests_shed_total.inc()
            raise OverloadedError(
                f"pending queue is full ({self.max_queue_depth} deep)"
            )

    def _resolve_deadline(self, deadline_s) -> Optional[float]:
        """Client-requested TTL → effective TTL: default when absent,
        clamped to max_deadline_s when configured. Validation of the
        value itself (finite, > 0) lives in engine submit()."""
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        if deadline_s is not None and self.max_deadline_s is not None:
            deadline_s = min(float(deadline_s), self.max_deadline_s)
        return deadline_s

    def _submit(self, prompt: list[int], max_tokens: Optional[int],
                model: Optional[str] = None,
                temperature: Optional[float] = None,
                stop=None, logit_bias=None,
                deadline_s: Optional[float] = None,
                kv_export=None,  # skip-key set → register a pending export
                ) -> tuple[int, queue.Queue]:
        self._shed_check()  # fast path: 429/503 without the engine lock
        q: queue.Queue = queue.Queue()
        deadline_s = self._resolve_deadline(deadline_s)
        with self._work:
            # Re-check under the lock: flags may have flipped while we
            # waited for a decode step to finish.
            if self._engine_error is not None:
                # The drive thread is dead; a submit would register a
                # queue nothing will ever close.
                raise EngineFailedError(self._engine_error)
            if self._draining or self._shutdown:
                raise DrainingError("server is draining; retry elsewhere")
            if model is not None and model == self.model_name:
                model = None  # the served base model, by its public name
            if model is not None:
                # Multi-LoRA routing (models/multilora.py): the request's
                # "model" selects the adapter; resolve_adapter raises
                # ValueError (→ 400) for unknown names.
                if not hasattr(self.engine, "resolve_adapter"):
                    raise ValueError(
                        f"unknown model {model!r} (this server serves "
                        f"{self.model_name!r})"
                    )
                rid = self.engine.submit(
                    prompt, max_new_tokens=max_tokens, adapter=model,
                    temperature=temperature, stop=stop,
                    logit_bias=logit_bias, deadline_s=deadline_s,
                )
            else:
                rid = self.engine.submit(prompt, max_new_tokens=max_tokens,
                                         temperature=temperature, stop=stop,
                                         logit_bias=logit_bias,
                                         deadline_s=deadline_s)
            if kv_export is not None:
                # Registered under the same lock hold as the submit:
                # on_token cannot fire for this rid until the drive
                # thread re-acquires the lock, so the registry is always
                # visible before the export moment.
                self._kv_exports[rid] = {
                    "skip": kv_export, "payload": None, "error": None,
                }
            self._queues[rid] = q
            self._submit_ts[rid] = time.monotonic()
            if tracing.enabled():
                # Handler thread: the request root span (do_POST's with-
                # block) is this thread's current span; queue_wait starts
                # now and ends at batcher pickup in the ENGINE thread —
                # begin_span, because a cross-thread span must not become
                # this thread's contextvar-current span.
                root = tracing.current_span()
                self._req_spans[rid] = {
                    "root": root,
                    "queue_wait": tracing.get_tracer("server").begin_span(
                        "queue_wait", parent=root, rid=rid,
                        queue_depth=len(self.engine._queue),
                    ),
                }
            if self.metrics is not None:
                self.metrics.serving_queue_depth.set(
                    len(self.engine._queue)
                )
            self._work.notify_all()
        return rid, q

    def _submit_import(self, payload: dict, max_tokens: Optional[int],
                       temperature: Optional[float] = None,
                       stop=None, logit_bias=None,
                       deadline_s: Optional[float] = None,
                       ) -> tuple[int, queue.Queue]:
        """Decode-tier admission: install an exported KV payload directly
        into a slot (no re-prefill, no queue). Mirrors _submit's
        bookkeeping; the queue-wait phase is zero by construction, so the
        span registered under "prefill" is the import itself — _on_token
        closes it at the (deferred) first token, keeping the
        queue_wait + prefill + first_decode TTFT decomposition intact."""
        self._shed_check()
        q: queue.Queue = queue.Queue()
        deadline_s = self._resolve_deadline(deadline_s)
        with self._work:
            if self._engine_error is not None:
                raise EngineFailedError(self._engine_error)
            if self._draining or self._shutdown:
                raise DrainingError("server is draining; retry elsewhere")
            if not hasattr(self.engine, "import_blocks"):
                raise ValueError(
                    "this replica's engine cannot import KV payloads "
                    "(paged engines only)"
                )
            rid = self.engine.import_blocks(
                payload, max_new_tokens=max_tokens,
                temperature=temperature, stop=stop,
                logit_bias=logit_bias, deadline_s=deadline_s,
            )
            if rid is None:
                # Admission-watermark refusal: no slot or blocks free.
                # 429 like any other shed — the gateway retries/falls
                # back to fused routing.
                with self._shed_lock:
                    self._shed += 1
                if self.metrics is not None:
                    self.metrics.serving_requests_shed_total.inc()
                raise OverloadedError(
                    "no free slot/blocks for KV import; retry elsewhere"
                )
            now = time.monotonic()
            self._queues[rid] = q
            self._submit_ts[rid] = now
            self._admit_ts[rid] = now
            if tracing.enabled():
                root = tracing.current_span()
                self._req_spans[rid] = {
                    "root": root,
                    "prefill": tracing.get_tracer("server").begin_span(
                        "kv_import", parent=root, rid=rid,
                        blocks=len(payload.get("blocks") or []),
                    ),
                }
            self._work.notify_all()
        return rid, q

    def _cancel(self, rid: int, reason: str = "client disconnected") -> None:
        """Disconnect/abandonment path: mark the request cancelled under
        the engine lock. Queued requests abort immediately (on_abort
        fires inline); slotted ones retire at their next _note_token —
        within one engine step — instead of decoding dead work to full
        budget. Idempotent; unknown rids are a no-op."""
        with self._work:
            if self._engine_error is None and not self._stopped:
                self.engine.cancel(rid, reason)
            self._work.notify_all()

    def _finish(self, rid: int) -> None:
        with self._lock:
            self._queues.pop(rid, None)
            self._kv_exports.pop(rid, None)
            # Aborted requests never retire: reap their stamps here so
            # the timing dicts stay bounded on a long-running server.
            self._submit_ts.pop(rid, None)
            self._first_ts.pop(rid, None)
            self._admit_ts.pop(rid, None)
            self._last_tok_ts.pop(rid, None)
            self._end_request_spans(rid)

    def _decode_prompt(self, prompt) -> list[int]:
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError(
                    "text prompt needs a tokenizer; send token ids"
                )
            return list(self.tokenizer(prompt)["input_ids"])
        if (isinstance(prompt, list)
                and all(isinstance(t, int) for t in prompt)):
            return prompt
        raise ValueError("prompt must be a string or a list of token ids")

    def _text(self, tokens: list[int]) -> Optional[str]:
        if self.tokenizer is None:
            return None
        return self.tokenizer.decode(tokens)

    def _handler_class(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 for chunk-free streaming semantics, but one
            # request per connection: an idle keep-alive connection would
            # pin a ThreadingHTTPServer handler thread per client with no
            # read timeout.
            protocol_version = "HTTP/1.1"

            # Correlation id echoed on every completion response
            # (X-Request-Id header and mid-stream SSE error payloads):
            # the trace id when the caller sent a traceparent, a fresh
            # id otherwise, so any response line can be joined against
            # the trace export.
            _req_id = None

            def log_message(self, *args):  # quiet by default
                pass

            def _json(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                if self._req_id:
                    self.send_header("X-Request-Id", self._req_id)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                # send_header("Connection", "close") also sets
                # self.close_connection in stdlib http.server.
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)

            def _retry_after_close(self, error: str,
                                   retry_after: int = 1) -> None:
                """Finish a shed/drain response: the status line was
                already sent; add Retry-After (RFC 6585 for 429,
                RFC 9110 for 503) and the JSON detail."""
                body = json.dumps({"error": error}).encode()
                self.send_header("Retry-After", str(retry_after))
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    if server._engine_error is not None:
                        self._json(503, {"status": "engine failed",
                                         "error": server._engine_error})
                    elif server._draining:
                        # Unready the INSTANT drain starts: the load
                        # balancer must route around this replica while
                        # in-flight requests finish, not after.
                        self._json(503, {"status": "draining"})
                    else:
                        self._json(200, {"status": "ok"})
                elif self.path == "/v1/models":
                    ids = [server.model_name] + list(
                        getattr(server.engine, "adapter_names", [])
                    )
                    self._json(200, {
                        "object": "list",
                        "data": [{"id": i, "object": "model"} for i in ids],
                    })
                elif self.path == "/stats":
                    with server._lock:
                        active = sum(
                            r is not None for r in server.engine._by_slot
                        )
                        depth = len(server.engine._queue)
                        # Mid-admission work is in neither queue nor
                        # slot: one chunked admission, or any number of
                        # ragged prompt cursors.
                        admitting = int(
                            getattr(server.engine, "_admitting", None)
                            is not None
                        ) + len(getattr(server.engine, "_ragged_admit", {}))
                        pc = None
                        if getattr(server.engine, "_prefix_cache_enabled",
                                   False):
                            hits = server.engine.prefix_hits
                            misses = server.engine.prefix_misses
                            pc = {
                                "hits": hits,
                                "misses": misses,
                                "evictions": server.engine.prefix_evictions,
                                "cached_blocks":
                                    server.engine.prefix_cached_blocks,
                                "hit_ratio": round(
                                    hits / (hits + misses), 4
                                ) if hits + misses else 0.0,
                            }
                        kv = None
                        if hasattr(server.engine, "import_blocks"):
                            kv = {
                                "exports": server.engine.kv_exports,
                                "imports": server.engine.kv_imports,
                                "import_blocks_reused":
                                    server.engine.kv_import_blocks_reused,
                                "import_blocks_written":
                                    server.engine.kv_import_blocks_written,
                                # Fleet KV tier: cache-chain peer
                                # export/import traffic (no live
                                # request attached).
                                "chain_exports": getattr(
                                    server.engine, "kv_chain_exports", 0
                                ),
                                "chain_imports": getattr(
                                    server.engine, "kv_chain_imports", 0
                                ),
                            }
                        swap = None
                        if getattr(server.engine, "swap_bytes_limit", 0):
                            swap = {
                                "swap_out": server.engine.kv_swap_out,
                                "swap_in": server.engine.kv_swap_in,
                                "restored_tokens":
                                    server.engine.kv_swap_restored_tokens,
                                "swap_bytes": server.engine.swap_bytes_used,
                                "swap_blocks": server.engine.swap_blocks,
                                "swap_bytes_limit":
                                    server.engine.swap_bytes_limit,
                            }
                        pool = None
                        if getattr(server.engine, "num_blocks", None):
                            pool = {
                                "num_blocks": server.engine.num_blocks,
                                "source": getattr(
                                    server.engine, "pool_source", "config"
                                ),
                            }
                        # Tensor-parallel replica: the engine spans a
                        # mesh. Absent (not null) for one-chip engines,
                        # so their /stats bytes are unchanged.
                        mesh = getattr(server.engine, "mesh_axes", None)
                        rag = None
                        if getattr(server.engine, "ragged", False):
                            steps = server.engine.ragged_steps
                            rag = {
                                "batch_fill": round(
                                    server.engine.ragged_fill, 4
                                ),
                                "steps": steps,
                                "tokens": server.engine.ragged_tokens,
                                "tokens_per_step": round(
                                    server.engine.ragged_tokens / steps, 2
                                ) if steps else 0.0,
                            }
                        # Speculative wrapper stats ("accepted"/"rounds"
                        # surface tpu_serving_spec_* per STATS_PARITY)
                        # and the bounded hot-adapter cache's counters
                        # ("hits"/"misses"/"evictions" →
                        # tpu_serving_lora_cache_*).
                        spec = (server._spec.spec_stats()
                                if server._spec is not None else None)
                        lc_fn = getattr(server.engine,
                                        "lora_cache_stats", None)
                        lora = lc_fn() if lc_fn is not None else None
                        ttft = list(server._ttft)
                        e2e = list(server._e2e)
                        queue_wait = list(server._queue_wait)
                        itl = list(server._itl)
                        tokens_out = server._tokens_out
                        cancelled = server._cancelled
                        deadline_expired = server._deadline_expired
                    with server._shed_lock:
                        shed = server._shed
                    up = (
                        time.monotonic() - server._started_at
                        if server._started_at is not None else 0.0
                    )
                    fl = server.flight.snapshot()
                    if server._stall_profiler is not None:
                        fl["stall_profiles"] = (
                            server._stall_profiler.summary()
                        )
                    self._json(200, {
                        "active_slots": active,
                        "queued": depth,
                        # A chunked admission in flight is in neither
                        # queue nor slot — it must not vanish from the
                        # outstanding-work picture.
                        "admitting": admitting,
                        "slots": server.engine.slots,
                        "served": server._served,
                        "tokens_generated": tokens_out,
                        "tokens_per_sec_lifetime": round(
                            tokens_out / up, 2
                        ) if up > 0 else 0.0,
                        "ttft_s": _percentiles(ttft),
                        "e2e_latency_s": _percentiles(e2e),
                        # Telemetry-plane inputs: the gateway's
                        # FleetTelemetry scrape turns these into the
                        # queue_wait_p95 SLO gauge per replica.
                        "queue_wait_s": _percentiles(queue_wait),
                        "inter_token_s": _percentiles(itl),
                        # Lifecycle counters (the tentpole's observables):
                        "requests_shed": shed,
                        "requests_cancelled": cancelled,
                        "deadline_expired": deadline_expired,
                        "max_queue_depth": server.max_queue_depth,
                        "draining": server._draining,
                        "drain_duration_s": server._drain_duration,
                        # Disaggregated serving: the gateway's tier-
                        # membership signal plus the engine's handoff
                        # counters.
                        "tier_role": server.tier_role,
                        **({"kv_handoff": kv} if kv is not None else {}),
                        **({"kv_swap": swap} if swap is not None else {}),
                        # HBM-economy sizing outcome: what
                        # pool_blocks_from_hbm actually chose, so an
                        # operator can tell a measured-HBM pool from the
                        # conservative fallback floor.
                        **({"kv_pool": pool} if pool is not None else {}),
                        **({"mesh": mesh} if mesh is not None else {}),
                        **({"ragged": rag} if rag is not None else {}),
                        **({"speculative": spec}
                           if spec is not None else {}),
                        **({"lora_cache": lora}
                           if lora is not None else {}),
                        **({"prefix_cache": pc} if pc is not None else {}),
                        # Flight-recorder view (stall count surfaces the
                        # tpu_engine_step_stall_total family per the
                        # STATS_PARITY table in metrics/metrics.py).
                        "engine_step_stalls": fl["stalls"],
                        "flight": fl,
                    })
                elif self.path == "/debug/traces":
                    ring = tracing.trace_ring()
                    self._json(200, {
                        "traces": ring.snapshot() if ring else [],
                    })
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                if self.path not in ("/v1/completions", "/kv/prefill",
                                     "/kv/probe", "/kv/chain",
                                     "/kv/chain/import"):
                    self._json(404, {"error": "not found"})
                    return
                # Root span for the replica-side request. A gateway hop
                # arrives with a traceparent header — the span joins
                # that trace so the export shows one gateway→server→
                # engine chain per request. Every /kv/* hop joins too:
                # a peer fetch appears in the same trace as the request
                # that triggered it.
                with tracing.get_tracer("server").start_span(
                    "server.request",
                    traceparent=self.headers.get("traceparent"),
                ) as span:
                    self._req_id = (
                        self.headers.get("x-request-id")
                        or span.trace_id
                        or tracing.new_trace_id()
                    )
                    if self.path == "/kv/probe":
                        self._kv_probe(span)
                    elif self.path == "/kv/chain":
                        self._kv_chain(span)
                    elif self.path == "/kv/chain/import":
                        self._kv_chain_import(span)
                    elif self.path == "/kv/prefill":
                        self._kv_prefill(span)
                    else:
                        self._completions(span)

            def _kv_probe(self, span):
                """Suffix-transfer negotiation: given the payload's chain
                keys (hex, chain order), how many leading blocks does
                this replica's prefix cache already hold? Swap-resident
                blocks count as held — import promotes them back to the
                device pool. Matching does NOT pin — an eviction can
                race the subsequent import, which then refuses the
                stubbed payload (KeyError → 409) and the gateway falls
                back to a full transfer."""
                try:
                    body = _read_body(self, server.max_body_bytes)
                    req = json.loads(body or b"{}")
                    keys = req.get("keys") or []
                    if not isinstance(keys, list) or not all(
                        isinstance(k, str) for k in keys
                    ):
                        raise ValueError("keys must be a list of hex strings")
                    raw = [bytes.fromhex(k) for k in keys]
                except BodyTooLarge as err:
                    self._json(413, {"error": str(err)})
                    return
                except (ValueError, json.JSONDecodeError) as err:
                    self._json(400, {"error": str(err)})
                    return
                matched = 0
                block_bytes = 0
                with server._lock:
                    entries = getattr(server.engine, "_prefix_entries", None)
                    if entries is not None and getattr(
                        server.engine, "_prefix_cache_enabled", False
                    ):
                        swap_has = getattr(
                            server.engine, "swap_contains", lambda _k: False
                        )
                        for k in raw:
                            if k not in entries and not swap_has(k):
                                break
                            matched += 1
                    bb = getattr(server.engine, "chain_block_bytes", None)
                    if bb is not None:
                        block_bytes = int(bb())
                span.set_attribute("kv_probe_matched", matched)
                # The byte advisory: per-block wire cost and the whole
                # matched chain's estimate, so a peer fetcher can refuse
                # an oversized transfer BEFORE pulling it.
                self._json(200, {
                    "matched": matched,
                    "block_bytes": block_bytes,
                    "payload_bytes": matched * block_bytes,
                })

            def _kv_chain(self, span):
                """Peer-fetch export hop: serialize the longest held
                prefix of the requested chain keys straight from the
                prefix cache (swap-resident links promoted first). No
                request state is touched — the chains stay registered
                and warm on this replica too."""
                try:
                    body = _read_body(self, server.max_body_bytes)
                    req = json.loads(body or b"{}")
                    keys = req.get("keys") or []
                    if not isinstance(keys, list) or not all(
                        isinstance(k, str) for k in keys
                    ):
                        raise ValueError(
                            "keys must be a list of hex strings"
                        )
                    raw = [bytes.fromhex(k) for k in keys]
                except BodyTooLarge as err:
                    self._json(413, {"error": str(err)})
                    return
                except (ValueError, json.JSONDecodeError) as err:
                    self._json(400, {"error": str(err)})
                    return
                export = getattr(server.engine, "export_chain", None)
                if export is None or not raw:
                    self._json(200, {"matched": 0, "payload": None})
                    return
                try:
                    with server._lock:
                        payload = export(raw)
                except RuntimeError as err:
                    self._json(409, {"error": str(err)})
                    return
                matched = len(payload["blocks"]) if payload else 0
                span.set_attribute("kv_chain_blocks", matched)
                self._json(200, {"matched": matched, "payload": payload})

            def _kv_chain_import(self, span):
                """Peer-fetch import hop: validate + register an exported
                cache chain against this request's own prompt tokens.
                Validation failures are 400s — the fetching gateway
                quarantines the payload and the request re-prefills
                locally; nothing on this path can fail a user request."""
                try:
                    body = _read_body(self, server.max_body_bytes)
                    req = json.loads(body or b"{}")
                    tokens = req.get("tokens")
                    if not (isinstance(tokens, list) and tokens and all(
                        isinstance(t, int) and not isinstance(t, bool)
                        for t in tokens
                    )):
                        raise ValueError(
                            "tokens must be a non-empty list of ints"
                        )
                    payload = req.get("payload")
                except BodyTooLarge as err:
                    self._json(413, {"error": str(err)})
                    return
                except (ValueError, json.JSONDecodeError) as err:
                    self._json(400, {"error": str(err)})
                    return
                imp = getattr(server.engine, "import_chain", None)
                if imp is None:
                    self._json(409, {
                        "error": "this replica's engine cannot import "
                                 "cache chains"
                    })
                    return
                try:
                    with server._lock:
                        registered = imp(payload, tokens)
                except ValueError as err:
                    self._json(400, {"error": str(err)})
                    return
                span.set_attribute("kv_chain_registered", registered)
                self._json(200, {"registered": registered})

            def _kv_prefill(self, span):
                """Prefill-tier hop: run the prompt's chunked prefill,
                sample ONE token, and cut the paged-KV handoff payload at
                first-token time. The request retires immediately after
                (its prefix chains stay registered, so the prefill tier
                self-warms); the decode continuation happens wherever the
                gateway imports the payload. A request that finishes AT
                the first token (EOS / 1-token stop match) returns its
                final tokens with no decode hop needed."""
                try:
                    body = _read_body(self, server.max_body_bytes)
                except BodyTooLarge as err:
                    self._json(413, {"error": str(err)})
                    return
                except ValueError as err:
                    self._json(400, {"error": str(err)})
                    return
                try:
                    req = json.loads(body or b"{}")
                    prompt = server._decode_prompt(req.get("prompt"))
                    skip = req.get("skip_keys") or []
                    if not isinstance(skip, list) or not all(
                        isinstance(k, str) for k in skip
                    ):
                        raise ValueError(
                            "skip_keys must be a list of hex strings"
                        )
                    temperature = req.get("temperature")
                    stop = server._decode_stop(req.get("stop"))
                    logit_bias = req.get("logit_bias")
                    if logit_bias is not None and not isinstance(
                        logit_bias, dict
                    ):
                        raise ValueError(
                            "logit_bias must be an object mapping token "
                            "ids to biases"
                        )
                    deadline_s = req.get("deadline_s")
                    if deadline_s is not None and (
                        isinstance(deadline_s, bool)
                        or not isinstance(deadline_s, (int, float))
                        or not math.isfinite(deadline_s)
                        or deadline_s <= 0
                    ):
                        raise ValueError(
                            f"deadline_s must be a finite number > 0, "
                            f"got {deadline_s!r}"
                        )
                    if not hasattr(server.engine, "export_blocks"):
                        raise ValueError(
                            "this replica's engine cannot export KV "
                            "payloads (prefix_cache paged engines only)"
                        )
                except (ValueError, TypeError, json.JSONDecodeError) as err:
                    self._json(400, {"error": str(err)})
                    return
                span.set_attribute("prompt_tokens", len(prompt))
                span.set_attribute("kv_prefill", True)
                try:
                    rid, q = server._submit(
                        prompt, 1, req.get("model"), temperature, stop,
                        logit_bias, deadline_s, kv_export=frozenset(skip),
                    )
                except OverloadedError as err:
                    self.send_response(429)
                    self._retry_after_close(str(err))
                    return
                except DrainingError as err:
                    self.send_response(503)
                    self._retry_after_close(str(err))
                    return
                except EngineFailedError as err:
                    self._json(503, {"error": str(err)})
                    return
                except ValueError as err:
                    self._json(400, {"error": str(err)})
                    return
                # The registry entry outlives _finish's pop — grab the
                # reference now, read it once _Final lands.
                exp = server._kv_exports.get(rid)
                try:
                    tokens: list = []
                    while True:
                        try:
                            item = q.get(timeout=0.25)
                        except queue.Empty:
                            if _client_gone(self.connection):
                                server._cancel(rid)
                                return
                            continue
                        if isinstance(item, (_Final, _Abort)):
                            break
                        tokens.append(item)
                    if isinstance(item, _Abort):
                        code = 504 if item.reason == "deadline" else 500
                        self._json(code, {"error": item.reason,
                                          "partial_tokens": tokens})
                        return
                    if exp is not None and exp["error"] is not None:
                        self._json(500, {"error": exp["error"]})
                        return
                    self._json(200, {
                        "id": f"cmpl-{rid}",
                        "payload": exp["payload"] if exp else None,
                        "finished": {
                            "tokens": item.tokens,
                            "logprobs": item.logprobs,
                            "finish_reason": item.finish_reason,
                        },
                    })
                finally:
                    server._finish(rid)

            def _completions(self, span):
                try:
                    body = _read_body(self, server.max_body_bytes)
                except BodyTooLarge as err:
                    self._json(413, {"error": str(err)})
                    return
                except ValueError as err:
                    self._json(400, {"error": str(err)})
                    return
                try:
                    req = json.loads(body or b"{}")
                    kv_import = req.get("kv_import")
                    if kv_import is not None and not isinstance(
                        kv_import, dict
                    ):
                        raise ValueError(
                            "kv_import must be an exported KV payload "
                            "object"
                        )
                    if kv_import is not None:
                        # The payload carries the prompt; its token list
                        # doubles as the usage/span accounting below.
                        prompt = [
                            int(t) for t in kv_import.get("tokens") or []
                        ]
                    else:
                        prompt = server._decode_prompt(req.get("prompt"))
                    max_tokens = req.get("max_tokens")
                    if max_tokens is not None and (
                        not isinstance(max_tokens, int)
                        or isinstance(max_tokens, bool)
                    ):
                        raise ValueError(
                            f"max_tokens must be an integer, got "
                            f"{max_tokens!r}"
                        )
                    # temperature is validated by the engine's submit()
                    # (isfinite incl. the JSON NaN/Infinity hole) — the
                    # ValueError it raises already becomes a 400 below;
                    # a second copy here could silently diverge.
                    temperature = req.get("temperature")
                    n = req.get("n", 1)
                    if not isinstance(n, int) or isinstance(n, bool) or (
                        not 1 <= n <= 64
                    ):
                        raise ValueError(
                            f"n must be an integer in [1, 64], got {n!r}"
                        )
                    stop = server._decode_stop(req.get("stop"))
                    logit_bias = req.get("logit_bias")
                    if logit_bias is not None and not isinstance(
                        logit_bias, dict
                    ):
                        raise ValueError(
                            "logit_bias must be an object mapping token "
                            "ids to biases"
                        )
                    deadline_s = req.get("deadline_s")
                    if deadline_s is not None and (
                        isinstance(deadline_s, bool)
                        or not isinstance(deadline_s, (int, float))
                        or not math.isfinite(deadline_s)
                        or deadline_s <= 0
                    ):
                        raise ValueError(
                            f"deadline_s must be a finite number > 0, "
                            f"got {deadline_s!r}"
                        )
                    stream = bool(req.get("stream", False))
                    if stream and n > 1:
                        raise ValueError("stream does not support n > 1")
                    want_logprobs = bool(req.get("logprobs", False))
                    if want_logprobs and stream:
                        raise ValueError(
                            "stream does not support logprobs"
                        )
                    if want_logprobs and not getattr(
                        server.engine, "supports_logprobs", False
                    ):
                        raise ValueError(
                            "this engine does not compute logprobs "
                            "(speculative serving verifies argmax rounds)"
                        )
                    if kv_import is not None and n != 1:
                        raise ValueError("kv_import does not support n > 1")
                except (ValueError, TypeError, json.JSONDecodeError) as err:
                    self._json(400, {"error": str(err)})
                    return
                span.set_attribute("stream", stream)
                span.set_attribute("n", n)
                span.set_attribute("prompt_tokens", len(prompt))
                subs = []
                try:
                    try:
                        if kv_import is not None:
                            subs.append(server._submit_import(
                                kv_import, max_tokens, temperature,
                                stop, logit_bias, deadline_s,
                            ))
                        else:
                            for _ in range(n):
                                subs.append(server._submit(
                                    prompt, max_tokens, req.get("model"),
                                    temperature, stop, logit_bias,
                                    deadline_s,
                                ))
                    except OverloadedError as err:
                        # Shed mid-loop for n>1: already-submitted
                        # choices are dead work — cancel them so the
                        # engine never decodes for a response that will
                        # never be written.
                        for rid, _ in subs:
                            server._cancel(rid, "sibling choice shed")
                        self.send_response(429)
                        self._retry_after_close(str(err))
                        return
                    except DrainingError as err:
                        for rid, _ in subs:
                            server._cancel(rid, "sibling choice refused")
                        self.send_response(503)
                        self._retry_after_close(str(err))
                        return
                    except EngineFailedError as err:
                        self._json(503, {"error": str(err)})
                        return
                    except ValueError as err:  # over-bucket prompt etc.
                        self._json(400, {"error": str(err)})
                        return
                    except KeyError as err:
                        # Stubbed KV payload whose chain is no longer
                        # cached here (suffix transfer raced an eviction):
                        # 409 tells the gateway to resend with full data
                        # or fall back to fused routing.
                        self._json(409, {"error": str(err)})
                        return
                    if stream:
                        self._stream(*subs[0])
                    else:
                        self._complete(subs, len(prompt), want_logprobs)
                finally:
                    for rid, _ in subs:
                        server._finish(rid)

            def _complete(self, subs, prompt_len, want_logprobs=False):
                choices = []
                for idx, (rid, q) in enumerate(subs):
                    tokens = []
                    while True:
                        try:
                            # Timed get doubles as a disconnect poll: a
                            # client that hung up while its request was
                            # still queued/decoding would otherwise pin
                            # a slot to full budget writing to nobody.
                            item = q.get(timeout=0.25)
                        except queue.Empty:
                            if _client_gone(self.connection):
                                for r, _ in subs:
                                    server._cancel(r)
                                return  # nobody to answer
                            continue
                        if isinstance(item, (_Final, _Abort)):
                            break
                        tokens.append(item)
                    logprobs = []
                    finish_reason = "stop"
                    if isinstance(item, _Final):
                        # Authoritative: a stop match truncated tokens
                        # the stream already delivered.
                        tokens = item.tokens
                        logprobs = item.logprobs
                        finish_reason = item.finish_reason
                    # Drop the queue BEFORE writing: a client that has
                    # seen the response must be able to observe the
                    # server state already cleaned up (the finally stays
                    # as a safety net).
                    server._finish(rid)
                    if isinstance(item, _Abort):
                        # Deadline expiry is the client's own budget
                        # running out — 504, with whatever was decoded.
                        code = 504 if item.reason == "deadline" else 500
                        self._json(code, {"error": item.reason,
                                          "partial_tokens": tokens})
                        return
                    choice = {"index": idx, "tokens": tokens,
                              "finish_reason": finish_reason}
                    if want_logprobs:
                        choice["logprobs"] = {
                            "tokens": tokens,
                            "token_logprobs": logprobs,
                        }
                    text = server._text(tokens)
                    if text is not None:
                        choice["text"] = text
                    choices.append(choice)
                total = sum(len(c["tokens"]) for c in choices)
                self._json(200, {
                    "id": f"cmpl-{subs[0][0]}",
                    "object": "text_completion",
                    "model": server.model_name,
                    "choices": choices,
                    "usage": {
                        "prompt_tokens": prompt_len,
                        "completion_tokens": total,
                        "total_tokens": prompt_len + total,
                    },
                })

            def _stream(self, rid, q):
                try:
                    self.send_response(200)
                    if self._req_id:
                        self.send_header("X-Request-Id", self._req_id)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    # Length-unknown: close delimits the body.
                    self.send_header("Connection", "close")
                    self.end_headers()
                    while True:
                        item = q.get()
                        # A write into a dead socket only fails once the
                        # peer's RST round-trips, so a fast decode can
                        # drain its whole budget into the send buffer
                        # before EPIPE ever fires. Peek for the FIN
                        # before each write instead — deterministic the
                        # moment the client hangs up.
                        if _client_gone(self.connection):
                            server._cancel(rid)
                            return
                        if isinstance(item, (_Final, _Abort)):
                            server._finish(rid)
                            # An abort-truncated stream must be
                            # distinguishable from a completed one.
                            if isinstance(item, _Abort):
                                # The error event carries the request id
                                # so a truncated stream can be joined
                                # against server logs and the trace
                                # export without the (already-consumed)
                                # response headers.
                                self.wfile.write(
                                    b"data: " + json.dumps(
                                        {"error": item.reason,
                                         "request_id": self._req_id}
                                    ).encode() + b"\n\n"
                                )
                            self.wfile.write(b"data: [DONE]\n\n")
                            self.wfile.flush()
                            return
                        payload = {"id": f"cmpl-{rid}", "token": item}
                        text = server._text([item])
                        if text is not None:
                            payload["text"] = text
                        self.wfile.write(
                            b"data: " + json.dumps(payload).encode()
                            + b"\n\n"
                        )
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    # The peer hung up mid-stream. Without cancellation
                    # the slot decodes to full budget for nobody — the
                    # disconnect-storm failure mode. Cancel retires it
                    # at the engine's next _note_token.
                    server._cancel(rid)

        return Handler
