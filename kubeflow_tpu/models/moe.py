"""Mixture-of-Experts transformer (Mixtral-style) with expert parallelism.

TPU-first design: expert FFN weights carry a leading experts axis sharded
over the ``ep`` mesh axis; token routing is expressed as dense one-hot
dispatch/combine einsums, so XLA's SPMD partitioner inserts the
all_to_all/psum collectives itself (the scaling-book recipe: annotate
shardings, let XLA place communication on ICI). No manual collective calls
in the model body — the same code runs single-chip.

Attention reuses the Llama building blocks; only the FFN differs: a top-k
softmax router with a load-balancing auxiliary loss (Switch/Mixtral
formulation: aux = E * mean(fraction_routed * mean_router_prob)).

The reference has no model stack (SURVEY.md §2.5 — not an ML framework);
this is part of the framework's in-notebook compute story.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeflow_tpu.models.llama import (
    _merge_heads,
    _split_heads,
    apply_rope,
    rms_norm,
    rope_frequencies,
)
from kubeflow_tpu.ops.attention import flash_attention
from kubeflow_tpu.parallel.mesh import MeshPlan


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_hidden: int = 14336
    n_experts: int = 8
    top_k: int = 2
    aux_loss_coef: float = 0.01
    norm_eps: float = 1e-5
    rope_theta: float = 1e6
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


MOE_CONFIGS = {
    "mixtral-8x7b": MoEConfig(),
    "tiny-moe": MoEConfig(
        vocab_size=512,
        dim=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_hidden=256,
        n_experts=4,
        top_k=2,
    ),
}


def init_params(cfg: MoEConfig, key: jax.Array) -> dict:
    """Stacked-layer params; expert weights carry (L, E, ...) axes."""
    k_embed, k_head, k_layers = jax.random.split(key, 3)
    L, E = cfg.n_layers, cfg.n_experts
    keys = iter(jax.random.split(k_layers, 8))

    def dense(k, shape):
        scale = 1.0 / jnp.sqrt(jnp.asarray(shape[-2], jnp.float32))
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(cfg.dtype)

    layers = {
        "attn_norm": jnp.ones((L, cfg.dim), cfg.dtype),
        "mlp_norm": jnp.ones((L, cfg.dim), cfg.dtype),
        "wq": dense(next(keys), (L, cfg.dim, cfg.n_heads * cfg.head_dim)),
        "wk": dense(next(keys), (L, cfg.dim, cfg.n_kv_heads * cfg.head_dim)),
        "wv": dense(next(keys), (L, cfg.dim, cfg.n_kv_heads * cfg.head_dim)),
        "wo": dense(next(keys), (L, cfg.n_heads * cfg.head_dim, cfg.dim)),
        # Router in f32: tiny, and logit precision decides routing.
        "router": jax.random.normal(next(keys), (L, cfg.dim, E), jnp.float32) * 0.02,
        "w_gate": dense(next(keys), (L, E, cfg.dim, cfg.ffn_hidden)),
        "w_up": dense(next(keys), (L, E, cfg.dim, cfg.ffn_hidden)),
        "w_down": dense(next(keys), (L, E, cfg.ffn_hidden, cfg.dim)),
    }
    return {
        "embed": dense(k_embed, (cfg.vocab_size, cfg.dim)),
        "final_norm": jnp.ones((cfg.dim,), cfg.dtype),
        "lm_head": dense(k_head, (cfg.vocab_size, cfg.dim)),
        "layers": layers,
    }


def moe_ffn(layer: dict, cfg: MoEConfig, x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Top-k routed expert FFN. x: (B, S, D) → (out, aux_loss).

    Dense one-hot dispatch: gates (B,S,E) select/weight experts; the
    dispatch einsum produces (E,B,S,D) sharded over ep, each expert runs its
    SwiGLU, and the combine einsum reduces back — XLA turns the E-dim
    movement into all_to_alls when ep > 1.
    """
    router_logits = (x.astype(jnp.float32) @ layer["router"])  # (B,S,E)
    probs = jax.nn.softmax(router_logits, axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, cfg.top_k)  # (B,S,K)
    # Renormalized top-k gates, scattered back to (B,S,E).
    top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    one_hot = jax.nn.one_hot(top_idx, cfg.n_experts, dtype=jnp.float32)  # (B,S,K,E)
    gates = jnp.einsum("bsk,bske->bse", top_vals, one_hot)
    mask = jnp.sum(one_hot, axis=2)  # (B,S,E) in {0,1}

    # Load-balancing aux loss (Switch eq. 4 / Mixtral): experts should see
    # equal token fractions with equal router mass.
    frac_routed = jnp.mean(mask, axis=(0, 1))  # (E,)
    mean_prob = jnp.mean(probs, axis=(0, 1))  # (E,)
    aux = cfg.n_experts * jnp.sum(frac_routed * mean_prob)

    # Dispatch → per-expert SwiGLU → combine.
    xin = jnp.einsum("bsd,bse->ebsd", x.astype(jnp.float32), mask).astype(x.dtype)

    def expert(xin_e, wg, wu, wd):
        h = jax.nn.silu(xin_e @ wg) * (xin_e @ wu)
        return h @ wd

    out_e = jax.vmap(expert)(xin, layer["w_gate"], layer["w_up"], layer["w_down"])
    out = jnp.einsum(
        "ebsd,bse->bsd", out_e.astype(jnp.float32), gates
    ).astype(x.dtype)
    return out, aux


def _layer_fwd(layer: dict, cfg: MoEConfig, x, cos, sin):
    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = apply_rope(_split_heads(h @ layer["wq"], cfg.n_heads), cos, sin)
    k = apply_rope(_split_heads(h @ layer["wk"], cfg.n_kv_heads), cos, sin)
    v = _split_heads(h @ layer["wv"], cfg.n_kv_heads)
    attn = flash_attention(q, k, v, causal=True)  # GQA folded in the kernel
    x = x + _merge_heads(attn) @ layer["wo"]
    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    ffn_out, aux = moe_ffn(layer, cfg, h)
    return x + ffn_out, aux


@partial(jax.jit, static_argnames=("cfg",))
def forward(params: dict, cfg: MoEConfig, tokens: jax.Array):
    """(logits (B,S,V) f32, mean aux loss)."""
    x = params["embed"][tokens]
    positions = jnp.arange(tokens.shape[1])
    cos, sin = rope_frequencies(cfg, positions)

    def body(x, layer):
        x, aux = _layer_fwd(layer, cfg, x, cos, sin)
        return x, aux

    x, aux_per_layer = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"].T).astype(jnp.float32)
    return logits, jnp.mean(aux_per_layer)


# ---------------------------------------------------------------------------
# Expert-parallel training


def moe_param_spec(path: tuple[str, ...]) -> P:
    """Sharding rules: experts over ep; within-expert dims over fsdp/tp;
    attention follows the llama rules."""
    name = "/".join(path)
    if any(k in name for k in ("w_gate", "w_up")):
        return P(None, "ep", "fsdp", "tp")  # (L, E, dim, hidden)
    if "w_down" in name:
        return P(None, "ep", "tp", "fsdp")  # (L, E, hidden, dim)
    if "router" in name:
        return P()  # tiny; replicated
    if "embed" in name or "lm_head" in name:
        return P("tp", "fsdp")
    if any(k in name for k in ("wq", "wk", "wv")):
        return P(None, "fsdp", "tp")
    if "wo" in name:
        return P(None, "tp", "fsdp")
    return P()


def shard_moe_params(plan: MeshPlan, params: dict) -> dict:
    def place(path, value):
        spec = moe_param_spec(tuple(str(p.key) for p in path))
        return jax.device_put(value, NamedSharding(plan.mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def make_moe_train_step(cfg: MoEConfig, plan: MeshPlan, optimizer=None):
    """(init_state, step) jitted over plan.mesh with ep expert sharding."""
    optimizer = optimizer or optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1)
    mesh = plan.mesh

    def loss_fn(params, tokens):
        logits, aux = forward(params, cfg, tokens)
        targets = tokens[:, 1:]
        logprobs = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(nll) + cfg.aux_loss_coef * aux

    def init_state(params):
        return {
            "params": params,
            "opt_state": optimizer.init(params),
            "step": jnp.zeros((), jnp.int32),
        }

    def train_step(state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], tokens)
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        return {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }, loss

    batch_sharding = NamedSharding(mesh, P(("dp", "fsdp"), None))
    jitted = jax.jit(
        train_step, in_shardings=(None, batch_sharding), donate_argnums=(0,)
    )

    def shard_state(state):
        def place(path, value):
            keys = tuple(str(getattr(p, "key", p)) for p in path)
            # Optimizer moments mirror params' tree paths.
            param_keys = tuple(k for k in keys if k not in ("params", "opt_state")
                               and not k.isdigit() and k not in ("mu", "nu", "count"))
            if "step" in keys or "count" in keys:
                return jax.device_put(value, NamedSharding(mesh, P()))
            spec = moe_param_spec(param_keys) if value.ndim else P()
            return jax.device_put(value, NamedSharding(mesh, spec))

        return jax.tree_util.tree_map_with_path(place, state)

    return init_state, jitted, shard_state
