"""Continuous batching: slot-based serving with admit-on-free.

``batch_generate`` (models/serving.py) runs one fused program per batch —
every request waits for the slowest. Continuous batching instead keeps a
fixed pool of B cache SLOTS stepping together; when a request finishes
(EOS or budget), its slot is freed and the next queued prompt is admitted
immediately, without disturbing in-flight neighbors. Throughput stops
being gated by the longest request in a batch.

TPU-first shape discipline:
- ONE compiled decode step for the life of the server: (B, 1) tokens,
  per-slot write positions, a (B, cache_len) validity mask — all static
  shapes, no per-request recompilation;
- ONE compiled admit program per prompt-length bucket: the prompt is
  left-padded to the bucket, prefilled into a single-row cache, and the
  rows are written into the slot with dynamic_update_slice;
- per-slot correctness falls out of the same invariants batch_generate
  proved: left-padding + static kv_mask + absolute-position RoPE means
  each slot's tokens follow exactly the greedy path of its own prompt.

Host/device traffic per step: ONE positions upload, ONE tokens upload,
ONE (B,) next-token readback (the standard continuous-batching sync
point — the host must see tokens to retire/admit). All other state
mutation happens on host numpy.

No reference counterpart (control plane only); this sits with serving/
speculative as the in-notebook inference surface.
"""

from __future__ import annotations

import dataclasses
import math
import time
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.observability import tracing

from kubeflow_tpu.models.llama import (
    LlamaConfig,
    _cache_store_rows,
    _decode_chunk_batch_impl,
    _embed,
    _gqa_decode_attention,
    _lm_head_logits,
    _merge_heads,
    _mlp,
    _mm,
    _norm,
    _prefill_impl,
    _qkv,
    _split_heads,
    apply_rope,
    init_kv_cache,
    rope_frequencies,
    sample_logits,
    sample_logits_per_row,
)
from kubeflow_tpu.models.serving import GenerationConfig, left_pad


# ---------------------------------------------------------------------------
# Jitted programs


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(4, 5))
def _admit_slot(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,  # (1, Lb) left-padded prompt
    prompt_mask: Optional[jax.Array],  # (1, Lb) bool, None = no padding
    cache: dict,  # batch cache (Lyr, B, Hkv, C, D)
    kv_mask: jax.Array,  # (B, C) bool slot-validity state
    slot: jax.Array,  # scalar int32 — traced, so ONE compile per bucket
) -> tuple[jax.Array, dict, jax.Array]:
    """Prefill one prompt into ``slot``: returns (first logits (V,),
    updated cache, updated kv_mask)."""
    cache_len = cache["k"].shape[3]
    lb = tokens.shape[1]
    # The temp cache mirrors the batch cache's storage format (the pytree
    # structure carries it — int8 + scale leaves when kv_bits=8), so the
    # row copy below is format-agnostic: scale leaves are rank-4
    # (L, B, Hkv, C), value leaves rank-5.
    temp = init_kv_cache(cfg, 1, cache_len,
                         kv_bits=8 if "k_scale" in cache else 0)
    logits, temp = _prefill_impl(params, cfg, tokens, temp, kv_mask=prompt_mask)
    row = jnp.ones((1, cache_len), bool)
    if prompt_mask is not None:
        row = row.at[:, :lb].set(prompt_mask)
    new_cache, new_mask = _install_rows(temp, cache, kv_mask, row, slot)
    return logits[0], new_cache, new_mask


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(3,))
def _admit_chunk(params, cfg, tok_chunk, temp, pos, kv_mask):
    """One admission piece: decode a (1, CS) prompt chunk into the
    1-row temp cache at ``pos`` (chunk-causal, pads fenced by the full
    kv_mask row); returns (last-position logits (V,), cache)."""
    logits, temp = _decode_chunk_batch_impl(
        params, cfg, tok_chunk, temp, pos, kv_mask=kv_mask
    )
    return logits[0, -1], temp


def _install_rows(temp, cache, kv_mask, row, slot):
    """THE slot-install: copy a finished 1-row temp cache into ``slot``
    of the batch cache + validity mask. One home for both admission
    paths — inlined by _admit_slot's jit, wrapped below for chunked
    admission."""
    new_cache = {
        name: jax.lax.dynamic_update_slice(
            cache[name], temp[name], (0, slot) + (0,) * (cache[name].ndim - 2)
        )
        for name in cache
    }
    new_mask = jax.lax.dynamic_update_slice(kv_mask, row, (slot, 0))
    return new_cache, new_mask


@partial(jax.jit, donate_argnums=(1,))
def _install_temp_cache(temp, cache, kv_mask, row, slot):
    return _install_rows(temp, cache, kv_mask, row, slot)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "top_k", "top_p", "decode_attn",
        "attn_kernel",
    ),
    donate_argnums=(3,),
)
def _cb_step(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,  # (B, 1) current input token per slot
    cache: dict,
    positions: jax.Array,  # (B,) write position per slot
    kv_mask: jax.Array,  # (B, C)
    key: jax.Array,
    temps: jax.Array,  # (B,) per-slot sampling temperature (0 = greedy)
    top_k: int,
    top_p: float,
    bias=None,  # (B, V) per-slot logit bias, or None (bias-free program)
    decode_attn=None,  # mesh-bound SP decode (make_sharded_sp_decode)
    attn_kernel: int = 0,  # >0: pallas length-bounded decode, chunk size
) -> tuple[jax.Array, dict]:
    """One decode step across every slot at its own position.

    ``decode_attn`` (static) swaps the attention for a mesh-bound
    sequence-parallel split-KV decode when the cache's sequence axis is
    sharded over sp; None is the dense/GSPMD path. ``attn_kernel`` > 0
    swaps the XLA attention for ops/paged_attention.py's dense kernel
    with that chunk size: XLA reads ALL cache_len slots per step, the
    kernel reads each slot's filled prefix only (bf16 caches, no
    window, no sp)."""
    x = _embed(params, cfg, tokens)  # (B, 1, D)
    cos, sin = rope_frequencies(cfg, positions)  # (B, half)

    def body(x, scanned):
        layer, cache_l = scanned  # per-layer cache dict, leaves (B, Hkv, …)
        h = _norm(x, layer["attn_norm"], cfg)
        hq, hk, hv = _qkv(h, layer)
        q = apply_rope(_split_heads(hq, cfg.n_heads), cos, sin, per_batch=True)
        k = apply_rope(_split_heads(hk, cfg.n_kv_heads), cos, sin,
                       per_batch=True)
        v = _split_heads(hv, cfg.n_kv_heads)
        # Per-row write at each slot's own position; the cache pytree's
        # structure decides the storage format (quantize-on-write when the
        # scale leaves are present — models.llama init_kv_cache kv_bits=8).
        cache_l = _cache_store_rows(cache_l, k, v, positions)
        if attn_kernel and decode_attn is None and "k_scale" not in cache_l:
            from kubeflow_tpu.ops.paged_attention import (
                dense_decode_attention,
            )

            attn = dense_decode_attention(
                q[:, :, 0, :], cache_l["k"], cache_l["v"], kv_mask,
                positions + 1, block_size=attn_kernel,
                interpret=jax.default_backend() not in ("tpu", "axon"),
            )[:, :, None, :]
        elif decode_attn is None:
            attn = _gqa_decode_attention(
                q, cache_l["k"], cache_l["v"], positions,
                window=cfg.sliding_window, kv_mask=kv_mask, per_batch=True,
                k_scale=cache_l.get("k_scale"),
                v_scale=cache_l.get("v_scale"),
            )
        else:
            # GQA-native split-KV decode: the unrepeated cache shard goes
            # straight in (sp_decode_attention folds the group mapping) —
            # decode is KV-bandwidth-bound, so a rep-times-broadcast here
            # would multiply the step's HBM traffic. int8 scale shards ride
            # along sp exactly like their values.
            attn = decode_attn(
                q, cache_l["k"], cache_l["v"], positions,
                window=cfg.sliding_window, kv_mask=kv_mask, per_batch=True,
                k_scale=cache_l.get("k_scale"),
                v_scale=cache_l.get("v_scale"),
            )
        x = x + _mm(_merge_heads(attn), layer["wo"])
        h = _norm(x, layer["mlp_norm"], cfg)
        x = x + _mlp(layer, h, cfg)
        return x, cache_l

    x, new_cache = jax.lax.scan(body, x, (params["layers"], cache))
    logits = _lm_head_logits(_norm(x[:, 0], params["final_norm"], cfg), params)
    if bias is not None:
        logits = logits + bias
    nxt = sample_logits_per_row(logits, key, temps, top_k, top_p)
    # Per-token logprob of the CHOSEN token under the (biased,
    # temperature-independent) distribution — piggybacks on the step's
    # existing (B,) readback.
    lp = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), nxt[:, None], axis=-1
    )[:, 0]
    return nxt, lp, new_cache


@partial(
    jax.jit,
    static_argnames=("cfg", "top_k", "top_p"),
    donate_argnums=(3,),
)
def _cb_ragged_step(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,  # (B, K) per-slot chunk (decode rows: 1 real + pads)
    cache: dict,
    positions: jax.Array,  # (B,) chunk start per slot
    kv_mask: jax.Array,  # (B, C)
    cols: jax.Array,  # (B,) last-real column per row (0 for decode rows)
    key: jax.Array,
    temps: jax.Array,
    top_k: int,
    top_p: float,
    bias=None,
) -> tuple[jax.Array, jax.Array, dict]:
    """One fused mixed prefill/decode dispatch for the fixed-slot
    batcher: every row is a K-token chunk written at its own position —
    decode rows carry one real token plus pads (pad writes at later
    positions are causally invisible until real tokens overwrite them),
    the admitting row carries its next prompt chunk — with chunk-causal
    attention inside each row. Sampling reads each row's own last-real
    column, so a completing admission's first token comes from the same
    dispatch that finished its prefill."""
    logits, cache = _decode_chunk_batch_impl(
        params, cfg, tokens, cache, positions, kv_mask=kv_mask
    )
    row_logits = jnp.take_along_axis(
        logits, cols[:, None, None], axis=1
    )[:, 0]  # (B, V)
    if bias is not None:
        row_logits = row_logits + bias
    nxt = sample_logits_per_row(row_logits, key, temps, top_k, top_p)
    lp = jnp.take_along_axis(
        jax.nn.log_softmax(row_logits, axis=-1), nxt[:, None], axis=-1
    )[:, 0]
    return nxt, lp, cache


# ---------------------------------------------------------------------------
# Host-side server


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: list[int]
    tokens: list[int] = dataclasses.field(default_factory=list)
    budget: int = 0
    # Per-request cap (None = the engine-wide gen.max_new_tokens). Admits
    # clamp to the engine-wide value: cache/table shapes are compiled for
    # it, so a request can ask for less, never more.
    max_new: Optional[int] = None
    # Per-request sampling temperature (None = the engine-wide
    # gen.temperature). 0 = greedy for this row; top_k/top_p stay
    # engine-wide (their shapes are compiled in).
    temperature: Optional[float] = None
    # Per-request stop sequences (token-id lists). Checked host-side in
    # _note_token after each emitted token; on a suffix match the
    # request retires with the stop sequence EXCLUDED from its output
    # (OpenAI semantics).
    stop: tuple = ()
    # Chosen-token log-probabilities, aligned with ``tokens`` (may lag
    # on engines that don't compute them, e.g. speculative rounds).
    logprobs: list = dataclasses.field(default_factory=list)
    # Per-request logit bias {token_id: bias}, added to the row's logits
    # before sampling (OpenAI logit_bias; ±100 effectively forces or
    # bans a token). Device-resident per-slot rows — uploaded once at
    # admit, not per step.
    logit_bias: Optional[dict] = None
    # Paged batcher only: physical block ids this request holds, in
    # position order. Harmless (empty) for the fixed-slot batcher.
    blocks: list[int] = dataclasses.field(default_factory=list)
    # Paged prompt cache only: the subset of ``blocks`` that is SHARED
    # (refcounted) rather than owned — released by decref, never freed
    # directly to the pool.
    shared: frozenset = frozenset()
    # Absolute monotonic deadline (None = no deadline). Checked at every
    # _note_token: an expired request retires through the abort path at
    # its next emitted token, freeing the slot for live work instead of
    # decoding to full budget for a caller that stopped waiting.
    deadline: Optional[float] = None
    # Multi-LoRA engines only: resolved adapter row index (None = base
    # model). Travels with the request through preemption/requeue and is
    # folded into the prefix chain key so KV never crosses adapters.
    adapter_id: Optional[int] = None


class _AdmissionCursor:
    """Prompt-prefill cursor for one in-flight admission.

    THE position bookkeeping shared by ContinuousBatcher chunked
    admission and the PagedBatcher ragged scheduler: the left-padded
    prompt's validity row and the next position to prefill travel
    together across pieces instead of being recomputed per chunk.
    ``align`` keeps piece starts on compiled chunk boundaries (chunked
    admission dispatches fixed-width pieces); the ragged scheduler
    takes variable-width pieces under its token budget (align=1)."""

    def __init__(self, mask_row, bucket: int, align: int = 1) -> None:
        self.bucket = int(bucket)
        row = np.asarray(mask_row).reshape(-1)[: self.bucket]
        self.mask_row = row
        # Left-padding puts all pads FIRST: pieces before the first real
        # token are pure padding (kv_mask-fenced anyway) and would
        # multiply a short prompt's TTFT for zero work — start at the
        # aligned piece containing the first real token.
        first_real = int(np.argmax(row)) if row.any() else 0
        self.pos = (first_real // align) * align

    @property
    def done(self) -> bool:
        return self.pos >= self.bucket

    def take(self, width: int) -> tuple[int, int]:
        """Claim the next up-to-``width`` positions: returns (start, n)
        and advances the cursor past them."""
        start = self.pos
        n = min(int(width), self.bucket - start)
        self.pos = start + n
        return start, n


class _BatcherBase:
    """Host-side scaffolding shared by the fixed-slot and paged batchers:
    request queue/ids, submit validation, the drive loop, and per-token
    retirement. Subclasses provide ``_admit_free_slots``, ``_step``, and
    ``_release_slot`` (what freeing a slot means for their storage)."""

    # Engines whose steps emit chosen-token logprobs. The speculative
    # inner engines flip this off: their verified tokens come from
    # chunked argmax rounds that never compute per-token logprobs.
    supports_logprobs = True

    def _init_base(self, gen: GenerationConfig, slots: int,
                   prompt_bucket: int) -> None:
        self.gen = gen
        self.slots = slots
        self.prompt_bucket = prompt_bucket
        # Per-slot effective temperature (request override or the
        # engine-wide default), uploaded with each step.
        self.temps = np.full((slots,), gen.temperature, np.float32)
        # Per-slot logit-bias rows, device-resident, allocated lazily on
        # the first biased request (None keeps the unbiased step's
        # compiled program bias-free).
        self._bias = None
        self._queue: list[_Request] = []
        self._by_slot: list[Optional[_Request]] = [None] * slots
        self._results: dict[int, list[int]] = {}
        # Chosen-token logprobs per retired request, parallel to
        # _results (run_logprobs() drains it alongside run()).
        self._result_logprobs: dict[int, list[float]] = {}
        # rid → abort reason for requests retired WITHOUT completing
        # (cancel/deadline), parallel to _results; drained by run() into
        # run_aborted().
        self._aborted: dict[int, str] = {}
        self._next_rid = 0
        # Serving-frontend hooks (models/server.py): called under the
        # frontend's engine lock. on_token(rid, token) per emitted token;
        # on_retire(rid, tokens, logprobs, finish_reason) when a request
        # completes — when set, completed requests are DELIVERED instead
        # of accumulating in _results (a long-running server must not
        # grow without bound). on_abort(rid, tokens, reason) when a
        # request is retired WITHOUT completing (cancel/deadline).
        self.on_token = None
        self.on_retire = None
        self.on_abort = None
        # on_admit(rid): fires the moment a queued request is popped for
        # admission (every admission path goes through _pop_queue) — the
        # serving frontend ends its queue-wait span here, which is what
        # lets TTFT decompose into queue_wait + prefill + first_decode.
        self.on_admit = None
        # Optional observability.flight.FlightRecorder attached by the
        # serving frontend; drive_once feeds it one sample per quantum.
        self.flight = None
        # What the most recent drive quantum did (engine-specific: fill
        # ratio, decode/prefill row split) — stamped by _step/_step_ragged,
        # read by drive_once for the engine.step span attributes.
        self.last_step: dict = {}
        # rid → reason for requests cancelled while holding a slot (or
        # mid-admission): checked at the next _note_token so the slot is
        # reclaimed within one engine step. Mutated only under the
        # frontend's engine lock (cancel() and the drive loop both run
        # under it).
        self._cancelled: dict[int, str] = {}
        # Injectable time source (tests swap in a fake clock to drive
        # deadline expiry deterministically).
        self._clock = time.monotonic

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               temperature: Optional[float] = None,
               stop: Optional[Sequence[Sequence[int]]] = None,
               logit_bias: Optional[dict] = None,
               deadline_s: Optional[float] = None) -> int:
        req = self._build_request(
            prompt, max_new_tokens=max_new_tokens, temperature=temperature,
            stop=stop, logit_bias=logit_bias, deadline_s=deadline_s,
        )
        self._queue.append(req)
        return req.rid

    def _build_request(self, prompt: Sequence[int],
                       max_new_tokens: Optional[int] = None,
                       temperature: Optional[float] = None,
                       stop: Optional[Sequence[Sequence[int]]] = None,
                       logit_bias: Optional[dict] = None,
                       deadline_s: Optional[float] = None) -> _Request:
        """Validate client-supplied sampling fields and mint a _Request
        with a fresh rid — shared by submit() and the paged KV-import
        path (which installs a request directly into a slot instead of
        queueing it)."""
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) > self.prompt_bucket:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds bucket "
                f"{self.prompt_bucket} (raise prompt_bucket)"
            )
        if max_new_tokens is not None and max_new_tokens <= 0:
            raise ValueError(f"max_new_tokens must be > 0, got {max_new_tokens}")
        if temperature is not None and (
            not isinstance(temperature, (int, float))
            or isinstance(temperature, bool)
            or not math.isfinite(temperature) or temperature < 0
        ):
            # isfinite: JSON's NaN/Infinity parse as floats, pass a bare
            # `< 0` check, and turn the row's logits into garbage.
            raise ValueError(
                f"temperature must be a finite number >= 0, got "
                f"{temperature!r}"
            )
        stop_seqs: tuple = ()
        if stop:
            stop_seqs = tuple(tuple(int(t) for t in seq) for seq in stop)
            if (not all(stop_seqs) or len(stop_seqs) > 8
                    or any(len(s) > 64 for s in stop_seqs)):
                # Bounded like every other client input: the suffix
                # compare runs per emitted token under the engine lock —
                # an unbounded sequence would stall every slot.
                raise ValueError(
                    "stop must be 1..8 non-empty token-id sequences of "
                    "at most 64 tokens each"
                )
        bias = None
        if logit_bias:
            bias = {}
            for tok, b in logit_bias.items():
                tok = int(tok)
                if not 0 <= tok < self.cfg.vocab_size:
                    raise ValueError(
                        f"logit_bias token {tok} outside vocab "
                        f"[0, {self.cfg.vocab_size})"
                    )
                b = float(b)
                if not math.isfinite(b):
                    raise ValueError(f"logit_bias value {b!r} not finite")
                # OpenAI clamps to ±100 (±100 effectively forces/bans).
                bias[tok] = max(-100.0, min(100.0, b))
        if deadline_s is not None and (
            not isinstance(deadline_s, (int, float))
            or isinstance(deadline_s, bool)
            or not math.isfinite(deadline_s) or deadline_s <= 0
        ):
            raise ValueError(
                f"deadline_s must be a finite number > 0, got "
                f"{deadline_s!r}"
            )
        rid = self._next_rid
        self._next_rid += 1
        return _Request(
            rid, list(prompt), max_new=max_new_tokens,
            temperature=None if temperature is None else float(temperature),
            stop=stop_seqs, logit_bias=bias,
            deadline=None if deadline_s is None
            else self._clock() + float(deadline_s),
        )

    def cancel(self, rid: int, reason: str = "cancelled") -> bool:
        """Retire ``rid`` without completing it. A queued request is
        aborted immediately (it never cost a prefill); a request holding
        a slot — or mid-chunked-admission — is marked and retired at its
        next _note_token, i.e. within one engine step. Must be called
        under the same lock that serializes the drive loop (the serving
        frontend's engine lock). Returns False when the rid is unknown
        or already retired (the cancel raced a normal completion — the
        caller must not count it)."""
        for i, req in enumerate(self._queue):
            if req.rid == rid:
                del self._queue[i]
                self._deliver_abort(req, reason)
                return True
        admitting = getattr(self, "_admitting", None)
        if admitting is not None and admitting["req"].rid == rid:
            self._cancelled[rid] = reason
            return True
        for a in getattr(self, "_ragged_admit", {}).values():
            if a["req"].rid == rid:
                self._cancelled[rid] = reason
                return True
        for req in self._by_slot:
            if req is not None and req.rid == rid:
                self._cancelled[rid] = reason
                return True
        return False

    def _deliver_abort(self, req: _Request, reason: str) -> None:
        if self.on_abort is not None:
            self.on_abort(req.rid, req.tokens, reason)
        else:
            # Drive-to-completion callers still get the partial output
            # under the rid (deadline truncation is a result, not a
            # crash); run_aborted() names the reason.
            self._results[req.rid] = req.tokens
            self._result_logprobs[req.rid] = req.logprobs
            self._aborted[req.rid] = reason

    def _abort_slot(self, slot: int, reason: str) -> None:
        """Retire a slot through the abort path: deliver the partial
        tokens with the abort reason, then free the slot exactly like a
        normal retirement (same _release_slot invariants)."""
        req = self._by_slot[slot]
        self._deliver_abort(req, reason)
        self._release_slot(slot)

    def run_aborted(self) -> dict[int, str]:
        """{rid: reason} for requests the most recent run() retired
        through the abort path (cancel/deadline)."""
        return getattr(self, "_last_aborted", {})

    def _initial_budget(self, req: _Request) -> int:
        """Per-request budget at admit time, clamped to the engine-wide
        max (every compiled shape is sized for gen.max_new_tokens)."""
        if req.max_new is None:
            return self.gen.max_new_tokens
        return min(req.max_new, self.gen.max_new_tokens)

    def _install_bias(self, slot: int, req: _Request):
        """Write the slot's logit-bias row (zeros for unbiased requests —
        a stale row from the previous occupant must never leak) and
        return the row's bias as a (V,) array for the ADMISSION sample,
        or None. The (B, V) array is device-resident: uploaded rows at
        admit, read every step, never re-uploaded."""
        if req.logit_bias is None and self._bias is None:
            return None
        if self._bias is None:
            self._bias = jnp.zeros(
                (self.slots, self.cfg.vocab_size), jnp.float32
            )
        row = np.zeros((self.cfg.vocab_size,), np.float32)
        for tok, b in (req.logit_bias or {}).items():
            row[tok] = b
        row = jnp.asarray(row)
        self._bias = self._bias.at[slot].set(row)
        return row if req.logit_bias else None

    def _pending(self) -> bool:
        """Work exists: queued, decoding, or mid-(chunked-)admission."""
        return (
            bool(self._queue)
            or any(r is not None for r in self._by_slot)
            or getattr(self, "_admitting", None) is not None
            or bool(getattr(self, "_ragged_admit", {}))
        )

    def _pop_queue(self, index: int = 0) -> "_Request":
        """THE queue→admission transition: every admission path pops
        through here so on_admit fires exactly once per request at
        batcher pickup."""
        req = self._queue.pop(index)
        if self.on_admit is not None:
            self.on_admit(req.rid)
        return req

    def drive_once(self) -> None:
        """One drive quantum (admit + step), timed: shared by the batch
        run() loop and the serving frontend's engine thread. Feeds the
        attached flight recorder and — only when a recording tracer is
        installed, so the default path pays nothing — wraps the quantum
        in an ``engine.step`` span carrying whatever the engine stamped
        into ``last_step`` (ragged fill, decode/prefill split)."""
        span = None
        if tracing.enabled():
            span = tracing.get_tracer("engine").start_span("engine.step")
        t0 = self._clock()
        self.last_step = {}
        try:
            self._admit_free_slots()
            self._step()
        except Exception as err:
            if span is not None:
                span.record_error(err)
            raise
        finally:
            dt = self._clock() - t0
            stalled = False
            if self.flight is not None:
                stalled = self.flight.record_step(
                    dt, self.last_step.get("fill")
                )
                if stalled:
                    self.last_step["stalled"] = True
            if span is not None:
                for k, v in self.last_step.items():
                    span.set_attribute(k, v)
                span.set_attribute("duration_s", round(dt, 6))
                if stalled:
                    span.add_event("stall", {"duration_s": round(dt, 6)})
                span.end()

    def run(self) -> dict[int, list[int]]:
        """Drive until queue and slots drain; returns {rid: tokens}."""
        while self._pending():
            self.drive_once()
        out, self._results = self._results, {}
        self._last_logprobs, self._result_logprobs = (
            self._result_logprobs, {}
        )
        self._last_aborted, self._aborted = self._aborted, {}
        return out

    def run_logprobs(self) -> dict[int, list[float]]:
        """Chosen-token logprobs for the most recent run(), {rid: [lp]}.
        Engines that don't compute logprobs (speculative rounds) return
        shorter-than-tokens lists."""
        return getattr(self, "_last_logprobs", {})

    def _note_token(self, slot: int, token: int,
                    logprob: Optional[float] = None) -> None:
        """Record a sampled token for the slot's request; retire on EOS or
        exhausted budget; otherwise feed it back as the next input.
        ``logprob`` (chosen-token log-probability, engines that compute
        it) accumulates alongside the tokens."""
        req = self._by_slot[slot]
        if req is None:
            return
        # Retire-before-emit: a cancelled (disconnected client) or
        # deadline-expired request must not hold its slot for another
        # step, and its caller must never mistake the truncation for a
        # completion — the abort path delivers the partial tokens with
        # the reason instead of a _Final.
        reason = self._cancelled.pop(req.rid, None)
        if reason is None and req.deadline is not None \
                and self._clock() >= req.deadline:
            reason = "deadline"
        if reason is not None:
            self._abort_slot(slot, reason)
            return
        req.budget -= 1
        if token == self.gen.eos_id:
            self._retire(slot)
            return
        req.tokens.append(token)
        if logprob is not None:
            req.logprobs.append(logprob)
        if self.on_token is not None:
            self.on_token(req.rid, token)
        for seq in req.stop:
            if (len(req.tokens) >= len(seq)
                    and tuple(req.tokens[-len(seq):]) == seq):
                # OpenAI semantics: generation ends AT the stop sequence
                # and the sequence itself is excluded from the output.
                del req.tokens[-len(seq):]
                del req.logprobs[len(req.tokens):]
                self._retire(slot)
                return
        if req.budget <= 0:
            # Budget exhaustion is TRUNCATION, not completion — OpenAI
            # reports it as finish_reason "length".
            self._retire(slot, finish_reason="length")
            return
        self.tokens[slot, 0] = token

    def _post_admit(self, slot: int, padded, prompt_mask) -> None:
        """Hook for subclasses that keep a SECOND cache in lockstep (the
        speculative batchers prefill their draft cache here)."""

    def _retire(self, slot: int, finish_reason: str = "stop") -> None:
        req = self._by_slot[slot]
        if self.on_retire is not None:
            self.on_retire(req.rid, req.tokens, req.logprobs, finish_reason)
        else:
            self._results[req.rid] = req.tokens
            self._result_logprobs[req.rid] = req.logprobs
        self._release_slot(slot)


class ContinuousBatcher(_BatcherBase):
    """Fixed-slot continuous-batching server.

    >>> cb = ContinuousBatcher(params, cfg, slots=4, cache_len=256)
    >>> ids = [cb.submit(p) for p in prompts]
    >>> results = cb.run()           # {rid: tokens}, EOS-truncated
    """

    def __init__(
        self,
        params: dict,
        cfg: LlamaConfig,
        gen: Optional[GenerationConfig] = None,
        slots: int = 8,
        cache_len: int = 1024,
        prompt_bucket: int = 64,
        key: Optional[jax.Array] = None,
        plan=None,  # parallel.mesh.MeshPlan → tp/sp-sharded serving
        kv_bits: int = 0,  # 8 → int8 KV storage (halved cache HBM)
        attn_kernel: Optional[bool] = None,  # length-bounded pallas decode
        admit_chunk: Optional[int] = None,  # interleave admission pieces
        ragged: bool = False,  # fuse admission chunk + decodes per step
    ):
        self.gen = gen or GenerationConfig()
        # Chunked admission: a long prompt's prefill runs in admit_chunk-
        # token pieces with a DECODE STEP between pieces (the drive loop
        # alternates _admit_free_slots/_step), so in-flight neighbors'
        # inter-token latency stops paying for whole admissions. One
        # admission in flight at a time; token-parity with one-shot
        # admission is pinned by tests.
        if admit_chunk is not None:
            if admit_chunk <= 0 or prompt_bucket % admit_chunk:
                raise ValueError(
                    f"admit_chunk {admit_chunk} must be a positive "
                    f"divisor-multiple of prompt_bucket {prompt_bucket}"
                )
            if plan is not None:
                raise ValueError(
                    "admit_chunk does not compose with plan= yet — "
                    "drop one of the two"
                )
        self._admit_chunk = admit_chunk
        self._admitting: Optional[dict] = None
        # Ragged mode: admission chunks and decode tokens FUSE into one
        # (B, admit_chunk) chunk-causal dispatch per step (_cb_ragged_step)
        # instead of alternating admit-then-step — admission stops
        # stalling in-flight decodes, and a completing admission's first
        # token arrives with the same dispatch. Token-parity with the
        # alternating path is pinned by tests.
        if ragged:
            if admit_chunk is None:
                raise ValueError(
                    "ragged=True needs admit_chunk= (the fused step's "
                    "chunk width)"
                )
            if kv_bits:
                raise ValueError(
                    "ragged=True does not compose with kv_bits — "
                    "drop one of the two"
                )
            if attn_kernel:
                raise ValueError(
                    "ragged=True does not compose with attn_kernel=True "
                    "(the fused chunk step is XLA) — drop one of the two"
                )
        self.ragged = ragged
        # Length-bounded decode attention (ops/paged_attention.py dense
        # kernel): XLA reads ALL cache_len slots per step; the kernel
        # reads each slot's filled prefix only. Auto-on under the TPU
        # backend for plain bf16 single-device serving; explicit True
        # with an unsupported composition is a reasoned rejection, never
        # a silent fallback.
        if attn_kernel:
            if plan is not None:
                raise ValueError(
                    "attn_kernel=True does not compose with plan= (the "
                    "dense kernel is single-device) — drop one of the two"
                )
            if kv_bits:
                raise ValueError(
                    "attn_kernel=True does not compose with kv_bits (the "
                    "kernel reads bf16 caches) — drop one of the two"
                )
            if cfg.sliding_window:
                raise ValueError(
                    "attn_kernel=True does not support sliding-window "
                    "configs — drop attn_kernel for this model"
                )
        explicit = attn_kernel is True
        if attn_kernel is None:
            attn_kernel = (
                jax.default_backend() in ("tpu", "axon") and plan is None
                and not kv_bits and not cfg.sliding_window and not ragged
            )
        # Chunk size: the largest power-of-two divisor of cache_len in
        # [16, 512]. EXPLICIT True with an indivisible cache_len raises
        # (same contract as plan/kv_bits/window above); the auto default
        # quietly keeps XLA only because nothing was requested.
        self._attn_kernel = 0
        if attn_kernel:
            for cand in (512, 256, 128, 64, 32, 16):
                if cache_len % cand == 0:
                    self._attn_kernel = cand
                    break
            if explicit and not self._attn_kernel:
                raise ValueError(
                    f"attn_kernel=True needs cache_len divisible by a "
                    f"power of two in [16, 512]; {cache_len} is not — "
                    "adjust cache_len or drop attn_kernel"
                )
        if prompt_bucket + self.gen.max_new_tokens > cache_len:
            raise ValueError(
                f"cache_len {cache_len} too small for prompt_bucket "
                f"{prompt_bucket} + max_new_tokens {self.gen.max_new_tokens}"
            )
        self.params = params
        self.cfg = cfg
        self.cache_len = cache_len
        self.key = jax.random.PRNGKey(0) if key is None else key
        self.kv_bits = kv_bits  # ONE home; never re-sniffed from keys
        self.cache = init_kv_cache(cfg, slots, cache_len, kv_bits=kv_bits)
        self.kv_mask = jnp.zeros((slots, cache_len), bool)
        # Host-side mutable state; uploaded once per step.
        self.positions = np.zeros((slots,), np.int32)
        self.tokens = np.full((slots, 1), self.gen.pad_id, np.int32)
        self._decode_attn = None
        if plan is not None:
            # Multi-host serving: params tp-sharded per the model-wide
            # plan; the cache's kv-head axis over tp and its SEQUENCE axis
            # over sp; kv_mask follows the cache columns. The jitted
            # programs are unchanged — GSPMD propagates the shardings and
            # inserts the collectives (psum for tp matmuls); when sp > 1
            # the decode attention swaps to the explicit split-KV
            # shard_map (flash-decoding pmax/psum merge) so the cache read
            # stays local to each sp shard.
            from jax.sharding import NamedSharding, PartitionSpec as P

            from kubeflow_tpu.parallel.ring_attention import (
                make_sharded_sp_decode,
            )

            mesh = plan.mesh
            sp = mesh.shape.get("sp", 1)
            if sp > 1 and cache_len % sp:
                raise ValueError(
                    f"cache_len {cache_len} not divisible by sp={sp}"
                )
            # Cache first: shard_kv_cache owns the tp-divides-kv-heads
            # validation, and must fire before params are placed.
            self.cache = plan.shard_kv_cache(self.cache, seq_over_sp=True)
            self.params = plan.shard_params(params)
            self.kv_mask = jax.device_put(
                self.kv_mask, NamedSharding(mesh, P(None, "sp"))
            )
            if sp > 1:
                self._decode_attn = make_sharded_sp_decode(mesh)
        self.plan = plan
        # Mesh observability (/stats `mesh` block, bench provenance):
        # None for the classic one-chip engine so its records stay
        # byte-identical; same convention as PagedBatcher.
        self.mesh_axes = plan.axes if plan is not None else None
        self._init_base(self.gen, slots, prompt_bucket)

    # -- internals ---------------------------------------------------------

    def _admit_free_slots(self) -> None:
        if getattr(self, "ragged", False):
            self._stage_ragged_admission()
            return
        if getattr(self, "_admit_chunk", None):
            self._admit_one_chunk()
            return
        # kftpu-lint: disable=kftpu-host-sync-in-hot-path — bounded per-slot admission host->device upload (at most `slots` iterations when requests are queued), not a per-token readback
        for slot in range(self.slots):
            if self._by_slot[slot] is not None or not self._queue:
                continue
            req = self._pop_queue()
            padded, mask = left_pad(
                [req.prompt], self.gen.pad_id, self.prompt_bucket
            )
            prompt_mask = None if mask.all() else jnp.asarray(mask)
            logits = self._prefill_into_slot(slot, req, jnp.asarray(padded),
                                             prompt_mask)
            self._install_admitted(slot, req, jnp.asarray(padded),
                                   prompt_mask, logits)

    def _admit_one_chunk(self) -> None:
        """Advance chunked admission by ONE piece (the drive loop runs a
        decode step between calls — that interleaving is the feature)."""
        a = self._admitting
        if a is None:
            slot = next(
                (i for i in range(self.slots)
                 if self._by_slot[i] is None), None,
            )
            if slot is None or not self._queue:
                return
            req = self._pop_queue()
            padded, mask = left_pad(
                [req.prompt], self.gen.pad_id, self.prompt_bucket
            )
            row = np.ones((1, self.cache_len), bool)
            row[:, :self.prompt_bucket] = np.asarray(mask)
            a = self._admitting = {
                "slot": slot,
                "req": req,
                "padded": np.array(padded),
                "prompt_mask": None if mask.all() else jnp.array(mask),
                "row": jnp.array(row),
                "temp": init_kv_cache(self.cfg, 1, self.cache_len,
                                      kv_bits=self.kv_bits),
                "cursor": _AdmissionCursor(np.asarray(mask)[0],
                                           self.prompt_bucket,
                                           align=self._admit_chunk),
                "logits": None,
            }
        cs = self._admit_chunk
        start, _ = a["cursor"].take(cs)
        # jnp.array (copy), not asarray: the CPU backend aliases numpy
        # memory zero-copy and basic slicing returns a VIEW — dispatched
        # chunks must never share mutable host buffers. The explicit
        # block serializes each admission piece at its boundary: the
        # interleaving this feature exists for is host-loop-level
        # (decode step between pieces), and an unsynchronized per-chunk
        # dispatch chain showed nondeterministic token corruption in
        # review stress runs.
        tok = jnp.array(a["padded"][:, start:start + cs])
        a["logits"], a["temp"] = _admit_chunk(
            self.params, self.cfg, tok, a["temp"],
            jnp.asarray([start], jnp.int32), a["row"],
        )
        jax.block_until_ready(a["logits"])
        if a["cursor"].done:
            self.cache, self.kv_mask = _install_temp_cache(
                a["temp"], self.cache, self.kv_mask, a["row"],
                jnp.asarray(a["slot"], jnp.int32),
            )
            self._install_admitted(
                a["slot"], a["req"], jnp.asarray(a["padded"]),
                a["prompt_mask"], a["logits"],
            )
            self._admitting = None

    def _stage_ragged_admission(self) -> None:
        """Stage (not dispatch) the next admission: in ragged mode the
        prefill chunks ride the fused step dispatch, so staging only
        claims the slot and installs the row's validity mask,
        temperature, and bias — sampling state must be live BEFORE the
        completing chunk's dispatch samples the first token."""
        if self._admitting is not None or not self._queue:
            return
        slot = next(
            (i for i in range(self.slots) if self._by_slot[i] is None),
            None,
        )
        if slot is None:
            return
        req = self._pop_queue()
        padded, mask = left_pad(
            [req.prompt], self.gen.pad_id, self.prompt_bucket
        )
        row = np.ones((self.cache_len,), bool)
        row[: self.prompt_bucket] = np.asarray(mask)[0]
        # The row mask goes live before the positions are written;
        # garbage under it is only reachable by this slot's own
        # chunk-causal queries, which never look past their own chunk.
        self.kv_mask = self.kv_mask.at[slot].set(jnp.asarray(row))
        self.temps[slot] = (self.gen.temperature if req.temperature is None
                            else req.temperature)
        self._install_bias(slot, req)
        self._admitting = {
            "slot": slot,
            "req": req,
            "padded": np.array(padded),
            "prompt_mask": None if mask.all() else jnp.array(mask),
            "cursor": _AdmissionCursor(np.asarray(mask)[0],
                                       self.prompt_bucket,
                                       align=self._admit_chunk),
        }

    def _install_admitted(self, slot: int, req: _Request, padded,
                          prompt_mask, logits) -> None:
        """Admission tail shared by one-shot and chunked admission: the
        _post_admit hook, first-token sampling (request temperature +
        bias + logprob), and slot bookkeeping."""
        self._post_admit(slot, padded, prompt_mask)
        self.key, sub = jax.random.split(self.key)
        temp = (self.gen.temperature if req.temperature is None
                else req.temperature)
        bias_row = self._install_bias(slot, req)
        if bias_row is not None:
            logits = logits + bias_row
        first = int(
            sample_logits(
                logits[None], sub, temp, self.gen.top_k,
                self.gen.top_p,
            )[0]
        )
        first_lp = float(
            jax.nn.log_softmax(logits.astype(jnp.float32))[first]
        )
        self.positions[slot] = self.prompt_bucket
        self.temps[slot] = temp
        self._by_slot[slot] = req
        req.budget = self._initial_budget(req)
        self._note_token(slot, first, first_lp)

    def _prefill_into_slot(self, slot: int, req: _Request, padded,
                           prompt_mask) -> jax.Array:
        """The engine-specific half of admission: prefill ``padded`` into
        ``slot`` and return the first logits. Overridden by multi-LoRA
        (adapter-aware prefill) — everything around it (padding, the
        _post_admit hook, sampling, budget, bookkeeping) stays in ONE
        loop above so a fix there applies to every subclass."""
        del req
        logits, self.cache, self.kv_mask = _admit_slot(
            self.params, self.cfg, padded, prompt_mask,
            self.cache, self.kv_mask, jnp.asarray(slot, jnp.int32),
        )
        return logits

    def _release_slot(self, slot: int) -> None:
        self._by_slot[slot] = None
        # Invalidate the slot so stale cache rows can never be attended
        # before the next admit overwrites them.
        self.kv_mask = self.kv_mask.at[slot].set(False)

    def _step(self) -> None:
        if getattr(self, "ragged", False):
            self._step_ragged()
            return
        active = [i for i, r in enumerate(self._by_slot) if r is not None]
        if not active:
            return
        self.last_step = {
            "decode_rows": len(active),
            "prefill_rows": 0,
            "fill": len(active) / self.slots,
        }
        self.key, sub = jax.random.split(self.key)
        # jnp.array (not asarray): the CPU backend can alias numpy memory
        # zero-copy, and the host mutates tokens/positions below while the
        # dispatched step may still be reading them — upload COPIES.
        nxt, lps, self.cache = _cb_step(
            self.params, self.cfg, jnp.array(self.tokens), self.cache,
            jnp.array(self.positions), self.kv_mask, sub,
            jnp.array(self.temps), self.gen.top_k, self.gen.top_p,
            bias=self._bias,
            decode_attn=self._decode_attn,
            attn_kernel=self._attn_kernel,
        )
        # The emitted token will occupy the next cache index of its slot.
        for slot in active:
            self.positions[slot] += 1
        host_next = np.asarray(nxt)  # the one per-step readback
        host_lps = np.asarray(lps)
        for slot in active:
            self._note_token(slot, int(host_next[slot]),
                             float(host_lps[slot]))

    def _step_ragged(self) -> None:
        """One fused mixed prefill/decode step: every active slot's
        decode token plus the in-flight admission's next prompt chunk
        go out as ONE (B, admit_chunk) chunk-causal dispatch."""
        a = self._admitting
        active = [i for i, r in enumerate(self._by_slot) if r is not None]
        if not active and a is None:
            return
        cs = self._admit_chunk
        tokens = np.full((self.slots, cs), self.gen.pad_id, np.int32)
        positions = np.zeros((self.slots,), np.int32)
        cols = np.zeros((self.slots,), np.int32)
        for slot in active:
            tokens[slot, 0] = self.tokens[slot, 0]
            positions[slot] = self.positions[slot]
        admit_done = False
        if a is not None:
            start, n = a["cursor"].take(cs)
            tokens[a["slot"], :n] = a["padded"][0, start:start + n]
            positions[a["slot"]] = start
            cols[a["slot"]] = n - 1
            admit_done = a["cursor"].done
        prefill_rows = 0 if a is None else 1
        self.last_step = {
            "decode_rows": len(active),
            "prefill_rows": prefill_rows,
            "fill": (len(active) + prefill_rows) / self.slots,
        }
        self.key, sub = jax.random.split(self.key)
        nxt, lps, self.cache = _cb_ragged_step(
            self.params, self.cfg, jnp.array(tokens), self.cache,
            jnp.array(positions), self.kv_mask, jnp.array(cols), sub,
            jnp.array(self.temps), self.gen.top_k, self.gen.top_p,
            bias=self._bias,
        )
        host_next = np.asarray(nxt)
        host_lps = np.asarray(lps)
        for slot in active:
            self.positions[slot] += 1
        for slot in active:
            self._note_token(slot, int(host_next[slot]),
                             float(host_lps[slot]))
        if a is not None and admit_done:
            # The completing chunk's dispatch already sampled the first
            # token (its row's last-real column) — finish the admission
            # bookkeeping without a separate prefill readback.
            slot, req = a["slot"], a["req"]
            self._post_admit(slot, jnp.asarray(a["padded"]),
                             a["prompt_mask"])
            self.positions[slot] = self.prompt_bucket
            self._by_slot[slot] = req
            req.budget = self._initial_budget(req)
            self._admitting = None
            self._note_token(slot, int(host_next[slot]),
                             float(host_lps[slot]))
