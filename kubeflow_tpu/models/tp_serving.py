"""Tensor-parallel serving replicas: a "replica" is a MESH, not a chip.

The serving engines (PagedBatcher and everything stacked on it — the
ragged fused dispatch, disagg handoff, host-RAM swap, speculation,
multi-LoRA) take a ``plan=`` MeshPlan and run their existing jitted
steps unchanged: weights are NamedSharding-partitioned on the ``tp``
axis (attention heads / MLP hidden split; embeddings and lm_head
vocab-sharded), the paged KV block pool is HEAD-sharded (each chip
holds only its heads' K/V rows, so per-chip pool bytes drop by the TP
degree), and GSPMD inserts the two collectives the math requires — a
psum on ``tp`` after the attention output projection and after the MLP
down-projection — INSIDE the jitted step. Host bookkeeping (allocator,
tables, chain keys) never changes: np.asarray on a sharded leaf
gathers, so export/import and swap wire formats are TP-invariant.

This module is the thin serving-specific layer over parallel.mesh:
validation that fails FAST at replica startup (a bad degree must kill
the pod before it takes traffic, tpu_env.py discipline), the
one-replica mesh constructor, and the fleet-side device partitioner
that carves a host's chips into TP replica groups.

Token-exactness is the contract (pinned by tests/test_tp_serving.py):
a tp=N replica matches the 1-chip engine token-for-token.
"""

from __future__ import annotations

from typing import Optional

import jax

from kubeflow_tpu.models.llama import LlamaConfig
from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh


def validate_serving_tp(cfg: LlamaConfig, tp: int,
                        n_devices: Optional[int] = None) -> int:
    """Fail-fast validation of a serving TP degree against a model
    config (and optionally the visible device count). Returns the
    degree. Raises ValueError with an operator-actionable message —
    serve_http surfaces it at startup, before the replica takes
    traffic. The kv-head rule is the hard one (a finer-than-head split
    silently corrupts attention; mesh.shard_kv_cache re-checks it at
    pool placement as the last line of defense)."""
    tp = int(tp)
    if tp < 1:
        raise ValueError(f"serving tp degree must be >= 1, got {tp}")
    if cfg.n_kv_heads % tp:
        raise ValueError(
            f"tp={tp} must divide n_kv_heads={cfg.n_kv_heads}: the paged "
            "pool shards by kv head, and a finer split would cut a head "
            "in half"
        )
    if cfg.n_heads % tp:
        raise ValueError(
            f"tp={tp} must divide n_heads={cfg.n_heads}: query heads "
            "partition over the tp axis"
        )
    if n_devices is not None and tp > n_devices:
        raise ValueError(
            f"tp={tp} needs {tp} devices, have {n_devices}"
        )
    return tp


def serving_plan(tp: int, devices=None,
                 cfg: Optional[LlamaConfig] = None) -> Optional[MeshPlan]:
    """The one-replica serving mesh: a pure-tp MeshPlan over the first
    ``tp`` devices (or the given explicit list). tp=1 returns None —
    the classic single-chip engine, with zero plan-path overhead — so
    callers can thread the result straight into ``plan=``. ``cfg``
    opts into the model-shape validation up front."""
    tp = int(tp)
    if cfg is not None:
        validate_serving_tp(
            cfg, tp,
            n_devices=len(devices) if devices is not None else None,
        )
    if tp <= 1:
        return None
    pool = list(devices) if devices is not None else jax.devices()
    if len(pool) < tp:
        raise ValueError(
            f"serving tp={tp} needs {tp} devices, have {len(pool)}"
        )
    return MeshPlan(make_mesh(tp=tp, devices=pool[:tp]))


def replica_device_groups(tp: int, devices=None) -> list:
    """Carve the visible chips into disjoint tp-sized replica groups —
    the fleet-side partitioner: N chips host N//tp mesh replicas, each
    one HTTP endpoint (the gateway never learns the difference). The
    remainder chips (len % tp) are left out rather than forming a
    ragged replica."""
    tp = int(tp)
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    pool = list(devices) if devices is not None else jax.devices()
    return [pool[i:i + tp] for i in range(0, len(pool) - tp + 1, tp)]
