"""Llama model family: functional JAX, TPU-first.

The in-notebook flagship for the benchmark target (BASELINE.md: Llama-2-7B
tokens/sec/chip on v5e). Design choices for the MXU/XLA (not a torch port):

- pure functional: params are a pytree of bf16 arrays; every entry point is
  jit-able and shard-able with the PartitionSpecs from
  kubeflow_tpu.parallel.mesh.MeshPlan,
- **stacked layers + lax.scan**: all transformer layers live in one pytree
  with a leading (n_layers, ...) axis and the forward pass scans over it —
  XLA compiles ONE layer body instead of unrolling 32, keeping compile
  times interactive-notebook friendly,
- static shapes everywhere: prefill takes a fixed block, decode is a single
  fused step over a preallocated KV cache (lax.dynamic_update_slice), so
  XLA compiles exactly two programs for generation,
- attention goes through kubeflow_tpu.ops.flash_attention (pallas on TPU),
- f32 for norms/softmax/rope accumulation, bf16 weights and activations —
  the MXU-native mix.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.attention import NEG_INF, flash_attention


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Llama-3.1 "llama3" rope scaling (frequency-dependent NTK stretch).

    Frozen/hashable so it can live inside the jit-static LlamaConfig.
    Field semantics follow the HF config.json rope_scaling block.
    """

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Llama-family transformer config.

    The family flags cover the popular decoder-only variants without
    separate model classes (they share the HF module layout, so
    models/convert.py loads all of them):
    - Mistral: ``sliding_window`` > 0 (local attention band)
    - Gemma: ``act="gelu"`` (GeGLU, tanh approximation),
      ``norm_add_unit`` (RMSNorm multiplies by 1+w), ``embed_scale``
      (embeddings scaled by sqrt(dim)), ``head_dim_override`` (head_dim
      decoupled from dim//n_heads), ``tie_embeddings``
    - Qwen2: ``attn_bias`` (biases on the q/k/v projections).
    """

    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_hidden: int = 11008
    rope_theta: float = 10000.0
    rope_scaling: Optional[RopeScaling] = None
    max_seq_len: int = 4096
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    sliding_window: int = 0  # 0 = full causal attention
    act: str = "silu"  # "silu" (llama/mistral) | "gelu" (gemma, tanh approx)
    norm_add_unit: bool = False  # RMSNorm weight is (1 + w) (gemma)
    embed_scale: bool = False  # scale embeddings by sqrt(dim) (gemma)
    head_dim_override: int = 0  # 0 = dim // n_heads
    tie_embeddings: bool = False  # lm_head shares the embedding matrix
    attn_bias: bool = False  # q/k/v projections carry biases (qwen2)

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or self.dim // self.n_heads

    def param_count(self) -> int:
        embed = self.vocab_size * self.dim
        attn = self.dim * self.head_dim * (2 * self.n_heads + 2 * self.n_kv_heads)
        mlp = 3 * self.dim * self.ffn_hidden
        norms = 2 * self.dim
        n_embed = 1 if self.tie_embeddings else 2
        return n_embed * embed + self.n_layers * (attn + mlp + norms) + self.dim


LLAMA_CONFIGS: dict[str, LlamaConfig] = {
    "llama-2-7b": LlamaConfig(),
    "llama-2-13b": LlamaConfig(dim=5120, n_layers=40, n_heads=40, n_kv_heads=40,
                               ffn_hidden=13824),
    "llama-2-70b": LlamaConfig(dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
                               ffn_hidden=28672),
    "llama-3-8b": LlamaConfig(vocab_size=128256, dim=4096, n_layers=32,
                              n_heads=32, n_kv_heads=8, ffn_hidden=14336,
                              rope_theta=500000.0, max_seq_len=8192),
    "llama-3.1-8b": LlamaConfig(vocab_size=128256, dim=4096, n_layers=32,
                                n_heads=32, n_kv_heads=8, ffn_hidden=14336,
                                rope_theta=500000.0, max_seq_len=131072,
                                rope_scaling=RopeScaling()),
    "mistral-7b": LlamaConfig(vocab_size=32000, dim=4096, n_layers=32,
                              n_heads=32, n_kv_heads=8, ffn_hidden=14336,
                              max_seq_len=32768, sliding_window=4096),
    "gemma-2b": LlamaConfig(vocab_size=256000, dim=2048, n_layers=18,
                            n_heads=8, n_kv_heads=1, ffn_hidden=16384,
                            max_seq_len=8192, act="gelu", norm_add_unit=True,
                            embed_scale=True, head_dim_override=256,
                            tie_embeddings=True),
    "gemma-7b": LlamaConfig(vocab_size=256000, dim=3072, n_layers=28,
                            n_heads=16, n_kv_heads=16, ffn_hidden=24576,
                            max_seq_len=8192, act="gelu", norm_add_unit=True,
                            embed_scale=True, head_dim_override=256,
                            tie_embeddings=True),
    "qwen2.5-7b": LlamaConfig(vocab_size=152064, dim=3584, n_layers=28,
                              n_heads=28, n_kv_heads=4, ffn_hidden=18944,
                              rope_theta=1000000.0, max_seq_len=32768,
                              norm_eps=1e-6, attn_bias=True),
    # Tiny configs for tests / compile checks.
    "tiny": LlamaConfig(vocab_size=256, dim=128, n_layers=2, n_heads=4,
                        n_kv_heads=4, ffn_hidden=256, max_seq_len=256),
    "tiny-gqa": LlamaConfig(vocab_size=256, dim=128, n_layers=2, n_heads=4,
                            n_kv_heads=2, ffn_hidden=256, max_seq_len=256),
}


# ---------------------------------------------------------------------------
# Init — layer params are STACKED along a leading n_layers axis.


def init_params(cfg: LlamaConfig, key: jax.Array) -> dict:
    """Random init, 1/sqrt(fan_in) scaling, stacked layers."""
    k_embed, k_head, k_layers = jax.random.split(key, 3)

    def dense(k, shape):
        # Generate directly in the target dtype: a 7B init must never
        # materialize f32 temporaries (2× HBM) on a 16 GB chip.
        scale = jnp.asarray(1.0 / math.sqrt(shape[-2]), cfg.dtype)
        return jax.random.normal(k, shape, cfg.dtype) * scale

    hd = cfg.head_dim
    L = cfg.n_layers
    lk = iter(jax.random.split(k_layers, 7))
    layers = {
        "attn_norm": jnp.ones((L, cfg.dim), cfg.dtype),
        "wq": dense(next(lk), (L, cfg.dim, cfg.n_heads * hd)),
        "wk": dense(next(lk), (L, cfg.dim, cfg.n_kv_heads * hd)),
        "wv": dense(next(lk), (L, cfg.dim, cfg.n_kv_heads * hd)),
        "wo": dense(next(lk), (L, cfg.n_heads * hd, cfg.dim)),
        "mlp_norm": jnp.ones((L, cfg.dim), cfg.dtype),
        "w_gate": dense(next(lk), (L, cfg.dim, cfg.ffn_hidden)),
        "w_up": dense(next(lk), (L, cfg.dim, cfg.ffn_hidden)),
        "w_down": dense(next(lk), (L, cfg.ffn_hidden, cfg.dim)),
    }
    if cfg.attn_bias:
        layers["bq"] = jnp.zeros((L, cfg.n_heads * hd), cfg.dtype)
        layers["bk"] = jnp.zeros((L, cfg.n_kv_heads * hd), cfg.dtype)
        layers["bv"] = jnp.zeros((L, cfg.n_kv_heads * hd), cfg.dtype)
    out = {
        "embed": dense(k_embed, (cfg.vocab_size, cfg.dim)),
        "final_norm": jnp.ones((cfg.dim,), cfg.dtype),
        "layers": layers,
    }
    # Tied configs (gemma) carry NO separate lm_head leaf: one storage,
    # so allocation matches param_count() and — crucially — gradients
    # from the embedding lookup and the head projection flow into the
    # SAME leaf (two aliased leaves would silently untie during training).
    if not cfg.tie_embeddings:
        out["lm_head"] = dense(k_head, (cfg.vocab_size, cfg.dim))
    return out


# ---------------------------------------------------------------------------
# Building blocks (f32 internals, bf16 boundaries)


def _qkv(h: jax.Array, layer: dict) -> tuple[jax.Array, jax.Array, jax.Array]:
    """q/k/v projections with optional qwen2-style biases."""
    q, k, v = _mm(h, layer["wq"]), _mm(h, layer["wk"]), _mm(h, layer["wv"])
    if "bq" in layer:
        q, k, v = q + layer["bq"], k + layer["bk"], v + layer["bv"]
    return q, k, v


def _mm(x: jax.Array, w) -> jax.Array:
    """x @ w where w is dense OR quantized (models/quant.py) OR an
    fp8-training wrapper (models/fp8.py). Quantized: int8/fp8 with a
    per-output-channel scale (dequant fuses into the matmul EPILOGUE) or
    group-wise int4 (dequant fuses into the weight-operand read) — either
    way the quantized tensor is what crosses HBM, the whole
    weight-only-quant decode win. fp8 training: master weight "hp" +
    delayed-scaling metas, matmul runs with fp8 operands."""
    if isinstance(w, dict):
        if "hp" in w:
            from kubeflow_tpu.models.fp8 import fp8_matmul

            return fp8_matmul(x, w["hp"], w["fp8"])
        if w["q"].dtype == jnp.int4:
            from kubeflow_tpu.models.quant import dequantize_weight

            return x @ dequantize_weight(w, x.dtype)
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


def _lm_head_logits(x: jax.Array, params: dict) -> jax.Array:
    """x @ lm_head.T → f32 logits. Tied trees (no "lm_head" leaf) project
    through the embedding matrix; either may be quantized (int8 per-row
    scale folds into the output; int4 dequantizes on the operand)."""
    w = params["lm_head"] if "lm_head" in params else params["embed"]
    if isinstance(w, dict):
        if w["q"].dtype == jnp.int4:
            from kubeflow_tpu.models.quant import dequantize_weight

            return (x @ dequantize_weight(w, x.dtype).T).astype(jnp.float32)
        logits = (x @ w["q"].T.astype(x.dtype)).astype(jnp.float32)
        return logits * w["s"][:, 0]
    return (x @ w.T).astype(jnp.float32)


def rms_norm(
    x: jax.Array, weight: jax.Array, eps: float, add_unit: bool = False
) -> jax.Array:
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    if add_unit:
        # Gemma: multiply by (1 + w) in f32, THEN cast (matches HF).
        return ((xf * rms) * (weight.astype(jnp.float32) + 1.0)).astype(x.dtype)
    return (xf * rms).astype(x.dtype) * weight


def _norm(x: jax.Array, weight: jax.Array, cfg: LlamaConfig) -> jax.Array:
    return rms_norm(x, weight, cfg.norm_eps, add_unit=cfg.norm_add_unit)


def _embed(params: dict, cfg: LlamaConfig, tokens: jax.Array) -> jax.Array:
    x = params["embed"][tokens]
    if cfg.embed_scale:
        x = x * jnp.asarray(math.sqrt(cfg.dim), x.dtype)
    return x


def rope_frequencies(cfg: LlamaConfig, positions: jax.Array) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for the given positions: (S, head_dim/2) each, f32."""
    half = cfg.head_dim // 2
    freqs = cfg.rope_theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    # getattr: duck-typed configs (MoEConfig) reuse this without carrying
    # every llama-family field.
    scaling = getattr(cfg, "rope_scaling", None)
    if scaling is not None:
        freqs = _llama3_scale_freqs(scaling, freqs)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def _llama3_scale_freqs(rs: RopeScaling, freqs: jax.Array) -> jax.Array:
    """Llama-3.1 frequency-dependent scaling: high-frequency components are
    kept, low-frequency components are stretched by ``factor``, with a
    smooth ramp between the two wavelength cutoffs (matches the HF
    "llama3" rope_type implementation numerically)."""
    low_wavelen = rs.original_max_position_embeddings / rs.low_freq_factor
    high_wavelen = rs.original_max_position_embeddings / rs.high_freq_factor
    wavelen = 2.0 * math.pi / freqs
    # Ramp ∈ [0,1]: 0 at the low-frequency cutoff, 1 at the high-frequency.
    smooth = (rs.original_max_position_embeddings / wavelen - rs.low_freq_factor) / (
        rs.high_freq_factor - rs.low_freq_factor
    )
    smooth = jnp.clip(smooth, 0.0, 1.0)
    scaled = jnp.where(
        wavelen > low_wavelen,
        freqs / rs.factor,
        jnp.where(
            wavelen < high_wavelen,
            freqs,
            (1.0 - smooth) * freqs / rs.factor + smooth * freqs,
        ),
    )
    return scaled


def apply_rope(
    x: jax.Array, cos: jax.Array, sin: jax.Array, per_batch: bool = False
) -> jax.Array:
    """x: (B, H, S, D). Rotate pairs (split-half convention).

    ``per_batch=False``: cos/sin are (S, half), shared across the batch.
    ``per_batch=True``: cos/sin are (B, half) with S == 1 — one position
    per batch row (continuous-batching decode, where every slot sits at
    its own offset). 3-D cos/sin (B, S, half) are per-batch per-position
    (batched speculative verification: every row's chunk starts at its
    own offset)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    if cos.ndim == 3:
        c = cos[:, None, :, :]
        s = sin[:, None, :, :]
    elif per_batch:
        c = cos[:, None, None, :]
        s = sin[:, None, None, :]
    else:
        c = cos[None, None, :, :]
        s = sin[None, None, :, :]
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out1 = x1f * c - x2f * s
    out2 = x2f * c + x1f * s
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def _split_heads(x: jax.Array, n_heads: int) -> jax.Array:
    b, s, _ = x.shape
    return x.reshape(b, s, n_heads, -1).transpose(0, 2, 1, 3)  # (B, H, S, D)


def _merge_heads(x: jax.Array) -> jax.Array:
    b, h, s, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, s, h * d)


def _layer_fwd(
    layer: dict, cfg: LlamaConfig, x: jax.Array,
    cos: jax.Array, sin: jax.Array, attn_impl: str,
) -> jax.Array:
    """One transformer layer, full-sequence (prefill/training)."""
    h = _norm(x, layer["attn_norm"], cfg)
    hq, hk, hv = _qkv(h, layer)
    q = apply_rope(_split_heads(hq, cfg.n_heads), cos, sin)
    k = apply_rope(_split_heads(hk, cfg.n_kv_heads), cos, sin)
    v = _split_heads(hv, cfg.n_kv_heads)
    # K/V go in UNREPEATED: flash_attention folds the GQA group mapping
    # into its kernel index maps (or broadcasts for XLA/SP impls), so no
    # n_heads-sized K/V buffer is materialized here.
    attn = flash_attention(
        q, k, v, causal=True, impl=attn_impl, window=cfg.sliding_window,
    )
    x = x + _mm(_merge_heads(attn), layer["wo"])
    h = _norm(x, layer["mlp_norm"], cfg)
    return x + _mlp(layer, h, cfg)


def _mlp(layer: dict, x: jax.Array, cfg: LlamaConfig) -> jax.Array:
    pre = _mm(x, layer["w_gate"]).astype(jnp.float32)
    if cfg.act == "gelu":
        gate = jax.nn.gelu(pre, approximate=True)  # pytorch-tanh gelu
    else:
        gate = jax.nn.silu(pre)
    up = _mm(x, layer["w_up"]).astype(jnp.float32)
    return _mm((gate * up).astype(x.dtype), layer["w_down"])


# ---------------------------------------------------------------------------
# Entry points


# Remat policies for the layer scan, keyed by name so callers (train step,
# bench) can trade HBM for recompute FLOPs per hardware budget:
# - "full": rematerialize everything; the scan stores only the (B, S, dim)
#   carry per layer. Cheapest memory, recomputes the whole layer forward
#   (~2N extra FLOPs) in the backward — the default that always fits.
# - "dots": save MXU outputs (dot_general results with no batch dims —
#   the qkv/wo/mlp projections), recompute only VPU-cheap elementwise ops
#   (norms, rope, activations). Removes most of the recompute FLOPs at
#   ~B*S*(heads*d + 2*ffn + 2*dim) saved bytes per layer.
# - "none": no checkpointing; XLA stores what it needs. Fastest when it
#   fits (small models / short S).
_REMAT_POLICIES = {
    "full": lambda body: jax.checkpoint(body),
    "dots": lambda body: jax.checkpoint(
        body,
        policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    ),
    "none": lambda body: body,
}


@partial(jax.jit, static_argnames=("cfg", "attn_impl", "remat"))
def forward_hidden(
    params: dict, cfg: LlamaConfig, tokens: jax.Array,
    attn_impl: str = "auto", remat: str = "full",
) -> jax.Array:
    """Forward through the layer stack + final norm: tokens (B, S) →
    hidden (B, S, dim), WITHOUT the lm-head projection — the seam the
    chunked cross-entropy needs (models/train.py) so full (B, S, vocab)
    logits never materialize. ``remat`` picks the _REMAT_POLICIES entry.
    Free at inference (no cotangent → no recompute)."""
    if remat not in _REMAT_POLICIES:
        raise ValueError(
            f"unknown remat policy {remat!r} (want {sorted(_REMAT_POLICIES)})"
        )
    x = _embed(params, cfg, tokens)
    cos, sin = rope_frequencies(cfg, jnp.arange(tokens.shape[1]))

    def body(x, layer):
        return _layer_fwd(layer, cfg, x, cos, sin, attn_impl), None

    x, _ = jax.lax.scan(_REMAT_POLICIES[remat](body), x, params["layers"])
    return _norm(x, params["final_norm"], cfg)


@partial(jax.jit, static_argnames=("cfg", "attn_impl"))
def forward(
    params: dict, cfg: LlamaConfig, tokens: jax.Array, attn_impl: str = "auto"
) -> jax.Array:
    """Full prefill / training forward: tokens (B, S) → logits (B, S, V)."""
    return _lm_head_logits(
        forward_hidden(params, cfg, tokens, attn_impl), params
    )


def init_kv_cache(
    cfg: LlamaConfig, batch: int, max_len: int, kv_bits: int = 0
) -> dict:
    """Stacked KV cache: (L, B, Hkv, max_len, head_dim).

    ``kv_bits=8`` stores K/V as int8 with a per-(head, position) scale —
    long-context decode reads cache bytes that grow with context, and
    int8 halves them. The cache's STRUCTURE carries the format (the
    ``k_scale``/``v_scale`` leaves), so every consumer keys off the
    pytree, not a flag: writes quantize, attention dequantizes in the
    score/value einsum epilogues, prefill attention still runs on the
    fresh full-precision K/V (only storage quantizes)."""
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
    return _kv_cache_leaves(shape, cfg.dtype, kv_bits)


def _kv_cache_leaves(shape: tuple, dtype, kv_bits: int) -> dict:
    """ONE constructor for the structure-keyed storage format, shared by
    the stacked cache (above) and the paged block pool (models.paged):
    ``shape`` is the (..., S, D) value-leaf shape; kv_bits=8 adds the
    bf16 scale leaves one rank lower. Keeping it single-homed means a
    format change (scale dtype, a new kv_bits) cannot diverge them."""
    if kv_bits == 8:
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(shape[:-1], jnp.bfloat16),
            "v_scale": jnp.zeros(shape[:-1], jnp.bfloat16),
        }
    if kv_bits:
        raise ValueError(f"kv_bits must be 0 or 8, got {kv_bits}")
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _kv_quantize(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(..., S, D) → (int8 values, (..., S) bf16 scales): symmetric
    per-(position, head) amax quantization over the head dim."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.round(xf / scale[..., None]).astype(jnp.int8)
    return q, scale.astype(jnp.bfloat16)


def _cache_store(cache_l: dict, k: jax.Array, v: jax.Array, position) -> dict:
    """Write (B, Hkv, S, D) K/V into one LAYER's cache slice at a shared
    scalar ``position``. Quantizes on write when the cache carries scale
    leaves (init_kv_cache kv_bits=8)."""
    out = dict(cache_l)
    if "k_scale" in cache_l:
        kq, ks = _kv_quantize(k)
        vq, vs = _kv_quantize(v)
        out["k"] = jax.lax.dynamic_update_slice(
            cache_l["k"], kq, (0, 0, position, 0))
        out["v"] = jax.lax.dynamic_update_slice(
            cache_l["v"], vq, (0, 0, position, 0))
        out["k_scale"] = jax.lax.dynamic_update_slice(
            cache_l["k_scale"], ks, (0, 0, position))
        out["v_scale"] = jax.lax.dynamic_update_slice(
            cache_l["v_scale"], vs, (0, 0, position))
        return out
    out["k"] = jax.lax.dynamic_update_slice(
        cache_l["k"], k, (0, 0, position, 0))
    out["v"] = jax.lax.dynamic_update_slice(
        cache_l["v"], v, (0, 0, position, 0))
    return out


def _cache_store_rows(cache_l: dict, k: jax.Array, v: jax.Array,
                      positions: jax.Array) -> dict:
    """Per-ROW offsets variant of _cache_store (batched speculative:
    row b writes at positions[b])."""
    if "k_scale" in cache_l:
        kq, ks = _kv_quantize(k)
        vq, vs = _kv_quantize(v)

        def row(ck, cv, cks, cvs, kk, vv, kks, vvs, pos):
            return (
                jax.lax.dynamic_update_slice(ck, kk, (0, pos, 0)),
                jax.lax.dynamic_update_slice(cv, vv, (0, pos, 0)),
                jax.lax.dynamic_update_slice(cks, kks, (0, pos)),
                jax.lax.dynamic_update_slice(cvs, vvs, (0, pos)),
            )

        k_, v_, ks_, vs_ = jax.vmap(row)(
            cache_l["k"], cache_l["v"], cache_l["k_scale"],
            cache_l["v_scale"], kq, vq, ks, vs, positions,
        )
        return {"k": k_, "v": v_, "k_scale": ks_, "v_scale": vs_}

    def row(ck, cv, kk, vv, pos):
        return (
            jax.lax.dynamic_update_slice(ck, kk, (0, pos, 0)),
            jax.lax.dynamic_update_slice(cv, vv, (0, pos, 0)),
        )

    k_, v_ = jax.vmap(row)(cache_l["k"], cache_l["v"], k, v, positions)
    return {"k": k_, "v": v_}


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(3,))
def decode_step(
    params: dict,
    cfg: LlamaConfig,
    token: jax.Array,  # (B, 1)
    kv_cache: dict,
    position: jax.Array,  # scalar int32: write position
) -> tuple[jax.Array, dict]:
    """One autoregressive step: token at ``position`` → logits (B, V).

    Cache buffers are donated so decode mutates HBM in place; the step is
    KV-cache-bandwidth-bound, exactly as it should be. The per-layer scan
    carries x and updates the stacked cache slice for its layer.
    """
    return _decode_impl(params, cfg, token, kv_cache, position)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(3,))
def prefill(
    params: dict, cfg: LlamaConfig, tokens: jax.Array, kv_cache: dict
) -> tuple[jax.Array, dict]:
    """Prompt pass: (last-position logits, primed cache) in ONE pass."""
    return _prefill_impl(params, cfg, tokens, kv_cache)


def greedy_generate(
    params: dict,
    cfg: LlamaConfig,
    prompt: jax.Array,  # (B, S_prompt)
    max_new_tokens: int,
    kv_cache: Optional[dict] = None,
) -> jax.Array:
    """Greedy decoding driver: prefill once, then stepwise decode.

    A caller-provided ``kv_cache`` is DONATED to the compiled prefill/decode
    steps (its buffers are reused in place) — the passed-in arrays are
    invalid afterwards. Pass a fresh ``init_kv_cache(...)`` or let this
    function allocate its own; do not reuse the argument after the call.
    """
    b, s_prompt = prompt.shape
    max_len = s_prompt + max_new_tokens
    if kv_cache is None:
        kv_cache = init_kv_cache(cfg, b, max_len)

    last_logits, kv_cache = prefill(params, cfg, prompt, kv_cache)
    next_token = jnp.argmax(last_logits, axis=-1)[:, None]

    tokens = [next_token]
    position = jnp.asarray(s_prompt, jnp.int32)
    for _ in range(max_new_tokens - 1):
        logits, kv_cache = decode_step(params, cfg, next_token, kv_cache, position)
        next_token = jnp.argmax(logits, axis=-1)[:, None]
        tokens.append(next_token)
        position = position + 1
    return jnp.concatenate(tokens, axis=1)


def _prefill_impl(
    params: dict, cfg: LlamaConfig, tokens: jax.Array, kv_cache: dict,
    kv_mask: Optional[jax.Array] = None,
) -> tuple[jax.Array, dict]:
    """Prefill: write prompt K/V into the cache AND return last-position
    logits (B, V) — one pass, no duplicated compute.

    ``kv_mask`` (B, S) bool marks real (non-pad) prompt tokens for
    LEFT-padded batches. RoPE positions stay absolute cache indices: rope
    is shift-equivariant, so a per-sequence pad offset cancels in q·k and
    the result matches HF's pad-adjusted position_ids exactly."""
    x = _embed(params, cfg, tokens)
    s = tokens.shape[1]
    cos, sin = rope_frequencies(cfg, jnp.arange(s))

    def body(x, scanned):
        layer, cache_l = scanned
        h = _norm(x, layer["attn_norm"], cfg)
        hq, hk, hv = _qkv(h, layer)
        q = apply_rope(_split_heads(hq, cfg.n_heads), cos, sin)
        k = apply_rope(_split_heads(hk, cfg.n_kv_heads), cos, sin)
        v = _split_heads(hv, cfg.n_kv_heads)
        cache_l = _cache_store(cache_l, k, v, jnp.asarray(0, jnp.int32))
        # Attention runs on the FRESH full-precision K/V; an int8 cache
        # quantizes storage only (what later decode steps read back).
        attn = flash_attention(q, k, v,  # GQA handled inside (no repeat)
                               causal=True, impl="auto",
                               window=cfg.sliding_window, kv_mask=kv_mask)
        x = x + _mm(_merge_heads(attn), layer["wo"])
        h = _norm(x, layer["mlp_norm"], cfg)
        x = x + _mlp(layer, h, cfg)
        return x, cache_l

    x, new_cache = jax.lax.scan(
        body, x, (params["layers"], kv_cache)
    )
    x_last = _norm(x[:, -1], params["final_norm"], cfg)
    logits = _lm_head_logits(x_last, params)
    return logits, new_cache


@partial(jax.jit, static_argnames=("cfg", "chunk"), donate_argnums=(3,))
def prefill_chunked(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,  # (B, S_prompt), S_prompt % chunk == 0
    kv_cache: dict,
    chunk: int = 512,
) -> tuple[jax.Array, dict]:
    """Long-prompt prefill in fixed chunks: (last-position logits, cache).

    One compiled program regardless of prompt length (a lax.scan over
    chunks), with activation and logits memory bounded at O(chunk) rows
    instead of O(S) — the path for prompts whose full-sequence logits
    (B, S, vocab) would not fit HBM. Numerically identical to the
    single-shot ``prefill``: each chunk attends the cache slots written so
    far plus itself, with chunk-causal masking inside the chunk.
    """
    b, s = tokens.shape
    if s % chunk:
        raise ValueError(f"prompt length {s} not divisible by chunk {chunk}")
    chunks = tokens.reshape(b, s // chunk, chunk).transpose(1, 0, 2)

    def step(carry, tok_chunk):
        cache, pos = carry
        logits, cache = _decode_chunk_impl(params, cfg, tok_chunk, cache, pos)
        return (cache, pos + chunk), logits[:, -1]

    (cache, _), last = jax.lax.scan(
        step, (kv_cache, jnp.asarray(0, jnp.int32)), chunks
    )
    return last[-1], cache


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(3,))
def prime_kv_cache(
    params: dict, cfg: LlamaConfig, tokens: jax.Array, kv_cache: dict
) -> dict:
    """Write the prompt's K/V into the cache (prefill side-product)."""
    _, cache = _prefill_impl(params, cfg, tokens, kv_cache)
    return cache


def _gqa_decode_attention(
    q: jax.Array,  # (B, H, 1, D)
    k: jax.Array,  # (B, Hkv, L, D) — int8 when k_scale given
    v: jax.Array,  # (B, Hkv, L, D)
    position: jax.Array,  # scalar | (sq,) | (B,) with per_batch=True
    window: int = 0,
    kv_mask: Optional[jax.Array] = None,  # (B, L) valid-key mask
    per_batch: bool = False,
    k_scale: Optional[jax.Array] = None,  # (B, Hkv, L) int8-cache scales
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Grouped-query decode attention against the UNREPEATED KV cache.

    Decode is KV-bandwidth-bound; materializing a rep-times-repeated cache
    per step would multiply HBM traffic (and working set) by H/Hkv, which
    is exactly what GQA exists to avoid. Instead q is folded to
    (B, Hkv, rep, 1, D) and attends the shared cache directly.
    """
    b, h, sq, d = q.shape
    hkv = k.shape[1]
    qg = q.reshape(b, hkv, h // hkv, sq, d)
    scale = 1.0 / math.sqrt(d)
    if k_scale is not None:
        # int8 cache: the MXU dot runs on the int8 values upcast to q's
        # dtype; the per-(head, position) scale folds into the f32 score
        # epilogue — only int8 bytes ever cross HBM.
        k = k.astype(q.dtype)
    scores = (
        jnp.einsum("bgrqd,bgkd->bgrqk", qg, k, preferred_element_type=jnp.float32)
        * scale
    )
    if k_scale is not None:
        scores = scores * k_scale.astype(jnp.float32)[:, :, None, None, :]
    # ``position`` may be a scalar (single-token decode), a (sq,) vector
    # (chunked decode, e.g. speculative verification — query i attends
    # cache slots <= position[i]), or with per_batch=True a (B,) vector
    # (continuous batching — every batch row at its own offset).
    pos = jnp.asarray(position)
    if per_batch:
        if pos.ndim == 2:  # (B, Sq): per-row chunk offsets (batched spec)
            pos_q = pos[:, None, None, :, None]
        else:
            pos_q = pos[:, None, None, None, None]  # (B, 1, 1, 1, 1)
    else:
        if pos.ndim == 0:
            pos = jnp.broadcast_to(pos, (sq,))
        pos_q = pos[None, None, None, :, None]  # (.., sq, 1)
    k_pos = jnp.arange(k.shape[2])[None, None, None, None, :]
    mask = k_pos <= pos_q
    if window:
        mask = mask & (k_pos > pos_q - window)
    if kv_mask is not None:
        mask = mask & kv_mask[:, None, None, None, :]
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    if v_scale is not None:
        # Fold the value scales into the probabilities (cheap: (…, L) vs
        # the (…, L, D) a dequantized V would cost), then dot int8 V
        # upcast to q's dtype.
        probs = probs * v_scale.astype(jnp.float32)[:, :, None, None, :]
        v = v.astype(q.dtype)
        return jnp.einsum(
            "bgrqk,bgkd->bgrqd", probs.astype(q.dtype), v
        ).reshape(b, h, sq, d)
    out = jnp.einsum("bgrqk,bgkd->bgrqd", probs.astype(v.dtype), v)
    return out.reshape(b, h, sq, d)


def _decode_impl(params, cfg, token, kv_cache, position, kv_mask=None):
    """Unjitted single-token decode (shared by decode_step and the fused
    generation loops): (B, 1) token → (B, V) logits."""
    logits, cache = _decode_chunk_impl(
        params, cfg, token, kv_cache, position, kv_mask=kv_mask
    )
    return logits[:, 0], cache


def _chunk_decode_scan(params, cfg, tokens, kv_cache, cos, sin, store,
                       attn_positions, kv_mask, per_batch):
    """The ONE cached-chunk decode body (scan over layers), parameterized
    by the two things the scalar- and per-row-offset variants differ in:
    the cache ``store(cache_l, k, v)`` strategy and the attention position
    argument. Keeping a single body means a future change (norm
    placement, bias, window semantics) cannot diverge the ordinary
    decode and batched-speculative paths. The cache pytree's structure
    decides the storage format (int8 + scales, or native dtype)."""
    x = _embed(params, cfg, tokens)

    def body(x, scanned):
        layer, cache_l = scanned
        h = _norm(x, layer["attn_norm"], cfg)
        hq, hk, hv = _qkv(h, layer)
        q = apply_rope(_split_heads(hq, cfg.n_heads), cos, sin)
        k = apply_rope(_split_heads(hk, cfg.n_kv_heads), cos, sin)
        v = _split_heads(hv, cfg.n_kv_heads)
        cache_l = store(cache_l, k, v)
        attn = _gqa_decode_attention(
            q, cache_l["k"], cache_l["v"], attn_positions,
            window=cfg.sliding_window, kv_mask=kv_mask, per_batch=per_batch,
            k_scale=cache_l.get("k_scale"), v_scale=cache_l.get("v_scale"),
        )
        x = x + _mm(_merge_heads(attn), layer["wo"])
        h = _norm(x, layer["mlp_norm"], cfg)
        x = x + _mlp(layer, h, cfg)
        return x, cache_l

    x, new_cache = jax.lax.scan(body, x, (params["layers"], kv_cache))
    x = _norm(x, params["final_norm"], cfg)
    logits = _lm_head_logits(x, params)  # (B, K, V)
    return logits, new_cache


def _decode_chunk_impl(params, cfg, tokens, kv_cache, position, kv_mask=None):
    """Cached decode of a CHUNK: (B, K) tokens written at cache slots
    ``position .. position+K-1`` → logits (B, K, V) + updated cache.

    K == 1 is ordinary autoregressive decode; K > 1 is the speculative
    verification forward — the target reads its weights ONCE for K tokens.
    Chunk-causality: query i attends cache slots <= position+i (vector
    positions in _gqa_decode_attention). ``kv_mask`` (B, cache_len) marks
    valid cache slots (serving: False on left-pad slots; slots past the
    write pointer are causally excluded anyway)."""
    k_len = tokens.shape[1]
    positions = position + jnp.arange(k_len)
    cos, sin = rope_frequencies(cfg, positions)

    def store(cache_l, k, v):
        # One whole-batch slice write at the shared scalar offset.
        return _cache_store(cache_l, k, v, position)

    return _chunk_decode_scan(
        params, cfg, tokens, kv_cache, cos, sin, store, positions, kv_mask,
        per_batch=False,
    )


def _decode_chunk_batch_impl(params, cfg, tokens, kv_cache, positions,
                             kv_mask=None):
    """Cached decode of a chunk at PER-ROW offsets: (B, K) tokens, row b
    written at cache slots ``positions[b] .. positions[b]+K-1`` → logits
    (B, K, V) + updated cache. The batched-speculative verification
    forward — after round one every row has accepted a different prefix,
    so the write pointers diverge. Chunk-causality per row: query i of
    row b attends cache slots <= positions[b]+i. Same decode body as
    _decode_chunk_impl (_chunk_decode_scan); only the write strategy and
    position shapes differ."""
    k_len = tokens.shape[1]
    posmat = positions[:, None] + jnp.arange(k_len)[None, :]  # (B, K)
    cos, sin = rope_frequencies(cfg, posmat.reshape(-1))
    cos = cos.reshape(*posmat.shape, -1)  # (B, K, half)
    sin = sin.reshape(*posmat.shape, -1)

    def store(cache_l, k, v):
        return _cache_store_rows(cache_l, k, v, positions)

    return _chunk_decode_scan(
        params, cfg, tokens, kv_cache, cos, sin, store, posmat, kv_mask,
        per_batch=True,
    )


@partial(jax.jit, static_argnames=("cfg", "steps"), donate_argnums=(3,))
def generate_tokens(
    params: dict,
    cfg: LlamaConfig,
    prompt: jax.Array,  # (B, S_prompt)
    kv_cache: dict,
    steps: int,
) -> jax.Array:
    """Fused generation: prefill + ``steps`` greedy decode steps in ONE
    compiled program — a single dispatch regardless of length, which is
    what makes decode throughput measurable (and fast) behind any
    host↔device latency."""
    return _generate_impl(params, cfg, prompt, kv_cache, steps)


def sample_logits(
    logits: jax.Array,  # (B, V) f32
    key: jax.Array,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Sample next tokens: temperature → top-k filter → top-p (nucleus)
    filter → categorical. All shapes static; jit/scan-safe.

    temperature == 0 is greedy (argmax), matching generate()."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = _filter_top_k_top_p(logits / temperature, top_k, top_p)
    return jax.random.categorical(key, logits, axis=-1)


def _filter_top_k_top_p(logits: jax.Array, top_k: int,
                        top_p: float) -> jax.Array:
    """THE top-k / nucleus filter (shared by the scalar- and per-row
    samplers so the edge cases cannot drift): top-k keeps the k best per
    row; top-p cuts tokens whose EXCLUSIVE prefix mass already covers
    top_p — the best token always survives."""
    if top_k:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]  # (B, 1)
        logits = jnp.where(logits < kth, NEG_INF, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1) - probs
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True) - 1
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, NEG_INF, logits)
    return logits


def sample_logits_per_row(
    logits: jax.Array,  # (B, V) f32
    key: jax.Array,
    temps: jax.Array,  # (B,) f32 — 0 = greedy for that row
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """sample_logits with a PER-ROW temperature: a serving batch mixes
    requests that asked for different temperatures (greedy rows ride the
    same categorical via a where — no branching, one compiled step for
    any mix). top_k/top_p stay engine-wide: their shapes are static."""
    greedy = jnp.argmax(logits, axis=-1)
    scaled = _filter_top_k_top_p(
        logits / jnp.maximum(temps, 1e-6)[:, None], top_k, top_p
    )
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temps <= 0.0, greedy, sampled)


@partial(
    jax.jit,
    static_argnames=("cfg", "steps", "cache_len", "temperature", "top_k", "top_p"),
)
def sample(
    params: dict,
    cfg: LlamaConfig,
    prompt: jax.Array,  # (B, S_prompt)
    key: jax.Array,
    steps: int,
    cache_len: int,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Fused sampling generation: prefill + ``steps`` sampled decode steps
    in ONE compiled program (the sampling counterpart of generate())."""
    kv_cache = init_kv_cache(cfg, prompt.shape[0], cache_len)
    return _generate_impl(
        params, cfg, prompt, kv_cache, steps,
        key=key, temperature=temperature, top_k=top_k, top_p=top_p,
    )


@partial(jax.jit, static_argnames=("cfg", "steps", "cache_len", "kv_bits"))
def generate(
    params: dict,
    cfg: LlamaConfig,
    prompt: jax.Array,  # (B, S_prompt)
    steps: int,
    cache_len: int,
    kv_bits: int = 0,
) -> jax.Array:
    """Fused generation that allocates its KV cache INSIDE the compiled
    program. Preferred over generate_tokens for fresh generations: the
    cache never exists as a host-visible buffer, so there is nothing to
    donate (and no donation-layout mismatch) — XLA places the zeros
    directly in the layout the scan wants. ``kv_bits=8`` decodes against
    an int8-quantized KV cache (halves the cache bytes read per token —
    the long-context decode bandwidth lever)."""
    cache = init_kv_cache(cfg, prompt.shape[0], cache_len, kv_bits=kv_bits)
    return _generate_impl(params, cfg, prompt, cache, steps)


def _generate_impl(
    params, cfg, prompt, kv_cache, steps,
    key=None, temperature=0.0, top_k=0, top_p=1.0,
):
    """ONE fused prefill+decode loop for greedy AND sampled generation.

    temperature == 0 is greedy: sample_logits short-circuits to argmax and
    never consumes the key (a dummy key threads through the scan carry)."""
    b, s_prompt = prompt.shape
    if key is None:
        key = jax.random.PRNGKey(0)  # untouched when temperature == 0
    logits, kv_cache = _prefill_impl(params, cfg, prompt, kv_cache)
    key, sub = jax.random.split(key)
    first = sample_logits(logits, sub, temperature, top_k, top_p)[:, None]

    def step(carry, _):
        tok, cache, pos, key = carry
        logits, cache = _decode_impl(params, cfg, tok, cache, pos)
        key, sub = jax.random.split(key)
        nxt = sample_logits(logits, sub, temperature, top_k, top_p)[:, None]
        return (nxt, cache, pos + 1, key), tok[:, 0]

    (_, _, _, _), toks = jax.lax.scan(
        step,
        (first, kv_cache, jnp.asarray(s_prompt, jnp.int32), key),
        length=steps,
    )
    return toks.T  # (B, steps)
