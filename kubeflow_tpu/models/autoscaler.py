"""Trace-driven fleet autoscaler: closes the signals→slices loop.

The telemetry plane (observability/signals.py + slo.py) was built as
this controller's input contract; here it finally gets consumed. A
:class:`FleetAutoscaler` hangs off the gateway's probe loop and, once
per pass, turns the SignalSnapshot + SLO burn report into at most one
capacity action per tier:

- **scale-up**: claim a warm slice through the provisioner (production:
  :class:`WarmSliceProvisioner` → ``WarmSliceReplicaSource`` →
  ``claim_warm_slice``) ahead of a ramp;
- **scale-down**: drain the least-loaded replica (PR 2 lifecycle: out
  of the ring first, in-flight streams keep flowing), wait out a
  bounded drain budget, only then release the slice.

Per-tier signal routing (``tier_mode="disagg"`` scales prefill and
decode **independently**; "fused" fleets are one tier fed by all
signals):

- prefill: TTFT p95 burn in both fast SLO windows, or any member's
  queue-wait p95 gauge over the SLO threshold — long-prompt storms
  grow the prefill tier only;
- decode: inter-token p95 burn in both fast windows, or mean ragged
  batch fill over ``high_batch_fill``.

Robustness invariants (the bulk of this module):

- **hysteresis**: up/down pressure must persist ``up_consecutive`` /
  ``down_consecutive`` ticks, burn confirmation already spans both
  fast SLO windows, and each direction has its own cooldown;
- **rate limit**: at most ``max_actions_per_window`` scale actions per
  ``actions_window_s`` fleet-wide;
- **never kill a stream**: scale-down drains before it releases — the
  victim leaves the ring immediately (no new routes) but keeps serving
  its in-flight streams until the provisioner reports it idle or the
  drain budget expires; capacity-after-removal must clear
  ``headroom ×`` current in-flight, so shedding an under-share tenant
  is structurally impossible;
- **never flap on claim failures**: a failed warm-slice claim backs
  off exponentially with jitter and degrades to "hold capacity";
- **freeze on garbage**: missing telemetry, an empty ring, or any
  in-ring replica whose scrape age exceeds ``stale_after_s`` freezes
  all scaling until fresh signals return;
- **explainable**: every decision is a traced span plus a ring-buffer
  entry with a reasons list, served at ``/debug/autoscaler``; counters
  flow through metrics.py (STATS_PARITY) and the signal hub (windowed
  in ``/debug/signals``).

Inert by default: the gateway only constructs one when
``KUBEFLOW_TPU_AUTOSCALE_ENABLE`` opts in (or a config is passed
explicitly), mirroring the telemetry plane's hot-path-no-op stance.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

from kubeflow_tpu.observability import tracing

# Which fleet SLO objectives feed each tier's burn-based pressure. The
# queue-wait objective is fleet-wide (any replica trips it), so disagg
# tiers use the per-member queue-wait gauge instead — a decode replica's
# queue must not grow the prefill tier.
TIER_OBJECTIVES = {
    "prefill": ("ttft_p95",),
    "decode": ("inter_token_p95",),
    "fused": ("ttft_p95", "inter_token_p95", "queue_wait_p95"),
}


@dataclass(frozen=True)
class AutoscalerConfig:
    """Control-loop shape. Frozen + validated: a bad knob must fail the
    gateway's construction, not surface as runtime flapping."""

    min_replicas: int = 1
    max_replicas: int = 4
    # Pressure thresholds: burn >= up_burn in BOTH fast SLO windows is
    # up-pressure; every burn <= down_burn (plus an idle queue) is ebb.
    up_burn: float = 1.0
    down_burn: float = 0.25
    high_batch_fill: float = 0.85
    low_batch_fill: float = 0.30
    # Hysteresis: consecutive ticks of sustained pressure before acting.
    up_consecutive: int = 2
    down_consecutive: int = 3
    up_cooldown_s: float = 30.0
    down_cooldown_s: float = 60.0
    # Fleet-wide action rate limit.
    max_actions_per_window: int = 4
    actions_window_s: float = 300.0
    # Scale-down drains this long before force-releasing the slice.
    drain_budget_s: float = 60.0
    # Any in-ring replica scraped longer ago than this freezes scaling.
    stale_after_s: float = 10.0
    # Claim-failure backoff (exponential, jittered, degrade-to-hold).
    claim_backoff_base_s: float = 1.0
    claim_backoff_max_s: float = 60.0
    claim_backoff_jitter: float = 0.25
    # Scale-down headroom guard: capacity after removal must cover
    # in-flight × headroom, so a drain can never force a shed.
    headroom: float = 1.2
    decision_ring: int = 256

    def __post_init__(self):
        def _bad(msg):
            raise ValueError(f"AutoscalerConfig: {msg}")

        if not (0 <= self.min_replicas <= self.max_replicas):
            _bad(f"want 0 <= min_replicas <= max_replicas, got "
                 f"{self.min_replicas}/{self.max_replicas}")
        if self.max_replicas < 1:
            _bad(f"max_replicas must be >= 1, got {self.max_replicas}")
        if not (0.0 <= self.down_burn < self.up_burn):
            _bad(f"want 0 <= down_burn < up_burn, got "
                 f"{self.down_burn}/{self.up_burn}")
        if not (0.0 < self.low_batch_fill < self.high_batch_fill <= 1.0):
            _bad(f"want 0 < low_batch_fill < high_batch_fill <= 1, got "
                 f"{self.low_batch_fill}/{self.high_batch_fill}")
        if self.up_consecutive < 1 or self.down_consecutive < 1:
            _bad("up/down_consecutive must be >= 1")
        if self.up_cooldown_s < 0 or self.down_cooldown_s < 0:
            _bad("cooldowns must be >= 0")
        if self.max_actions_per_window < 1:
            _bad(f"max_actions_per_window must be >= 1, got "
                 f"{self.max_actions_per_window}")
        for name in ("actions_window_s", "drain_budget_s", "stale_after_s",
                     "claim_backoff_base_s", "claim_backoff_max_s"):
            if getattr(self, name) <= 0:
                _bad(f"{name} must be > 0, got {getattr(self, name)}")
        if self.claim_backoff_jitter < 0:
            _bad("claim_backoff_jitter must be >= 0")
        if self.headroom < 1.0:
            _bad(f"headroom must be >= 1.0, got {self.headroom}")
        if self.decision_ring < 1:
            _bad("decision_ring must be >= 1")


@dataclass
class _TierState:
    up_streak: int = 0
    down_streak: int = 0
    up_cooldown_until: float = 0.0
    down_cooldown_until: float = 0.0
    claim_failures: int = 0
    claim_backoff_until: float = 0.0
    # Dedupe key so a suppressed action logs one hold per episode, not
    # one per probe tick.
    last_hold_key: str = ""


class WarmSliceProvisioner:
    """Production provisioner: capacity is warm slices.

    The provisioner contract the autoscaler drives (duck-typed, so
    tests/loadtests substitute in-process fleets):

    - ``scale_up(tier, now=None)`` → claim handle (pool name /
      endpoint) or ``None`` on failure;
    - ``drain(endpoint)`` → begin the replica's graceful drain;
    - ``drained(endpoint)`` → True once its in-flight work finished;
    - ``release(endpoint)`` → give the capacity back.

    Here scale-up claims through the gateway's
    ``WarmSliceReplicaSource`` (the claimed slice's InferenceServer
    registers itself via ``add_replica`` once healthy). Drain/release
    are delegated callables because slice teardown is a deployment
    concern — typically "delete the replica's pod with a termination
    grace period >= the drain budget", letting SIGTERM start the
    server's own graceful drain. Without a ``drained_fn`` the replica's
    /stats is polled directly: idle means no active slots and an empty
    queue (an unreachable replica counts as drained — it is gone).
    """

    def __init__(self, gateway, *,
                 drain_fn: Optional[Callable[[str], None]] = None,
                 drained_fn: Optional[Callable[[str], bool]] = None,
                 release_fn: Optional[Callable[[str], None]] = None,
                 probe_timeout_s: float = 2.0):
        self.gateway = gateway
        self._drain_fn = drain_fn
        self._drained_fn = drained_fn
        self._release_fn = release_fn
        self.probe_timeout_s = probe_timeout_s

    def scale_up(self, tier: str, now: Optional[float] = None):
        return self.gateway.scale_up(now=now)

    def drain(self, endpoint: str) -> None:
        if self._drain_fn is not None:
            self._drain_fn(endpoint)
        # Without a drain hook the gateway-side ring removal is still
        # what stops new streams; the replica keeps its in-flight work.

    def drained(self, endpoint: str) -> bool:
        if self._drained_fn is not None:
            return bool(self._drained_fn(endpoint))
        host, _, port = endpoint.rpartition(":")
        try:
            conn = http.client.HTTPConnection(
                host, int(port), timeout=self.probe_timeout_s
            )
            try:
                conn.request("GET", "/stats")
                stats = json.loads(conn.getresponse().read())
            finally:
                conn.close()
        except (OSError, ValueError):
            return True  # unreachable: its streams are already gone
        return not (stats.get("active_slots") or stats.get("queued"))

    def release(self, endpoint: str) -> None:
        if self._release_fn is not None:
            self._release_fn(endpoint)


class FleetAutoscaler:
    """The control loop. ``tick()`` rides the gateway's probe cadence;
    everything it reads comes from ``gateway.stats()`` (fleet
    membership, per-replica load), ``telemetry.snapshot()`` (gauges,
    scrape ages) and ``telemetry.evaluate_slo()`` (burn rates)."""

    def __init__(self, gateway, config: Optional[AutoscalerConfig] = None,
                 *, provisioner=None,
                 clock: Optional[Callable[[], float]] = None,
                 rng: Optional[Callable[[], float]] = None,
                 metrics=None):
        self.gateway = gateway
        self.config = config or AutoscalerConfig()
        self.provisioner = (
            provisioner if provisioner is not None
            else WarmSliceProvisioner(gateway)
        )
        self._clock = clock
        self.rng = rng or random.random
        self.metrics = metrics
        # Two locks with distinct jobs (kftpu-lock-held-await forced the
        # split: a tick used to hold the state lock across provisioner
        # HTTP and the k8s claim walk, starving stats()/debug readers
        # for seconds):
        #  - _tick_lock single-flights the control loop; taken with
        #    blocking=False so an overlapping cadence tick returns
        #    immediately instead of queueing behind a slow claim;
        #  - _lock guards the reader-visible state (counters, decision
        #    ring, _draining, tier sizes) and is only ever held for
        #    brief mutations/reads — never across a provisioner or
        #    gateway call. RLock: debug() re-enters via stats().
        self._tick_lock = threading.Lock()
        self._lock = threading.RLock()
        self._tier_state: dict = {}
        self._tier_sizes: dict = {}
        # endpoint -> {"tier", "since", "deadline"} while draining.
        self._draining: dict = {}
        self._action_times: deque = deque()
        self._decisions: deque = deque(maxlen=self.config.decision_ring)
        self._frozen = False
        self._scale_ups = 0
        self._scale_downs = 0
        self._holds = 0
        self._freezes = 0
        self._claim_attempts = 0
        self._claim_failures = 0
        self._claim_latency_last = 0.0

    # -- clock -------------------------------------------------------------

    def _now(self) -> float:
        if self._clock is not None:
            return self._clock()
        tel = self.gateway.telemetry
        return tel.clock() if tel is not None else time.monotonic()

    # -- the loop ----------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> list:
        """One control pass; returns the decisions it recorded (empty
        on a quiet tick, or when another tick is still in flight — the
        loop is single-flighted so a slow claim walk never queues
        ticks). At most one scale action per tier per tick. The state
        lock is never held across provisioner/gateway I/O, so stats()
        and /debug/autoscaler stay responsive mid-tick."""
        if not self._tick_lock.acquire(blocking=False):
            return []
        try:
            now = self._now() if now is None else now
            done: list = []
            self._advance_drains(now, done)
            freeze = self._staleness_reason(now)
            if freeze is not None:
                self._freeze(now, freeze, done)
                return done
            with self._lock:
                self._frozen = False
            tel = self.gateway.telemetry
            gwstats = self.gateway.stats()
            slo = tel.evaluate_slo(now=now)
            snap = tel.snapshot(now=now)
            for tier in self._tiers():
                self._evaluate_tier(tier, gwstats, slo, snap, now, done)
            return done
        finally:
            self._tick_lock.release()

    def _tiers(self):
        if getattr(self.gateway, "tier_mode", "fused") == "disagg":
            return ("prefill", "decode")
        return ("fused",)

    # -- staleness freeze --------------------------------------------------

    def _staleness_reason(self, now: float) -> Optional[str]:
        tel = self.gateway.telemetry
        if tel is None:
            return "telemetry disabled: no signals to act on"
        eps = sorted(self.gateway.ring_nodes())
        if not eps:
            return "no in-ring replicas to read signals from"
        ages = tel.scrape_ages(now=now)
        missing = [ep for ep in eps if ep not in ages]
        if missing:
            return f"no scrape yet from {', '.join(missing[:3])}"
        worst_ep = max(eps, key=lambda e: ages[e])
        worst = ages[worst_ep]
        if worst > self.config.stale_after_s:
            return (f"stale telemetry: {worst_ep} last scraped "
                    f"{worst:.1f}s ago (> {self.config.stale_after_s:g}s)")
        return None

    def _freeze(self, now: float, reason: str, done: list) -> None:
        with self._lock:
            if self._frozen:
                return  # one freeze decision per episode, not per tick
            self._frozen = True
            self._freezes += 1
            for st in self._tier_state.values():
                st.up_streak = st.down_streak = 0
                st.last_hold_key = ""
        if self.metrics is not None:
            self.metrics.autoscaler_freeze_total.inc()
        tel = self.gateway.telemetry
        if tel is not None:
            tel.observe_autoscale("freeze")
        self._record(now, "fleet", "freeze", None, [reason], done)

    # -- pressure signals --------------------------------------------------

    @staticmethod
    def _fast_burns(obj: Optional[dict]) -> Optional[dict]:
        """The two fastest-window burns out of an SLO objective report
        (keys like '60s'; the engine's windows are configurable)."""
        if not obj:
            return None
        burn = obj.get("burn") or {}
        keys = sorted(burn, key=lambda k: int(k[:-1]))[:2]
        if len(keys) < 2:
            return None
        return {k: burn[k] for k in keys}

    @staticmethod
    def _member_fills(fleet: dict, in_ring) -> list:
        fills = fleet.get("replica_batch_fill") or {}
        return [fills[ep] for ep in in_ring
                if isinstance(fills.get(ep), (int, float))]

    def _up_pressure(self, tier: str, slo: dict, snap: dict,
                     in_ring) -> list:
        cfg = self.config
        objs = slo.get("objectives", {})
        fleet = snap.get("fleet", {})
        reasons = []
        for name in TIER_OBJECTIVES[tier]:
            burns = self._fast_burns(objs.get(name))
            if burns and all(b >= cfg.up_burn for b in burns.values()):
                pretty = ", ".join(f"{k}={v:.2f}" for k, v in burns.items())
                reasons.append(
                    f"slo {name}: burn {pretty} >= {cfg.up_burn:g} "
                    f"in both fast windows"
                )
        if tier in ("prefill", "fused"):
            thr = (objs.get("queue_wait_p95") or {}).get("threshold")
            if thr:
                qw = fleet.get("replica_queue_wait_p95_s") or {}
                hot = sorted(
                    ep for ep in in_ring
                    if isinstance(qw.get(ep), (int, float))
                    and qw[ep] > thr
                )
                if hot:
                    reasons.append(
                        f"queue-wait p95 over {thr:g}s on "
                        f"{', '.join(hot)}"
                    )
        if tier in ("decode", "fused"):
            fills = self._member_fills(fleet, in_ring)
            if fills:
                mean = sum(fills) / len(fills)
                if mean >= cfg.high_batch_fill:
                    reasons.append(
                        f"mean batch fill {mean:.2f} >= "
                        f"{cfg.high_batch_fill:g}"
                    )
        return reasons

    def _down_pressure(self, tier: str, slo: dict, snap: dict,
                       in_ring) -> list:
        """Ebb requires EVERY signal quiet: burns at/under down_burn in
        both fast windows, idle member queues, low batch fill."""
        cfg = self.config
        objs = slo.get("objectives", {})
        fleet = snap.get("fleet", {})
        for name in TIER_OBJECTIVES[tier]:
            burns = self._fast_burns(objs.get(name))
            if burns is None or any(b > cfg.down_burn
                                    for b in burns.values()):
                return []
        qdepth = fleet.get("replica_queue_depth") or {}
        queued = sum(
            qdepth[ep] for ep in in_ring
            if isinstance(qdepth.get(ep), (int, float))
        )
        if queued > 0:
            return []
        if tier in ("prefill", "fused"):
            thr = (objs.get("queue_wait_p95") or {}).get("threshold")
            if thr:
                qw = fleet.get("replica_queue_wait_p95_s") or {}
                if any(isinstance(qw.get(ep), (int, float))
                       and qw[ep] > thr for ep in in_ring):
                    return []
        reasons = [f"burns <= {cfg.down_burn:g} in both fast windows; "
                   f"member queues idle"]
        if tier in ("decode", "fused"):
            fills = self._member_fills(fleet, in_ring)
            if fills:
                mean = sum(fills) / len(fills)
                if mean > cfg.low_batch_fill:
                    return []
                reasons.append(
                    f"mean batch fill {mean:.2f} <= "
                    f"{cfg.low_batch_fill:g}"
                )
        return reasons

    # -- per-tier evaluation -----------------------------------------------

    def _evaluate_tier(self, tier: str, gwstats: dict, slo: dict,
                       snap: dict, now: float, done: list) -> None:
        st = self._tier_state.setdefault(tier, _TierState())
        reps = gwstats.get("replicas", {})
        if tier == "fused":
            members = dict(reps)
        else:
            members = {ep: r for ep, r in reps.items()
                       if r.get("role") == tier}
        in_ring = sorted(ep for ep, r in members.items()
                         if r.get("in_ring"))
        with self._lock:
            self._tier_sizes[tier] = len(in_ring)
        if self.metrics is not None:
            self.metrics.autoscaler_replicas.labels(tier=tier).set(
                len(in_ring)
            )
        up = self._up_pressure(tier, slo, snap, in_ring)
        down = [] if up else self._down_pressure(tier, slo, snap, in_ring)
        with self._lock:
            if up:
                st.up_streak += 1
                st.down_streak = 0
            elif down:
                st.down_streak += 1
                st.up_streak = 0
            else:
                st.up_streak = st.down_streak = 0
                st.last_hold_key = ""
        if up and st.up_streak >= self.config.up_consecutive:
            self._try_scale_up(tier, st, in_ring, up, now, done)
        elif down and st.down_streak >= self.config.down_consecutive:
            self._try_scale_down(tier, st, gwstats, members, in_ring, down,
                                 now, done)

    def _rate_limit_ok(self, now: float) -> bool:
        with self._lock:
            cutoff = now - self.config.actions_window_s
            while self._action_times and self._action_times[0] <= cutoff:
                self._action_times.popleft()
            return len(self._action_times) < self.config.max_actions_per_window

    def _try_scale_up(self, tier: str, st: _TierState, in_ring,
                      reasons: list, now: float, done: list) -> None:
        cfg = self.config
        if len(in_ring) >= cfg.max_replicas:
            self._hold(now, tier, st, "max",
                       f"at max_replicas={cfg.max_replicas}", reasons,
                       done)
            return
        if now < st.claim_backoff_until:
            self._hold(now, tier, st, "backoff",
                       f"claim backoff {st.claim_backoff_until - now:.1f}s "
                       f"remaining after {st.claim_failures} failure(s)",
                       reasons, done)
            return
        if now < st.up_cooldown_until:
            self._hold(now, tier, st, "cooldown_up",
                       f"up cooldown {st.up_cooldown_until - now:.1f}s "
                       f"remaining", reasons, done)
            return
        if not self._rate_limit_ok(now):
            self._hold(now, tier, st, "rate_limit",
                       f"rate limit: {cfg.max_actions_per_window} actions "
                       f"per {cfg.actions_window_s:g}s", reasons, done)
            return
        with self._lock:
            self._claim_attempts += 1
        if self.metrics is not None:
            self.metrics.autoscaler_claim_attempts_total.inc()
        t0 = time.perf_counter()
        err = None
        try:
            # The claim walk (k8s list + slice claim + provisioner HTTP)
            # runs unlocked: only _tick_lock single-flights it.
            got = self.provisioner.scale_up(tier, now=now)
        except Exception as exc:  # a claim error is a failure, not a crash
            got, err = None, repr(exc)
        latency = time.perf_counter() - t0
        with self._lock:
            self._claim_latency_last = latency
        if self.metrics is not None:
            self.metrics.autoscaler_claim_latency_seconds.set(latency)
        if got is None:
            with self._lock:
                st.claim_failures += 1
                self._claim_failures += 1
                backoff = min(
                    cfg.claim_backoff_base_s * 2 ** (st.claim_failures - 1),
                    cfg.claim_backoff_max_s,
                ) * (1.0 + cfg.claim_backoff_jitter * self.rng())
                st.claim_backoff_until = now + backoff
            if self.metrics is not None:
                self.metrics.autoscaler_claim_failures_total.inc()
            why = (f"warm-slice claim failed"
                   f"{' (' + err + ')' if err else ''}; holding capacity, "
                   f"backoff {backoff:.1f}s")
            self._hold(now, tier, st, "claim_failed", why, reasons, done,
                       force=True)
            return
        with self._lock:
            st.claim_failures = 0
            st.claim_backoff_until = 0.0
            st.up_cooldown_until = now + cfg.up_cooldown_s
            st.up_streak = 0
            st.last_hold_key = ""
            self._action_times.append(now)
            self._scale_ups += 1
        if self.metrics is not None:
            self.metrics.autoscaler_scale_up_total.inc()
        tel = self.gateway.telemetry
        if tel is not None:
            tel.observe_autoscale("up")
        self._record(
            now, tier, "scale_up", str(got),
            reasons + [f"claimed {got} in {latency * 1000:.0f}ms"],
            done,
        )

    def _try_scale_down(self, tier: str, st: _TierState, gwstats: dict,
                        members: dict, in_ring, reasons: list, now: float,
                        done: list) -> None:
        cfg = self.config
        if len(in_ring) <= cfg.min_replicas:
            self._hold(now, tier, st, "min",
                       f"at min_replicas={cfg.min_replicas}", reasons,
                       done)
            return
        if now < st.down_cooldown_until:
            self._hold(now, tier, st, "cooldown_down",
                       f"down cooldown {st.down_cooldown_until - now:.1f}s "
                       f"remaining", reasons, done)
            return
        if not self._rate_limit_ok(now):
            self._hold(now, tier, st, "rate_limit",
                       f"rate limit: {cfg.max_actions_per_window} actions "
                       f"per {cfg.actions_window_s:g}s", reasons, done)
            return

        def _load(ep):
            s = members[ep].get("stats") or {}
            return ((s.get("active_slots") or 0) + (s.get("queued") or 0),
                    ep)

        # A live migration restoring onto a replica pins it: draining it
        # now would release the very slice the migration is landing on.
        pinned_fn = getattr(self.gateway, "migration_pinned", None)
        pinned = pinned_fn() if pinned_fn is not None else frozenset()
        eligible = [ep for ep in in_ring if ep not in pinned]
        if not eligible:
            self._hold(
                now, tier, st, "migration_pinned",
                f"all {len(in_ring)} in-ring replicas are migration "
                f"restore targets; holding scale-down", reasons, done,
            )
            return
        victim = min(eligible, key=_load)
        # Headroom guard over the WHOLE fleet: the capacity left after
        # this removal must still cover every in-flight stream with
        # margin, or tenant-fair admission could start shedding a tenant
        # that is under its fair share. Capacity mirrors the gateway's
        # own heuristic (2× slots per ring node, 16 unknown).
        total_inflight = sum((gwstats.get("inflight") or {}).values())
        cap_after = 0
        for ep, r in gwstats.get("replicas", {}).items():
            if ep == victim or not r.get("in_ring"):
                continue
            slots = (r.get("stats") or {}).get("slots")
            cap_after += 2 * slots if slots else 16
        if total_inflight * cfg.headroom > cap_after:
            self._hold(
                now, tier, st, "headroom",
                f"insufficient headroom: {total_inflight} in-flight × "
                f"{cfg.headroom:g} > capacity {cap_after} after removing "
                f"{victim} (would risk shedding an under-share tenant)",
                reasons, done,
            )
            return
        try:
            self.provisioner.drain(victim)
        except Exception as exc:
            self._hold(now, tier, st, "drain_failed",
                       f"drain({victim}) failed: {exc!r}", reasons, done,
                       force=True)
            return
        # Out of the ring the instant the drain starts: new streams
        # route elsewhere, in-flight ones keep flowing to the victim.
        self.gateway.begin_drain(victim)
        with self._lock:
            self._draining[victim] = {
                "tier": tier, "since": now,
                "deadline": now + cfg.drain_budget_s,
            }
            st.down_cooldown_until = now + cfg.down_cooldown_s
            st.down_streak = 0
            st.last_hold_key = ""
            self._action_times.append(now)
            self._scale_downs += 1
        if self.metrics is not None:
            self.metrics.autoscaler_scale_down_total.inc()
        tel = self.gateway.telemetry
        if tel is not None:
            tel.observe_autoscale("down")
        self._record(
            now, tier, "scale_down", victim,
            reasons + [f"least-loaded of {len(in_ring)} in-ring; "
                       f"drain budget {cfg.drain_budget_s:g}s"],
            done,
        )

    def _advance_drains(self, now: float, done: list) -> None:
        # Snapshot under the lock, poll the provisioner (HTTP) outside it:
        # a slow drained() probe must not block stats()/debug() readers.
        with self._lock:
            draining = {ep: dict(d) for ep, d in self._draining.items()}
        for ep in sorted(draining):
            d = draining[ep]
            over = now >= d["deadline"]
            try:
                idle = self.provisioner.drained(ep)
            except Exception:
                idle = False
            if not idle and not over:
                continue
            with self._lock:
                if self._draining.pop(ep, None) is None:
                    continue  # raced with a concurrent reconfigure
            reasons = []
            if idle:
                reasons.append(
                    f"drained in {now - d['since']:.1f}s; slice released"
                )
            else:
                reasons.append(
                    f"drain budget {self.config.drain_budget_s:g}s "
                    f"exceeded; releasing (replica's own drain deadline "
                    f"ends its remaining work)"
                )
            try:
                self.provisioner.release(ep)
            except Exception as exc:
                reasons.append(f"release failed: {exc!r}")
            self.gateway.remove_replica(ep)
            self._record(now, d["tier"], "release", ep, reasons, done)

    # -- recording ---------------------------------------------------------

    def _hold(self, now: float, tier: str, st: _TierState, kind: str,
              why: str, pressure: list, done: list, *,
              force: bool = False) -> None:
        with self._lock:
            if not force and st.last_hold_key == kind:
                return  # same suppression as last tick: one hold per episode
            st.last_hold_key = kind
            self._holds += 1
        if self.metrics is not None:
            self.metrics.autoscaler_hold_total.inc()
        tel = self.gateway.telemetry
        if tel is not None:
            tel.observe_autoscale("hold")
        self._record(now, tier, "hold", None, list(pressure) + [why], done)

    def _record(self, now: float, tier: str, action: str,
                endpoint: Optional[str], reasons: list,
                done: list) -> None:
        entry = {"t": round(now, 3), "tier": tier, "action": action,
                 "reasons": list(reasons)}
        if endpoint:
            entry["endpoint"] = endpoint
        with self._lock:
            self._decisions.append(entry)
        done.append(entry)
        if tracing.enabled():
            attrs = {"autoscaler.tier": tier,
                     "autoscaler.action": action}
            if endpoint:
                attrs["autoscaler.endpoint"] = endpoint
            sp = tracing.get_tracer("autoscaler").begin_span(
                f"autoscaler.{action}", **attrs
            )
            sp.add_event("autoscaler.reasons",
                         {"reasons": "; ".join(reasons)})
            sp.end()

    # -- surfaces ----------------------------------------------------------

    def stats(self) -> dict:
        """The /stats block; key literals here are the STATS_PARITY
        surface for the tpu_autoscaler_* metric families."""
        with self._lock:
            return {
                "enabled": True,
                "frozen": self._frozen,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
                "holds": self._holds,
                "freezes": self._freezes,
                "claim_attempts": self._claim_attempts,
                "claim_failures": self._claim_failures,
                "claim_latency_s": round(self._claim_latency_last, 6),
                "tier_replicas": dict(sorted(self._tier_sizes.items())),
                "draining": sorted(self._draining),
            }

    def debug(self) -> dict:
        """The /debug/autoscaler payload: config, per-tier loop state,
        in-progress drains, and the decision ring (newest last)."""
        with self._lock:
            return {
                **self.stats(),
                "config": dataclasses.asdict(self.config),
                "tiers": {
                    tier: {
                        "size": self._tier_sizes.get(tier, 0),
                        "up_streak": st.up_streak,
                        "down_streak": st.down_streak,
                        "up_cooldown_until": round(st.up_cooldown_until, 3),
                        "down_cooldown_until": round(
                            st.down_cooldown_until, 3
                        ),
                        "claim_failures": st.claim_failures,
                        "claim_backoff_until": round(
                            st.claim_backoff_until, 3
                        ),
                    }
                    for tier, st in sorted(self._tier_state.items())
                },
                "draining": {
                    ep: {k: round(v, 3) if isinstance(v, float) else v
                         for k, v in d.items()}
                    for ep, d in sorted(self._draining.items())
                },
                "decisions": list(self._decisions),
            }


def autoscaler_from_env() -> Optional[AutoscalerConfig]:
    """None unless KUBEFLOW_TPU_AUTOSCALE_ENABLE opts in (the autoscaler
    must be inert by default). Raises on garbage — a hand-set env var
    must not silently fall back to defaults."""
    import os

    from kubeflow_tpu.webhook.tpu_env import (
        KUBEFLOW_TPU_AUTOSCALE_DOWN_COOLDOWN_S,
        KUBEFLOW_TPU_AUTOSCALE_DRAIN_BUDGET_S,
        KUBEFLOW_TPU_AUTOSCALE_ENABLE,
        KUBEFLOW_TPU_AUTOSCALE_MAX_ACTIONS,
        KUBEFLOW_TPU_AUTOSCALE_MAX_REPLICAS,
        KUBEFLOW_TPU_AUTOSCALE_MIN_REPLICAS,
        KUBEFLOW_TPU_AUTOSCALE_STALE_AFTER_S,
        KUBEFLOW_TPU_AUTOSCALE_UP_COOLDOWN_S,
        KUBEFLOW_TPU_AUTOSCALE_WINDOW_S,
    )

    raw = os.environ.get(KUBEFLOW_TPU_AUTOSCALE_ENABLE, "").strip().lower()
    if raw not in ("", "0", "false", "1", "true"):
        raise ValueError(
            f"{KUBEFLOW_TPU_AUTOSCALE_ENABLE}={raw!r}: want 0/1/true/false"
        )
    if raw not in ("1", "true"):
        return None
    defaults = AutoscalerConfig()

    def _num(name, default, minimum, cast):
        value = os.environ.get(name, "").strip()
        if not value:
            return default
        try:
            got = cast(value)
        except ValueError:
            got = minimum - 1
        if got < minimum:
            raise ValueError(f"{name}={value!r}: want a number >= {minimum}")
        return got

    return AutoscalerConfig(
        min_replicas=_num(KUBEFLOW_TPU_AUTOSCALE_MIN_REPLICAS,
                          defaults.min_replicas, 0, int),
        max_replicas=_num(KUBEFLOW_TPU_AUTOSCALE_MAX_REPLICAS,
                          defaults.max_replicas, 1, int),
        up_cooldown_s=float(_num(KUBEFLOW_TPU_AUTOSCALE_UP_COOLDOWN_S,
                                 defaults.up_cooldown_s, 0, float)),
        down_cooldown_s=float(_num(KUBEFLOW_TPU_AUTOSCALE_DOWN_COOLDOWN_S,
                                   defaults.down_cooldown_s, 0, float)),
        max_actions_per_window=_num(KUBEFLOW_TPU_AUTOSCALE_MAX_ACTIONS,
                                    defaults.max_actions_per_window, 1,
                                    int),
        actions_window_s=float(_num(KUBEFLOW_TPU_AUTOSCALE_WINDOW_S,
                                    defaults.actions_window_s, 1, float)),
        drain_budget_s=float(_num(KUBEFLOW_TPU_AUTOSCALE_DRAIN_BUDGET_S,
                                  defaults.drain_budget_s, 1, float)),
        stale_after_s=float(_num(KUBEFLOW_TPU_AUTOSCALE_STALE_AFTER_S,
                                 defaults.stale_after_s, 1, float)),
    )
