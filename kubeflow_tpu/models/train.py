"""Training step: causal-LM loss + AdamW, sharded over the device mesh.

The multi-chip path the driver dry-runs: one jitted train step whose
params/optimizer state are sharded per MeshPlan (fsdp/tp), batch over
(dp, fsdp), sequence over sp via ring attention when the mesh has an sp
axis. XLA inserts the collectives (psum for grads over dp/fsdp, all-gathers
for fsdp params, ppermute inside ring attention) and lays them on ICI.

jax.checkpoint on the per-layer body trades FLOPs for HBM (rematerialize
activations in the backward pass) — the standard TPU memory lever.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeflow_tpu.models.llama import LlamaConfig, forward
from kubeflow_tpu.parallel.mesh import MeshPlan
from kubeflow_tpu.parallel.ring_attention import make_sharded_ring_attention
from kubeflow_tpu.parallel.ulysses import make_sharded_ulysses_attention


def causal_lm_loss(
    params: dict, cfg: LlamaConfig, tokens: jax.Array, attn_impl: str = "auto"
) -> jax.Array:
    """Next-token cross entropy over (B, S) token batches."""
    logits = forward(params, cfg, tokens, attn_impl=attn_impl)
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.1):
    return optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=weight_decay)


def make_train_step(
    cfg: LlamaConfig,
    plan: MeshPlan,
    optimizer=None,
    use_ring_sp: Optional[bool] = None,
    sp_impl: str = "ring",
):
    """Build (init_state, train_step) jitted over plan.mesh.

    When the mesh has an sp axis > 1 (``use_ring_sp`` defaults to True
    then), attention runs sequence-parallel using ``sp_impl``:
    "ring" (K/V rotate via ppermute, overlapped with compute) or
    "ulysses" (two all_to_alls trade sequence shards for head shards;
    needs heads-per-tp-shard divisible by sp).
    """
    if sp_impl not in ("ring", "ulysses"):
        # Validate even when sp ends up inactive: a typo'd sp_impl on an
        # sp=1 mesh must not silently run dense attention.
        raise ValueError(f"unknown sp_impl {sp_impl!r} (want 'ring'|'ulysses')")
    optimizer = optimizer or make_optimizer()
    mesh = plan.mesh
    if use_ring_sp is None:
        use_ring_sp = mesh.shape.get("sp", 1) > 1
    # Pass the mesh-bound impl as a callable: a global registry entry named
    # "ring" would be rebound by every make_train_step call, so a step built
    # for mesh A could silently pick up mesh B's shard_map on retrace.
    if not use_ring_sp:
        attn_impl = "auto"
    elif sp_impl == "ring":
        attn_impl = make_sharded_ring_attention(mesh)
    else:
        attn_impl = make_sharded_ulysses_attention(mesh)

    def init_state(params):
        opt_state = optimizer.init(params)
        return {"params": params, "opt_state": opt_state, "step": jnp.zeros((), jnp.int32)}

    def train_step(state, tokens):
        loss, grads = jax.value_and_grad(causal_lm_loss)(
            state["params"], cfg, tokens, attn_impl
        )
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        return {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }, loss

    batch_sharding = NamedSharding(mesh, P(("dp", "fsdp"), "sp"))
    jitted = jax.jit(
        train_step,
        in_shardings=(None, batch_sharding),  # state placement propagates
        donate_argnums=(0,),
    )
    return init_state, jitted


def shard_state(plan: MeshPlan, state: dict) -> dict:
    """Place params + optimizer state onto the mesh per the plan."""
    def place(path, value):
        # Optimizer moments mirror the param tree under ['opt_state'][...];
        # reuse the param rule by stripping non-param path components.
        keys = tuple(
            str(p.key) for p in path if hasattr(p, "key") and str(p.key) not in
            ("params", "opt_state", "mu", "nu")
        )
        if getattr(value, "ndim", 0) == 0:
            # Replicate scalars explicitly: an uncommitted scalar restored
            # from a checkpoint lands on one device and then conflicts with
            # the mesh-wide arrays inside jit.
            return jax.device_put(value, NamedSharding(plan.mesh, P()))
        spec = plan.param_spec(keys, value.ndim)
        return jax.device_put(value, NamedSharding(plan.mesh, spec))

    return jax.tree_util.tree_map_with_path(place, state)
