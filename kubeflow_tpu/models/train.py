"""Training step: causal-LM loss + AdamW, sharded over the device mesh.

The multi-chip path the driver dry-runs: one jitted train step whose
params/optimizer state are sharded per MeshPlan (fsdp/tp), batch over
(dp, fsdp), sequence over sp via ring attention when the mesh has an sp
axis. XLA inserts the collectives (psum for grads over dp/fsdp, all-gathers
for fsdp params, ppermute inside ring attention) and lays them on ICI.

jax.checkpoint on the per-layer body trades FLOPs for HBM (rematerialize
activations in the backward pass) — the standard TPU memory lever.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeflow_tpu.models.llama import (
    LlamaConfig, _lm_head_logits, forward, forward_hidden,
)
from kubeflow_tpu.parallel.mesh import MeshPlan
from kubeflow_tpu.parallel.ring_attention import make_sharded_ring_attention
from kubeflow_tpu.parallel.ulysses import make_sharded_ulysses_attention


def per_token_nll(
    params: dict, cfg: LlamaConfig, tokens: jax.Array, attn_impl: str = "auto"
) -> jax.Array:
    """(B, S-1) next-token negative log likelihoods — the one place the
    NLL math lives (training mean-loss and perplexity eval both fold it)."""
    logits = forward(params, cfg, tokens, attn_impl=attn_impl)[:, :-1]
    targets = tokens[:, 1:]
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]


def causal_lm_loss(
    params: dict, cfg: LlamaConfig, tokens: jax.Array, attn_impl: str = "auto"
) -> jax.Array:
    """Next-token cross entropy over (B, S) token batches."""
    return jnp.mean(per_token_nll(params, cfg, tokens, attn_impl))


def chunked_causal_lm_loss(
    params: dict, cfg: LlamaConfig, tokens: jax.Array,
    attn_impl: str = "auto", chunk: int = 512, remat: str = "full",
) -> jax.Array:
    """causal_lm_loss without ever materializing (B, S, vocab) logits.

    The lm-head + cross entropy run per sequence CHUNK inside a
    checkpointed lax.scan: each step projects (B, chunk, dim) → logits,
    reduces them to (lse − target logit), and the remat recomputes the
    chunk's logits in the backward — so peak HBM holds one chunk of f32
    logits instead of the full batch (≈1 GB at B=4, S=2048, V=32k, plus
    log_softmax temporaries). Numerically identical to causal_lm_loss
    (same lse − target arithmetic in f32). ``remat`` threads through to
    the layer stack (llama._REMAT_POLICIES)."""
    b, s = tokens.shape
    if s < 2:
        raise ValueError(
            f"causal LM loss needs sequences of >= 2 tokens, got S={s}"
        )
    if chunk <= 0:
        raise ValueError(f"chunk must be positive, got {chunk}")
    hidden = forward_hidden(params, cfg, tokens, attn_impl, remat=remat)
    hidden = hidden[:, :-1]
    targets = tokens[:, 1:]
    n_pos = s - 1
    chunk = min(chunk, n_pos)
    n_chunks = n_pos // chunk
    tail = n_pos - n_chunks * chunk  # S-1 is rarely chunk-aligned

    def chunk_nll_sum(h_c, t_c):
        logits = _lm_head_logits(h_c, params)  # (B, c, V) f32, one chunk
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, t_c[..., None], axis=-1)[..., 0]
        return jnp.sum(lse - tgt)

    def body(acc, xs):
        h_c, t_c = xs
        return acc + chunk_nll_sum(h_c, t_c), None

    h_main = hidden[:, : n_chunks * chunk].reshape(
        b, n_chunks, chunk, -1
    ).transpose(1, 0, 2, 3)
    t_main = targets[:, : n_chunks * chunk].reshape(
        b, n_chunks, chunk
    ).transpose(1, 0, 2)
    total, _ = jax.lax.scan(
        jax.checkpoint(body), jnp.zeros((), jnp.float32), (h_main, t_main)
    )
    if tail:
        total = total + chunk_nll_sum(
            hidden[:, n_chunks * chunk:], targets[:, n_chunks * chunk:]
        )
    return total / (b * n_pos)


def make_optimizer(
    lr: float = 3e-4,
    weight_decay: float = 0.1,
    warmup_steps: int = 0,
    decay_steps: int = 0,
    end_lr_ratio: float = 0.1,
    clip_norm: float = 0.0,
):
    """AdamW with the standard LLM training schedule knobs.

    - warmup_steps > 0: linear warmup from 0 to ``lr``;
    - decay_steps > 0: cosine decay from ``lr`` to ``lr*end_lr_ratio``
      over exactly ``decay_steps`` steps AFTER warmup; with
      decay_steps=0 the lr stays at peak forever after warmup;
    - clip_norm > 0: global-norm gradient clipping before the update.
    """
    if warmup_steps or decay_steps:
        # Composed explicitly so the documented semantics hold exactly:
        # linear 0→lr over warmup_steps, then EITHER constant lr forever
        # (decay_steps=0) or cosine over decay_steps AFTER warmup down to
        # lr*end_lr_ratio. (optax.warmup_cosine_decay_schedule's
        # decay_steps is the TOTAL length including warmup — a warmup-only
        # request through it would cliff to the end value immediately.)
        pieces = [optax.linear_schedule(0.0, lr, max(warmup_steps, 1))]
        if decay_steps:
            pieces.append(
                optax.cosine_decay_schedule(
                    lr, decay_steps, alpha=end_lr_ratio
                )
            )
        else:
            pieces.append(optax.constant_schedule(lr))
        schedule = optax.join_schedules(pieces, boundaries=[warmup_steps])
    else:
        schedule = lr
    opt = optax.adamw(schedule, b1=0.9, b2=0.95, weight_decay=weight_decay)
    if clip_norm > 0:
        opt = optax.chain(optax.clip_by_global_norm(clip_norm), opt)
    return opt


def make_train_step(
    cfg: LlamaConfig,
    plan: MeshPlan,
    optimizer=None,
    use_ring_sp: Optional[bool] = None,
    sp_impl: str = "ring",
    grad_accum: int = 1,
    loss_chunk: int = 512,
    remat: str = "full",
    fp8: bool = False,
):
    """Build (init_state, train_step) jitted over plan.mesh.

    When the mesh has an sp axis > 1 (``use_ring_sp`` defaults to True
    then), attention runs sequence-parallel using ``sp_impl``:
    "ring" (K/V rotate via ppermute, overlapped with compute),
    "ulysses" (two all_to_alls trade sequence shards for head shards;
    needs heads-per-tp-shard divisible by sp), or "zigzag" (balanced
    causal ring — each device holds a front+back chunk pair, halving
    the causal ring's wasted FLOPs; causal-only, so incompatible with
    sliding-window configs).

    ``grad_accum`` > 1 splits the batch into that many microbatches and
    accumulates gradients in a lax.scan before ONE optimizer update —
    the HBM lever for effective batch sizes past what activations allow
    (composes with jax.checkpoint inside the loss). The batch's leading
    dim must be divisible by grad_accum.

    ``loss_chunk`` > 0 uses chunked_causal_lm_loss (full (B, S, vocab)
    logits never materialize); 0 falls back to the dense loss.
    ``remat`` picks the layer-stack checkpoint policy
    (llama._REMAT_POLICIES: "full" | "dots" | "none").

    ``fp8=True`` trains with fp8 matmul operands (models/fp8.py): pass
    params through ``fp8.wrap_params_fp8`` first; the optimizer is
    partitioned so AdamW sees the master weights while the fp8 metas are
    overwritten with their autodiff-carried next values. init_state
    raises if the params tree and the flag disagree — a wrapped tree
    under a plain optimizer would adamw the amax histories.
    """
    if sp_impl not in ("ring", "ulysses", "zigzag"):
        # Validate even when sp ends up inactive: a typo'd sp_impl on an
        # sp=1 mesh must not silently run dense attention.
        raise ValueError(
            f"unknown sp_impl {sp_impl!r} (want 'ring'|'ulysses'|'zigzag')"
        )
    optimizer = optimizer or make_optimizer()
    if fp8:
        from kubeflow_tpu.models.fp8 import (
            fp8_meta_replace,
            fp8_partition_labels,
        )

        optimizer = optax.multi_transform(
            {"default": optimizer, "fp8_meta": fp8_meta_replace()},
            fp8_partition_labels,
        )
    mesh = plan.mesh
    if use_ring_sp is None:
        use_ring_sp = mesh.shape.get("sp", 1) > 1
    # Pass the mesh-bound impl as a callable: a global registry entry named
    # "ring" would be rebound by every make_train_step call, so a step built
    # for mesh A could silently pick up mesh B's shard_map on retrace.
    if not use_ring_sp:
        attn_impl = "auto"
    elif sp_impl == "ring":
        attn_impl = make_sharded_ring_attention(mesh)
    elif sp_impl == "zigzag":
        from kubeflow_tpu.parallel.zigzag_attention import (
            make_sharded_zigzag_attention,
        )

        attn_impl = make_sharded_zigzag_attention(mesh)
    else:
        attn_impl = make_sharded_ulysses_attention(mesh)

    def init_state(params):
        from kubeflow_tpu.models.fp8 import has_fp8_params

        if has_fp8_params(params) != fp8:
            raise ValueError(
                "params tree and fp8 flag disagree: "
                f"has_fp8_params={has_fp8_params(params)}, fp8={fp8} "
                "(wrap with fp8.wrap_params_fp8 AND pass fp8=True)"
            )
        opt_state = optimizer.init(params)
        return {"params": params, "opt_state": opt_state, "step": jnp.zeros((), jnp.int32)}

    if loss_chunk:
        def _loss(params, tokens):
            return chunked_causal_lm_loss(
                params, cfg, tokens, attn_impl, chunk=loss_chunk, remat=remat
            )
    else:
        def _loss(params, tokens):
            return causal_lm_loss(params, cfg, tokens, attn_impl)

    def _grads(params, tokens):
        return jax.value_and_grad(_loss)(params, tokens)

    def train_step(state, tokens):
        if grad_accum == 1:
            loss, grads = _grads(state["params"], tokens)
        else:
            b = tokens.shape[0]
            if b % grad_accum:
                raise ValueError(
                    f"batch {b} not divisible by grad_accum {grad_accum}"
                )
            # STRIDED split (micro[i] = tokens[i::ga]): each microbatch
            # keeps rows from every dp shard, so no resharding collective
            # per scan iteration — a contiguous reshape would put each
            # microbatch on a fraction of the dp devices.
            micro = tokens.reshape(
                b // grad_accum, grad_accum, -1
            ).transpose(1, 0, 2)

            def accum(carry, mb):
                loss_sum, grads_sum = carry
                loss, grads = _grads(state["params"], mb)
                return (
                    loss_sum + loss,
                    jax.tree_util.tree_map(jnp.add, grads_sum, grads),
                ), None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state["params"]
            )
            (loss_sum, grads_sum), _ = jax.lax.scan(
                accum, (jnp.zeros((), jnp.float32), zeros), micro
            )
            loss = loss_sum / grad_accum
            grads = jax.tree_util.tree_map(
                lambda g, p: (g / grad_accum).astype(p.dtype),
                grads_sum, state["params"],
            )
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        return {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }, loss

    batch_sharding = NamedSharding(mesh, P(("dp", "fsdp"), "sp"))
    jitted = jax.jit(
        train_step,
        in_shardings=(None, batch_sharding),  # state placement propagates
        donate_argnums=(0,),
    )
    return init_state, jitted


@partial(jax.jit, static_argnames=("cfg",))
def token_nll(params: dict, cfg: LlamaConfig, tokens: jax.Array) -> jax.Array:
    """Per-batch (sum NLL, token count) for perplexity evaluation."""
    nll = per_token_nll(params, cfg, tokens)
    return jnp.sum(nll), nll.size


def evaluate_perplexity(params: dict, cfg: LlamaConfig, batches) -> dict:
    """Corpus perplexity over an iterable of (B, S) token batches.

    Returns {"nll": mean per-token NLL, "perplexity": exp(nll),
    "tokens": count}. Standard next-token evaluation: positions 1..S-1
    are scored against the model's prediction from the prefix.
    """
    total = 0.0
    count = 0
    for tokens in batches:
        s, n = token_nll(params, cfg, tokens)
        total += float(s)
        count += int(n)
    if count == 0:
        raise ValueError("no evaluation tokens")
    nll = total / count
    return {"nll": nll, "perplexity": float(jnp.exp(nll)), "tokens": count}


def make_tiny_trainer(steps: int = 4, batch: int = 2, seq: int = 16,
                      seed: int = 0):
    """Deterministic single-device tiny-llama trainer for durability/chaos
    tests: ``(step_fn, fresh_state, batches)`` where ``fresh_state(key)``
    builds a sharded init state and ``batches`` is a fixed token list.
    Rebuilding with the same seed reproduces the exact run — which is what
    lets checkpoint experiments assert ZERO loss-curve divergence between
    an interrupted-and-resumed run and an uninterrupted one.
    """
    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.parallel.mesh import make_mesh

    plan = MeshPlan(make_mesh(devices=jax.devices()[:1]))
    cfg = L.LLAMA_CONFIGS["tiny"]
    init_state, step_fn = make_train_step(cfg, plan)

    def fresh_state(key: int = 0):
        params = L.init_params(cfg, jax.random.PRNGKey(key))
        return shard_state(plan, init_state(params))

    batches = [
        jax.random.randint(
            jax.random.PRNGKey(seed * 1000 + 100 + i),
            (batch, seq), 0, cfg.vocab_size,
        )
        for i in range(steps)
    ]
    return step_fn, fresh_state, batches


def shard_state(plan: MeshPlan, state: dict) -> dict:
    """Place params + optimizer state onto the mesh per the plan."""
    def place(path, value):
        # Optimizer moments mirror the param tree under ['opt_state'][...];
        # reuse the param rule by stripping non-param path components.
        keys = tuple(
            str(p.key) for p in path if hasattr(p, "key") and str(p.key) not in
            ("params", "opt_state", "mu", "nu")
        )
        if getattr(value, "ndim", 0) == 0:
            # Replicate scalars explicitly: an uncommitted scalar restored
            # from a checkpoint lands on one device and then conflicts with
            # the mesh-wide arrays inside jit.
            return jax.device_put(value, NamedSharding(plan.mesh, P()))
        spec = plan.param_spec(keys, value.ndim)
        return jax.device_put(value, NamedSharding(plan.mesh, spec))

    return jax.tree_util.tree_map_with_path(place, state)
