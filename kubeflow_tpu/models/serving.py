"""Batched in-notebook serving for the Llama family.

Variable-length prompts are LEFT-padded to one static shape, generation
runs as ONE fused prefill+decode program per (batch, prompt_len, steps)
bucket, and per-sequence EOS is handled inside the scan (finished rows
emit pad and stop influencing anything). Static shapes are the TPU
constraint this design serves: XLA compiles a handful of bucketed
programs instead of one per request shape.

Why left-padding works unmodified:
- every sequence ENDS at the same index, so the decode write position
  stays one scalar;
- pad slots are excluded via a STATIC kv_mask (True for all generated
  slots — causality already hides the future);
- RoPE uses absolute cache indices: rope is shift-equivariant, so the
  per-sequence pad offset cancels in q·k, matching HF's pad-adjusted
  position_ids numerically.

No reference counterpart (control plane only); this is the in-notebook
inference surface next to train/LoRA/quant.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.llama import (
    LlamaConfig,
    _decode_impl,
    _prefill_impl,
    init_kv_cache,
    sample_logits,
)


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    max_new_tokens: int = 128
    temperature: float = 0.0  # 0 = greedy
    top_k: int = 0
    top_p: float = 1.0
    eos_id: int = 2  # llama tokenizer </s>
    pad_id: int = 0


def left_pad(
    prompts: Sequence[Sequence[int]], pad_id: int, length: Optional[int] = None
) -> tuple[np.ndarray, np.ndarray]:
    """Ragged token lists → (tokens (B, L) int32, mask (B, L) bool)."""
    if not prompts:
        raise ValueError("empty prompt batch")
    longest = max(len(p) for p in prompts)
    length = longest if length is None else length
    if length < longest:
        raise ValueError(f"length {length} < longest prompt {longest}")
    batch = len(prompts)
    tokens = np.full((batch, length), pad_id, np.int32)
    mask = np.zeros((batch, length), bool)
    for i, prompt in enumerate(prompts):
        if len(prompt) == 0:
            raise ValueError(f"prompt {i} is empty")
        tokens[i, length - len(prompt):] = np.asarray(prompt, np.int32)
        mask[i, length - len(prompt):] = True
    return tokens, mask


@partial(
    jax.jit,
    static_argnames=("cfg", "steps", "cache_len", "temperature", "top_k", "top_p",
                     "eos_id", "pad_id", "kv_bits"),
)
def _batch_generate_fused(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,  # (B, L) left-padded
    prompt_mask: Optional[jax.Array],  # (B, L) bool; None = no padding
    key: jax.Array,
    steps: int,
    cache_len: int,
    temperature: float,
    top_k: int,
    top_p: float,
    eos_id: int,
    pad_id: int,
    kv_bits: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """(generated (B, steps), lengths (B,)) in one compiled program."""
    b, s_prompt = tokens.shape
    # kv_bits=8 → int8 cache storage; prefill/decode dispatch off the
    # cache pytree's structure (models.llama init_kv_cache).
    kv_cache = init_kv_cache(cfg, b, cache_len, kv_bits=kv_bits)
    # Static full-cache mask: pad slots False forever, every slot from the
    # prompt end onward True (causality hides not-yet-written slots).
    kv_mask = (
        None
        if prompt_mask is None
        else jnp.concatenate(
            [prompt_mask, jnp.ones((b, cache_len - s_prompt), bool)], axis=1
        )
    )
    logits, kv_cache = _prefill_impl(
        params, cfg, tokens, kv_cache, kv_mask=prompt_mask
    )
    key, sub = jax.random.split(key)
    first = sample_logits(logits, sub, temperature, top_k, top_p)
    done0 = first == eos_id
    first = jnp.where(done0, pad_id, first)[:, None]

    def step(carry, _):
        tok, cache, pos, key, done = carry
        logits, cache = _decode_impl(
            params, cfg, tok, cache, pos, kv_mask=kv_mask
        )
        key, sub = jax.random.split(key)
        nxt = sample_logits(logits, sub, temperature, top_k, top_p)
        now_done = done | (nxt == eos_id)
        nxt = jnp.where(now_done, pad_id, nxt)[:, None]
        # Emit the carry token WITH its done-before flag: valid-length
        # counting must not key on pad_id (a model may legitimately emit
        # token 0).
        return (nxt, cache, pos + 1, key, now_done), (tok[:, 0], done)

    (_, _, _, _, _), (toks, dones) = jax.lax.scan(
        step,
        (first, kv_cache, jnp.asarray(s_prompt, jnp.int32), key, done0),
        length=steps,
    )
    out = toks.T  # (B, steps)
    lengths = jnp.sum(~dones.T, axis=1)
    return out, lengths


def batch_generate(
    params: dict,
    cfg: LlamaConfig,
    prompts: Sequence[Sequence[int]],
    gen: Optional[GenerationConfig] = None,
    key: Optional[jax.Array] = None,
    pad_to: Optional[int] = None,
    kv_bits: int = 0,
) -> list[list[int]]:
    """Generate completions for a ragged batch of prompts.

    Returns one token list per prompt, truncated at (and excluding) EOS.
    ``pad_to`` buckets the prompt length so repeated calls reuse one
    compiled program. ``kv_bits=8`` stores the KV cache as int8
    (~half the cache HBM; logits drift within quantization error).
    """
    gen = gen or GenerationConfig()
    key = jax.random.PRNGKey(0) if key is None else key
    tokens, np_mask = left_pad(prompts, gen.pad_id, pad_to)
    # Uniform-length bucket: drop the all-True mask (host-side check,
    # before jit) so prefill keeps the pallas flash kernel — auto falls
    # back to the XLA path whenever any kv_mask is present.
    mask = None if np_mask.all() else jnp.asarray(np_mask)
    cache_len = tokens.shape[1] + gen.max_new_tokens
    out, lengths = _batch_generate_fused(
        params, cfg, jnp.asarray(tokens), mask, key,
        steps=gen.max_new_tokens, cache_len=cache_len,
        temperature=gen.temperature, top_k=gen.top_k, top_p=gen.top_p,
        eos_id=gen.eos_id, pad_id=gen.pad_id, kv_bits=kv_bits,
    )
    out = np.asarray(out)
    lengths = np.asarray(lengths)
    return [list(row[:n]) for row, n in zip(out, lengths)]
