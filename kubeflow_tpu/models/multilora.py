"""Multi-LoRA serving: one base model, many adapters, chosen per request.

A notebook that fine-tuned several LoRA adapters (models/lora.py) should
serve them all from ONE copy of the base weights — merging each adapter
(merge_lora) costs a full weight copy per adapter (13.5 GB on 7B), and a
batcher per adapter forfeits cross-adapter batching. Here the adapters
are STACKED on a leading adapter axis and every request carries an
adapter id; one compiled step serves a batch whose rows use different
adapters (the vLLM multi-LoRA insight, shaped for TPU):

- stacked adapters: per target t, a: (N, L, in, r), b: (N, L, r, out) —
  static shapes, so one executable regardless of which adapters are in
  the batch;
- per step, each slot's adapter pair is GATHERED by id ((B, L, in, r) —
  tiny: rank·dim, not dim²) and the delta rides the base matmul as two
  skinny einsums: y = x@W + (x@a_sel)@b_sel · scaling;
- id -1 = base model, implemented as a zero row appended to the stack —
  no branching inside jit, base and adapted rows share every op;
- admission prefills THROUGH the same adapted body (a prompt prefilled
  base-only would hand the adapter a cache it never produced).

Correctness contract (pinned by tests/test_multilora.py): for every
request tagged with adapter i, the emitted tokens are IDENTICAL to a
plain ContinuousBatcher serving merge_lora(params, adapter_i) — and
base-tagged rows match the unmerged base server.

No reference counterpart (the reference has no serving stack —
SURVEY.md §2.5); composes with the HTTP server (models/server.py): the
request's "model" field selects the adapter by name.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.continuous import ContinuousBatcher
from kubeflow_tpu.models.paged import PagedBatcher
from kubeflow_tpu.models.llama import (
    LlamaConfig,
    _cache_store_rows,
    _embed,
    _gqa_decode_attention,
    _lm_head_logits,
    _merge_heads,
    _mlp,
    _mm,
    _norm,
    _qkv,
    _split_heads,
    apply_rope,
    init_kv_cache,
    rope_frequencies,
    sample_logits_per_row,
)
from kubeflow_tpu.models.lora import LoraConfig, init_lora_params
from kubeflow_tpu.models.serving import GenerationConfig


def stack_adapters(adapters: Sequence[dict], cfg: LlamaConfig,
                   lcfg: LoraConfig) -> dict:
    """[adapter tree, ...] → stacked tree with a zero "base" row LAST:
    per target, {"a": (N+1, L, in, r), "b": (N+1, L, r, out)}. Requests
    with no adapter index the zero row — the delta vanishes without any
    branching inside the compiled step."""
    if not adapters:
        raise ValueError("need at least one adapter (else use "
                         "ContinuousBatcher)")
    zero = jax.tree_util.tree_map(
        jnp.zeros_like, init_lora_params(cfg, lcfg, jax.random.PRNGKey(0))
    )
    want = set(adapters[0])
    for i, ad in enumerate(adapters[1:], 1):
        if set(ad) != want:
            # A silently-dropped target would break the merge_lora
            # parity contract with no error; a missing one would be an
            # unexplained KeyError below.
            raise ValueError(
                f"adapter {i} targets {sorted(ad)} != adapter 0 targets "
                f"{sorted(want)}: all adapters must share one LoraConfig"
            )
    out = {}
    for target in adapters[0]:
        for ad in adapters:
            if ad[target]["a"].shape != adapters[0][target]["a"].shape:
                raise ValueError(
                    f"adapter shape mismatch on {target}: all adapters "
                    "must share one LoraConfig"
                )
        out[target] = {
            "a": jnp.stack([ad[target]["a"] for ad in adapters]
                           + [zero[target]["a"]]),
            "b": jnp.stack([ad[target]["b"] for ad in adapters]
                           + [zero[target]["b"]]),
        }
    return out


def _delta(h: jax.Array, sel: dict, target: str,
           scaling: float) -> jax.Array:
    """Per-row LoRA delta: h (B, K, D) × a (B, D, r) × b (B, r, O).
    f32 accumulation like merge_lora, cast back to h's dtype."""
    a, b = sel[target]["a"], sel[target]["b"]
    lo = jnp.einsum("bkd,bdr->bkr", h.astype(jnp.float32),
                    a.astype(jnp.float32))
    return (
        jnp.einsum("bkr,bro->bko", lo, b.astype(jnp.float32)) * scaling
    ).astype(h.dtype)


def _adapted_qkv(h, layer, sel, scaling):
    q, k, v = _qkv(h, layer)
    if "wq" in sel:
        q = q + _delta(h, sel, "wq", scaling)
    if "wk" in sel:
        k = k + _delta(h, sel, "wk", scaling)
    if "wv" in sel:
        v = v + _delta(h, sel, "wv", scaling)
    return q, k, v


def _adapted_mlp(layer, x, cfg, sel, scaling):
    if not (set(sel) & {"w_gate", "w_up", "w_down"}):
        return _mlp(layer, x, cfg)
    pre = _mm(x, layer["w_gate"])
    if "w_gate" in sel:
        pre = pre + _delta(x, sel, "w_gate", scaling)
    pre = pre.astype(jnp.float32)
    gate = (jax.nn.gelu(pre, approximate=True) if cfg.act == "gelu"
            else jax.nn.silu(pre))
    up = _mm(x, layer["w_up"])
    if "w_up" in sel:
        up = up + _delta(x, sel, "w_up", scaling)
    hidden = (gate * up.astype(jnp.float32)).astype(x.dtype)
    out = _mm(hidden, layer["w_down"])
    if "w_down" in sel:
        out = out + _delta(hidden, sel, "w_down", scaling)
    return out


def _gather_adapters(stacked: dict, ids: jax.Array) -> dict:
    """Per-slot adapter slices, layer axis moved LEADING for the scan:
    {"t": {"a": (L, B, in, r), "b": (L, B, r, out)}}."""
    return {
        t: {
            "a": jnp.swapaxes(ab["a"][ids], 0, 1),
            "b": jnp.swapaxes(ab["b"][ids], 0, 1),
        }
        for t, ab in stacked.items()
    }


def _scan_body(params, cfg, scaling, x, cos, sin, positions, kv_mask,
               store_rows, per_batch):
    """Shared layer-scan body builder for the adapted decode step and the
    adapted prefill — ONE body so the two cannot drift (the same
    discipline as llama._chunk_decode_scan / paged._paged_chunk_scan).
    ``per_batch`` must be explicit: the decode step's (B,) positions and
    the prefill's (sq,) positions are both rank-1 but mean different
    things to rope and the attention mask."""

    def body(x, scanned):
        layer, cache_l, sel = scanned
        h = _norm(x, layer["attn_norm"], cfg)
        hq, hk, hv = _adapted_qkv(h, layer, sel, scaling)
        q = apply_rope(_split_heads(hq, cfg.n_heads), cos, sin,
                       per_batch=per_batch)
        k = apply_rope(_split_heads(hk, cfg.n_kv_heads), cos, sin,
                       per_batch=per_batch)
        v = _split_heads(hv, cfg.n_kv_heads)
        cache_l = store_rows(cache_l, k, v)
        attn = _gqa_decode_attention(
            q, cache_l["k"], cache_l["v"], positions,
            window=cfg.sliding_window, kv_mask=kv_mask,
            per_batch=per_batch,
        )
        merged = _merge_heads(attn)
        o = _mm(merged, layer["wo"])
        if "wo" in sel:
            o = o + _delta(merged, sel, "wo", scaling)
        x = x + o
        h = _norm(x, layer["mlp_norm"], cfg)
        x = x + _adapted_mlp(layer, h, cfg, sel, scaling)
        return x, cache_l

    return body


@partial(
    jax.jit,
    static_argnames=("cfg", "scaling", "top_k", "top_p"),
    donate_argnums=(4,),
)
def _ml_step(params, stacked, ids, tokens, cache, positions, kv_mask, key,
             temps, bias, cfg: LlamaConfig, scaling: float,
             top_k: int, top_p: float):
    """One decode step across every slot, each under its own adapter."""
    x = _embed(params, cfg, tokens)
    cos, sin = rope_frequencies(cfg, positions)
    sel = _gather_adapters(stacked, ids)

    def store(cache_l, k, v):
        return _cache_store_rows(cache_l, k, v, positions)

    body = _scan_body(params, cfg, scaling, x, cos, sin, positions,
                      kv_mask, store, per_batch=True)
    x, new_cache = jax.lax.scan(body, x, (params["layers"], cache, sel))
    logits = _lm_head_logits(_norm(x[:, 0], params["final_norm"], cfg),
                             params)
    if bias is not None:
        logits = logits + bias
    nxt = sample_logits_per_row(logits, key, temps, top_k, top_p)
    lp = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), nxt[:, None], axis=-1
    )[:, 0]
    return nxt, lp, new_cache


@partial(jax.jit, static_argnames=("cfg", "scaling"))
def _ml_admit(params, stacked, aid, tokens, prompt_mask, cache, kv_mask,
              slot, cfg: LlamaConfig, scaling: float):
    """Prefill one prompt THROUGH its adapter into ``slot``; mirrors
    continuous._admit_slot but with the adapted body (a base-only
    prefill would hand the adapter a cache it never produced)."""
    cache_len = cache["k"].shape[3]
    lb = tokens.shape[1]
    temp = init_kv_cache(cfg, 1, cache_len)
    x = _embed(params, cfg, tokens)
    pos = jnp.arange(lb)
    cos, sin = rope_frequencies(cfg, pos)
    sel = _gather_adapters(stacked, aid[None])  # (1,) adapter row

    def store(cache_l, k, v):
        # temp cache leaves are (B=1, Hkv, C, D); write positions 0..lb
        new_k = cache_l["k"].at[:, :, :lb].set(k)
        new_v = cache_l["v"].at[:, :, :lb].set(v)
        return {**cache_l, "k": new_k, "v": new_v}

    mask = prompt_mask if prompt_mask is not None else jnp.ones(
        (1, lb), bool
    )
    row = jnp.ones((1, cache_len), bool).at[:, :lb].set(mask)
    # kv_mask spans the FULL cache width (the attention broadcasts it
    # against the cache's key axis); keys beyond lb are additionally
    # fenced by the positional bound (k_pos <= pos, max lb-1).
    body = _scan_body(params, cfg, scaling, x, cos, sin, pos,
                      row, store, per_batch=False)
    x, temp = jax.lax.scan(body, x, (params["layers"], temp, sel))
    logits = _lm_head_logits(
        _norm(x[:, -1], params["final_norm"], cfg), params
    )
    new_cache = {
        name: jax.lax.dynamic_update_slice(
            cache[name], temp[name],
            (0, slot) + (0,) * (cache[name].ndim - 2),
        )
        for name in cache
    }
    new_mask = jax.lax.dynamic_update_slice(kv_mask, row, (slot, 0))
    return logits[0], new_cache, new_mask


class _AdapterHotCache:
    """Bounded per-replica hot-adapter LRU — the residency model for a
    fleet where every replica holds the base weights but only
    ``slots`` adapters stay "hot" (resident/uploaded) at once. On this
    stack the stacked adapters already sit in device memory, so the
    cache's job is OBSERVABILITY plus an honest miss cost: ``load_s``
    simulates the host→device adapter upload a real deployment pays on
    a cold adapter, and hits/misses/evictions feed `/stats` →
    `tpu_serving_lora_cache_*` — the counters the gateway's
    (prefix, adapter) affinity routing is meant to drive toward hits.
    The base row is exempt (it IS the resident model)."""

    def __init__(self, slots: int, load_s: float = 0.0):
        if slots < 1:
            raise ValueError(f"lora cache slots must be >= 1, got {slots}")
        self.slots = int(slots)
        self.load_s = float(load_s)
        self._lru: dict[int, None] = {}  # insertion-ordered residency set
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def touch(self, aid: int) -> None:
        if aid in self._lru:
            self._lru.pop(aid)
            self._lru[aid] = None  # re-insert = move to MRU end
            self.hits += 1
            return
        self.misses += 1
        if len(self._lru) >= self.slots:
            self._lru.pop(next(iter(self._lru)))  # LRU end
            self.evictions += 1
        self._lru[aid] = None
        if self.load_s:
            time.sleep(self.load_s)  # simulated adapter upload

    def stats(self) -> dict:
        return {
            "slots": self.slots,
            "resident": len(self._lru),
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
        }


class _AdapterRegistry:
    """Shared adapter bookkeeping for the multi-LoRA engines (continuous
    and paged): the stacked-weights registry, name→row resolution, and
    the optional hot-adapter cache. One home so the two engines cannot
    drift on what an adapter id MEANS (requests carry None for base,
    0..n-1 for adapters; the stacked zero row n_adapters is a gather
    detail, never a request-visible id)."""

    def _init_adapters(self, stacked: dict, lcfg: LoraConfig,
                       adapter_names: Optional[Sequence[str]],
                       lora_cache_slots: int = 0,
                       lora_load_s: float = 0.0) -> None:
        first = next(iter(stacked.values()))["a"]
        self.n_adapters = first.shape[0] - 1  # last row is the zero/base
        self.stacked = stacked
        self.scaling = lcfg.scaling
        names = list(adapter_names) if adapter_names is not None else [
            str(i) for i in range(self.n_adapters)
        ]
        if len(names) != self.n_adapters:
            raise ValueError(
                f"{len(names)} adapter_names for {self.n_adapters} adapters"
            )
        self.adapter_names = names
        self._adapter_cache = (
            _AdapterHotCache(lora_cache_slots, lora_load_s)
            if lora_cache_slots else None
        )

    def resolve_adapter(self, adapter) -> int:
        """Name | index | None → stacked row id (None = the base row).
        Only str/int are accepted: a float would silently truncate to a
        DIFFERENT adapter and a list/bool is a client bug — both must be
        a clean ValueError (the HTTP layer turns it into a 400), never a
        TypeError or a wrong-adapter response."""
        if adapter is None:
            return self.n_adapters
        if isinstance(adapter, str):
            try:
                return self.adapter_names.index(adapter)
            except ValueError:
                raise ValueError(
                    f"unknown adapter {adapter!r} "
                    f"(serving: {', '.join(self.adapter_names)} + base)"
                ) from None
        if not isinstance(adapter, int) or isinstance(adapter, bool):
            raise ValueError(
                f"adapter must be a name, an integer index, or None — "
                f"got {type(adapter).__name__} {adapter!r}"
            )
        if not 0 <= adapter < self.n_adapters:
            raise ValueError(
                f"adapter index {adapter} out of range "
                f"[0, {self.n_adapters})"
            )
        return adapter

    def _touch_adapter(self, aid: int) -> None:
        """Count the hot-cache access for a non-base adapter; called on
        the SUBMITTING thread so a simulated upload stall lands on the
        request path (where a real upload would), never inside the
        engine-driving loop."""
        if self._adapter_cache is not None and aid != self.n_adapters:
            self._adapter_cache.touch(aid)

    def lora_cache_stats(self) -> Optional[dict]:
        """The /stats "lora_cache" block, or None when uncapped."""
        if self._adapter_cache is None:
            return None
        return self._adapter_cache.stats()


class MultiLoraBatcher(_AdapterRegistry, ContinuousBatcher):
    """Fixed-slot continuous batching with a per-request LoRA adapter.

    >>> stacked = stack_adapters([ad_math, ad_code], cfg, lcfg)
    >>> mb = MultiLoraBatcher(params, cfg, stacked, lcfg,
    ...                       adapter_names=["math", "code"])
    >>> mb.submit(p1, adapter="math"); mb.submit(p2, adapter="code")
    >>> mb.submit(p3)                  # base model, same batch
    >>> results = mb.run()
    """

    def __init__(self, params, cfg, stacked: dict, lcfg: LoraConfig,
                 adapter_names: Optional[Sequence[str]] = None,
                 lora_cache_slots: int = 0, lora_load_s: float = 0.0,
                 **kw):
        for unsupported in ("plan", "kv_bits", "attn_kernel",
                            "admit_chunk"):
            if kw.get(unsupported):
                raise ValueError(
                    f"MultiLoraBatcher does not support {unsupported}= yet"
                )
        kw["attn_kernel"] = False
        # admit_chunk: truthy values are rejected above (chunked
        # admission bypasses the adapter-aware prefill); falsy ones flow
        # through so the parent's own validation still fires (e.g. 0).
        super().__init__(params, cfg, **kw)
        self._init_adapters(stacked, lcfg, adapter_names,
                            lora_cache_slots, lora_load_s)
        self._slot_adapter = np.full((self.slots,), self.n_adapters,
                                     np.int32)  # base row

    def submit(self, prompt, max_new_tokens=None, adapter=None,
               temperature=None, stop=None, logit_bias=None,
               deadline_s=None) -> int:
        aid = self.resolve_adapter(adapter)
        self._touch_adapter(aid)
        rid = super().submit(prompt, max_new_tokens=max_new_tokens,
                             temperature=temperature, stop=stop,
                             logit_bias=logit_bias, deadline_s=deadline_s)
        # None = base everywhere a request travels (chain keys, export
        # payloads); the zero-row index exists only at gather time.
        self._queue[-1].adapter_id = (
            None if aid == self.n_adapters else aid
        )
        return rid

    def _prefill_into_slot(self, slot, req, padded, prompt_mask):
        """Adapter-aware half of admission; the shared loop (padding,
        _post_admit, sampling, budget) lives in ContinuousBatcher."""
        aid = (self.n_adapters if req.adapter_id is None
               else req.adapter_id)
        logits, self.cache, self.kv_mask = _ml_admit(
            self.params, self.stacked, jnp.asarray(aid, jnp.int32),
            padded, prompt_mask, self.cache, self.kv_mask,
            jnp.asarray(slot, jnp.int32), self.cfg, self.scaling,
        )
        self._slot_adapter[slot] = aid
        return logits

    def _step(self) -> None:
        active = [i for i, r in enumerate(self._by_slot) if r is not None]
        if not active:
            return
        self.key, sub = jax.random.split(self.key)
        nxt, lps, self.cache = _ml_step(
            self.params, self.stacked, jnp.asarray(self._slot_adapter),
            jnp.array(self.tokens), self.cache, jnp.array(self.positions),
            self.kv_mask, sub, jnp.array(self.temps), self._bias,
            self.cfg, self.scaling, self.gen.top_k, self.gen.top_p,
        )
        for slot in active:
            self.positions[slot] += 1
        host_next = np.asarray(nxt)
        host_lps = np.asarray(lps)
        for slot in active:
            self._note_token(slot, int(host_next[slot]),
                             float(host_lps[slot]))


class MultiLoraPagedBatcher(_AdapterRegistry, PagedBatcher):
    """Per-request LoRA on the PAGED RAGGED engine: adapter deltas ride
    EVERY row of the fused dispatch — decode rows, admission prefill
    chunk rows, and speculative verify spans alike — through the
    `_ragged_adapters` hook (paged._row_adapters gathers each row's
    owning slot's pair, so one compiled step serves a mixed-adapter
    mixed-phase batch). The adapter id is folded into the prefix chain
    key (paged._chain_key salts the root), so exported/imported KV
    blocks never cross adapters, and int8 pools / the ragged attention
    kernel compose unchanged (the delta touches projections, never the
    cache format).

    Ragged-only by design: the legacy alternating path admits through
    base-only prefill programs, which would hand an adapter a cache it
    never produced — exactly the bug the continuous engine's
    `_ml_admit` exists to prevent. Requires ``ragged=True``.

    >>> mb = MultiLoraPagedBatcher(params, cfg, stacked, lcfg,
    ...                            adapter_names=["math", "code"],
    ...                            ragged=True, lora_cache_slots=16)
    >>> mb.submit(p1, adapter="math"); mb.submit(p2)   # adapter + base
    >>> results = mb.run()
    >>> mb.lora_cache_stats()   # {"hits": ..., "evictions": ...}
    """

    def __init__(self, params, cfg, stacked: dict, lcfg: LoraConfig,
                 adapter_names: Optional[Sequence[str]] = None,
                 lora_cache_slots: int = 0, lora_load_s: float = 0.0,
                 **kw):
        if not kw.get("ragged"):
            raise ValueError(
                "MultiLoraPagedBatcher requires ragged=True: adapter "
                "deltas are applied per-row inside the fused ragged "
                "dispatch; the legacy alternating path admits through "
                "base-only prefill programs"
            )
        # plan= composes: the base weights shard per the plan while the
        # stacked adapter deltas stay replicated (skinny (in, r) factors
        # are a rounding error next to the base matmuls) — GSPMD keeps
        # the adapted projections partitioned and psums once at the
        # output, same as the base path.
        for unsupported in ("prompt_cache", "prefix_cache"):
            if kw.get(unsupported):
                raise ValueError(
                    f"MultiLoraPagedBatcher does not support "
                    f"{unsupported}= yet"
                )
        super().__init__(params, cfg, **kw)
        self._init_adapters(stacked, lcfg, adapter_names,
                            lora_cache_slots, lora_load_s)

    def submit(self, prompt, max_new_tokens=None, adapter=None,
               temperature=None, stop=None, logit_bias=None,
               deadline_s=None) -> int:
        aid = self.resolve_adapter(adapter)
        # Touch on the submitting thread: a simulated upload stall lands
        # on the request path, never inside the engine-driving loop.
        self._touch_adapter(aid)
        rid = super().submit(prompt, max_new_tokens=max_new_tokens,
                             temperature=temperature, stop=stop,
                             logit_bias=logit_bias, deadline_s=deadline_s)
        self._queue[-1].adapter_id = (
            None if aid == self.n_adapters else aid
        )
        return rid

    def _ragged_adapters(self):
        """(stacked, ids (S,), scaling) for this step's dispatch: each
        slot's row — decoding OR mid-admission — maps to its request's
        adapter (None → the stacked zero/base row), so prefill chunks
        run through the same adapted body their decode rows will."""
        ids = np.full((self.slots,), self.n_adapters, np.int32)
        for slot, req in enumerate(self._by_slot):
            if req is not None and req.adapter_id is not None:
                ids[slot] = req.adapter_id
        for slot, a in self._ragged_admit.items():
            aid = a["req"].adapter_id
            if aid is not None:
                ids[slot] = aid
        return self.stacked, jnp.asarray(ids), self.scaling
