"""Live slice migration: proactive save → warm-claim → restore → flip.

PRs 1/3 made preemption *survivable* (the reactive escalation ladder
recreates the slice; crash-safe checkpoints make the state restorable),
and PR 16 made warm capacity *claimable* under a bounded deadline. This
module composes them into the NotebookOS-style proactive move (PAPERS.md
arxiv 2503.20591, ROADMAP item 4): when a preemption notice, an
idle-cull decision, or an operator trigger says a slice is about to go
away, the :class:`MigrationOrchestrator` runs a deadline-budgeted
four-step pipeline instead of waiting to ride the reactive ladder:

1. **save** — one emergency save through the PR-3 ``CheckpointManager``
   (same grace-budget arithmetic SIGTERM gets, but initiated *before*
   SIGTERM arrives, so the whole budget is ours). Skip-if-fresh rides
   ``CheckpointManager.last_commit_age()`` — the injected-monotonic-clock
   freshness source — never wall clock.
2. **claim** — a warm slice from ``controller/slicepool.py`` through the
   fenced, deadline-bounded claim path. The claimant id is stamped as
   the ``CLAIMED_BY`` fence, so a migration and the fleet autoscaler can
   never both believe they own one placeholder.
3. **restore** — rebuild training state on the new slice with the exact
   ``start_batch`` cursor (``resume_start_batch``) and per-process shard
   assembly; the chaos gate asserts the resumed loss stream is
   bit-identical to an uninterrupted control run.
4. **flip** — route traffic to the new slice and release the old one
   drain-style (``gateway.begin_drain``: out of the ring immediately,
   in-flight streams keep flowing until done). A flip never severs a
   stream.

**Migration is an optimization, never a new failure mode.** Every step
carries its own budget from :class:`MigrationConfig`; a step that blows
its budget, returns nothing, or raises triggers ``fallback_fn`` — wired
by the controller to the PR-1 reactive ladder (mark the slice
interrupted and let ``SliceHealthReconciler`` drive recovery) — records
a ``MigrationFellBack`` event, and the pipeline stops. Completion and
fallback are both terminal and always reported: no hang, no silent
loss.

Observability: the whole pipeline is ONE ``migration`` trace with a
child span per step (each budget visible as span attributes), Notebook
events (``MigrationProgress`` per step, ``MigrationCompleted`` /
``MigrationFellBack`` terminal), ``tpu_migration_*`` counters in
metrics.py STATS_PARITY surfaced by :meth:`MigrationOrchestrator.stats`
(this module is a registered STATS_PARITY surface), and windowed
``migration_*_per_s`` rates in /debug/signals via
``FleetTelemetry.observe_migration``.

Inert by default: ``migration_from_env()`` returns ``None`` unless
``KUBEFLOW_TPU_MIGRATE_ENABLE`` opts in, and parses fail-fast — a
hand-set knob must never silently fall back to defaults.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from kubeflow_tpu.observability import tracing

log = logging.getLogger(__name__)

# Pipeline step names, in order. Budgets, spans, events, and the forced-
# failure tests all key off these.
MIGRATION_STEPS = ("save", "claim", "restore", "flip")


class MigrationFellBack(Exception):
    """Internal control flow: a step blew its budget / failed; the
    pipeline degrades to the reactive ladder. Never escapes
    :meth:`MigrationOrchestrator.migrate`."""

    def __init__(self, step: str, reason: str):
        super().__init__(f"{step}: {reason}")
        self.step = step
        self.reason = reason


@dataclass(frozen=True)
class MigrationConfig:
    """Per-step budgets. Frozen + validated: a bad knob fails
    construction, not a migration mid-preemption."""

    save_budget_s: float = 30.0
    claim_budget_s: float = 10.0
    restore_budget_s: float = 60.0
    flip_budget_s: float = 10.0
    # A commit younger than this (monotonic, last_commit_age) makes the
    # save step a skip: re-saving what is already durable wastes the
    # preemption notice window.
    fresh_within_s: float = 5.0

    def __post_init__(self):
        for name in ("save_budget_s", "claim_budget_s",
                     "restore_budget_s", "flip_budget_s"):
            if getattr(self, name) <= 0:
                raise ValueError(
                    f"MigrationConfig: {name} must be > 0, "
                    f"got {getattr(self, name)}"
                )
        if self.fresh_within_s < 0:
            raise ValueError(
                f"MigrationConfig: fresh_within_s must be >= 0, "
                f"got {self.fresh_within_s}"
            )

    def budget(self, step: str) -> float:
        return float(getattr(self, f"{step}_budget_s"))


@dataclass
class MigrationReport:
    """What one migrate() call did — every outcome is reported, never
    raised. ``steps`` maps step name -> {"ok", "duration_s", "detail"}
    for the steps that ran."""

    trigger: str
    completed: bool = False
    fell_back: bool = False
    failed_step: Optional[str] = None
    reason: str = ""
    pool: Optional[str] = None
    restored_step: Optional[int] = None
    start_batch: Optional[int] = None
    duration_s: float = 0.0
    steps: Optional[dict] = None


class MigrationOrchestrator:
    """Drives the four-step pipeline; every collaborator is an injected
    seam so the controller, the chaos harness, and the forced-failure
    tests wire the same object differently:

    - ``checkpoint``: a ``CheckpointManager`` (or None: nothing to save
      — the step is a recorded skip);
    - ``claim_fn(claimant, deadline)`` -> pool name or None. Production
      wraps ``claim_warm_slice(..., claimant=..., deadline=...)``;
    - ``restore_fn(deadline)`` -> ``{"step": int, "start_batch": int}``
      (extra keys kept in the report detail). Production restores the
      checkpoint into the new slice's freshly-sharded template;
    - ``flip_fn(deadline)`` -> truthy on success. Production adds the
      new replica to the gateway ring and ``begin_drain``s the old one;
    - ``fallback_fn(step, reason)``: the reactive-ladder entry point.
      Exceptions out of it are contained — the ladder hook must not be
      able to turn a fallback into a crash.

    Thread-safe: one migration at a time per orchestrator (a second
    trigger while one is in flight reports a fallback with reason
    "migration already in progress" rather than racing it).
    """

    def __init__(
        self,
        config: Optional[MigrationConfig] = None,
        *,
        checkpoint: Any = None,
        claim_fn: Optional[Callable[[str, float], Optional[str]]] = None,
        restore_fn: Optional[Callable[[float], Optional[dict]]] = None,
        flip_fn: Optional[Callable[[float], Any]] = None,
        fallback_fn: Optional[Callable[[str, str], None]] = None,
        metrics: Any = None,
        telemetry: Any = None,
        recorder: Any = None,
        notebook: Optional[dict] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.config = config or MigrationConfig()
        self.checkpoint = checkpoint
        self.claim_fn = claim_fn
        self.restore_fn = restore_fn
        self.flip_fn = flip_fn
        self.fallback_fn = fallback_fn
        self.metrics = metrics
        self.telemetry = telemetry
        self.recorder = recorder
        self.notebook = notebook
        self._clock = clock or time.monotonic
        self._busy = threading.Lock()
        self._stats_lock = threading.Lock()
        self._started = 0
        self._completed = 0
        self._fell_back = 0
        self._last_duration_s = 0.0
        self._last_trigger = ""
        self._last_failed_step: Optional[str] = None

    # -- the pipeline ------------------------------------------------------

    def migrate(self, trigger: str) -> MigrationReport:
        """Run the pipeline once for ``trigger`` (``"preemption-notice"``,
        ``"idle-cull"``, ``"operator"``, ...). Returns a report; never
        raises — failure IS the fallback path."""
        if not self._busy.acquire(blocking=False):
            # A concurrent trigger must not double-claim or double-flip;
            # the in-flight migration already covers this slice.
            return MigrationReport(
                trigger=trigger, fell_back=False, completed=False,
                reason="migration already in progress",
            )
        try:
            return self._migrate(trigger)
        finally:
            self._busy.release()

    def _migrate(self, trigger: str) -> MigrationReport:
        cfg = self.config
        report = MigrationReport(trigger=trigger, steps={})
        self._count("started", trigger)
        t_start = self._clock()
        with tracing.get_tracer("migration").start_span(
            "migration", trigger=trigger,
        ) as root:
            try:
                self._step_save(report)
                self._step_claim(report)
                self._step_restore(report)
                self._step_flip(report)
            except MigrationFellBack as fb:
                self._fall_back(report, fb, root)
            else:
                report.completed = True
                self._count("completed", trigger)
                root.set_attribute("completed", True)
                self._event(
                    "Normal", "MigrationCompleted",
                    f"migration ({trigger}) completed: resumed step "
                    f"{report.restored_step} (start_batch "
                    f"{report.start_batch}) on slice from pool "
                    f"{report.pool}",
                )
            report.duration_s = max(0.0, self._clock() - t_start)
            root.set_attribute("duration_s", round(report.duration_s, 6))
            with self._stats_lock:
                self._last_duration_s = report.duration_s
            if self.metrics is not None:
                gauge = getattr(self.metrics, "migration_seconds", None)
                if gauge is not None:
                    gauge.set(report.duration_s)
        return report

    def _run_step(self, report: MigrationReport, step: str,
                  body: Callable[[float, Any], str]) -> None:
        """One budgeted step: a child span, the budget as a deadline
        handed INTO the body, an elapsed check after it, and a
        MigrationProgress event on success. ``body(deadline, span)``
        returns a human detail string; raising MigrationFellBack (or
        anything else) degrades the pipeline."""
        budget = self.config.budget(step)
        t0 = self._clock()
        with tracing.get_tracer("migration").start_span(
            f"migration.{step}", budget_s=budget,
        ) as span:
            try:
                detail = body(t0 + budget, span)
            except MigrationFellBack:
                raise
            except Exception as err:  # a step crash is a fallback, not ours
                raise MigrationFellBack(step, repr(err)) from err
            elapsed = max(0.0, self._clock() - t0)
            span.set_attribute("duration_s", round(elapsed, 6))
            if elapsed > budget:
                # The step "succeeded" but ate someone else's budget: the
                # remaining steps would run against a slice that may
                # already be gone. Degrade.
                raise MigrationFellBack(
                    step, f"budget blown: {elapsed:.2f}s > {budget:g}s"
                )
            report.steps[step] = {
                "ok": True, "duration_s": round(elapsed, 6),
                "detail": detail,
            }
            self._event(
                "Normal", "MigrationProgress",
                f"migration step {step} done in {elapsed:.2f}s: {detail}",
            )

    # -- steps -------------------------------------------------------------

    def _step_save(self, report: MigrationReport) -> None:
        def body(deadline: float, span) -> str:
            ckpt = self.checkpoint
            if ckpt is None:
                span.set_attribute("skipped", "no checkpoint manager")
                return "no checkpoint manager; nothing to save"
            age = ckpt.last_commit_age()
            if age <= self.config.fresh_within_s:
                span.set_attribute("skipped", "fresh")
                return (f"last commit {age:.2f}s old "
                        f"(<= {self.config.fresh_within_s:g}s); skipped")
            committed = ckpt.emergency_save(
                grace_s=max(0.0, deadline - self._clock())
            )
            if not committed and ckpt.latest_step() is None:
                raise MigrationFellBack(
                    "save", "no checkpoint committed and none on disk"
                )
            return (f"committed step {ckpt.latest_step()}" if committed
                    else f"nothing newer than committed step "
                         f"{ckpt.latest_step()}")

        self._run_step(report, "save", body)

    def _step_claim(self, report: MigrationReport) -> None:
        def body(deadline: float, span) -> str:
            if self.claim_fn is None:
                raise MigrationFellBack("claim", "no claim path configured")
            claimant = f"migration-{report.trigger}"
            span.set_attribute("claimant", claimant)
            pool = self.claim_fn(claimant, deadline)
            if pool is None:
                raise MigrationFellBack(
                    "claim", "warm-slice claim exhausted (no matching "
                    "warm capacity within deadline)"
                )
            report.pool = pool
            span.set_attribute("pool", pool)
            return f"claimed warm slice from pool {pool} as {claimant}"

        self._run_step(report, "claim", body)

    def _step_restore(self, report: MigrationReport) -> None:
        def body(deadline: float, span) -> str:
            if self.restore_fn is None:
                raise MigrationFellBack(
                    "restore", "no restore path configured"
                )
            out = self.restore_fn(deadline)
            if not out or out.get("step") is None:
                raise MigrationFellBack(
                    "restore", "restore produced no valid step"
                )
            report.restored_step = int(out["step"])
            if out.get("start_batch") is not None:
                report.start_batch = int(out["start_batch"])
            span.set_attribute("restored_step", report.restored_step)
            if report.start_batch is not None:
                span.set_attribute("start_batch", report.start_batch)
            return (f"restored step {report.restored_step}, resuming at "
                    f"start_batch {report.start_batch}")

        self._run_step(report, "restore", body)

    def _step_flip(self, report: MigrationReport) -> None:
        def body(deadline: float, span) -> str:
            if self.flip_fn is None:
                raise MigrationFellBack("flip", "no flip path configured")
            ok = self.flip_fn(deadline)
            if not ok:
                raise MigrationFellBack(
                    "flip", "routing flip refused (endpoint conflict or "
                    "unknown replica)"
                )
            return ("routing flipped to the new slice; old slice "
                    "draining (in-flight streams keep flowing)")

        self._run_step(report, "flip", body)

    # -- fallback ----------------------------------------------------------

    def _fall_back(self, report: MigrationReport, fb: MigrationFellBack,
                   root) -> None:
        report.fell_back = True
        report.failed_step = fb.step
        report.reason = fb.reason
        report.steps[fb.step] = {"ok": False, "detail": fb.reason}
        self._count("fell_back", report.trigger, failed_step=fb.step)
        root.set_attribute("completed", False)
        root.set_attribute("failed_step", fb.step)
        root.record_error(fb)
        self._event(
            "Warning", "MigrationFellBack",
            f"migration ({report.trigger}) fell back at step {fb.step}: "
            f"{fb.reason}; reactive recovery ladder takes over",
        )
        log.warning(
            "migration (%s) fell back at %s: %s",
            report.trigger, fb.step, fb.reason,
        )
        if self.fallback_fn is not None:
            try:
                self.fallback_fn(fb.step, fb.reason)
            except Exception:
                # The ladder hook failing must not escalate a degraded
                # migration into a crash; the reactive controller is
                # level-triggered and will see the slice state anyway.
                log.exception("migration fallback hook raised")

    # -- bookkeeping -------------------------------------------------------

    def _count(self, what: str, trigger: str,
               failed_step: Optional[str] = None) -> None:
        with self._stats_lock:
            if what == "started":
                self._started += 1
                self._last_trigger = trigger
                self._last_failed_step = None
            elif what == "completed":
                self._completed += 1
            else:
                self._fell_back += 1
                self._last_failed_step = failed_step
        if self.metrics is not None:
            counter = getattr(self.metrics, {
                "started": "migration_started_total",
                "completed": "migration_completed_total",
                "fell_back": "migration_fallback_total",
            }[what], None)
            if counter is not None:
                counter.inc()
        if self.telemetry is not None:
            observe = getattr(self.telemetry, "observe_migration", None)
            if observe is not None:
                observe(what)

    def _event(self, etype: str, reason: str, message: str) -> None:
        if self.recorder is not None and self.notebook is not None:
            self.recorder.eventf(self.notebook, etype, reason, message)

    def stats(self) -> dict:
        """The /stats ``migration`` block; key literals here are the
        STATS_PARITY surface for the tpu_migration_* metric families."""
        with self._stats_lock:
            return {
                "migrations_started": self._started,
                "migrations_completed": self._completed,
                "migrations_fell_back": self._fell_back,
                "migration_last_s": round(self._last_duration_s, 6),
                "last_trigger": self._last_trigger,
                "last_failed_step": self._last_failed_step,
            }


def migration_from_env(env: Optional[dict] = None) -> Optional[MigrationConfig]:
    """None unless KUBEFLOW_TPU_MIGRATE_ENABLE opts in (migration must
    be inert by default). Raises on garbage — a hand-set env var must
    not silently fall back to defaults."""
    import os

    from kubeflow_tpu.webhook.tpu_env import (
        KUBEFLOW_TPU_MIGRATE_CLAIM_BUDGET_S,
        KUBEFLOW_TPU_MIGRATE_ENABLE,
        KUBEFLOW_TPU_MIGRATE_FLIP_BUDGET_S,
        KUBEFLOW_TPU_MIGRATE_FRESH_WITHIN_S,
        KUBEFLOW_TPU_MIGRATE_RESTORE_BUDGET_S,
        KUBEFLOW_TPU_MIGRATE_SAVE_BUDGET_S,
    )

    src = os.environ if env is None else env
    raw = src.get(KUBEFLOW_TPU_MIGRATE_ENABLE, "").strip().lower()
    if raw not in ("", "0", "false", "1", "true"):
        raise ValueError(
            f"{KUBEFLOW_TPU_MIGRATE_ENABLE}={raw!r}: want 0/1/true/false"
        )
    if raw not in ("1", "true"):
        return None
    defaults = MigrationConfig()

    def _num(name: str, default: float, minimum: float) -> float:
        value = src.get(name, "").strip()
        if not value:
            return default
        try:
            got = float(value)
        except ValueError:
            got = minimum - 1
        if got < minimum:
            raise ValueError(f"{name}={value!r}: want a number >= {minimum:g}")
        return got

    return MigrationConfig(
        save_budget_s=_num(KUBEFLOW_TPU_MIGRATE_SAVE_BUDGET_S,
                           defaults.save_budget_s, 1.0),
        claim_budget_s=_num(KUBEFLOW_TPU_MIGRATE_CLAIM_BUDGET_S,
                            defaults.claim_budget_s, 1.0),
        restore_budget_s=_num(KUBEFLOW_TPU_MIGRATE_RESTORE_BUDGET_S,
                              defaults.restore_budget_s, 1.0),
        flip_budget_s=_num(KUBEFLOW_TPU_MIGRATE_FLIP_BUDGET_S,
                           defaults.flip_budget_s, 1.0),
        fresh_within_s=_num(KUBEFLOW_TPU_MIGRATE_FRESH_WITHIN_S,
                            defaults.fresh_within_s, 0.0),
    )
