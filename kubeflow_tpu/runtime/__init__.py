from kubeflow_tpu.runtime.bootstrap import (  # noqa: F401
    SliceRuntime,
    bootstrap,
    runtime_from_env,
)
