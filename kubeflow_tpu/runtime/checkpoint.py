"""Crash-safe in-notebook checkpointing: the durability half of preemption
recovery.

The control plane recovers the *slice* (SliceHealthReconciler's escalation
ladder recreates preempted host pods), but in-notebook JAX state dies with
the pod. This module makes the on-disk training state survive every way a
notebook pod actually dies:

- **Atomic commit.** Each step is written into a ``.tmp-*`` staging dir
  with a manifest recording per-file sizes + CRC32 checksums, every byte is
  fsynced, and only then is the staging dir renamed over the final
  ``<step>/`` name (``CheckpointIO.commit`` — the single place a rename is
  allowed, enforced by the ``kftpu-unfsynced-rename`` semgrep rule). A pod
  SIGKILLed mid-save leaves a ``.tmp-*`` turd that restore never looks at;
  it can never leave a torn "latest".
- **Validated restore with quarantine.** ``restore_latest`` walks committed
  steps newest-first, re-verifies the manifest (sizes + checksums), moves
  anything torn or bit-rotted aside as ``corrupt-<step>-*`` (counted by
  ``tpu_checkpoint_corrupt_total``), and falls back to the newest step that
  still verifies instead of crashing on the newest directory.
- **Deadline-bounded emergency save.** ``emergency_save`` is the SIGTERM
  path (runtime.bootstrap.install_preemption_handler): one final
  synchronous save sized to the pod's grace budget, skipped when a fresh
  save already exists or the last observed save duration would blow the
  budget — half a checkpoint helps nobody.
- **Exact resume.** ``train_with_checkpointing`` records the data-loader
  cursor (``{"start_batch": step}``) in each save's metadata;
  ``restore_latest`` surfaces it via ``restored_metadata`` /
  ``resume_start_batch`` so ``data.loader.sharded_loader(start_batch=...)``
  replays nothing and skips nothing.
- **Multi-host: one root per process.** On a multi-host slice every
  process commits into its own ``proc<k>/`` subtree of the shared
  checkpoint directory (identity from the webhook's TPU env contract, or
  explicit ``process_index``/``process_count``), so commits never race on
  one rename target. Non-fully-addressable ``jax.Array`` leaves are
  serialized as this process's *addressable shards* (index + bytes) —
  the full array is never gathered to one host — and restored straight
  into the template's sharding. A step counts as restorable only when
  EVERY process committed it, so a host that died mid-save poisons
  nothing: survivors skip that step by intersection.

The format is plain numpy-bytes + JSON — no orbax dependency, so the
save/restore path has no library between it and the fsyncs it promises.
ml_dtypes dtypes (bfloat16, int4, fp8) round-trip exactly: leaves are
serialized with ``tobytes()`` and revived via ``np.frombuffer`` with the
dtype *name* from the manifest (resolved through a lazy ``ml_dtypes``
import when numpy alone does not know the name). jax is imported lazily
(tree flatten / device placement only), so constructing a manager and
validating checkpoints needs no accelerator stack.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import shutil
import threading
import time
import zlib
from pathlib import Path
from typing import Any, Callable, Optional

import numpy as np

from kubeflow_tpu.observability import tracing

log = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.json"
MANIFEST_FORMAT = 1
# Staging dirs start with "." so ``<step>``.isdigit() scans never see them;
# quarantine keeps the step number visible for the operator but breaks the
# isdigit() match the same way.
_TMP_PREFIX = ".tmp-"
CORRUPT_PREFIX = "corrupt-"
# An emergency save must never block forever: when no grace budget was
# given, draining in-flight async saves is still bounded by this.
_DEFAULT_EMERGENCY_DRAIN_S = 30.0


class CorruptCheckpointError(Exception):
    """A committed step directory failed manifest validation."""


def process_identity_from_env(env: Optional[dict] = None) -> tuple:
    """(process_index, process_count) from the webhook's TPU env contract
    (TPU_WORKER_ID / TPU_WORKER_HOSTNAMES / MEGASCALE_*), via the same
    parser bootstrap uses. Deliberately backend-free: asking jax would
    initialize the TPU client, and constructing a manager must not."""
    from kubeflow_tpu.runtime.bootstrap import runtime_from_env

    rt = runtime_from_env(env)
    return rt.process_id, rt.num_workers


class CheckpointIO:
    """The file-IO seam of the commit protocol.

    Split out so chaos experiments can inject faults (ENOSPC, a crash
    between file writes) without touching the manager's policy logic.
    Durability ordering is: file bytes fsynced → manifest fsynced →
    staging dir fsynced → rename → parent dir fsynced. Only after the
    final fsync is the step durably visible under its committed name.
    """

    def write_file(self, path: Path, data: bytes) -> None:
        with open(path, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    def fsync_dir(self, path: Path) -> None:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def commit(self, staged: Path, final: Path) -> None:
        """Atomically publish ``staged`` as ``final``.

        The ONE place checkpoint code may rename (semgrep
        kftpu-unfsynced-rename pins this): the staged dir is fsynced so
        its entries are durable BEFORE the rename makes them reachable,
        and the parent is fsynced after so the rename itself is durable.
        """
        self.fsync_dir(staged)
        os.replace(staged, final)
        self.fsync_dir(final.parent)


class CheckpointManager:
    """Durable checkpoint policy: atomic saves, validated restores.

    - ``save(step, state)`` honors ``save_interval_steps`` (returns whether
      a save actually happened) and keeps ``max_to_keep`` checkpoints.
      With ``async_save=True`` the state is snapshotted to host memory
      synchronously (safe with donated buffers) and written by a worker
      thread; ``wait()`` joins the queue.
    - Save *failures* (ENOSPC, quota) are contained: the staging dir is
      removed, ``save_failures``/``last_save_error`` record the outcome,
      training continues, and the previous committed step stays valid.
    - ``restore_latest(template)`` restores into the template's shardings
      (pass the freshly-sharded init state; arrays land where the mesh
      says, not on host 0), quarantining any step that fails validation.
    - ``emergency_save(grace_s)`` is the preemption path: one synchronous
      save of the newest state handed to ``save()``, skipped when already
      committed or when it cannot finish inside the grace budget.
    - Multi-host: each process owns ``<directory>/proc<k>/``; saves and
      quarantines touch only the local root, while ``latest_step`` /
      ``restore_latest`` consider only steps present in EVERY process's
      root. Identity comes from ``process_index``/``process_count`` when
      given, else from the webhook's TPU env contract, else (0, 1).
    """

    def __init__(
        self,
        directory: str | Path,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        async_save: bool = False,
        metrics: Any = None,
        io: Optional[CheckpointIO] = None,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        env: Optional[dict] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        # Every budget/freshness computation in this manager reads THIS
        # clock, monotonic by default. Wall clock is not an option here:
        # the emergency path runs exactly when preemptions land, and
        # maintenance events correlate with NTP steps on the host — a
        # backwards jump mid-grace-window would inflate "remaining" and
        # start a save SIGKILL then tears. Injectable so tests can prove
        # the budget math under a controlled (or deliberately jumpy)
        # source.
        self._clock = clock or time.monotonic
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max(1, int(max_to_keep))
        self.save_interval_steps = max(1, int(save_interval_steps))
        self.io = io or CheckpointIO()
        self.metrics = metrics
        if process_index is None or process_count is None:
            env_index, env_count = process_identity_from_env(env)
            process_index = env_index if process_index is None else process_index
            process_count = env_count if process_count is None else process_count
        self.process_index = int(process_index)
        self.process_count = max(1, int(process_count))
        if not 0 <= self.process_index < self.process_count:
            raise ValueError(
                f"process_index {self.process_index} not in "
                f"[0, {self.process_count})"
            )
        self._root = (
            self.directory
            if self.process_count == 1
            else self.directory / f"proc{self.process_index}"
        )
        self._root.mkdir(parents=True, exist_ok=True)
        # Metadata dict of the step restore_latest() last returned.
        self.restored_metadata: dict = {}
        self.last_save_error: Optional[BaseException] = None
        self.save_failures = 0
        # Serializes whole checkpoint writes. The emergency path acquires
        # it with a timeout (never blocking the exit path on a frozen
        # writer); the save-outcome state has its own lock (_seq_lock) so
        # outcome bookkeeping stays consistent even on the emergency path
        # that writes WITHOUT _lock after the acquire timed out.
        self._lock = threading.RLock()
        # Guards _seq plus the save-outcome state (last_save_error,
        # save_failures, _last_save_duration, _last_committed_step,
        # _last_commit_at): written from the async worker thread, the
        # caller's save(), the signal-path emergency save, and restore.
        # Always taken after _lock (never around I/O) — keeping the
        # documented _lock -> _seq_lock order cycle-free.
        self._seq_lock = threading.Lock()
        self._seq = 0  # staging-dir uniquifier (reentrant saves)
        self._last_saved_step: Optional[int] = None  # interval gate
        self._last_committed_step: Optional[int] = self.latest_step()
        self._last_save_duration: Optional[float] = None
        # Clock instant of the last durable commit THIS process performed.
        # None for a step inherited from disk at construction: its age is
        # unknowable by a monotonic clock (file mtimes are wall time), so
        # last_commit_age() reports +inf and freshness-gated callers save
        # rather than trust it.
        self._last_commit_at: Optional[float] = None
        # Newest (step, host_leaves, treedef-free paths, metadata) handed to
        # save(), committed or not — what emergency_save flushes.
        self._pending: Optional[tuple] = None
        self._async = bool(async_save)
        self._queue: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None

    # -- save ----------------------------------------------------------------

    def save(
        self,
        step: int,
        state: Any,
        force: bool = False,
        metadata: Optional[dict] = None,
    ) -> bool:
        """Persist ``state`` as ``step`` per policy; returns whether a save
        was enqueued (async) or durably committed (sync). The state is
        snapshotted to host memory before this returns, so callers may
        donate/overwrite the device buffers."""
        step = int(step)
        meta = dict(metadata or {})
        snapshot = _snapshot_to_host(state)
        if self.process_count == 1 and any(
            isinstance(payload, dict) for _, payload in snapshot
        ):
            raise RuntimeError(
                "state contains jax.Arrays spanning non-addressable devices "
                "but this CheckpointManager believes it is the only process. "
                "Construct it with process_index=jax.process_index(), "
                "process_count=jax.process_count() — the webhook's TPU env "
                "contract fills these automatically in notebook pods — so "
                "every host saves its own shards instead of attempting a "
                "cross-host gather."
            )
        # Remember the newest state even when the interval skips it: an
        # emergency save must flush what training last produced, not what
        # the cadence last chose to keep.
        self._pending = (step, snapshot, meta)
        # Orbax-compatible cadence: steps that are multiples of the
        # interval commit (plus the very first call, so short runs are
        # never checkpoint-less); everything else is interval-skipped.
        if (
            not force
            and self._last_saved_step is not None
            and step % self.save_interval_steps != 0
        ):
            return False
        self._last_saved_step = step
        if self._async:
            self._ensure_worker()
            self._queue.put((step, snapshot, meta))
            return True
        with self._lock:
            return self._write_step(step, snapshot, meta)

    def emergency_save(self, grace_s: Optional[float] = None) -> bool:
        """One final synchronous save inside a termination grace budget.
        (Traced as ``checkpoint.emergency_save`` — the grace-window span
        is how a preemption trace shows where the budget went.)"""
        with tracing.get_tracer("checkpoint").start_span(
            "checkpoint.emergency_save",
            **({"grace_s": grace_s} if grace_s is not None else {}),
        ) as span:
            ok = self._emergency_save(grace_s)
            span.set_attribute("committed", ok)
            return ok

    def _emergency_save(self, grace_s: Optional[float] = None) -> bool:
        """The emergency-save body (see ``emergency_save``).

        Returns True only if a new step was durably committed. Skips (and
        returns False) when there is nothing newer than the last committed
        step, or when ``grace_s`` minus the time spent draining in-flight
        saves is smaller than the last observed save duration — starting a
        save that SIGKILL will tear only wastes the budget.

        Every blocking step in here is time-bounded: the drain of
        in-flight async saves and the acquisition of the write lock both
        carry deadlines, because this runs on the exit path — possibly
        while the thread the signal interrupted still holds the queue
        mutex or the write lock. The pending snapshot supersedes anything
        still queued, so giving up on the drain loses nothing.
        """
        t0 = self._clock()
        if grace_s is None:
            drain_timeout = _DEFAULT_EMERGENCY_DRAIN_S
        else:
            reserve = (self._last_save_duration or 0.0) + 1.0
            drain_timeout = max(
                0.0, min(float(grace_s) - reserve, float(grace_s) / 2)
            )
        if not self.wait(timeout=drain_timeout):
            log.error(
                "emergency save: pending async saves did not drain within "
                "%.1fs; writing the newest snapshot anyway",
                drain_timeout,
            )
        pending = self._pending
        if pending is None:
            log.info("emergency save: no state has been handed to save()")
            return False
        step, snapshot, meta = pending
        if self._last_committed_step == step:
            log.info(
                "emergency save: step %d already durably committed; skipping",
                step,
            )
            return False
        if grace_s is not None:
            remaining = float(grace_s) - (self._clock() - t0)
            estimate = self._last_save_duration
            if remaining <= 0 or (estimate is not None and estimate > remaining):
                log.error(
                    "emergency save: skipping step %d — estimated save "
                    "duration %s exceeds remaining grace budget %.2fs",
                    step,
                    f"{estimate:.2f}s" if estimate is not None else "unknown",
                    max(0.0, remaining),
                )
                return False
        if grace_s is not None:
            lock_timeout = max(0.0, float(grace_s) - (self._clock() - t0))
        else:
            lock_timeout = _DEFAULT_EMERGENCY_DRAIN_S
        locked = self._lock.acquire(timeout=lock_timeout)
        if not locked:
            # The holder is frozen (likely the very thread this signal
            # interrupted). Writing anyway is safe: staging names are
            # uniquified under _seq_lock, and a later duplicate commit of
            # the same step surfaces as a contained OSError.
            log.error(
                "emergency save: write lock not acquired within %.1fs; "
                "writing without it",
                lock_timeout,
            )
        try:
            ok = self._write_step(step, snapshot, meta)
        finally:
            if locked:
                self._lock.release()
        if ok:
            self._last_saved_step = step
            counter = getattr(self.metrics, "checkpoint_emergency_total", None)
            if counter is not None:
                counter.inc()
            log.warning(
                "emergency save: committed step %d in %.2fs",
                step,
                self._clock() - t0,
            )
        return ok

    def _write_step(self, step: int, snapshot: list, meta: dict) -> bool:
        with tracing.get_tracer("checkpoint").start_span(
            "checkpoint.write", step=step,
        ) as span:
            ok = self._write_step_inner(step, snapshot, meta)
            span.set_attribute("committed", ok)
            return ok

    def _write_step_inner(
        self, step: int, snapshot: list, meta: dict
    ) -> bool:
        """The atomic commit protocol; returns whether ``step`` committed.
        OSError (disk full, quota, permissions) is contained — training
        must outlive a sick disk, and its staging dir is cleaned up.
        Everything else propagates and abandons the staging dir exactly
        as SIGKILL would: invisible to restore, evidence for debugging."""
        t0 = self._clock()
        final = self._root / str(step)
        with self._seq_lock:
            self._seq += 1
            staged = self._root / (
                f"{_TMP_PREFIX}{step}-{os.getpid()}-{self._seq}"
            )
        try:
            if staged.exists():
                shutil.rmtree(staged)
            staged.mkdir(parents=True)
            files = []
            for i, (path_str, payload) in enumerate(snapshot):
                if isinstance(payload, dict):  # this process's shards
                    for j, (index, arr) in enumerate(payload["shards"]):
                        name = f"{i:05d}.s{j}.bin"
                        data = arr.tobytes()
                        self.io.write_file(staged / name, data)
                        files.append({
                            "name": name,
                            "path": path_str,
                            "dtype": arr.dtype.name,
                            "shape": list(arr.shape),
                            "size": len(data),
                            "crc32": zlib.crc32(data) & 0xFFFFFFFF,
                            "shard": {
                                "index": [list(p) for p in index],
                                "global_shape": list(
                                    payload["global_shape"]
                                ),
                            },
                        })
                else:
                    name = f"{i:05d}.bin"
                    data = payload.tobytes()
                    self.io.write_file(staged / name, data)
                    files.append({
                        "name": name,
                        "path": path_str,
                        "dtype": payload.dtype.name,
                        "shape": list(payload.shape),
                        "size": len(data),
                        "crc32": zlib.crc32(data) & 0xFFFFFFFF,
                    })
            manifest = {
                "format": MANIFEST_FORMAT,
                "step": step,
                "metadata": meta,
                "process": {
                    "index": self.process_index,
                    "count": self.process_count,
                },
                "files": files,
            }
            # Manifest written LAST: its presence certifies every data file
            # above already hit the disk (write_file fsyncs each).
            self.io.write_file(
                staged / MANIFEST_NAME,
                json.dumps(manifest, sort_keys=True).encode(),
            )
            if final.exists():  # re-saving a step (re-run notebook cell)
                shutil.rmtree(final)
            self.io.commit(staged, final)
        except OSError as err:
            with self._seq_lock:
                self.last_save_error = err
                self.save_failures += 1
            log.error("checkpoint save of step %d failed: %s", step, err)
            shutil.rmtree(staged, ignore_errors=True)
            return False
        duration = self._clock() - t0
        with self._seq_lock:
            self._last_save_duration = duration
            self._last_committed_step = step
            self._last_commit_at = self._clock()
        hist = getattr(self.metrics, "checkpoint_save_seconds", None)
        if hist is not None:
            hist.observe(duration)
        self._prune()
        return True

    def _prune(self) -> None:
        for s in self._local_steps()[: -self.max_to_keep]:
            shutil.rmtree(self._root / str(s), ignore_errors=True)

    # -- async worker --------------------------------------------------------

    def _ensure_worker(self) -> None:
        if self._worker is not None and not self._worker.is_alive():
            # _drain survives failing saves, but belt and braces: a dead
            # worker must never turn save() into an enqueue-to-nowhere.
            log.error("checkpoint worker thread died; restarting it")
            self._worker = None
        if self._worker is None:
            if self._queue is None:
                self._queue = queue.Queue()
            self._worker = threading.Thread(
                target=self._drain, name="checkpoint-save", daemon=True
            )
            self._worker.start()

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            try:
                if item is None:
                    return
                step, snapshot, meta = item
                try:
                    with self._lock:
                        self._write_step(step, snapshot, meta)
                except BaseException as err:
                    # _write_step contains OSError itself; anything else
                    # (unserializable metadata, MemoryError) must not kill
                    # the worker and wedge every later wait()/close() in
                    # queue.join() — record it and keep draining.
                    with self._seq_lock:
                        self.last_save_error = err
                        self.save_failures += 1
                    log.exception(
                        "async checkpoint save of step %d failed", step
                    )
            finally:
                self._queue.task_done()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every enqueued async save has committed or failed;
        returns whether the queue fully drained. With a ``timeout`` the
        wait is bounded — including the queue-lock acquisition itself, so
        a caller on the signal path (which may have interrupted a thread
        inside the queue's non-reentrant mutex) cannot deadlock."""
        q = self._queue
        if q is None:
            return True
        worker = self._worker
        if worker is not None and not worker.is_alive() and q.unfinished_tasks:
            log.error(
                "checkpoint worker thread is dead with %d saves queued",
                q.unfinished_tasks,
            )
            return False
        if timeout is None:
            q.join()
            return True
        deadline = self._clock() + timeout
        if not q.all_tasks_done.acquire(timeout=timeout):
            return False
        try:
            while q.unfinished_tasks:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return False
                q.all_tasks_done.wait(remaining)
            return True
        finally:
            q.all_tasks_done.release()

    def close(self) -> None:
        self.wait()
        if self._worker is not None:
            if self._worker.is_alive():
                self._queue.put(None)
            self._worker.join()
            self._worker = None
            self._queue = None

    # -- restore -------------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        """Newest restorable step: manifest present locally AND (on a
        multi-host slice) in every other process's root. Cheap — full
        size/checksum validation happens at restore."""
        steps = self._committed_steps()
        return steps[-1] if steps else None

    def last_commit_age(self) -> float:
        """Seconds since THIS process last durably committed (or restored)
        a step, measured on the injected monotonic clock — immune to the
        wall-clock jumps that cluster preemptions love to coincide with.
        +inf when no commit has been observed this process lifetime (steps
        inherited on disk have only wall-time mtimes, whose age a
        monotonic clock cannot vouch for), so freshness-gated callers
        save rather than trust."""
        with self._seq_lock:
            last = self._last_commit_at
        if last is None:
            return float("inf")
        return max(0.0, self._clock() - last)

    def _local_steps(self) -> list:
        return sorted(
            int(p.name)
            for p in self._root.iterdir()
            if p.is_dir() and p.name.isdigit() and (p / MANIFEST_NAME).exists()
        )

    def _committed_steps(self) -> list:
        """Locally committed steps, intersected with every peer root on a
        multi-host slice — a step a dead host never committed is not a
        checkpoint, it is a torn save with better marketing."""
        steps = self._local_steps()
        if self.process_count == 1:
            return steps
        return [s for s in steps if self._peers_committed(s)]

    def _peers_committed(self, step: int) -> bool:
        return all(
            (self.directory / f"proc{j}" / str(step) / MANIFEST_NAME).exists()
            for j in range(self.process_count)
            if j != self.process_index
        )

    def restore_latest(self, template: Any) -> tuple:
        with tracing.get_tracer("checkpoint").start_span(
            "checkpoint.restore",
        ) as span:
            state, step = self._restore_latest(template)
            span.set_attribute("restored_step", step)
            return state, step

    def _restore_latest(self, template: Any) -> tuple:
        """(state, step) from the newest checkpoint that VALIDATES, or
        (template, None). Steps failing validation are quarantined as
        ``corrupt-<step>-*`` (never deleted: torn bytes are evidence) and
        the walk falls back to the next-newest step. The restored step's
        metadata lands in ``self.restored_metadata``.

        Multi-host: only steps every process committed are considered,
        and each process restores its own shards from its own root. A
        quarantine on one host removes the step from every later
        restore's intersection, so hosts that restore after the
        discovery agree on the fallback.
        """
        self.restored_metadata = {}
        candidates = sorted(
            (
                int(p.name)
                for p in self._root.iterdir()
                if p.is_dir() and p.name.isdigit()
            ),
            reverse=True,
        )
        for step in candidates:
            if self.process_count > 1 and not self._peers_committed(step):
                continue
            step_dir = self._root / str(step)
            try:
                arrays, meta = _load_validated(step_dir)
            except CorruptCheckpointError as err:
                self._quarantine(step_dir, step, err)
                continue
            state = _restore_into_template(template, arrays, step_dir)
            self.restored_metadata = meta
            with self._seq_lock:
                self._last_committed_step = step
                # A restore just validated these bytes, so "as fresh as a
                # commit made now" is the honest monotonic reading.
                self._last_commit_at = self._clock()
            return state, step
        return template, None

    def _quarantine(
        self, step_dir: Path, step: int, err: CorruptCheckpointError
    ) -> None:
        # The existence probe runs outside _seq_lock: the lock also guards
        # the save-outcome state, and this path runs during restore — it
        # must not stall a concurrent save's bookkeeping on disk stats.
        while True:
            with self._seq_lock:
                self._seq += 1
                dest = self._root / f"{CORRUPT_PREFIX}{step}-{self._seq}"
            if not dest.exists():
                break
        log.error(
            "checkpoint step %d failed validation (%s); quarantined as %s",
            step, err, dest.name,
        )
        # commit() (not a bare rename): quarantine is also a publication —
        # after a crash the torn step must be durably OUT of the restore
        # path, not resurrected by a lost rename.
        self.io.commit(step_dir, dest)
        counter = getattr(self.metrics, "checkpoint_corrupt_total", None)
        if counter is not None:
            counter.inc()


# -- serialization helpers ---------------------------------------------------


def _tree_util():
    import jax  # lazy: validation/repair tooling must not need a backend

    return jax.tree_util


def _snapshot_to_host(state: Any) -> list:
    """[(keypath_str, payload), ...] in tree-flatten order. Fully
    addressable leaves (numpy, single-host jax arrays, ml_dtypes views)
    become host np.ndarrays via np.asarray — the copy makes
    donation/overwrite safe. Non-fully-addressable jax.Arrays (multi-host
    shardings) are NEVER gathered: the payload is this process's
    addressable shards, ``{"global_shape": ..., "shards": [(index, np),
    ...]}``, deduped by index and sorted for deterministic manifests."""
    tu = _tree_util()
    leaves_with_paths, _ = tu.tree_flatten_with_path(state)
    return [
        (tu.keystr(path), _snapshot_leaf(leaf))
        for path, leaf in leaves_with_paths
    ]


def _snapshot_leaf(leaf: Any):
    if getattr(leaf, "is_fully_addressable", True) or not hasattr(
        leaf, "addressable_shards"
    ):
        return np.asarray(leaf)
    global_shape = tuple(int(d) for d in leaf.shape)
    shards: dict = {}
    for shard in leaf.addressable_shards:
        index = _normalize_index(shard.index, global_shape)
        if index not in shards:  # replicas on sibling local devices
            shards[index] = np.asarray(shard.data)
    return {"global_shape": global_shape, "shards": sorted(shards.items())}


def _normalize_index(index, global_shape) -> tuple:
    """A shard index (jax's tuple of slices) as hashable, JSON-able
    ``((start, stop), ...)`` pairs covering every dimension."""
    out = []
    for dim, sl in zip(global_shape, index):
        start, stop, step = sl.indices(dim)
        if step != 1:
            raise ValueError(f"non-contiguous shard slice {sl!r}")
        out.append((start, stop))
    return tuple(out)


def _resolve_dtype(name: str) -> np.dtype:
    """np.dtype for a manifest dtype name. ml_dtypes names (bfloat16,
    int4, the fp8 family) are not resolvable by numpy's string lookup, so
    fall back to the ml_dtypes attribute of the same name; a name neither
    knows makes the checkpoint unreadable — CorruptCheckpointError, so
    restore quarantines and falls back instead of crashing."""
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError, TypeError) as err:
        raise CorruptCheckpointError(
            f"unknown dtype {name!r}: {err}"
        ) from err


def _load_validated(step_dir: Path) -> tuple:
    """(arrays, metadata) for a committed step, re-verifying sizes and
    CRC32s against the manifest. Raises CorruptCheckpointError on ANY
    mismatch — a checkpoint is valid entirely or not at all. Shard
    entries of one leaf are grouped into a single
    ``{"global_shape", "dtype", "shards"}`` record."""
    manifest_path = step_dir / MANIFEST_NAME
    if not manifest_path.exists():
        raise CorruptCheckpointError("manifest missing")
    try:
        manifest = json.loads(manifest_path.read_text())
    except (OSError, ValueError) as err:
        raise CorruptCheckpointError(f"manifest unreadable: {err}") from err
    if manifest.get("format") != MANIFEST_FORMAT:
        raise CorruptCheckpointError(
            f"unknown manifest format {manifest.get('format')!r}"
        )
    arrays: list = []
    sharded: dict = {}
    for entry in manifest.get("files", []):
        fpath = step_dir / entry["name"]
        try:
            data = fpath.read_bytes()
        except OSError as err:
            raise CorruptCheckpointError(
                f"{entry['name']} unreadable: {err}"
            ) from err
        if len(data) != entry["size"]:
            raise CorruptCheckpointError(
                f"{entry['name']}: size {len(data)} != manifest {entry['size']}"
            )
        if (zlib.crc32(data) & 0xFFFFFFFF) != entry["crc32"]:
            raise CorruptCheckpointError(f"{entry['name']}: CRC32 mismatch")
        arr = np.frombuffer(data, dtype=_resolve_dtype(entry["dtype"]))
        try:
            arr = arr.reshape(entry["shape"])
        except ValueError as err:  # manifest shape/size disagree
            raise CorruptCheckpointError(
                f"{entry['name']}: {err}"
            ) from err
        shard = entry.get("shard")
        if shard is None:
            arrays.append((entry["path"], arr))
            continue
        rec = sharded.get(entry["path"])
        if rec is None:
            rec = {
                "global_shape": tuple(shard["global_shape"]),
                "dtype": arr.dtype,
                "shards": [],
            }
            sharded[entry["path"]] = rec
            arrays.append((entry["path"], rec))
        rec["shards"].append(
            (tuple((int(a), int(b)) for a, b in shard["index"]), arr)
        )
    return arrays, dict(manifest.get("metadata", {}))


def _restore_into_template(template: Any, arrays: list, step_dir: Path) -> Any:
    """Rebuild the state tree, placing each array per the template leaf's
    sharding. Structure mismatch is a caller error (wrong template), not
    corruption — it raises ValueError and quarantines nothing."""
    tu = _tree_util()
    leaves_with_paths, treedef = tu.tree_flatten_with_path(template)
    if len(leaves_with_paths) != len(arrays):
        raise ValueError(
            f"template has {len(leaves_with_paths)} leaves but checkpoint "
            f"{step_dir.name} stored {len(arrays)} — restoring into a "
            "different model/optimizer structure?"
        )
    placed = []
    for (path, leaf), (saved_path, value) in zip(leaves_with_paths, arrays):
        key = tu.keystr(path)
        if key != saved_path:
            raise ValueError(
                f"template leaf {key} does not match checkpoint leaf "
                f"{saved_path} in {step_dir.name}"
            )
        if isinstance(value, dict):  # saved as per-process shards
            placed.append(_assemble_sharded(leaf, value, key, step_dir))
        elif hasattr(leaf, "sharding"):
            import jax

            placed.append(jax.device_put(value, leaf.sharding))
        else:
            # frombuffer views are read-only; the restored state must be
            # as mutable as the state that was saved.
            placed.append(value.copy())
    return tu.tree_unflatten(treedef, placed)


def _assemble_sharded(leaf: Any, rec: dict, key: str, step_dir: Path) -> Any:
    """Rebuild a leaf saved as per-process shards. With a sharded template
    leaf the shards land directly on this process's devices
    (``jax.make_array_from_single_device_arrays`` — the exact inverse of
    the save, no host gather). A plain template leaf gets a dense
    np.ndarray, valid only when this process's shards cover the whole
    array (single-host validation tooling)."""
    global_shape = rec["global_shape"]
    shards = dict(rec["shards"])
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None and hasattr(
        sharding, "addressable_devices_indices_map"
    ):
        import jax

        per_device = []
        mapping = sharding.addressable_devices_indices_map(global_shape)
        for device, nd_index in mapping.items():
            index = _normalize_index(nd_index, global_shape)
            arr = shards.get(index)
            if arr is None:
                raise ValueError(
                    f"checkpoint {step_dir.name} leaf {key}: no saved shard "
                    f"for index {index} — sharding or process topology "
                    "changed since the save?"
                )
            per_device.append(jax.device_put(arr, device))
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, per_device
        )
    out = np.zeros(global_shape, dtype=rec["dtype"])
    seen = np.zeros(global_shape, dtype=bool)
    for index, arr in shards.items():
        region = tuple(slice(a, b) for a, b in index)
        out[region] = arr
        seen[region] = True
    if not seen.all():
        raise ValueError(
            f"checkpoint {step_dir.name} leaf {key}: this process's shards "
            "do not cover the whole array; restore into a template carrying "
            "the original sharding"
        )
    return out


# -- training loop -----------------------------------------------------------


def resume_start_batch(ckpt: CheckpointManager, restored_step=None) -> int:
    """The data-loader cursor to hand ``sharded_loader(start_batch=...)``
    after ``restore_latest``: the ``start_batch`` the restored step's save
    recorded, falling back to the restored step itself (the
    train_with_checkpointing convention is one batch per step)."""
    value = ckpt.restored_metadata.get("start_batch")
    if value is not None:
        return int(value)
    return int(restored_step or 0)


def train_with_checkpointing(
    step_fn,
    state: Any,
    batches,
    ckpt: CheckpointManager,
    start_step: int = 0,
) -> tuple:
    """Drive ``state, loss = step_fn(state, batch)`` over ``batches``,
    checkpointing per the manager's policy. Returns (state, losses).

    Resumable EXACTLY: each save carries ``{"start_batch": step}`` so a
    restored run knows how many batches the lost run consumed — feed
    ``resume_start_batch(ckpt, at)`` to ``sharded_loader(start_batch=...)``
    and pass ``start_step=at``; no batch is replayed or skipped.

    ``ckpt.wait()`` runs in a finally: an exception mid-loop (OOM, a NaN
    guard, KeyboardInterrupt) must not strand enqueued async saves, and an
    empty ``batches`` iterator is a no-op, not an IndexError.
    """
    losses = []
    step = start_step
    try:
        for batch in batches:
            state, loss = step_fn(state, batch)
            losses.append(loss)
            step += 1
            ckpt.save(step, state, metadata={"start_batch": step})
    finally:
        ckpt.wait()
    if losses:
        import jax

        jax.block_until_ready(losses[-1])
    return state, losses
