"""In-notebook checkpoint/resume: the other half of preemption recovery.

The control plane recovers the *slice* (SliceHealthReconciler recreates
preempted host pods), but in-notebook JAX state dies with the pod. This
module closes the loop: periodic sharded checkpoints via orbax, so a
notebook cell can resume training after a preemption with

    state, step = ckpt.restore_latest(state)

The reference has no counterpart — its checkpoint story is "all state lives
in CR annotations / PVCs" (SURVEY.md §5 checkpoint/resume); for an ML-facing
platform the training state is the state that matters, and a PVC mount is
exactly where these checkpoints land.

TPU notes: orbax writes each shard from its owning host (multi-host safe,
single-controller semantics via jax.distributed), and restore places shards
per the provided sharding tree — no host ever materializes the full model.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Optional

import jax


class CheckpointManager:
    """Thin policy wrapper over orbax CheckpointManager.

    - ``save(step, state)`` honors ``save_interval_steps`` (returns whether
      a save actually happened) and keeps ``max_to_keep`` checkpoints.
    - ``restore_latest(template)`` restores into the template's shardings
      (pass the freshly-sharded init state; arrays land where the mesh
      says, not on host 0).
    """

    def __init__(
        self,
        directory: str | Path,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=False,
            ),
        )

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        saved = self._mgr.save(
            step,
            args=self._ocp.args.StandardSave(state),
            force=force,
        )
        return bool(saved)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore_latest(self, template: Any) -> tuple[Any, Optional[int]]:
        """(state, step) from the newest checkpoint, or (template, None)."""
        step = self._mgr.latest_step()
        if step is None:
            return template, None
        restored = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(template)
        )
        return restored, step

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def train_with_checkpointing(
    step_fn,
    state: Any,
    batches,
    ckpt: CheckpointManager,
    start_step: int = 0,
) -> tuple[Any, list]:
    """Drive ``state, loss = step_fn(state, batch)`` over ``batches``,
    checkpointing per the manager's policy. Returns (state, losses).

    Resumable: pass ``start_step`` = the restored step (saves are labeled
    ``start_step + 1, start_step + 2, ...``) and the batch iterator
    fast-forwarded past the ``start_step`` batches already consumed.
    """
    losses = []
    step = start_step
    for batch in batches:
        state, loss = step_fn(state, batch)
        losses.append(loss)
        step += 1
        ckpt.save(step, state)
    ckpt.wait()
    jax.block_until_ready(losses[-1] if losses else state)
    return state, losses
