"""In-notebook TPU slice bootstrap.

The consumer of the control plane's environment contract (see
kubeflow_tpu.webhook.tpu_env): a user opens a notebook on a TPU slice and
runs

    from kubeflow_tpu.runtime import bootstrap
    rt = bootstrap()          # jax.distributed over the slice if multi-host
    mesh = rt.mesh(dp=2, tp=8)

and gets the whole slice visible (``jax.device_count() == slice chips``, the
north-star check) plus a ready device mesh. The controller made DNS/env
correct; libtpu/XLA own the ICI/DCN data plane (SURVEY.md §2.5) — this
module only wires identities together and never moves tensor bytes.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger(__name__)


def honor_jax_platforms_env(env: Optional[dict] = None) -> None:
    """Route ``JAX_PLATFORMS`` through jax.config before backend init.

    Some TPU platform plugins register themselves regardless of the
    env var (the env-var path is advisory), so ``JAX_PLATFORMS=cpu`` alone
    does not reliably keep a process off the TPU. Pushing the value into
    jax.config before the first backend touch does. No-op once a backend
    exists or when the var is unset.
    """
    env = dict(os.environ) if env is None else env
    want = env.get("JAX_PLATFORMS", "")
    if not want:
        return
    try:
        import jax

        jax.config.update("jax_platforms", want)
    except Exception:  # backend already initialized — leave it be
        log.debug("could not apply JAX_PLATFORMS=%s via jax.config", want)


@dataclass
class SliceRuntime:
    """Resolved view of this host's place in the slice (or multislice)."""

    worker_id: int  # slice-LOCAL worker id (libtpu's TPU_WORKER_ID)
    num_workers: int  # total jax processes across ALL slices
    worker_hostnames: list[str]
    coordinator_address: str  # "" on single-host slices
    accelerator_type: str
    topology: str
    # Multislice (MEGASCALE): which slice this host belongs to.
    slice_id: int = 0
    num_slices: int = 1
    hosts_per_slice: int = 1
    distributed_initialized: bool = False

    @property
    def is_multi_host(self) -> bool:
        return self.num_workers > 1

    @property
    def process_id(self) -> int:
        """Global jax.distributed process id: slices are laid out
        contiguously, so slice j's workers are [j*hosts, (j+1)*hosts)."""
        return self.slice_id * self.hosts_per_slice + self.worker_id

    @property
    def is_coordinator(self) -> bool:
        return self.slice_id == 0 and self.worker_id == 0

    # -- mesh helpers ------------------------------------------------------
    def mesh(self, **axis_sizes: int):
        """Build a jax.sharding.Mesh over the whole slice.

        Axis sizes must multiply to the global device count; a single axis
        of -1 is inferred. Example: ``rt.mesh(dp=2, tp=8)`` on 16 chips.
        """
        import jax
        import numpy as np
        from jax.sharding import Mesh

        devices = jax.devices()
        total = len(devices)
        names = list(axis_sizes.keys())
        sizes = list(axis_sizes.values())
        if sizes.count(-1) > 1:
            raise ValueError("at most one axis size may be -1")
        if -1 in sizes:
            known = 1
            for s in sizes:
                if s != -1:
                    known *= s
            if total % known != 0:
                raise ValueError(
                    f"cannot infer axis: {total} devices not divisible by {known}"
                )
            sizes[sizes.index(-1)] = total // known
        prod = 1
        for s in sizes:
            prod *= s
        if prod != total:
            raise ValueError(
                f"mesh axes {dict(zip(names, sizes))} multiply to {prod}, "
                f"but the slice has {total} devices"
            )
        mesh_devices = np.array(devices).reshape(sizes)
        return Mesh(mesh_devices, axis_names=tuple(names))


def runtime_from_env(env: Optional[dict] = None) -> SliceRuntime:
    """Parse the controller/webhook-injected environment into a
    SliceRuntime (multislice-aware: MEGASCALE_* + TPU_HOSTS_PER_SLICE)."""
    from kubeflow_tpu.webhook import tpu_env as contract

    env = dict(os.environ) if env is None else env
    hostnames_raw = env.get(contract.TPU_WORKER_HOSTNAMES, "")
    hostnames = [h for h in hostnames_raw.split(",") if h]
    hosts_per_slice = int(
        env.get(contract.TPU_HOSTS_PER_SLICE) or str(max(1, len(hostnames)))
    )
    num_slices = int(env.get(contract.MEGASCALE_NUM_SLICES, "1") or 1)
    num = int(
        env.get(contract.JAX_NUM_PROCESSES) or str(hosts_per_slice * num_slices)
    )
    return SliceRuntime(
        worker_id=int(env.get(contract.TPU_WORKER_ID, "0") or 0),
        num_workers=num,
        worker_hostnames=hostnames,
        coordinator_address=env.get(contract.JAX_COORDINATOR_ADDRESS, ""),
        accelerator_type=env.get(contract.TPU_ACCELERATOR_TYPE, ""),
        topology=env.get(contract.TPU_TOPOLOGY, ""),
        slice_id=int(env.get(contract.MEGASCALE_SLICE_ID, "0") or 0),
        num_slices=num_slices,
        hosts_per_slice=hosts_per_slice,
    )


def bootstrap(
    env: Optional[dict] = None,
    expected_devices: Optional[int] = None,
    initialize_distributed: bool = True,
) -> SliceRuntime:
    """Bring the slice up: jax.distributed over DCN when multi-host, then
    sanity-check the device count.

    Idempotent per process; safe to re-run in a notebook cell.
    """
    honor_jax_platforms_env(env)
    rt = runtime_from_env(env)
    if rt.is_multi_host and initialize_distributed:
        import jax

        try:
            jax.distributed.initialize(
                coordinator_address=rt.coordinator_address,
                num_processes=rt.num_workers,
                process_id=rt.process_id,
            )
            rt.distributed_initialized = True
        except RuntimeError as err:
            # Already initialized (re-run cell) — fine. jax raises
            # "distributed.initialize should only be called once"; older
            # versions said "already initialized".
            msg = str(err).lower()
            if "already" in msg or "only be called once" in msg:
                rt.distributed_initialized = True
            else:
                raise
    if expected_devices is not None:
        import jax

        actual = jax.device_count()
        if actual != expected_devices:
            raise RuntimeError(
                f"slice incomplete: expected {expected_devices} devices, "
                f"jax.device_count() == {actual}. A host may be missing "
                "(check Notebook status.tpu.readyHosts) or "
                "jax.distributed did not reach every worker."
            )
    maybe_start_profiler_server(env)
    return rt


_PROFILER_PORT: Optional[int] = None


def maybe_start_profiler_server(env: Optional[dict] = None) -> Optional[int]:
    """Start jax.profiler.start_server on KUBEFLOW_TPU_PROFILING_PORT (the
    webhook projects the tpu-profiling-port annotation into it; the
    controller surfaces worker-0's address as status.tpu.profilingServer).
    Idempotent per process — start_server raises if called twice, so the
    STARTED port is remembered and returned; asking for a different port
    after one is running raises instead of lying about where the server
    listens. Returns the listening port, or None when not configured."""
    global _PROFILER_PORT
    import os

    from kubeflow_tpu.api.annotations import (
        PROFILING_ENV_NAME,
        parse_profiling_port,
    )

    env = env if env is not None else dict(os.environ)
    value = env.get(PROFILING_ENV_NAME, "")
    if not value:
        return None
    port = parse_profiling_port(value)
    if port is None:
        raise ValueError(
            f"{PROFILING_ENV_NAME}={value!r}: not a port in 1024..65535"
        )
    if _PROFILER_PORT is not None:
        if _PROFILER_PORT != port:
            raise RuntimeError(
                f"profiler server already listens on {_PROFILER_PORT}; "
                f"cannot move it to {port} in this process"
            )
        return _PROFILER_PORT
    import jax

    jax.profiler.start_server(port)
    _PROFILER_PORT = port
    return port


# -- preemption-grace emergency checkpointing --------------------------------
#
# The other half of the webhook's checkpoint contract: the controller's
# escalation ladder (or GKE maintenance) kills a host with SIGTERM and waits
# terminationGracePeriodSeconds before SIGKILL. The webhook told us how much
# of that window is ours (TPU_CHECKPOINT_GRACE_S) and where checkpoints live
# (KUBEFLOW_TPU_CHECKPOINT_DIR); this wires a SIGTERM handler that spends
# the budget on ONE final synchronous save — or nothing, when a fresh save
# already exists or could not finish in time.


def checkpoint_dir_from_env(env: Optional[dict] = None) -> Optional[str]:
    """The webhook-projected checkpoint directory, or None off-platform."""
    from kubeflow_tpu.api.annotations import CHECKPOINT_DIR_ENV_NAME

    env = dict(os.environ) if env is None else env
    return env.get(CHECKPOINT_DIR_ENV_NAME) or None


def checkpoint_grace_from_env(env: Optional[dict] = None) -> Optional[int]:
    """The emergency-save grace budget in seconds, or None when the
    annotation was absent (same parser as admission: a value that would
    have been denied is treated as unset, never honored half-way)."""
    from kubeflow_tpu.api.annotations import (
        CHECKPOINT_GRACE_ENV_NAME,
        parse_checkpoint_grace,
    )

    env = dict(os.environ) if env is None else env
    value = env.get(CHECKPOINT_GRACE_ENV_NAME, "")
    return parse_checkpoint_grace(value) if value else None


def install_preemption_handler(
    ckpt,
    env: Optional[dict] = None,
    signum: Optional[int] = None,
):
    """Install a SIGTERM handler that runs ``ckpt.emergency_save`` with the
    webhook-injected grace budget, then chains to the previously-installed
    disposition (a notebook kernel's own SIGTERM handling must still run —
    we borrow the signal, we don't own it).

    Returns an ``uninstall()`` callable restoring the previous handler.
    Must run on the main thread (Python signal API restriction). The
    handler never touches the checkpoint queue in handler context: the
    save runs on a dedicated thread, because the signal may have
    interrupted the main thread anywhere — including inside
    ``queue.Queue.put``'s non-reentrant mutex, which a direct
    ``emergency_save`` would then deadlock on for the whole grace window.
    A worker thread contends on that lock like any other thread, bounded
    by the manager's drain budget.
    """
    import signal

    signum = signal.SIGTERM if signum is None else signum
    grace = checkpoint_grace_from_env(env)
    previous = signal.getsignal(signum)

    def handle(received_signum, frame):
        def run():
            try:
                ckpt.emergency_save(grace_s=grace)
            except Exception:
                # The exit path must keep exiting: a save bug cannot be
                # allowed to swallow the termination signal.
                log.exception("emergency checkpoint save failed")

        saver = threading.Thread(
            target=run, name="emergency-checkpoint", daemon=True
        )
        saver.start()
        saver.join(None if grace is None else float(grace) + 5.0)
        if saver.is_alive():
            log.error(
                "emergency checkpoint save still running past the grace "
                "budget; proceeding with termination"
            )
        if callable(previous):
            previous(received_signum, frame)
        elif previous is signal.SIG_DFL:
            signal.signal(received_signum, signal.SIG_DFL)
            signal.raise_signal(received_signum)
        # SIG_IGN: the process had opted out of dying on this signal;
        # honor that — we only added the save, not a new exit.

    signal.signal(signum, handle)
    log.info(
        "installed emergency-checkpoint handler (signal %d, grace %s)",
        signum, f"{grace}s" if grace is not None else "unbounded",
    )

    def uninstall():
        if signal.getsignal(signum) is handle:
            signal.signal(signum, previous)

    return uninstall
