"""The mutating admission webhook: every Notebook create/update flows
through here before either controller sees it.

Rebuild of the reference Handle pipeline (reference
components/odh-notebook-controller/controllers/notebook_mutating_webhook.go:
360-516) with the accelerator steps re-targeted to TPU (north star):

CREATE only:
  1. reconciliation lock injection (InjectReconciliationLock :113-122) —
     the pod must not start before the platform reconciler has produced
     routes/auth/NetPols; the lock is the stop annotation with a sentinel
     value, removed by the platform controller when ready.
CREATE|UPDATE:
  2. image resolution from ImageStreams (:865-972),
  3. **TPU env injection** — TPU_WORKER_ID/TPU_WORKER_HOSTNAMES/libtpu/JAX
     coordinator env (replaces the reference's CUDA-adjacent mutations),
  4. CA bundle mount, runtime-images mount, Elyra secret mount, Feast
     mount/unmount, MLflow env, cluster-proxy env,
  5. auth sidecar inject/remove by annotation,
  6. update-blocking: webhook-caused pod-template drift on a RUNNING
     notebook is reverted and surfaced as an update-pending annotation
     (maybeRestartRunningNotebook :522-581) — this matters more on TPU,
     where a surprise restart forfeits a whole slice.
"""

from __future__ import annotations

import copy
import logging
from dataclasses import dataclass
from typing import Optional

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.client import Client
from kubeflow_tpu.k8s.errors import NotFoundError, WebhookDeniedError
from kubeflow_tpu.k8s.fake import AdmissionRequest
from kubeflow_tpu.observability.tracing import get_tracer
from kubeflow_tpu.tpu.topology import InvalidTopologyError
from kubeflow_tpu.webhook import mounts
from kubeflow_tpu.webhook.auth_sidecar import (
    InvalidSidecarResources,
    inject_kube_rbac_proxy,
    remove_kube_rbac_proxy,
)
from kubeflow_tpu.webhook.diff import first_difference
from kubeflow_tpu.webhook.tpu_env import inject_tpu_env, remove_env, upsert_env

log = logging.getLogger(__name__)

_MLFLOW_ENV_NAMES = {
    "MLFLOW_TRACKING_URI",
    "MLFLOW_K8S_INTEGRATION",
    "MLFLOW_TRACKING_AUTH",
}
_PROXY_ENV_NAMES = {"HTTP_PROXY", "HTTPS_PROXY", "NO_PROXY"}


@dataclass
class WebhookConfig:
    controller_namespace: str = "opendatahub"
    rbac_proxy_image: str = "kube-rbac-proxy:latest"
    cluster_domain: str = "cluster.local"
    set_pipeline_secret: bool = False
    mlflow_enabled: bool = False
    inject_cluster_proxy_env: bool = False
    gateway_url: str = ""

    @classmethod
    def from_env(cls, env: dict) -> "WebhookConfig":
        return cls(
            controller_namespace=env.get("K8S_NAMESPACE", "opendatahub"),
            rbac_proxy_image=env.get("KUBE_RBAC_PROXY_IMAGE", "kube-rbac-proxy:latest"),
            cluster_domain=env.get("CLUSTER_DOMAIN", "cluster.local"),
            set_pipeline_secret=env.get("SET_PIPELINE_SECRET", "false").lower() == "true",
            mlflow_enabled=env.get("MLFLOW_ENABLED", "false").lower() == "true",
            inject_cluster_proxy_env=env.get("INJECT_CLUSTER_PROXY_ENV", "false").lower()
            == "true",
            gateway_url=env.get("GATEWAY_URL", ""),
        )


class NotebookMutatingWebhook:
    def __init__(self, client: Client, config: Optional[WebhookConfig] = None):
        self.client = client
        self.config = config or WebhookConfig()

    def register(self, cluster) -> None:
        cluster.register_mutating_webhook("Notebook", self.handle)

    # ------------------------------------------------------------------
    def handle(self, req: AdmissionRequest) -> dict:
        obj = req.object
        nb = Notebook(obj)
        # Root admission span (reference Handle :368-373: span per admission
        # with notebook/namespace/operation attributes; lazy tracer :74-76).
        with get_tracer("notebook-webhook").start_span(
            "mutate-notebook",
            notebook=nb.name,
            namespace=nb.namespace,
            operation=req.operation,
        ) as span:
            user_template = copy.deepcopy(
                obj.get("spec", {}).get("template", {}).get("spec", {})
            )

            if req.operation == "CREATE":
                self._inject_reconciliation_lock(nb)

            self._resolve_image_from_registry(nb, span)
            self._inject_tpu(nb)
            self._handle_quant_env(nb)
            self._handle_profiling_env(nb)
            self._handle_serving_env(nb)
            self._handle_checkpoint_env(nb)
            mounts.check_and_mount_ca_bundle(nb, self.client)
            mounts.mount_runtime_images(nb, self.client)
            if self.config.set_pipeline_secret:
                mounts.mount_elyra_secret(nb, self.client)
            mounts.sync_feast_mount(nb)
            if self.config.mlflow_enabled:
                self._handle_mlflow_env(nb)

            if nb.annotations.get(ann.INJECT_AUTH) == "true":
                try:
                    inject_kube_rbac_proxy(nb, self.config.rbac_proxy_image)
                except InvalidSidecarResources as err:
                    raise WebhookDeniedError(str(err)) from None
            else:
                remove_kube_rbac_proxy(nb)

            if self.config.inject_cluster_proxy_env:
                self._inject_cluster_proxy_env(nb)

            if req.operation == "UPDATE" and req.old_object is not None:
                # Child span (reference maybeRestartRunningNotebook :526).
                with get_tracer("notebook-webhook").start_span(
                    "maybe-restart-running-notebook", notebook=nb.name
                ):
                    self._maybe_block_running_update(nb, req.old_object, user_template)
            return obj

    # ------------------------------------------------------------------
    def _inject_reconciliation_lock(self, nb: Notebook) -> None:
        """Hold the pod down until the platform reconciler finishes
        (reference :113-122); never overwrite a user stop annotation."""
        if ann.STOP not in nb.annotations:
            nb.annotations[ann.STOP] = ann.RECONCILIATION_LOCK_VALUE

    def _inject_tpu(self, nb: Notebook) -> None:
        if nb.tpu is None:
            return
        try:
            topo = nb.tpu.slice_topology()
        except InvalidTopologyError:
            return  # validating webhook denies; controller reports otherwise
        inject_tpu_env(nb, topo, self.config.cluster_domain)
        obj_util.set_annotation(
            nb.obj, ann.TPU_RESOLVED_TOPOLOGY,
            f"{topo.accelerator_type}/{topo.topology_str}",
        )

    def _handle_quant_env(self, nb: Notebook) -> None:
        """Project the quantization annotation into the serving env
        (TPU-native runtime option; no reference counterpart). "bf16" and
        absence both mean full precision — the env var is removed so the
        in-notebook default (models.quant.quant_bits_from_env) applies."""
        container = nb.primary_container()
        if container is None:
            return
        value = nb.annotations.get(ann.TPU_QUANTIZATION, "")
        if value in ("", "bf16") or value not in ann.TPU_QUANTIZATION_VALUES:
            # Unknown values are denied by the validating webhook; never
            # propagate them into the pod regardless of webhook ordering.
            remove_env(container, {ann.QUANT_ENV_NAME})
            return
        upsert_env(container, [{"name": ann.QUANT_ENV_NAME, "value": value}])

    def _handle_port_env(self, nb: Notebook, annotation: str,
                         env_name: str) -> None:
        """Project a port annotation into its in-pod env: the profiling
        port (consumed by runtime.bootstrap's jax.profiler.start_server)
        and the serving port (bound by models/server.py
        serving_port_from_env) share one projection rule, so a fix to
        either applies to both. Invalid values are denied by the
        validating webhook; never propagate them here."""
        container = nb.primary_container()
        if container is None:
            return
        port = ann.parse_profiling_port(nb.annotations.get(annotation))
        if port is None:
            remove_env(container, {env_name})
            return
        upsert_env(container, [{"name": env_name, "value": str(port)}])

    def _handle_checkpoint_env(self, nb: Notebook) -> None:
        """The checkpoint durability contract (runtime/checkpoint.py).

        Every TPU notebook gets KUBEFLOW_TPU_CHECKPOINT_DIR (annotation
        override or the platform default) — runtime code never hardcodes
        the PVC mount path. The grace annotation additionally projects
        TPU_CHECKPOINT_GRACE_S for bootstrap's SIGTERM handler AND sizes
        terminationGracePeriodSeconds so the kubelet really waits that
        long (budget + flush margin); absent/invalid values remove the env
        and leave the user's grace period alone.
        """
        if nb.tpu is None:
            return
        container = nb.primary_container()
        if container is None:
            return
        ckpt_dir = (
            nb.annotations.get(ann.TPU_CHECKPOINT_DIR, "").strip()
            or ann.DEFAULT_CHECKPOINT_DIR
        )
        upsert_env(
            container,
            [{"name": ann.CHECKPOINT_DIR_ENV_NAME, "value": ckpt_dir}],
        )
        grace = ann.parse_checkpoint_grace(
            nb.annotations.get(ann.TPU_CHECKPOINT_GRACE)
        )
        if grace is None:
            remove_env(container, {ann.CHECKPOINT_GRACE_ENV_NAME})
            return
        upsert_env(
            container,
            [{"name": ann.CHECKPOINT_GRACE_ENV_NAME, "value": str(grace)}],
        )
        from kubeflow_tpu.deploy.manifests import termination_grace_seconds

        nb.pod_spec["terminationGracePeriodSeconds"] = (
            termination_grace_seconds(grace)
        )

    def _handle_profiling_env(self, nb: Notebook) -> None:
        self._handle_port_env(nb, ann.TPU_PROFILING_PORT,
                              ann.PROFILING_ENV_NAME)

    def _handle_serving_env(self, nb: Notebook) -> None:
        self._handle_port_env(nb, ann.TPU_SERVING_PORT,
                              ann.SERVING_ENV_NAME)

    def _resolve_image_from_registry(self, nb: Notebook, span=None) -> None:
        """Resolve "imagestream:tag" annotations to a digested image ref
        (reference SetContainerImageFromRegistry :865-972)."""
        selection = nb.annotations.get(ann.LAST_IMAGE_SELECTION, "")
        if ":" not in selection:
            return
        stream_name, tag = selection.rsplit(":", 1)
        namespace = nb.annotations.get(
            ann.WORKBENCH_IMAGE_NAMESPACE, self.config.controller_namespace
        )
        try:
            stream = self.client.get("ImageStream", stream_name, namespace)
        except NotFoundError:
            log.warning(
                "imagestream %s/%s not found for %s", namespace, stream_name, nb.name
            )
            # Span event (reference :912,:961 records imagestream-not-found).
            if span is not None:
                span.add_event(
                    "imagestream-not-found",
                    {"imagestream": f"{namespace}/{stream_name}"},
                )
            return
        image = _image_for_tag(stream, tag)
        if not image:
            return
        container = nb.primary_container()
        if container is not None and container.get("image") != image:
            container["image"] = image

    def _handle_mlflow_env(self, nb: Notebook) -> None:
        """MLflow env injection/removal by annotation (reference
        HandleMLflowEnvVars :287-322; URI from GATEWAY_URL or Gateway CR
        :107-142)."""
        container = nb.primary_container()
        if container is None:
            return
        instance = nb.annotations.get(ann.MLFLOW_INSTANCE)
        if not instance:
            remove_env(container, _MLFLOW_ENV_NAMES)
            return
        base = self.config.gateway_url or self._gateway_hostname()
        if not base:
            return
        upsert_env(
            container,
            [
                {"name": "MLFLOW_TRACKING_URI", "value": f"{base}/mlflow/{instance}"},
                {"name": "MLFLOW_K8S_INTEGRATION", "value": "true"},
                {"name": "MLFLOW_TRACKING_AUTH", "value": "oauth"},
            ],
        )

    def _gateway_hostname(self) -> str:
        try:
            gateway = self.client.get(
                "Gateway", "data-science-gateway", "openshift-ingress"
            )
        except NotFoundError:
            return ""
        for listener in gateway.get("spec", {}).get("listeners", []):
            hostname = listener.get("hostname")
            if hostname:
                return f"https://{hostname}"
        return ""

    def _inject_cluster_proxy_env(self, nb: Notebook) -> None:
        """Cluster-wide egress proxy env (reference :477-490)."""
        try:
            proxy = self.client.get("Proxy", "cluster")
        except NotFoundError:
            return
        spec = proxy.get("spec", {})
        entries = []
        if spec.get("httpProxy"):
            entries.append({"name": "HTTP_PROXY", "value": spec["httpProxy"]})
        if spec.get("httpsProxy"):
            entries.append({"name": "HTTPS_PROXY", "value": spec["httpsProxy"]})
        if spec.get("noProxy"):
            entries.append({"name": "NO_PROXY", "value": spec["noProxy"]})
        if not entries:
            return
        for container in nb.containers:
            upsert_env(container, entries)

    # ------------------------------------------------------------------
    def _maybe_block_running_update(
        self, nb: Notebook, old: dict, user_template: dict
    ) -> None:
        """Revert webhook-caused template drift on a running notebook
        (reference maybeRestartRunningNotebook :522-581).

        User-intended template changes pass through (the user accepted a
        restart); drift introduced by *this webhook's own mutations* (image
        re-resolution, cert rotation, ...) must not bounce a running slice.
        """
        old_template = (
            old.get("spec", {}).get("template", {}).get("spec", {})
        )
        mutated_template = nb.pod_spec
        if nb.stopped:
            # Stopped (or lock-held) notebooks restart on resume anyway;
            # let mutations land and clear any stale pending marker.
            obj_util.remove_annotation(nb.obj, ann.UPDATE_PENDING)
            return
        if mutated_template == old_template:
            obj_util.remove_annotation(nb.obj, ann.UPDATE_PENDING)
            return
        user_changed = user_template != old_template
        # An inject-auth flip is user intent too: the sidecar add/remove it
        # causes must roll out together with the platform reconciler's
        # SA/Service/ConfigMap changes, or the pod template would reference
        # deleted objects after the next restart.
        old_auth = old.get("metadata", {}).get("annotations", {}).get(ann.INJECT_AUTH)
        new_auth = nb.annotations.get(ann.INJECT_AUTH)
        if user_changed or old_auth != new_auth:
            obj_util.remove_annotation(nb.obj, ann.UPDATE_PENDING)
            return
        diff = first_difference(old_template, mutated_template) or "template changed"
        nb.obj["spec"]["template"]["spec"] = copy.deepcopy(old_template)
        obj_util.set_annotation(nb.obj, ann.UPDATE_PENDING, diff)


def _image_for_tag(stream: dict, tag: str) -> str:
    for entry in stream.get("status", {}).get("tags", []):
        if entry.get("tag") == tag:
            items = entry.get("items", [])
            if items:
                return items[0].get("dockerImageReference", "")
    for entry in stream.get("spec", {}).get("tags", []):
        if entry.get("name") == tag:
            return entry.get("from", {}).get("name", "")
    return ""
