"""Webhook-side mutations that mount platform resources into the notebook.

Counterparts of the reference webhook's mount pipeline (reference
components/odh-notebook-controller/controllers/notebook_mutating_webhook.go):

- CA trust bundle    — InjectCertConfig (:747-859): volume + SSL env block.
- Runtime images CM  — MountPipelineRuntimeImages (notebook_runtime.go:216-285).
- Elyra/DSPA secret  — MountElyraRuntimeConfigSecret (notebook_dspa_secret.go:403-477).
- Feast config       — label-gated mount/unmount (notebook_feast_config.go:25-146).

The corresponding *controller-side* sync (creating the ConfigMaps/Secrets in
the user namespace) lives in kubeflow_tpu.controller.platform; each mount
skips gracefully when the source object does not exist yet (the reference's
"optional CR → skip" pattern).
"""

from __future__ import annotations

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.names import (
    CA_BUNDLE_CONFIGMAP,
    ELYRA_SECRET_NAME,
    RUNTIME_IMAGES_CONFIGMAP,
)
from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.k8s.client import Client
from kubeflow_tpu.k8s.errors import NotFoundError
from kubeflow_tpu.webhook.tpu_env import remove_env, upsert_env

CA_MOUNT_PATH = "/etc/pki/tls/custom-certs"
CA_CERT_FILE = f"{CA_MOUNT_PATH}/ca-bundle.crt"

RUNTIME_IMAGES_MOUNT_PATH = "/opt/app-root/pipeline-runtimes"

ELYRA_MOUNT_PATH = "/opt/app-root/runtimes"

FEAST_MOUNT_PATH = "/opt/app-root/src/feast-config"

# Env vars pointed at the CA bundle (reference :747-859 sets the full set so
# pip/requests/git/SSL all trust the platform CA).
_CA_ENV_NAMES = {
    "PIP_CERT",
    "REQUESTS_CA_BUNDLE",
    "SSL_CERT_FILE",
    "GIT_SSL_CAINFO",
    "NODE_EXTRA_CA_CERTS",
}


def _mount_volume(nb: Notebook, volume: dict, mount: dict) -> bool:
    pod_spec = nb.pod_spec
    changed = False
    volumes = pod_spec.setdefault("volumes", [])
    existing = next(
        (i for i, v in enumerate(volumes) if v.get("name") == volume["name"]), None
    )
    if existing is None:
        volumes.append(volume)
        changed = True
    elif volumes[existing] != volume:
        volumes[existing] = volume
        changed = True
    container = nb.primary_container()
    if container is not None:
        mounts = container.setdefault("volumeMounts", [])
        existing = next(
            (i for i, m in enumerate(mounts) if m.get("name") == mount["name"]), None
        )
        if existing is None:
            mounts.append(mount)
            changed = True
        elif mounts[existing] != mount:
            mounts[existing] = mount
            changed = True
    return changed


def _unmount_volume(nb: Notebook, name: str) -> bool:
    pod_spec = nb.pod_spec
    changed = False
    volumes = pod_spec.get("volumes", [])
    kept = [v for v in volumes if v.get("name") != name]
    if len(kept) != len(volumes):
        pod_spec["volumes"] = kept
        changed = True
    container = nb.primary_container()
    if container is not None:
        mounts = container.get("volumeMounts", [])
        kept_m = [m for m in mounts if m.get("name") != name]
        if len(kept_m) != len(mounts):
            container["volumeMounts"] = kept_m
            changed = True
    return changed


# ---------------------------------------------------------------------------


def check_and_mount_ca_bundle(nb: Notebook, client: Client) -> bool:
    """Mount the namespace trust bundle if present (reference
    CheckAndMountCACertBundle :700-745); unmount + unset env when absent
    (UnsetNotebookCertConfig semantics, notebook_controller.go:668-733)."""
    try:
        cm = client.get("ConfigMap", CA_BUNDLE_CONFIGMAP, nb.namespace)
    except NotFoundError:
        changed = _unmount_volume(nb, "trusted-ca")
        container = nb.primary_container()
        if container is not None:
            changed |= remove_env(container, _CA_ENV_NAMES)
        return changed
    if not cm.get("data", {}).get("ca-bundle.crt"):
        return False
    changed = _mount_volume(
        nb,
        {
            "name": "trusted-ca",
            "configMap": {
                "name": CA_BUNDLE_CONFIGMAP,
                "items": [{"key": "ca-bundle.crt", "path": "ca-bundle.crt"}],
            },
        },
        {"name": "trusted-ca", "mountPath": CA_MOUNT_PATH, "readOnly": True},
    )
    container = nb.primary_container()
    if container is not None:
        changed |= upsert_env(
            container,
            [{"name": name, "value": CA_CERT_FILE} for name in sorted(_CA_ENV_NAMES)],
        )
    return changed


def mount_runtime_images(nb: Notebook, client: Client) -> bool:
    """Mount the synced runtime-images ConfigMap (reference :216-285)."""
    try:
        client.get("ConfigMap", RUNTIME_IMAGES_CONFIGMAP, nb.namespace)
    except NotFoundError:
        return _unmount_volume(nb, "runtime-images")
    return _mount_volume(
        nb,
        {"name": "runtime-images", "configMap": {"name": RUNTIME_IMAGES_CONFIGMAP}},
        {
            "name": "runtime-images",
            "mountPath": RUNTIME_IMAGES_MOUNT_PATH,
            "readOnly": True,
        },
    )


def mount_elyra_secret(nb: Notebook, client: Client) -> bool:
    """Mount the Elyra runtime config secret (reference :403-477)."""
    try:
        client.get("Secret", ELYRA_SECRET_NAME, nb.namespace)
    except NotFoundError:
        return _unmount_volume(nb, "elyra-dsp-config")
    return _mount_volume(
        nb,
        {"name": "elyra-dsp-config", "secret": {"secretName": ELYRA_SECRET_NAME}},
        {
            "name": "elyra-dsp-config",
            "mountPath": ELYRA_MOUNT_PATH,
            "readOnly": True,
        },
    )


def sync_feast_mount(nb: Notebook) -> bool:
    """Label-gated Feast config mount (reference notebook_feast_config.go:
    25-146 — webhook-only, the ConfigMap is user/operator-provided)."""
    enabled = (
        nb.obj.get("metadata", {}).get("labels", {}).get(ann.FEAST_INTEGRATION_LABEL)
        == "true"
    )
    volume_name = "feast-config"
    if not enabled:
        return _unmount_volume(nb, volume_name)
    return _mount_volume(
        nb,
        {"name": volume_name, "configMap": {"name": f"{nb.name}-feast-config"}},
        {"name": volume_name, "mountPath": FEAST_MOUNT_PATH, "readOnly": True},
    )
