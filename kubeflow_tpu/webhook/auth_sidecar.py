"""kube-rbac-proxy sidecar injection (auth mode).

Rebuild of the reference's InjectKubeRbacProxy
(reference components/odh-notebook-controller/controllers/
notebook_mutating_webhook.go:185-334): a TLS-terminating sidecar on port
8443 that authorizes each request via SubjectAccessReview (``get
notebooks.kubeflow.org/{name}``), with per-notebook ServiceAccount and
resource requests overridable through annotations
(parseAndValidateAuthSidecarResources :134-181).

On a TPU slice the sidecar rides **worker 0 only** in effect: the proxy
HTTPRoute targets the pod-0 Service, although the container is present on
every host pod (the template is shared — harmless, a few mCPU per host).
"""

from __future__ import annotations

import re
from typing import Optional

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.names import RBAC_PROXY_PORT
from kubeflow_tpu.api.notebook import Notebook

RBAC_PROXY_CONTAINER = "kube-rbac-proxy"

_QUANTITY_RE = re.compile(r"^\d+(\.\d+)?(m|k|Ki|Mi|Gi|Ti|M|G|T)?$")

_DEFAULT_RESOURCES = {
    "requests": {"cpu": "100m", "memory": "64Mi"},
    "limits": {"cpu": "100m", "memory": "64Mi"},
}


class InvalidSidecarResources(ValueError):
    pass


def parse_sidecar_resources(nb: Notebook) -> dict:
    """Resource overrides from annotations, validated (reference :134-181)."""
    resources = {
        "requests": dict(_DEFAULT_RESOURCES["requests"]),
        "limits": dict(_DEFAULT_RESOURCES["limits"]),
    }
    mapping = {
        ann.AUTH_SIDECAR_CPU_REQUEST: ("requests", "cpu"),
        ann.AUTH_SIDECAR_CPU_LIMIT: ("limits", "cpu"),
        ann.AUTH_SIDECAR_MEMORY_REQUEST: ("requests", "memory"),
        ann.AUTH_SIDECAR_MEMORY_LIMIT: ("limits", "memory"),
    }
    annotations = nb.obj.get("metadata", {}).get("annotations", {})
    for key, (section, resource) in mapping.items():
        value = annotations.get(key)
        if value is None:
            continue
        if not _QUANTITY_RE.match(value):
            raise InvalidSidecarResources(
                f"annotation {key}={value!r} is not a valid quantity"
            )
        resources[section][resource] = value
    return resources


def service_account_name(notebook_name: str) -> str:
    return f"{notebook_name}-auth-proxy"


def rbac_config_map_name(notebook_name: str) -> str:
    return f"{notebook_name}-kube-rbac-proxy-config"


def tls_secret_name(notebook_name: str) -> str:
    return f"{notebook_name}-tls"


def inject_kube_rbac_proxy(nb: Notebook, proxy_image: str) -> bool:
    """Add/refresh the sidecar, its volumes, and the dedicated SA."""
    resources = parse_sidecar_resources(nb)
    sidecar = {
        "name": RBAC_PROXY_CONTAINER,
        "image": proxy_image,
        "args": [
            f"--secure-listen-address=0.0.0.0:{RBAC_PROXY_PORT}",
            "--upstream=http://127.0.0.1:8888/",
            f"--config-file=/etc/kube-rbac-proxy/config-file.yaml",
            "--tls-cert-file=/etc/tls/private/tls.crt",
            "--tls-private-key-file=/etc/tls/private/tls.key",
        ],
        "ports": [
            {"containerPort": RBAC_PROXY_PORT, "name": "https", "protocol": "TCP"}
        ],
        "resources": resources,
        "livenessProbe": _probe(),
        "readinessProbe": _probe(),
        "volumeMounts": [
            {"name": "kube-rbac-proxy-config", "mountPath": "/etc/kube-rbac-proxy"},
            {"name": "kube-rbac-proxy-tls", "mountPath": "/etc/tls/private"},
        ],
    }
    pod_spec = nb.pod_spec
    changed = False

    containers = pod_spec.setdefault("containers", [])
    existing = next(
        (i for i, c in enumerate(containers) if c.get("name") == RBAC_PROXY_CONTAINER),
        None,
    )
    if existing is None:
        containers.append(sidecar)
        changed = True
    elif containers[existing] != sidecar:
        containers[existing] = sidecar
        changed = True

    volumes = pod_spec.setdefault("volumes", [])
    for vol in (
        {
            "name": "kube-rbac-proxy-config",
            "configMap": {"name": rbac_config_map_name(nb.name)},
        },
        {
            "name": "kube-rbac-proxy-tls",
            "secret": {"secretName": tls_secret_name(nb.name)},
        },
    ):
        if not any(v.get("name") == vol["name"] for v in volumes):
            volumes.append(vol)
            changed = True

    # Dedicated ServiceAccount so the SubjectAccessReview delegation chain
    # is per-notebook (reference :332).
    sa = service_account_name(nb.name)
    if pod_spec.get("serviceAccountName") != sa:
        pod_spec["serviceAccountName"] = sa
        changed = True
    return changed


def remove_kube_rbac_proxy(nb: Notebook) -> bool:
    """Strip the sidecar when auth is turned off (mode switching)."""
    pod_spec = nb.pod_spec
    changed = False
    containers = pod_spec.get("containers", [])
    kept = [c for c in containers if c.get("name") != RBAC_PROXY_CONTAINER]
    if len(kept) != len(containers):
        pod_spec["containers"] = kept
        changed = True
    volumes = pod_spec.get("volumes", [])
    kept_v = [
        v
        for v in volumes
        if v.get("name") not in ("kube-rbac-proxy-config", "kube-rbac-proxy-tls")
    ]
    if len(kept_v) != len(volumes):
        pod_spec["volumes"] = kept_v
        changed = True
    if pod_spec.get("serviceAccountName") == service_account_name(nb.name):
        del pod_spec["serviceAccountName"]
        changed = True
    return changed


def _probe() -> dict:
    return {
        "httpGet": {"path": "/healthz", "port": RBAC_PROXY_PORT, "scheme": "HTTPS"},
        "initialDelaySeconds": 5,
        "periodSeconds": 10,
    }
