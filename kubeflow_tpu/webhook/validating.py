"""Validating admission webhook.

Extends the reference's validator (reference
components/odh-notebook-controller/controllers/notebook_validating_webhook.go:
31-100 — denies MLflow-annotation removal on running notebooks) with the
TPU-native invariants from SURVEY.md §7 step 3:

- topology/accelerator changes on a RUNNING slice are denied (the slice
  would have to be torn down; the user must stop the notebook first),
- structurally invalid TPU specs are denied at admission, before any
  object lands (better UX than an event after the fact).
"""

from __future__ import annotations

from typing import Optional

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.k8s.client import Client
from kubeflow_tpu.k8s.errors import WebhookDeniedError
from kubeflow_tpu.k8s.fake import AdmissionRequest
from kubeflow_tpu.tpu.topology import InvalidTopologyError


class NotebookValidatingWebhook:
    def __init__(self, client: Optional[Client] = None):
        self.client = client

    def register(self, cluster) -> None:
        cluster.register_validating_webhook("Notebook", self.handle)

    def handle(self, req: AdmissionRequest) -> None:
        nb = Notebook(req.object)

        if nb.tpu is not None:
            try:
                nb.tpu.slice_topology()
            except InvalidTopologyError as err:
                raise WebhookDeniedError(f"invalid spec.tpu: {err}") from None

        quant = nb.annotations.get(ann.TPU_QUANTIZATION)
        if quant and quant not in ann.TPU_QUANTIZATION_VALUES:
            raise WebhookDeniedError(
                f"annotation {ann.TPU_QUANTIZATION}: unknown value {quant!r} "
                f"(want one of {', '.join(ann.TPU_QUANTIZATION_VALUES)})"
            )

        prof = nb.annotations.get(ann.TPU_PROFILING_PORT)
        if prof is not None:
            why = ann.profiling_port_error(prof)
            if why is not None:
                raise WebhookDeniedError(
                    f"annotation {ann.TPU_PROFILING_PORT}: {why}"
                )

        serving = nb.annotations.get(ann.TPU_SERVING_PORT)
        if serving is not None:
            why = ann.profiling_port_error(serving)  # same port rules
            if why is not None:
                raise WebhookDeniedError(
                    f"annotation {ann.TPU_SERVING_PORT}: {why}"
                )
            if prof is not None and (
                ann.parse_profiling_port(serving)
                == ann.parse_profiling_port(prof)
            ):
                raise WebhookDeniedError(
                    f"annotations {ann.TPU_SERVING_PORT} and "
                    f"{ann.TPU_PROFILING_PORT} claim the same port "
                    f"{serving} — two servers cannot bind it"
                )

        if req.operation != "UPDATE" or req.old_object is None:
            return
        old = Notebook(req.old_object)
        running = not old.stopped

        if running and old.tpu != nb.tpu:
            raise WebhookDeniedError(
                "spec.tpu cannot change while the notebook is running: changing "
                f"{old.tpu} -> {nb.tpu} would tear down the slice. "
                f"Stop the notebook (annotation {ann.STOP!r}) first."
            )

        # Reference rule: MLflow integration cannot be silently detached
        # from a running notebook (validateMLflowAnnotationRemoval :79-100).
        old_mlflow = old.obj.get("metadata", {}).get("annotations", {}).get(
            ann.MLFLOW_INSTANCE
        )
        new_mlflow = req.object.get("metadata", {}).get("annotations", {}).get(
            ann.MLFLOW_INSTANCE
        )
        if running and old_mlflow and not new_mlflow:
            raise WebhookDeniedError(
                f"annotation {ann.MLFLOW_INSTANCE} cannot be removed while the "
                "notebook is running; stop the notebook first"
            )
