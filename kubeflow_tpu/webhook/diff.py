"""First-difference reporting for update-blocking diagnostics.

Counterpart of the reference's go-cmp ``FirstDifferenceReporter``
(reference notebook_mutating_webhook.go:602-646, including its panic guards)
used to annotate *why* an update is pending on a running notebook.
"""

from __future__ import annotations

from typing import Any, Optional


def first_difference(a: Any, b: Any, path: str = "") -> Optional[str]:
    """Human-readable path + values of the first difference, or None."""
    if type(a) is not type(b):
        return f"{path or '.'}: type {type(a).__name__} != {type(b).__name__}"
    if isinstance(a, dict):
        for key in sorted(set(a) | set(b)):
            sub_path = f"{path}.{key}" if path else key
            if key not in a:
                return f"{sub_path}: added {_short(b[key])}"
            if key not in b:
                return f"{sub_path}: removed {_short(a[key])}"
            diff = first_difference(a[key], b[key], sub_path)
            if diff:
                return diff
        return None
    if isinstance(a, list):
        for i, (x, y) in enumerate(zip(a, b)):
            diff = first_difference(x, y, f"{path}[{i}]")
            if diff:
                return diff
        if len(a) != len(b):
            return f"{path}: length {len(a)} != {len(b)}"
        return None
    if a != b:
        return f"{path or '.'}: {_short(a)} != {_short(b)}"
    return None


def _short(value: Any, limit: int = 64) -> str:
    text = repr(value)
    return text if len(text) <= limit else text[: limit - 3] + "..."
