"""TPU runtime environment injection.

The TPU-native counterpart of the reference webhook's accelerator-adjacent
env mutations (the reference injects nothing TPU-aware; its webhook mutates
images/certs/sidecars only — SURVEY.md §2.2). Per the north star, this is
where ``TPU_WORKER_HOSTNAMES`` / ``TPU_WORKER_ID`` / libtpu env get injected
*instead of* CUDA env and GPU tolerations.

Contract consumed by kubeflow_tpu.runtime.bootstrap inside the notebook:

- ``TPU_WORKER_ID``       — this host's index, from the indexed-StatefulSet
  pod-index label via the downward API (stable across pod restarts).
- ``TPU_WORKER_HOSTNAMES``— comma-separated stable DNS of every slice host.
- ``TPU_ACCELERATOR_TYPE``/``TPU_TOPOLOGY`` — slice shape for libtpu.
- ``TPU_CHIPS_PER_HOST_BOUNDS``/``TPU_HOST_BOUNDS`` — libtpu grid bounds.
- ``JAX_COORDINATOR_ADDRESS`` — worker 0's DNS:port for
  jax.distributed.initialize over DCN.
- ``JAX_NUM_PROCESSES``   — host count (jax.distributed num_processes).
"""

from __future__ import annotations

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.names import JAX_COORDINATOR_PORT
from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.tpu.topology import SliceTopology

POD_INDEX_LABEL = "apps.kubernetes.io/pod-index"

# -- the environment contract ------------------------------------------------
#
# THE single spelling site for every TPU_* / JAX_* / MEGASCALE_* env var the
# platform produces. Producers (this module, the controller's multislice
# overrides, the webhook's annotation projections) and consumers
# (runtime/bootstrap, models, ops) import these names; kftpu-lint's
# env-contract rules flag any read of a TPU_*/JAX_* var that is not a key of
# ENV_CONTRACT, and any re-typed string literal outside this module and
# kubeflow_tpu/api/annotations.py.

TPU_WORKER_ID = "TPU_WORKER_ID"
TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
TPU_ACCELERATOR_TYPE = "TPU_ACCELERATOR_TYPE"
TPU_TOPOLOGY = "TPU_TOPOLOGY"
TPU_CHIPS_PER_HOST_BOUNDS = "TPU_CHIPS_PER_HOST_BOUNDS"
TPU_HOST_BOUNDS = "TPU_HOST_BOUNDS"
TPU_RUNTIME_VERSION = "TPU_RUNTIME_VERSION"
TPU_HOSTS_PER_SLICE = "TPU_HOSTS_PER_SLICE"
JAX_COORDINATOR_ADDRESS = "JAX_COORDINATOR_ADDRESS"
JAX_NUM_PROCESSES = "JAX_NUM_PROCESSES"
MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"
MEGASCALE_COORDINATOR_ADDRESS = "MEGASCALE_COORDINATOR_ADDRESS"
# Serving-engine tuning knobs (models/server.py serve_from_env): ragged
# mixed prefill/decode batching and its per-step token budget.
KUBEFLOW_TPU_SERVING_RAGGED = "KUBEFLOW_TPU_SERVING_RAGGED"
KUBEFLOW_TPU_RAGGED_TOKEN_BUDGET = "KUBEFLOW_TPU_RAGGED_TOKEN_BUDGET"
# Fleet serving gateway (models/gateway.py gateway_from_env): the HTTP
# front door over N InferenceServer replicas with consistent-hash
# prefix-affinity routing.
KUBEFLOW_TPU_GATEWAY_PORT = "KUBEFLOW_TPU_GATEWAY_PORT"
KUBEFLOW_TPU_GATEWAY_REPLICAS = "KUBEFLOW_TPU_GATEWAY_REPLICAS"
KUBEFLOW_TPU_GATEWAY_AFFINITY = "KUBEFLOW_TPU_GATEWAY_AFFINITY"
KUBEFLOW_TPU_GATEWAY_HASH_SEED = "KUBEFLOW_TPU_GATEWAY_HASH_SEED"
KUBEFLOW_TPU_GATEWAY_REROUTE_BUDGET = "KUBEFLOW_TPU_GATEWAY_REROUTE_BUDGET"
# Disaggregated prefill/decode serving (models/gateway.py tier routing +
# models/server.py tier_role_from_env): tier membership and the
# prefill→decode paged-KV transfer hop's limits.
KUBEFLOW_TPU_GATEWAY_TIER_MODE = "KUBEFLOW_TPU_GATEWAY_TIER_MODE"
KUBEFLOW_TPU_GATEWAY_TIER_PREFILL = "KUBEFLOW_TPU_GATEWAY_TIER_PREFILL"
KUBEFLOW_TPU_GATEWAY_TIER_DECODE = "KUBEFLOW_TPU_GATEWAY_TIER_DECODE"
KUBEFLOW_TPU_GATEWAY_TIER_ROLE = "KUBEFLOW_TPU_GATEWAY_TIER_ROLE"
KUBEFLOW_TPU_KV_TRANSFER_TIMEOUT_S = "KUBEFLOW_TPU_KV_TRANSFER_TIMEOUT_S"
KUBEFLOW_TPU_KV_TRANSFER_MAX_BYTES = "KUBEFLOW_TPU_KV_TRANSFER_MAX_BYTES"
# Fleet KV tier (models/gateway.py peer prefix fetch): on a local prefix
# miss the gateway probes ring successors for the chain and imports it
# instead of re-prefilling. Inert unless FANOUT is set.
KUBEFLOW_TPU_KV_PEER_FANOUT = "KUBEFLOW_TPU_KV_PEER_FANOUT"
KUBEFLOW_TPU_KV_PEER_TIMEOUT_S = "KUBEFLOW_TPU_KV_PEER_TIMEOUT_S"
KUBEFLOW_TPU_KV_PEER_MAX_BYTES = "KUBEFLOW_TPU_KV_PEER_MAX_BYTES"
# HBM economy (models/server.py kv_pool_from_env → PagedBatcher): KV
# quantization bits, HBM-fraction pool sizing, and the host-RAM swap
# tier's byte budget — a replica runs a quantized, HBM-sized,
# swap-enabled pool purely from env.
KUBEFLOW_TPU_KV_BITS = "KUBEFLOW_TPU_KV_BITS"
KUBEFLOW_TPU_HBM_FRACTION = "KUBEFLOW_TPU_HBM_FRACTION"
KUBEFLOW_TPU_KV_SWAP_BYTES = "KUBEFLOW_TPU_KV_SWAP_BYTES"
# Speculative decoding + multi-LoRA serving (models/server.py
# spec_from_env / lora_cache_from_env → SpeculativePagedBatcher /
# MultiLoraPagedBatcher): draft length, acceptance-adaptive draft
# shrink/grow, and the per-replica hot-adapter cache bound.
KUBEFLOW_TPU_SPEC_DRAFT_LEN = "KUBEFLOW_TPU_SPEC_DRAFT_LEN"
KUBEFLOW_TPU_SPEC_ADAPTIVE = "KUBEFLOW_TPU_SPEC_ADAPTIVE"
KUBEFLOW_TPU_LORA_CACHE_SLOTS = "KUBEFLOW_TPU_LORA_CACHE_SLOTS"
# Tensor-parallel serving replicas (models/server.py serving_tp_from_env
# → models/tp_serving.py serving_plan): the replica's engine spans a
# tp-degree mesh — weights model-sharded, paged KV head-sharded.
KUBEFLOW_TPU_SERVING_TP = "KUBEFLOW_TPU_SERVING_TP"
# Persistent JAX compilation cache (bench.py capture windows; any runtime
# entrypoint may opt in): compiled executables survive process restarts.
KUBEFLOW_TPU_COMPILE_CACHE_DIR = "KUBEFLOW_TPU_COMPILE_CACHE_DIR"
# Request tracing (observability/tracing.py configure_from_env): setting any
# of these switches the process from the no-op provider to a recording one.
KUBEFLOW_TPU_TRACE_EXPORT = "KUBEFLOW_TPU_TRACE_EXPORT"
KUBEFLOW_TPU_TRACE_SAMPLE = "KUBEFLOW_TPU_TRACE_SAMPLE"
KUBEFLOW_TPU_TRACE_RING = "KUBEFLOW_TPU_TRACE_RING"
# Fleet telemetry plane (observability/signals.py signals_from_env): the
# gateway's windowed-signal aggregator behind /debug/signals; a hot-path
# no-op unless SIGNALS_ENABLE opts in.
KUBEFLOW_TPU_SIGNALS_ENABLE = "KUBEFLOW_TPU_SIGNALS_ENABLE"
KUBEFLOW_TPU_SIGNALS_WINDOW_S = "KUBEFLOW_TPU_SIGNALS_WINDOW_S"
KUBEFLOW_TPU_SIGNALS_WINDOWS = "KUBEFLOW_TPU_SIGNALS_WINDOWS"
KUBEFLOW_TPU_SIGNALS_TENANTS = "KUBEFLOW_TPU_SIGNALS_TENANTS"
# SLO burn-rate engine (observability/slo.py slo_from_env): objective
# thresholds and burn alert lines over the telemetry plane's signals.
KUBEFLOW_TPU_SLO_TTFT_P95_MS = "KUBEFLOW_TPU_SLO_TTFT_P95_MS"
KUBEFLOW_TPU_SLO_INTER_TOKEN_P95_MS = "KUBEFLOW_TPU_SLO_INTER_TOKEN_P95_MS"
KUBEFLOW_TPU_SLO_QUEUE_WAIT_P95_MS = "KUBEFLOW_TPU_SLO_QUEUE_WAIT_P95_MS"
KUBEFLOW_TPU_SLO_ERROR_BUDGET = "KUBEFLOW_TPU_SLO_ERROR_BUDGET"
KUBEFLOW_TPU_SLO_FAST_BURN = "KUBEFLOW_TPU_SLO_FAST_BURN"
KUBEFLOW_TPU_SLO_SLOW_BURN = "KUBEFLOW_TPU_SLO_SLOW_BURN"
# Stall->profile capture (observability/flight.py stall_profiler_from_env):
# setting the dir arms a bounded jax.profiler capture on engine stalls.
KUBEFLOW_TPU_STALL_PROFILE_DIR = "KUBEFLOW_TPU_STALL_PROFILE_DIR"
KUBEFLOW_TPU_STALL_PROFILE_COOLDOWN_S = "KUBEFLOW_TPU_STALL_PROFILE_COOLDOWN_S"
KUBEFLOW_TPU_STALL_PROFILE_SECONDS = "KUBEFLOW_TPU_STALL_PROFILE_SECONDS"
# Fleet autoscaler (models/autoscaler.py autoscaler_from_env): the
# signals→slices control loop on the gateway; inert unless
# AUTOSCALE_ENABLE opts in.
KUBEFLOW_TPU_AUTOSCALE_ENABLE = "KUBEFLOW_TPU_AUTOSCALE_ENABLE"
KUBEFLOW_TPU_AUTOSCALE_MIN_REPLICAS = "KUBEFLOW_TPU_AUTOSCALE_MIN_REPLICAS"
KUBEFLOW_TPU_AUTOSCALE_MAX_REPLICAS = "KUBEFLOW_TPU_AUTOSCALE_MAX_REPLICAS"
KUBEFLOW_TPU_AUTOSCALE_UP_COOLDOWN_S = "KUBEFLOW_TPU_AUTOSCALE_UP_COOLDOWN_S"
KUBEFLOW_TPU_AUTOSCALE_DOWN_COOLDOWN_S = (
    "KUBEFLOW_TPU_AUTOSCALE_DOWN_COOLDOWN_S"
)
KUBEFLOW_TPU_AUTOSCALE_MAX_ACTIONS = "KUBEFLOW_TPU_AUTOSCALE_MAX_ACTIONS"
KUBEFLOW_TPU_AUTOSCALE_WINDOW_S = "KUBEFLOW_TPU_AUTOSCALE_WINDOW_S"
KUBEFLOW_TPU_AUTOSCALE_DRAIN_BUDGET_S = (
    "KUBEFLOW_TPU_AUTOSCALE_DRAIN_BUDGET_S"
)
KUBEFLOW_TPU_AUTOSCALE_STALE_AFTER_S = "KUBEFLOW_TPU_AUTOSCALE_STALE_AFTER_S"
# Live slice migration (runtime/migration.py migration_from_env): per-step
# budgets for the save → warm-claim → restore → flip pipeline; inert unless
# MIGRATE_ENABLE opts in.
KUBEFLOW_TPU_MIGRATE_ENABLE = "KUBEFLOW_TPU_MIGRATE_ENABLE"
KUBEFLOW_TPU_MIGRATE_SAVE_BUDGET_S = "KUBEFLOW_TPU_MIGRATE_SAVE_BUDGET_S"
KUBEFLOW_TPU_MIGRATE_CLAIM_BUDGET_S = "KUBEFLOW_TPU_MIGRATE_CLAIM_BUDGET_S"
KUBEFLOW_TPU_MIGRATE_RESTORE_BUDGET_S = (
    "KUBEFLOW_TPU_MIGRATE_RESTORE_BUDGET_S"
)
KUBEFLOW_TPU_MIGRATE_FLIP_BUDGET_S = "KUBEFLOW_TPU_MIGRATE_FLIP_BUDGET_S"
KUBEFLOW_TPU_MIGRATE_FRESH_WITHIN_S = "KUBEFLOW_TPU_MIGRATE_FRESH_WITHIN_S"

# name -> who produces it and from what. Annotation-projected env names are
# defined next to their annotations in kubeflow_tpu/api/annotations.py and
# joined into the contract here, so there is exactly one table that answers
# "where does this variable come from".
ENV_CONTRACT: dict = {
    TPU_WORKER_ID: "webhook inject_tpu_env: pod-index label via downward API",
    TPU_WORKER_HOSTNAMES: "webhook inject_tpu_env (this slice's hosts; "
    "controller _apply_multislice_env overrides per slice)",
    TPU_ACCELERATOR_TYPE: "webhook inject_tpu_env: spec.tpu.accelerator",
    TPU_TOPOLOGY: "webhook inject_tpu_env: spec.tpu.topology",
    TPU_CHIPS_PER_HOST_BOUNDS: "webhook inject_tpu_env: libtpu grid bounds",
    TPU_HOST_BOUNDS: "webhook inject_tpu_env: libtpu grid bounds",
    TPU_RUNTIME_VERSION: "webhook inject_tpu_env: spec.tpu.runtimeVersion",
    TPU_HOSTS_PER_SLICE: "controller _apply_multislice_env: hosts per slice",
    JAX_COORDINATOR_ADDRESS: "webhook inject_tpu_env (multi-host only); "
    "controller _apply_multislice_env overrides for multislice",
    JAX_NUM_PROCESSES: "webhook inject_tpu_env (multi-host only); "
    "controller _apply_multislice_env overrides for multislice",
    MEGASCALE_NUM_SLICES: "controller _apply_multislice_env",
    MEGASCALE_SLICE_ID: "controller _apply_multislice_env",
    MEGASCALE_COORDINATOR_ADDRESS: "controller _apply_multislice_env",
    ann.CHECKPOINT_GRACE_ENV_NAME: "webhook project_checkpoint_env: "
    "tpu-checkpoint-grace-seconds annotation",
    ann.CHECKPOINT_DIR_ENV_NAME: "webhook project_checkpoint_env: "
    "tpu-checkpoint-dir annotation (always set for TPU notebooks)",
    KUBEFLOW_TPU_SERVING_RAGGED: "operator-set on the notebook container "
    "(no webhook producer yet): 1 enables ragged mixed prefill/decode "
    "batching in models/server.py engine construction",
    KUBEFLOW_TPU_RAGGED_TOKEN_BUDGET: "operator-set on the notebook "
    "container: per-step ragged token budget (default 512; must be >= "
    "the engine's slot count)",
    KUBEFLOW_TPU_GATEWAY_PORT: "operator-set on the gateway container: "
    "listen port for models/gateway.py (default 8080; 0 = ephemeral)",
    KUBEFLOW_TPU_GATEWAY_REPLICAS: "operator-set on the gateway "
    "container: comma-separated host:port InferenceServer replica "
    "endpoints the gateway fronts at startup (the ring also follows "
    "live add/remove and healthz state)",
    KUBEFLOW_TPU_GATEWAY_AFFINITY: "operator-set on the gateway "
    "container: routing mode, 'prefix' (consistent-hash on the longest "
    "shared prompt prefix; default) or 'random' (uniform spread — the "
    "control arm loadtest/serve_fleet.py measures against)",
    KUBEFLOW_TPU_GATEWAY_HASH_SEED: "operator-set on the gateway "
    "container: integer seed mixed into the ring's vnode positions so "
    "parallel fleets don't co-shard hot prefixes (default 0)",
    KUBEFLOW_TPU_GATEWAY_REROUTE_BUDGET: "operator-set on the gateway "
    "container: max alternate ring nodes tried after a 503/429/connect "
    "failure before the gateway gives up (default 2)",
    KUBEFLOW_TPU_GATEWAY_TIER_MODE: "operator-set on the gateway "
    "container: 'fused' (default — every replica prefills and decodes) "
    "or 'disagg' (token-id requests prefill on the prefill tier, ship "
    "paged KV to the decode tier, and fall back to fused routing when "
    "either tier is empty or the transfer fails within budget)",
    KUBEFLOW_TPU_GATEWAY_TIER_PREFILL: "operator-set on the gateway "
    "container: comma-separated host:port endpoints pinned to the "
    "prefill tier (roles also follow each replica's /stats tier_role; "
    "this list wins at startup)",
    KUBEFLOW_TPU_GATEWAY_TIER_DECODE: "operator-set on the gateway "
    "container: comma-separated host:port endpoints pinned to the "
    "decode tier (see TIER_PREFILL)",
    KUBEFLOW_TPU_GATEWAY_TIER_ROLE: "operator-set on the serving "
    "container: the role this replica advertises on /stats — "
    "fused (default) / prefill / decode — consumed by "
    "models/server.py tier_role_from_env",
    KUBEFLOW_TPU_KV_TRANSFER_TIMEOUT_S: "operator-set on the gateway "
    "container: socket timeout for one prefill→decode KV-transfer hop "
    "in seconds (default 30; each hop is also capped by the request's "
    "remaining deadline)",
    KUBEFLOW_TPU_KV_TRANSFER_MAX_BYTES: "operator-set on the gateway "
    "container: serialized KV payload ceiling in bytes — larger "
    "transfers fall back to fused routing (default 64 MiB; replica "
    "max_body_bytes must admit at least this much)",
    KUBEFLOW_TPU_KV_PEER_FANOUT: "operator-set on the gateway "
    "container: how many ring successors a peer prefix fetch may probe "
    "on a local miss; unset keeps the fleet KV tier fully inert (zero "
    "hot-path cost, zero new sockets), set must be an integer >= 1",
    KUBEFLOW_TPU_KV_PEER_TIMEOUT_S: "operator-set on the gateway "
    "container: per-hop deadline for one peer probe/pull/import hop in "
    "seconds (default 5); the whole fetch is budgeted at "
    "TIMEOUT_S * (FANOUT + 1) and every expiry degrades to re-prefill",
    KUBEFLOW_TPU_KV_PEER_MAX_BYTES: "operator-set on the gateway "
    "container: peer chain payload ceiling in bytes — the probe's byte "
    "advisory refuses oversized chains before pulling, and the pull "
    "re-checks while reading (default 64 MiB)",
    KUBEFLOW_TPU_KV_BITS: "operator-set on the serving container: KV "
    "block-pool storage width — 8 stores int8 values + bf16 scales "
    "(half the KV HBM; composes with the ragged kernel), unset/0 keeps "
    "bf16 — consumed by models/server.py kv_pool_from_env",
    KUBEFLOW_TPU_HBM_FRACTION: "operator-set on the serving container: "
    "fraction of free device HBM to spend on the KV block pool "
    "(pool_blocks_from_hbm; unset keeps the configured block count, "
    "which is also the CPU fallback)",
    KUBEFLOW_TPU_KV_SWAP_BYTES: "operator-set on the serving container: "
    "byte budget for the host-RAM block-swap tier — demoted prefix "
    "chains park here instead of being lost, LRU within the budget; "
    "unset/0 disables the tier",
    KUBEFLOW_TPU_SPEC_DRAFT_LEN: "operator-set on the serving container: "
    "speculative draft length k — each decode slot contributes 1+k "
    "verify rows to the fused ragged dispatch; unset/0 disables "
    "speculation — consumed by models/server.py spec_from_env",
    KUBEFLOW_TPU_SPEC_ADAPTIVE: "operator-set on the serving container: "
    "1/true lets the acceptance-rate EMA shrink/grow the per-round "
    "draft length within [1, SPEC_DRAFT_LEN]; unset/0 keeps it fixed",
    KUBEFLOW_TPU_LORA_CACHE_SLOTS: "operator-set on the serving "
    "container: bound of the per-replica hot-adapter cache (LRU, "
    "eviction counters in /stats); unset/0 leaves adapter residency "
    "uncapped — consumed by models/server.py lora_cache_from_env",
    KUBEFLOW_TPU_SERVING_TP: "operator-set on the serving container: "
    "tensor-parallel degree of this replica's engine mesh — weights "
    "shard on the tp axis, the paged KV pool head-shards (per-chip "
    "pool bytes drop by the degree), the replica stays ONE HTTP "
    "endpoint; must be an integer >= 1 dividing the model's kv-head "
    "count and <= visible devices (startup fails fast otherwise); "
    "unset/1 keeps the classic single-chip engine — consumed by "
    "models/server.py serving_tp_from_env",
    KUBEFLOW_TPU_COMPILE_CACHE_DIR: "operator-set (bench watcher env or "
    "notebook container): directory for JAX's persistent compilation "
    "cache; bench.py enables it at startup and stamps the dir into "
    "record provenance so warm-cache captures are distinguishable",
    KUBEFLOW_TPU_TRACE_EXPORT: "operator-set (gateway / serving / bench "
    "container): path of a JSONL file that every finished span is appended "
    "to; setting it flips observability/tracing.py from the default no-op "
    "provider to a recording one at component startup",
    KUBEFLOW_TPU_TRACE_SAMPLE: "operator-set: head-sampling rate in [0,1] "
    "(default 1.0). The decision is deterministic in the trace id, so the "
    "gateway and every replica agree per request without coordination",
    KUBEFLOW_TPU_TRACE_RING: "operator-set: capacity of the in-memory span "
    "ring buffer behind the serving components' /debug/traces endpoint "
    "(default 512 spans, oldest evicted first)",
    KUBEFLOW_TPU_SIGNALS_ENABLE: "operator-set on the gateway container: "
    "1/true builds the FleetTelemetry signal plane (windowed fleet series, "
    "/debug/signals + /debug/slo, SLO burn-rate evaluation each probe "
    "pass); unset/0 keeps the gateway hot path telemetry-free",
    KUBEFLOW_TPU_SIGNALS_WINDOW_S: "operator-set: width of one aligned "
    "telemetry window in seconds (default 10)",
    KUBEFLOW_TPU_SIGNALS_WINDOWS: "operator-set: ring length in windows "
    "(default 180 — the horizon must cover the SLO engine's 30m slow "
    "window)",
    KUBEFLOW_TPU_SIGNALS_TENANTS: "operator-set: per-tenant breakdown "
    "cardinality — the first K distinct tenants get their own series and "
    "label, the rest fold into 'other' (default 8)",
    KUBEFLOW_TPU_SLO_TTFT_P95_MS: "operator-set: TTFT p95 objective "
    "threshold in milliseconds (default 500)",
    KUBEFLOW_TPU_SLO_INTER_TOKEN_P95_MS: "operator-set: inter-token p95 "
    "objective threshold in milliseconds (default 200)",
    KUBEFLOW_TPU_SLO_QUEUE_WAIT_P95_MS: "operator-set: per-replica "
    "queue-wait p95 objective threshold in milliseconds (default 250)",
    KUBEFLOW_TPU_SLO_ERROR_BUDGET: "operator-set: allowed bad fraction "
    "shared by the stock objectives, in (0, 1] (default 0.05)",
    KUBEFLOW_TPU_SLO_FAST_BURN: "operator-set: burn rate that must hold "
    "in BOTH fast windows (1m and 5m) to page (default 14.4)",
    KUBEFLOW_TPU_SLO_SLOW_BURN: "operator-set: burn rate over the 30m "
    "slow window that pages on its own (default 2.0)",
    KUBEFLOW_TPU_STALL_PROFILE_DIR: "operator-set on the serving "
    "container: directory for stall-triggered jax.profiler captures; "
    "setting it wires observability/flight.py's StallProfiler into the "
    "flight recorder (unset = no capture, the default)",
    KUBEFLOW_TPU_STALL_PROFILE_COOLDOWN_S: "operator-set: minimum seconds "
    "between stall captures (default 300; extra stalls are counted as "
    "skipped, never queued)",
    KUBEFLOW_TPU_STALL_PROFILE_SECONDS: "operator-set: duration of each "
    "stall-triggered profile capture (default 2.0)",
    KUBEFLOW_TPU_AUTOSCALE_ENABLE: "operator-set on the gateway container: "
    "1/true builds the FleetAutoscaler (per-tier signals→slices control "
    "loop riding the probe cadence, /debug/autoscaler surface); unset/0 "
    "keeps capacity operator-driven — the autoscaler is inert by default",
    KUBEFLOW_TPU_AUTOSCALE_MIN_REPLICAS: "operator-set: scale-down floor "
    "per tier (default 1)",
    KUBEFLOW_TPU_AUTOSCALE_MAX_REPLICAS: "operator-set: scale-up ceiling "
    "per tier (default 4)",
    KUBEFLOW_TPU_AUTOSCALE_UP_COOLDOWN_S: "operator-set: seconds after a "
    "scale-up before the same tier may scale up again (default 30)",
    KUBEFLOW_TPU_AUTOSCALE_DOWN_COOLDOWN_S: "operator-set: seconds after a "
    "scale-down before the same tier may scale down again (default 60)",
    KUBEFLOW_TPU_AUTOSCALE_MAX_ACTIONS: "operator-set: fleet-wide cap on "
    "scale actions per rate-limit window (default 4)",
    KUBEFLOW_TPU_AUTOSCALE_WINDOW_S: "operator-set: the rate-limit window "
    "in seconds (default 300)",
    KUBEFLOW_TPU_AUTOSCALE_DRAIN_BUDGET_S: "operator-set: how long a "
    "scale-down waits for the draining replica's in-flight streams before "
    "releasing its slice anyway (default 60)",
    KUBEFLOW_TPU_AUTOSCALE_STALE_AFTER_S: "operator-set: replica scrape "
    "age past which the autoscaler freezes all scaling instead of acting "
    "on stale telemetry (default 10)",
    KUBEFLOW_TPU_MIGRATE_ENABLE: "operator-set on the controller container: "
    "1/true arms proactive live migration (save → warm-claim → restore → "
    "flip on preemption notice / idle-cull / tpu-migrate-now annotation); "
    "unset/0 keeps recovery purely reactive — migration is inert by default",
    KUBEFLOW_TPU_MIGRATE_SAVE_BUDGET_S: "operator-set: emergency-save step "
    "budget in seconds (default 30; the step falls back to the reactive "
    "ladder when blown)",
    KUBEFLOW_TPU_MIGRATE_CLAIM_BUDGET_S: "operator-set: warm-slice claim "
    "step budget in seconds (default 10)",
    KUBEFLOW_TPU_MIGRATE_RESTORE_BUDGET_S: "operator-set: restore step "
    "budget in seconds (default 60)",
    KUBEFLOW_TPU_MIGRATE_FLIP_BUDGET_S: "operator-set: routing-flip step "
    "budget in seconds (default 10)",
    KUBEFLOW_TPU_MIGRATE_FRESH_WITHIN_S: "operator-set: a checkpoint "
    "commit younger than this (monotonic seconds, default 5) makes the "
    "save step a skip",
    ann.QUANT_ENV_NAME: "webhook: tpu-quantization annotation",
    ann.PROFILING_ENV_NAME: "webhook: tpu-profiling-port annotation",
    ann.SERVING_ENV_NAME: "webhook: tpu-serving-port annotation",
}


def inject_tpu_env(
    nb: Notebook, topo: SliceTopology, cluster_domain: str = "cluster.local"
) -> bool:
    """Idempotently set the TPU env block on the primary container.

    Returns True if the pod template changed. Values are recomputed from the
    current spec, so topology edits (on stopped notebooks) roll forward.
    """
    container = nb.primary_container()
    if container is None:
        return False
    # Name derivation must match the controller exactly, including the
    # long-name hashed fallback — TPU_WORKER_HOSTNAMES with the wrong STS
    # base would leave jax.distributed.initialize resolving nothing.
    from kubeflow_tpu.controller.notebook import (
        headless_service_name,
        slice_sts_name,
    )

    headless = headless_service_name(nb.name)
    hostnames = topo.worker_hostnames(
        slice_sts_name(nb.name, 0), headless, nb.namespace, cluster_domain
    )
    desired: list[dict] = [
        {
            "name": TPU_WORKER_ID,
            "valueFrom": {
                "fieldRef": {"fieldPath": f"metadata.labels['{POD_INDEX_LABEL}']"}
            },
        },
        {"name": TPU_WORKER_HOSTNAMES, "value": ",".join(hostnames)},
        {"name": TPU_ACCELERATOR_TYPE, "value": topo.accelerator_type},
        {"name": TPU_TOPOLOGY, "value": topo.topology_str},
        {"name": TPU_CHIPS_PER_HOST_BOUNDS, "value": topo.chip_bounds_str()},
        {"name": TPU_HOST_BOUNDS, "value": topo.host_bounds_str()},
    ]
    stale: set[str] = set()
    if topo.hosts > 1:
        desired += [
            {
                "name": JAX_COORDINATOR_ADDRESS,
                "value": f"{hostnames[0]}:{JAX_COORDINATOR_PORT}",
            },
            {"name": JAX_NUM_PROCESSES, "value": str(topo.hosts)},
        ]
    else:
        # A topology edit that shrank the slice to one host must drop the
        # multi-host env, or bootstrap would wait for workers that no
        # longer exist.
        stale |= {JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES}
    if nb.tpu is not None and nb.tpu.runtime_version:
        desired.append(
            {"name": TPU_RUNTIME_VERSION, "value": nb.tpu.runtime_version}
        )
    else:
        stale.add(TPU_RUNTIME_VERSION)
    changed = upsert_env(container, desired)
    changed |= remove_env(container, stale)
    return changed


def upsert_env(container: dict, desired: list[dict]) -> bool:
    """Merge env entries by name; True if anything changed."""
    env = container.setdefault("env", [])
    changed = False
    by_name = {e.get("name"): i for i, e in enumerate(env)}
    for entry in desired:
        idx = by_name.get(entry["name"])
        if idx is None:
            env.append(entry)
            by_name[entry["name"]] = len(env) - 1
            changed = True
        elif env[idx] != entry:
            env[idx] = entry
            changed = True
    return changed


def remove_env(container: dict, names: set[str]) -> bool:
    env = container.get("env", [])
    kept = [e for e in env if e.get("name") not in names]
    if len(kept) != len(env):
        container["env"] = kept
        return True
    return False
