from kubeflow_tpu.webhook.mutating import NotebookMutatingWebhook, WebhookConfig  # noqa: F401
from kubeflow_tpu.webhook.validating import NotebookValidatingWebhook  # noqa: F401
