"""AdmissionReview HTTPS server: the production webhook transport.

Reference parity: the ODH manager runs controller-runtime's webhook server
on :8443 with serving certs, exposing ``/mutate-notebook-v1`` and
``/validate-notebook-v1`` (reference components/odh-notebook-controller/
main.go:291-331; paths registered in notebook_mutating_webhook.go:54-68 and
notebook_validating_webhook.go:31-38), with the cluster TLS security
profile applied to the listener (main.go:237-269). This module does the
same: decode AdmissionReview v1, invoke the handler, encode an
AdmissionResponse with a granular RFC 6902 JSONPatch, over TLS terminated
in-process.

TLS behavior:
- ``cert_dir`` holds ``tls.crt``/``tls.key`` (the serving-cert Secret
  mount layout). Missing or unloadable certs FAIL CLOSED at start.
- The cluster ``TLSProfile`` sets the minimum TLS version and (for ≤1.2)
  the cipher list on the listener.
- Rotation: a background thread polls the cert files' mtimes and reloads
  the chain into the live SSLContext — new handshakes pick up the new
  certs without dropping the listener (cert-manager/service-ca rotate
  in place).
"""

from __future__ import annotations

import base64
import copy
import json
import logging
import os
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kubeflow_tpu.controller.tls import TLSProfile
from kubeflow_tpu.k8s.errors import WebhookDeniedError
from kubeflow_tpu.k8s.fake import AdmissionRequest

log = logging.getLogger(__name__)

# AdmissionReview bodies are small (a Notebook object + envelope); the
# apiserver itself caps at ~3MB. Anything bigger is not an admission
# review — refuse before buffering it into host memory (413).
MAX_ADMISSION_BODY_BYTES = 4 << 20


def _read_body(handler: BaseHTTPRequestHandler, limit: int) -> bytes:
    """THE body read for admission handlers (the
    kftpu-unbounded-handler-read semgrep rule forbids bare rfile.read
    here): refuses Content-Length past ``limit`` before reading a byte.
    Raises ValueError past the limit or on garbage lengths."""
    length = int(handler.headers.get("Content-Length", 0))
    if length < 0 or length > limit:
        raise ValueError(f"Content-Length {length} outside [0, {limit}]")
    return handler.rfile.read(length)


MUTATE_PATH = "/mutate-notebook-v1"
VALIDATE_PATH = "/validate-notebook-v1"

CERT_FILE = "tls.crt"
KEY_FILE = "tls.key"

# IANA cipher-suite names (what the OpenShift APIServer CR speaks) →
# OpenSSL names (what ssl.SSLContext.set_ciphers takes). TLS 1.3 suites are
# not listed: OpenSSL fixes them independently of set_ciphers, and all
# three profile variants' 1.3 suites are the defaults anyway.
_IANA_TO_OPENSSL = {
    "TLS_ECDHE_ECDSA_WITH_AES_128_GCM_SHA256": "ECDHE-ECDSA-AES128-GCM-SHA256",
    "TLS_ECDHE_RSA_WITH_AES_128_GCM_SHA256": "ECDHE-RSA-AES128-GCM-SHA256",
    "TLS_ECDHE_ECDSA_WITH_AES_256_GCM_SHA384": "ECDHE-ECDSA-AES256-GCM-SHA384",
    "TLS_ECDHE_RSA_WITH_AES_256_GCM_SHA384": "ECDHE-RSA-AES256-GCM-SHA384",
    "TLS_ECDHE_ECDSA_WITH_CHACHA20_POLY1305_SHA256": "ECDHE-ECDSA-CHACHA20-POLY1305",
    "TLS_ECDHE_RSA_WITH_CHACHA20_POLY1305_SHA256": "ECDHE-RSA-CHACHA20-POLY1305",
    "TLS_ECDHE_ECDSA_WITH_AES_128_CBC_SHA256": "ECDHE-ECDSA-AES128-SHA256",
    "TLS_ECDHE_RSA_WITH_AES_128_CBC_SHA256": "ECDHE-RSA-AES128-SHA256",
    "TLS_RSA_WITH_AES_128_GCM_SHA256": "AES128-GCM-SHA256",
    "TLS_RSA_WITH_AES_256_GCM_SHA384": "AES256-GCM-SHA384",
}

_MIN_VERSIONS = {
    "VersionTLS10": ssl.TLSVersion.TLSv1,
    "VersionTLS11": ssl.TLSVersion.TLSv1_1,
    "VersionTLS12": ssl.TLSVersion.TLSv1_2,
    "VersionTLS13": ssl.TLSVersion.TLSv1_3,
}


class CertError(RuntimeError):
    """Serving certs missing/unreadable: the server refuses to start
    (failurePolicy: Fail means a silently-broken webhook blocks the API
    server; better to crash-loop visibly)."""


def _pointer_escape(key: str) -> str:
    """RFC 6901 token escaping."""
    return key.replace("~", "~0").replace("/", "~1")


def json_patch(old, new, path: str = "") -> list[dict]:
    """Granular RFC 6902 patch from ``old`` to ``new``.

    controller-runtime's PatchResponseFromRaw computes exactly this shape
    (via json-patch diff); granular ops matter because the API server
    applies each webhook's patch to the CURRENT intermediate object — a
    whole-root replace would clobber concurrent mutations from other
    webhooks in the chain (VERDICT r1 weak #6).
    """
    if old == new:
        return []
    if isinstance(old, dict) and isinstance(new, dict):
        ops: list[dict] = []
        for key in old:
            esc = f"{path}/{_pointer_escape(key)}"
            if key not in new:
                ops.append({"op": "remove", "path": esc})
            else:
                ops.extend(json_patch(old[key], new[key], esc))
        for key in new:
            if key not in old:
                ops.append(
                    {"op": "add", "path": f"{path}/{_pointer_escape(key)}",
                     "value": new[key]}
                )
        return ops
    if isinstance(old, list) and isinstance(new, list):
        ops = []
        common = min(len(old), len(new))
        for i in range(common):
            ops.extend(json_patch(old[i], new[i], f"{path}/{i}"))
        # Remove from the tail backwards so indices stay valid.
        for i in range(len(old) - 1, common - 1, -1):
            ops.append({"op": "remove", "path": f"{path}/{i}"})
        for i in range(common, len(new)):
            ops.append({"op": "add", "path": f"{path}/-", "value": new[i]})
        return ops
    return [{"op": "replace", "path": path or "", "value": new}]


def apply_json_patch(doc, ops: list[dict]):
    """Apply an RFC 6902 patch (the subset ``json_patch`` emits) — the API
    server's side of the round-trip, used by tests to prove the emitted
    patch reproduces the handler's mutation exactly."""
    doc = copy.deepcopy(doc)
    for op in ops:
        path = op["path"]
        if path == "":
            doc = copy.deepcopy(op["value"])
            continue
        tokens = [t.replace("~1", "/").replace("~0", "~") for t in path.split("/")[1:]]
        parent = doc
        for tok in tokens[:-1]:
            parent = parent[int(tok)] if isinstance(parent, list) else parent[tok]
        last = tokens[-1]
        if isinstance(parent, list):
            if op["op"] == "add":
                if last == "-":
                    parent.append(op["value"])
                else:
                    parent.insert(int(last), op["value"])
            elif op["op"] == "remove":
                del parent[int(last)]
            else:
                parent[int(last)] = op["value"]
        else:
            if op["op"] == "remove":
                del parent[last]
            else:
                parent[last] = op["value"]
    return doc


def handle_admission_review(body: dict, mutating_handler, validating_handler) -> dict:
    """AdmissionReview(request) → AdmissionReview(response)."""
    request = body.get("request", {})
    uid = request.get("uid", "")
    operation = request.get("operation", "CREATE")
    obj = copy.deepcopy(request.get("object") or {})
    old_obj = request.get("oldObject") or None
    req = AdmissionRequest(operation=operation, object=obj, old_object=old_obj)

    response: dict = {"uid": uid, "allowed": True}
    try:
        if validating_handler is not None:
            validating_handler(req)
        if mutating_handler is not None:
            mutated = mutating_handler(req) or obj
            patch = json_patch(request.get("object") or {}, mutated)
            if patch:
                response["patchType"] = "JSONPatch"
                response["patch"] = base64.b64encode(
                    json.dumps(patch).encode()
                ).decode()
    except WebhookDeniedError as err:
        response = {
            "uid": uid,
            "allowed": False,
            "status": {"code": 403, "message": str(err)},
        }
    except Exception as err:  # fail closed, as failurePolicy: Fail expects
        response = {
            "uid": uid,
            "allowed": False,
            "status": {"code": 500, "message": f"webhook error: {err}"},
        }
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": response,
    }


def make_ssl_context(
    cert_dir: str, tls_profile: Optional[TLSProfile] = None
) -> ssl.SSLContext:
    """Server context from a serving-cert dir, hardened per the profile."""
    cert = os.path.join(cert_dir, CERT_FILE)
    key = os.path.join(cert_dir, KEY_FILE)
    if not (os.path.exists(cert) and os.path.exists(key)):
        raise CertError(f"serving certs not found in {cert_dir} "
                        f"(need {CERT_FILE} + {KEY_FILE})")
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    try:
        ctx.load_cert_chain(cert, key)
    except (ssl.SSLError, OSError) as err:
        raise CertError(f"cannot load serving certs from {cert_dir}: {err}") from err
    if tls_profile is not None:
        ctx.minimum_version = _MIN_VERSIONS.get(
            tls_profile.min_version, ssl.TLSVersion.TLSv1_2
        )
        openssl_names = [
            _IANA_TO_OPENSSL[c] for c in tls_profile.ciphers if c in _IANA_TO_OPENSSL
        ]
        if openssl_names:
            try:
                ctx.set_ciphers(":".join(openssl_names))
            except ssl.SSLError as err:
                raise CertError(f"TLS profile cipher list rejected: {err}") from err
    else:
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    return ctx


class _CertReloader(threading.Thread):
    """Polls cert mtimes; reloads the chain into the live context."""

    def __init__(self, ctx: ssl.SSLContext, cert_dir: str, interval: float = 10.0):
        super().__init__(daemon=True, name="webhook-cert-reload")
        self.ctx = ctx
        self.cert_dir = cert_dir
        self.interval = interval
        self._stop = threading.Event()
        self._mtimes = self._stat()
        self.reloads = 0

    def _stat(self):
        out = {}
        for f in (CERT_FILE, KEY_FILE):
            try:
                out[f] = os.stat(os.path.join(self.cert_dir, f)).st_mtime_ns
            except OSError:
                out[f] = None
        return out

    def poll_once(self) -> bool:
        """Check and maybe reload; returns True when a reload happened."""
        current = self._stat()
        if current == self._mtimes:
            return False
        try:
            self.ctx.load_cert_chain(
                os.path.join(self.cert_dir, CERT_FILE),
                os.path.join(self.cert_dir, KEY_FILE),
            )
            self._mtimes = current
            self.reloads += 1
            log.info("webhook serving certs reloaded from %s", self.cert_dir)
            return True
        except (ssl.SSLError, OSError) as err:
            # Keep serving with the previous chain; retry next poll.
            log.error("cert rotation failed (keeping old chain): %s", err)
            return False

    def run(self) -> None:
        while not self._stop.wait(self.interval):
            self.poll_once()

    def stop(self) -> None:
        self._stop.set()


class WebhookServer:
    """Serves the two admission paths, TLS-terminated when certs are given.

    ``cert_dir=None`` falls back to plain HTTP for in-process tests and
    sidecar-terminated deployments; production manifests mount the
    serving-cert Secret and pass ``--cert-dir``.
    """

    def __init__(
        self,
        mutating_handler=None,
        validating_handler=None,
        host: str = "127.0.0.1",
        port: int = 0,
        cert_dir: Optional[str] = None,
        tls_profile: Optional[TLSProfile] = None,
        reload_interval: float = 10.0,
    ):
        mutating = mutating_handler
        validating = validating_handler

        class Handler(BaseHTTPRequestHandler):
            # Avoid Nagle+delayed-ACK ~40ms stalls per request.
            disable_nagle_algorithm = True
            # Bounds both the deferred TLS handshake and request reads: a
            # half-open client costs one handler thread for 30s, never the
            # accept loop.
            timeout = 30

            def setup(self):
                super().setup()
                if isinstance(self.connection, ssl.SSLSocket):
                    # Deferred handshake (see wrap_socket below) under this
                    # handler's timeout; failures close just this thread.
                    self.connection.do_handshake()

            def do_POST(self):  # noqa: N802 (http.server API)
                try:
                    raw = _read_body(self, MAX_ADMISSION_BODY_BYTES)
                except ValueError:
                    self.send_response(413)
                    self.end_headers()
                    return
                try:
                    body = json.loads(raw or b"{}")
                except json.JSONDecodeError:
                    self.send_response(400)
                    self.end_headers()
                    return
                if self.path == MUTATE_PATH:
                    review = handle_admission_review(body, mutating, None)
                elif self.path == VALIDATE_PATH:
                    review = handle_admission_review(body, None, validating)
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                payload = json.dumps(review).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):
                pass

        class _QuietServer(ThreadingHTTPServer):
            daemon_threads = True

            def handle_error(self, request, client_address):
                # Handshake failures from probes/scans are expected noise;
                # a traceback per bad connection would flood the log.
                log.debug("webhook connection error from %s", client_address)

        self._server = _QuietServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None
        self._reloader: Optional[_CertReloader] = None
        self.tls_enabled = False
        if cert_dir:
            ctx = make_ssl_context(cert_dir, tls_profile)  # raises CertError: fail closed
            # do_handshake_on_connect=False: accept() returns immediately
            # and the handshake happens on the handler THREAD's first read.
            # Otherwise one client that connects and never speaks TLS
            # (port scan, half-open probe) wedges the accept loop and all
            # admission stops — failurePolicy: Fail would then block every
            # Notebook write cluster-wide.
            self._server.socket = ctx.wrap_socket(
                self._server.socket, server_side=True,
                do_handshake_on_connect=False,
            )
            self._reloader = _CertReloader(ctx, cert_dir, reload_interval)
            self.tls_enabled = True

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def cert_reloads(self) -> int:
        return self._reloader.reloads if self._reloader else 0

    def poll_certs(self) -> bool:
        """Force one rotation check now (tests; the thread does it live)."""
        return self._reloader.poll_once() if self._reloader else False

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        if self._reloader is not None:
            self._reloader.start()

    def stop(self) -> None:
        if self._reloader is not None:
            self._reloader.stop()
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
