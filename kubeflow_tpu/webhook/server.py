"""AdmissionReview HTTP server: the production webhook transport.

Reference parity: the ODH manager runs controller-runtime's webhook server
on :8443 with serving certs, exposing ``/mutate-notebook-v1`` and
``/validate-notebook-v1`` (reference components/odh-notebook-controller/
main.go:291-331; paths registered in notebook_mutating_webhook.go:54-68 and
notebook_validating_webhook.go:31-38). In tests the same handler objects are
registered directly on the FakeCluster's in-process admission chain; this
module provides the HTTP face for a real API server: decode AdmissionReview
v1, invoke the handler, encode an AdmissionResponse with a JSONPatch.
"""

from __future__ import annotations

import base64
import copy
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kubeflow_tpu.k8s.errors import WebhookDeniedError
from kubeflow_tpu.k8s.fake import AdmissionRequest

MUTATE_PATH = "/mutate-notebook-v1"
VALIDATE_PATH = "/validate-notebook-v1"


def _json_patch(old: dict, new: dict) -> list[dict]:
    """Minimal whole-document replace patch (admission allows any valid
    JSONPatch; controller-runtime's PatchResponseFromRaw computes granular
    ops, but a root replace is semantically identical for the API server)."""
    if old == new:
        return []
    return [{"op": "replace", "path": "", "value": new}]


def handle_admission_review(body: dict, mutating_handler, validating_handler) -> dict:
    """AdmissionReview(request) → AdmissionReview(response)."""
    request = body.get("request", {})
    uid = request.get("uid", "")
    operation = request.get("operation", "CREATE")
    obj = copy.deepcopy(request.get("object") or {})
    old_obj = request.get("oldObject") or None
    req = AdmissionRequest(operation=operation, object=obj, old_object=old_obj)

    response: dict = {"uid": uid, "allowed": True}
    try:
        if validating_handler is not None:
            validating_handler(req)
        if mutating_handler is not None:
            mutated = mutating_handler(req) or obj
            patch = _json_patch(request.get("object") or {}, mutated)
            if patch:
                response["patchType"] = "JSONPatch"
                response["patch"] = base64.b64encode(
                    json.dumps(patch).encode()
                ).decode()
    except WebhookDeniedError as err:
        response = {
            "uid": uid,
            "allowed": False,
            "status": {"code": 403, "message": str(err)},
        }
    except Exception as err:  # fail closed, as failurePolicy: Fail expects
        response = {
            "uid": uid,
            "allowed": False,
            "status": {"code": 500, "message": f"webhook error: {err}"},
        }
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": response,
    }


class WebhookServer:
    """Serves the two admission paths over HTTP.

    TLS termination is left to the pod's serving-cert sidecar/ingress in
    this environment; the handler wiring and review protocol are what the
    reference's webhook server provides on top of Go's TLS listener.
    """

    def __init__(
        self,
        mutating_handler=None,
        validating_handler=None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        mutating = mutating_handler
        validating = validating_handler

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (http.server API)
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError:
                    self.send_response(400)
                    self.end_headers()
                    return
                if self.path == MUTATE_PATH:
                    review = handle_admission_review(body, mutating, None)
                elif self.path == VALIDATE_PATH:
                    review = handle_admission_review(body, None, validating)
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                payload = json.dumps(review).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
