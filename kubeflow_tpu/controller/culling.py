"""Idle-culling controller: slice-aware Jupyter activity tracking.

Rebuild of the reference culling loop (reference
components/notebook-controller/controllers/culling_controller.go:87-218
Reconcile, notebookIsIdle :221, kernel/terminal probing :244-322, monotonic
annotation merge :360-437, setStopAnnotation :484) with the two TPU changes
from SURVEY.md §7 step 5:

1. **Multi-host activity merge** — Jupyter runs on worker 0, but any host of
   the slice may be active (profile servers, distributed jobs). The prober
   fans out to every host and activity merges with a monotonic guard, so a
   busy worker 3 keeps the slice alive and clock skew can never move
   last-activity backwards (the reference's flapping hazard).
2. **Atomic release** — culling sets the stop annotation once; the core
   reconciler scales the whole indexed StatefulSet to 0. Chips are reclaimed
   all-or-nothing; a cull can never leave a partial slice holding capacity.

Probing is behind the ``ActivityProber`` seam: production uses an HTTP
prober against each host's Jupyter API (and a C++ fan-out prober can slot in
for large slices); tests inject a fake.
"""

from __future__ import annotations

import concurrent.futures
import json
import logging
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Callable, Optional, Protocol

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.controller.notebook import (
    headless_service_name,
    slice_sts_names,
)
from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.client import Client, retry_on_conflict
from kubeflow_tpu.k8s.errors import NotFoundError
from kubeflow_tpu.k8s.events import EventRecorder
from kubeflow_tpu.k8s.manager import Manager, Reconciler, Request, Result
from kubeflow_tpu.metrics import Metrics

log = logging.getLogger(__name__)

TIME_FORMAT = "%Y-%m-%dT%H:%M:%SZ"


def _fmt(ts: float) -> str:
    return time.strftime(TIME_FORMAT, time.gmtime(ts))


def _parse(ts: str) -> Optional[float]:
    return obj_util.parse_timestamp(ts)


@dataclass
class CullerConfig:
    """Env knobs, names and defaults per the reference initGlobalVars
    (culling_controller.go:534-568)."""

    enable_culling: bool = False
    cull_idle_time_min: int = 1440  # 1 day, reference default
    idleness_check_period_min: int = 1
    cluster_domain: str = "cluster.local"
    dev_mode: bool = False

    @classmethod
    def from_env(cls, env: dict) -> "CullerConfig":
        return cls(
            enable_culling=env.get("ENABLE_CULLING", "false").lower() == "true",
            cull_idle_time_min=int(env.get("CULL_IDLE_TIME", "1440")),
            idleness_check_period_min=int(env.get("IDLENESS_CHECK_PERIOD", "1")),
            cluster_domain=env.get("CLUSTER_DOMAIN", "cluster.local"),
            dev_mode=env.get("DEV", "false").lower() == "true",
        )


@dataclass
class HostActivity:
    """Observed activity on one slice host."""

    host: str
    busy: bool = False
    last_activity: Optional[float] = None  # unix seconds
    reachable: bool = True


class ActivityProber(Protocol):
    def probe(self, nb: Notebook, hosts: list[str]) -> list[HostActivity]: ...


class JupyterHTTPProber:
    """Probes Jupyter's /api/kernels + /api/terminals on worker 0 and the
    activity endpoint on every other host (reference getNotebookApiKernels
    :277-322; DEV mode proxies via localhost as :253-257 does).

    Hosts are probed CONCURRENTLY under one per-slice deadline: serially, a
    16-host slice behind a partition pinned the culler reconcile for
    hosts × timeout (~80s); now the reconcile is bounded by
    ``slice_deadline_s`` no matter how many hosts stall. A host whose probe
    misses the deadline folds as unreachable — which the culler already
    treats as "never judge" — and ``fold_host_activity`` stays the single
    merge point shared with the native prober."""

    def __init__(
        self,
        timeout_s: float = 5.0,
        dev_proxy: Optional[str] = None,
        slice_deadline_s: float = 15.0,
        max_workers: int = 16,
    ):
        self.timeout_s = timeout_s
        self.dev_proxy = dev_proxy
        self.slice_deadline_s = slice_deadline_s
        self.max_workers = max_workers

    def probe(self, nb: Notebook, hosts: list[str]) -> list[HostActivity]:
        if not hosts:
            return []
        deadline = time.monotonic() + self.slice_deadline_s
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, min(len(hosts), self.max_workers)),
            thread_name_prefix="jupyter-probe",
        )
        try:
            futures = [
                pool.submit(self._probe_host, nb, host) for host in hosts
            ]
            out = []
            for host, fut in zip(hosts, futures):
                remaining = deadline - time.monotonic()
                try:
                    kernels, terminals = fut.result(
                        timeout=max(0.0, remaining)
                    )
                except concurrent.futures.TimeoutError:
                    fut.cancel()
                    kernels, terminals = None, None
                out.append(fold_host_activity(host, kernels, terminals))
            return out
        finally:
            # Never block the reconcile on stragglers: abandoned probes
            # finish (or time out) on daemon-ish pool threads.
            pool.shutdown(wait=False, cancel_futures=True)

    def _probe_host(self, nb: Notebook, host: str):
        base = (
            f"{self.dev_proxy}/notebook/{nb.namespace}/{nb.name}"
            if self.dev_proxy
            else f"http://{host}:8888/notebook/{nb.namespace}/{nb.name}"
        )
        kernels = self._get_json(f"{base}/api/kernels")
        # Dead host: don't burn a second timeout on terminals the fold
        # would ignore anyway.
        terminals = (
            self._get_json(f"{base}/api/terminals")
            if kernels is not None
            else None
        )
        return kernels, terminals

    def _get_json(self, url: str):
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
                return json.loads(resp.read().decode())
        except (urllib.error.URLError, OSError, ValueError):
            return None


def fold_host_activity(
    host: str,
    kernels: Optional[list],
    terminals: Optional[list],
) -> HostActivity:
    """Fold Jupyter kernel/terminal listings into one HostActivity.

    The single source of truth for the merge semantics (busy wins; last
    activity is the max across kernels AND terminals; ``kernels is None``
    means the host was unreachable) — shared by the Python and native
    probers so they cannot diverge.
    """
    activity = HostActivity(host=host)
    if kernels is None:
        activity.reachable = False
        return activity
    for kernel in kernels:
        if kernel.get("execution_state") == "busy":
            activity.busy = True
        ts = _parse_jupyter_time(kernel.get("last_activity", ""))
        if ts is not None:
            activity.last_activity = max(activity.last_activity or 0.0, ts)
    for term in terminals or []:
        ts = _parse_jupyter_time(term.get("last_activity", ""))
        if ts is not None:
            activity.last_activity = max(activity.last_activity or 0.0, ts)
    return activity


def _parse_jupyter_time(value: str) -> Optional[float]:
    """Jupyter emits e.g. 2026-07-29T12:00:00.123456Z."""
    if not value:
        return None
    value = value.split(".")[0].rstrip("Z") + "Z"
    return _parse(value)


class CullingReconciler(Reconciler):
    def __init__(
        self,
        client: Client,
        config: Optional[CullerConfig] = None,
        prober: Optional[ActivityProber] = None,
        metrics: Optional[Metrics] = None,
        recorder: Optional[EventRecorder] = None,
        clock: Optional[Callable[[], float]] = None,
        migration_trigger: Optional[Callable[[dict, str], None]] = None,
    ):
        self.client = client
        self.config = config or CullerConfig(enable_culling=True)
        self.prober = prober or JupyterHTTPProber()
        self.metrics = metrics or Metrics(client)
        self.recorder = recorder or EventRecorder(client, component="culler")
        self.clock = clock or time.time
        # Optional hook into runtime/migration.py: called with (notebook
        # object, "idle-cull") just before the stop annotation lands, so
        # an emergency save can start while the slice still exists. The
        # cull itself proceeds regardless — migration is an optimization,
        # never a gate on reclaiming idle chips.
        self.migration_trigger = migration_trigger

    def register(self, manager: Manager) -> None:
        manager.register(self, for_kind="Notebook", name="Culler")

    # ------------------------------------------------------------------
    def reconcile(self, req: Request) -> Result:
        if not self.config.enable_culling:
            return Result()
        try:
            obj = self.client.get("Notebook", req.name, req.namespace)
        except NotFoundError:
            return Result()
        if "deletionTimestamp" in obj["metadata"]:
            return Result()
        nb = Notebook(obj)
        now = self.clock()

        # Stopped → clear activity annotations, no requeue until resumed
        # (reference :105-118).
        if nb.stopped:
            self._remove_activity_annotations(nb)
            return Result()

        # Pod 0 gone → nothing to probe (reference :121-139).
        if not self.client.list(
            "Pod", nb.namespace, {ann.NOTEBOOK_NAME_LABEL: nb.name}
        ):
            self._remove_activity_annotations(nb)
            return Result(requeue_after=self._period_s())

        anns = obj.get("metadata", {}).get("annotations", {})
        if ann.LAST_ACTIVITY not in anns or ann.LAST_ACTIVITY_CHECK not in anns:
            self._init_activity_annotations(nb, now)
            return Result(requeue_after=self._period_s())

        last_check = _parse(anns.get(ann.LAST_ACTIVITY_CHECK, "")) or 0.0
        elapsed = now - last_check
        if elapsed < self._period_s():
            return Result(requeue_after=self._period_s() - elapsed)

        activities = self.prober.probe(nb, self._host_dns(nb))
        if activities and not any(a.reachable for a in activities):
            # Whole slice unobservable (partition, NetPol misconfig): never
            # cull blind — idle and unreachable are indistinguishable. The
            # reference bails the same way when the kernels endpoint errors
            # (getNotebookApiKernels :277-322 returns without updating).
            return Result(requeue_after=self._period_s())
        self._update_activity(nb, [a for a in activities if a.reachable], now)

        obj = self.client.get("Notebook", nb.name, nb.namespace)
        nb = Notebook(obj)
        last_activity = _parse(nb.annotations.get(ann.LAST_ACTIVITY, "")) or now
        if now - last_activity > self.config.cull_idle_time_min * 60:
            self._cull(nb, now, last_activity)
            return Result()
        return Result(requeue_after=self._period_s())

    # ------------------------------------------------------------------
    def _period_s(self) -> float:
        return self.config.idleness_check_period_min * 60.0

    def _host_dns(self, nb: Notebook) -> list[str]:
        if nb.tpu is not None:
            try:
                topo = nb.tpu.slice_topology()
            except Exception:
                topo = None
            slices = nb.tpu.slice_count
            if topo is not None and (topo.hosts > 1 or slices > 1):
                # Every host of EVERY slice: activity anywhere (profiling
                # server, distributed worker) must block the cull.
                return [
                    host
                    for sts in slice_sts_names(nb.name, slices)
                    for host in topo.worker_hostnames(
                        sts,
                        headless_service_name(nb.name),
                        nb.namespace,
                        self.config.cluster_domain,
                    )
                ]
        # Single pod: route via the plain Service, as the reference does.
        from kubeflow_tpu.api.names import routing_service_name

        return [
            f"{routing_service_name(nb.name)}.{nb.namespace}"
            f".svc.{self.config.cluster_domain}"
        ]

    def _update_activity(
        self, nb: Notebook, activities: list[HostActivity], now: float
    ) -> None:
        """Merge host activity with the monotonic guard (reference
        updateTimestampFromKernelsActivity :380-437 generalized to N hosts)."""
        busy = any(a.busy for a in activities)
        observed: Optional[float] = None
        for a in activities:
            if a.last_activity is not None:
                observed = max(observed or 0.0, a.last_activity)
        if busy:
            new_activity: Optional[float] = now
        else:
            new_activity = observed

        def write():
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
            anns = obj_util.annotations_of(fresh)
            if new_activity is not None:
                current = _parse(anns.get(ann.LAST_ACTIVITY, ""))
                # Monotonic: never move last-activity backwards.
                if current is None or new_activity > current:
                    anns[ann.LAST_ACTIVITY] = _fmt(new_activity)
            anns[ann.LAST_ACTIVITY_CHECK] = _fmt(now)
            self.client.update(fresh)

        retry_on_conflict(write)

    def _init_activity_annotations(self, nb: Notebook, now: float) -> None:
        def write():
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
            anns = obj_util.annotations_of(fresh)
            anns.setdefault(ann.LAST_ACTIVITY, _fmt(now))
            anns.setdefault(ann.LAST_ACTIVITY_CHECK, _fmt(now))
            self.client.update(fresh)

        retry_on_conflict(write)

    def _remove_activity_annotations(self, nb: Notebook) -> None:
        def write():
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
            removed = obj_util.remove_annotation(fresh, ann.LAST_ACTIVITY)
            removed |= obj_util.remove_annotation(fresh, ann.LAST_ACTIVITY_CHECK)
            if removed:
                self.client.update(fresh)

        retry_on_conflict(write)

    def _cull(self, nb: Notebook, now: float, last_activity: float) -> None:
        """Set the stop annotation → core reconciler scales slice to 0
        atomically (reference setStopAnnotation :484-500)."""
        chips = 0
        if nb.tpu is not None:
            try:
                chips = nb.tpu.slice_topology().chips
            except Exception:
                chips = 0

        if self.migration_trigger is not None:
            # Fire BEFORE the stop annotation: the save step needs the
            # slice pods alive. A hook crash must not block the cull.
            try:
                self.migration_trigger(nb.obj, "idle-cull")
            except Exception:
                log.exception(
                    "migration trigger (idle-cull) raised; culling anyway"
                )

        def write():
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
            anns = obj_util.annotations_of(fresh)
            if ann.STOP in anns:
                return
            anns[ann.STOP] = _fmt(now)
            self.client.update(fresh)

        retry_on_conflict(write)
        self.metrics.culling_total.inc()
        self.metrics.last_culling_timestamp.set(now)
        if chips:
            self.metrics.chips_reclaimed_total.inc(chips)
        idle_min = int((now - last_activity) / 60)
        self.recorder.eventf(
            nb.obj, "Normal", "NotebookCulled",
            f"Notebook idle for {idle_min}m "
            f"(> {self.config.cull_idle_time_min}m); "
            + (f"released {chips} TPU chips" if chips else "stopped"),
        )
