"""Native concurrent slice-activity prober binding.

Loads ``native/libkftpu_prober.so`` (see ``native/prober.cpp``) via ctypes
and exposes it behind the same ``ActivityProber`` protocol the culler uses
(kubeflow_tpu/controller/culling.py). Sequential probing costs
O(hosts × timeout) when hosts are unreachable; the native prober issues
all GETs concurrently, so an idleness verdict for a 64-host v5p-512 slice
costs one timeout, not sixty-four.

``make_prober()`` is the production factory: native fan-out when the
library is present, else the pure-Python ``JupyterHTTPProber`` (reference
behavior, culling_controller.go:244-322).
"""

from __future__ import annotations

import ctypes
import json
import pathlib
from typing import Optional

from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.controller.culling import (
    HostActivity,
    JupyterHTTPProber,
    fold_host_activity,
)

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libkftpu_prober.so"
# Kernel/terminal lists are a few hundred bytes each; 64 KiB leaves two
# orders of magnitude of headroom without allocating megabytes per cycle.
_BODY_CAP = 64 << 10


def _load_lib() -> Optional[ctypes.CDLL]:
    if not _LIB_PATH.exists():
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError:
        return None
    lib.pr_probe.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.pr_probe.restype = ctypes.c_int
    return lib


class NativeFanoutProber:
    """ActivityProber using the C++ concurrent prober.

    Probes ``/api/kernels`` and ``/api/terminals`` on every host in one
    concurrent batch (2 URLs per host), then folds responses into
    per-host ``HostActivity`` exactly like the Python prober does.
    """

    def __init__(
        self,
        timeout_s: float = 5.0,
        lib: Optional[ctypes.CDLL] = None,
        port: int = 8888,
    ):
        self.timeout_s = timeout_s
        self.port = port
        self._lib = lib if lib is not None else _load_lib()
        if self._lib is None:
            raise RuntimeError(f"native prober not available at {_LIB_PATH}")

    def probe(self, nb: Notebook, hosts: list[str]) -> list[HostActivity]:
        urls: list[str] = []
        for host in hosts:
            base = f"http://{host}:{self.port}/notebook/{nb.namespace}/{nb.name}"
            urls.append(f"{base}/api/kernels")
            urls.append(f"{base}/api/terminals")
        statuses, bodies = self._raw_probe(urls)

        out: list[HostActivity] = []
        for i, host in enumerate(hosts):
            kernels = _decode(statuses[2 * i], bodies[2 * i])
            if kernels is _TRUNCATED:
                # The kernel list overflowed _BODY_CAP — hundreds of
                # kernels means the server is plainly in use. Mark busy
                # (refreshes last-activity upstream) rather than
                # unreachable: an "unobservable" verdict would trip the
                # never-cull-blind rule and hold the slice forever.
                out.append(HostActivity(host=host, busy=True))
                continue
            terminals = _decode(statuses[2 * i + 1], bodies[2 * i + 1])
            if terminals is _TRUNCATED:
                terminals = None
            out.append(fold_host_activity(host, kernels, terminals))
        return out

    def _raw_probe(self, urls: list[str]) -> tuple[list[int], list[bytes]]:
        n = len(urls)
        if n == 0:
            return [], []
        c_urls = (ctypes.c_char_p * n)(*[u.encode() for u in urls])
        bodies = ctypes.create_string_buffer(n * _BODY_CAP)
        statuses = (ctypes.c_int * n)()
        rc = self._lib.pr_probe(
            c_urls,
            n,
            int(self.timeout_s * 1000),
            bodies,
            _BODY_CAP,
            statuses,
        )
        if rc != 0:
            raise RuntimeError(f"pr_probe returned {rc}")
        # string_at with no length stops at the first NUL, so only the
        # actual response bytes are copied out — not n × _BODY_CAP.
        base = ctypes.addressof(bodies)
        out_bodies = [
            ctypes.string_at(base + i * _BODY_CAP) for i in range(n)
        ]
        return list(statuses), out_bodies


# Sentinel: HTTP 200 but the body filled _BODY_CAP and won't parse — the
# response was cut mid-JSON, which is a "very long kernel list", not an
# unreachable host.
_TRUNCATED = object()


def _decode(status: int, body: bytes):
    if status != 200:
        return None
    try:
        parsed = json.loads(body.decode())
    except (ValueError, UnicodeDecodeError):
        if len(body) >= _BODY_CAP - 1:
            return _TRUNCATED
        return None
    return parsed if isinstance(parsed, list) else None


def make_prober(timeout_s: float = 5.0, dev_proxy: Optional[str] = None):
    """Production factory: native fan-out if built, Python fallback.

    DEV mode always uses the Python prober (the localhost proxy path,
    reference culling_controller.go:253-257).
    """
    if dev_proxy is None:
        try:
            return NativeFanoutProber(timeout_s=timeout_s)
        except RuntimeError:
            pass
    return JupyterHTTPProber(timeout_s=timeout_s, dev_proxy=dev_proxy)
