"""Platform integrations: CA bundles, runtime images, Elyra/DSPA, pipeline
RBAC, MLflow, legacy OAuth cleanup.

These are the reference's mechanically-independent sub-reconcilers
(SURVEY.md §7 step 8), each following its "optional CR → skip gracefully"
pattern (reference notebook_dspa_secret.go:49-66). File-level reference
anchors given per function.
"""

from __future__ import annotations

import json
import logging
import re
from typing import Optional

import base64

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.names import (
    CA_BUNDLE_CONFIGMAP,
    ELYRA_SECRET_NAME,
    MANAGED_BY_LABEL,
    MANAGED_BY_VALUE,
    RUNTIME_IMAGES_CONFIGMAP,
)
from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.controller import reconcilehelper as helper
from kubeflow_tpu.k8s.client import Client, retry_on_conflict
from kubeflow_tpu.k8s.errors import NotFoundError

log = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# CA trust bundle (reference notebook_controller.go CreateNotebookCertConfigMap
# :533-635: merges up to 3 source ConfigMaps with PEM validation into the
# per-namespace workbench-trusted-ca-bundle)

CA_SOURCE_CONFIGMAPS = (
    ("odh-trusted-ca-bundle", "ca-bundle.crt"),
    ("odh-trusted-ca-bundle", "odh-ca-bundle.crt"),
    ("kube-root-ca.crt", "ca.crt"),
)
CA_TARGET_CONFIGMAP = CA_BUNDLE_CONFIGMAP

_PEM_BLOCK_RE = re.compile(
    r"-----BEGIN CERTIFICATE-----[A-Za-z0-9+/=\s]+-----END CERTIFICATE-----"
)


def validate_pem_bundle(text: str) -> list[str]:
    """Extract well-formed PEM certificate blocks; malformed content is
    dropped rather than poisoning the merged bundle (reference :583-607)."""
    return _PEM_BLOCK_RE.findall(text or "")


def reconcile_ca_bundle(
    client: Client, nb: Notebook, controller_namespace: str
) -> None:
    blocks: list[str] = []
    for cm_name, key in CA_SOURCE_CONFIGMAPS:
        for source_ns in (controller_namespace, nb.namespace):
            try:
                cm = client.get("ConfigMap", cm_name, source_ns)
            except NotFoundError:
                continue
            blocks.extend(validate_pem_bundle(cm.get("data", {}).get(key, "")))
            break
    # Dedup, preserve order.
    seen: set[str] = set()
    unique = [b for b in blocks if not (b in seen or seen.add(b))]
    if not unique:
        # No sources → remove the target so the webhook unmounts it
        # (reference UnsetNotebookCertConfig :668-733).
        try:
            client.delete("ConfigMap", CA_TARGET_CONFIGMAP, nb.namespace)
        except NotFoundError:
            pass
        return
    desired = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": CA_TARGET_CONFIGMAP,
            "namespace": nb.namespace,
            "labels": {MANAGED_BY_LABEL: MANAGED_BY_VALUE},
        },
        "data": {"ca-bundle.crt": "\n".join(unique) + "\n"},
    }
    # Namespace-shared: not owned by one notebook.
    helper.reconcile_child(client, nb.obj, desired, set_owner=False)


# ---------------------------------------------------------------------------
# Runtime images (reference notebook_runtime.go SyncRuntimeImagesConfigMap
# :43-152: ImageStreams labeled opendatahub.io/runtime-image in the
# controller ns → per-user-ns ConfigMap; key sanitization :174-182)

RUNTIME_IMAGE_LABEL = ann.RUNTIME_IMAGE_LABEL


def format_key_name(display_name: str) -> str:
    """Reference formatKeyName :174-182: displayName → ConfigMap key."""
    key = re.sub(r"[^A-Za-z0-9._-]", "-", display_name.strip().lower())
    return key.strip("-._") or "runtime-image"


def sync_runtime_images_config_map(
    client: Client, nb: Notebook, controller_namespace: str
) -> None:
    streams = client.list(
        "ImageStream", controller_namespace, {RUNTIME_IMAGE_LABEL: "true"}
    )
    data = {}
    for stream in streams:
        meta = stream.get("metadata", {})
        display = meta.get("annotations", {}).get(
            ann.RUNTIME_IMAGE_NAME, meta.get("name", "")
        )
        image_ref = ""
        for tag in stream.get("status", {}).get("tags", []):
            items = tag.get("items", [])
            if items:
                image_ref = items[0].get("dockerImageReference", "")
                break
        if not image_ref:
            continue
        data[format_key_name(display) + ".json"] = json.dumps(
            {"display_name": display, "metadata": {"image_name": image_ref}}
        )
    if not data:
        # Sources gone → delete the synced CM (if it is ours) so the
        # webhook unmounts stale runtime definitions.
        try:
            existing = client.get("ConfigMap", RUNTIME_IMAGES_CONFIGMAP, nb.namespace)
            if (
                existing.get("metadata", {}).get("labels", {}).get(MANAGED_BY_LABEL)
                == MANAGED_BY_VALUE
            ):
                client.delete("ConfigMap", RUNTIME_IMAGES_CONFIGMAP, nb.namespace)
        except NotFoundError:
            pass
        return
    desired = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": RUNTIME_IMAGES_CONFIGMAP,
            "namespace": nb.namespace,
            "labels": {MANAGED_BY_LABEL: MANAGED_BY_VALUE},
        },
        "data": data,
    }
    helper.reconcile_child(client, nb.obj, desired, set_owner=False)


# ---------------------------------------------------------------------------
# Elyra / DSPA secret (reference notebook_dspa_secret.go
# SyncElyraRuntimeConfigSecret :305-399, extractElyraRuntimeConfigInfo
# :189-298, getHostnameForPublicEndpoint :106-148)


def _decode_secret_value(data: dict, key: str) -> str:
    """Secret.data values are base64 on the wire; Elyra wants plaintext."""
    raw = data.get(key, "")
    if not raw:
        return ""
    try:
        return base64.b64decode(raw).decode()
    except (ValueError, UnicodeDecodeError):
        return ""


def sync_elyra_runtime_config(
    client: Client, nb: Notebook, gateway_hostname: str = ""
) -> None:
    dspas = client.list("DataSciencePipelinesApplication", nb.namespace)
    if not dspas:
        return  # optional CR absent → skip gracefully (reference :49-66)
    dspa = dspas[0]
    dspa_name = dspa.get("metadata", {}).get("name", "dspa")
    object_storage = (
        dspa.get("spec", {}).get("objectStorage", {}).get("externalStorage", {})
    )
    s3_secret_name = (
        object_storage.get("s3CredentialsSecret", {}).get("secretName", "")
    )
    access_key = secret_key = ""
    if s3_secret_name:
        try:
            s3 = client.get("Secret", s3_secret_name, nb.namespace)
            access_key = _decode_secret_value(s3.get("data", {}), "AWS_ACCESS_KEY_ID")
            secret_key = _decode_secret_value(
                s3.get("data", {}), "AWS_SECRET_ACCESS_KEY"
            )
        except NotFoundError:
            pass
    api_endpoint = (
        f"https://{gateway_hostname}/pipelines/{nb.namespace}/{dspa_name}"
        if gateway_hostname
        else f"https://ds-pipeline-{dspa_name}.{nb.namespace}.svc:8443"
    )
    runtime_config = {
        "display_name": f"Data Science Pipeline: {dspa_name}",
        "schema_name": "kfp",
        "metadata": {
            "api_endpoint": api_endpoint,
            "engine": "Argo",
            "auth_type": "KUBERNETES_SERVICE_ACCOUNT_TOKEN",
            "cos_endpoint": object_storage.get("host", ""),
            "cos_bucket": object_storage.get("bucket", ""),
            "cos_username": access_key,
            "cos_password": secret_key,
            "runtime_type": "KUBEFLOW_PIPELINES",
        },
    }
    desired = {
        "apiVersion": "v1",
        "kind": "Secret",
        "metadata": {
            "name": ELYRA_SECRET_NAME,
            "namespace": nb.namespace,
            "labels": {MANAGED_BY_LABEL: MANAGED_BY_VALUE},
        },
        "stringData": {"odh_dsp.json": json.dumps(runtime_config)},
    }
    # Owned by the DSPA CR, not the notebook (reference :354-363): the
    # secret outlives notebooks and dies with the pipeline application.
    def write():
        try:
            existing = client.get("Secret", ELYRA_SECRET_NAME, nb.namespace)
            if helper.copy_generic_fields(desired, existing):
                client.update(existing)
        except NotFoundError:
            from kubeflow_tpu.k8s import objects as obj_util

            obj_util.set_controller_reference(dspa, desired)
            client.create(desired)

    retry_on_conflict(write)


# ---------------------------------------------------------------------------
# Pipeline RBAC (reference notebook_rbac.go :88-154)

PIPELINE_ROLE = "ds-pipeline-user-access-dspa"


def reconcile_pipeline_rbac(client: Client, nb: Notebook) -> None:
    try:
        client.get("Role", PIPELINE_ROLE, nb.namespace)
    except NotFoundError:
        return  # Role absent → skip (reference behavior)
    desired = {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "RoleBinding",
        "metadata": {
            "name": f"elyra-pipelines-{nb.name}",
            "namespace": nb.namespace,
            "labels": {"notebook-name": nb.name},
        },
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "Role",
            "name": PIPELINE_ROLE,
        },
        "subjects": [
            {"kind": "ServiceAccount", "name": nb.name, "namespace": nb.namespace}
        ],
    }
    helper.reconcile_child(client, nb.obj, desired)


# ---------------------------------------------------------------------------
# MLflow RoleBinding (reference notebook_mlflow.go :236-270: requeue until
# the operator's ClusterRole exists)

MLFLOW_CLUSTER_ROLE = "mlflow-operator-mlflow-integration"


def reconcile_mlflow_rbac(client: Client, nb: Notebook) -> Optional[float]:
    """Returns a requeue-after in seconds while the ClusterRole is missing."""
    if not nb.annotations.get(ann.MLFLOW_INSTANCE):
        return None
    try:
        client.get("ClusterRole", MLFLOW_CLUSTER_ROLE)
    except NotFoundError:
        return 30.0  # reference RequeueAfter 30s (:236-270)
    desired = {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "RoleBinding",
        "metadata": {
            "name": f"mlflow-{nb.name}",
            "namespace": nb.namespace,
            "labels": {"notebook-name": nb.name},
        },
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole",
            "name": MLFLOW_CLUSTER_ROLE,
        },
        "subjects": [
            {"kind": "ServiceAccount", "name": nb.name, "namespace": nb.namespace}
        ],
    }
    helper.reconcile_child(client, nb.obj, desired)
    return None


# ---------------------------------------------------------------------------
# Legacy OAuth cleanup (reference notebook_oauth.go :29-96: pre-3.0 releases
# created one OAuthClient CR per notebook; deletion must reap them)


def cleanup_legacy_oauth_client(client: Client, nb: Notebook) -> None:
    name = f"{nb.name}-{nb.namespace}-oauth-client"
    try:
        client.delete("OAuthClient", name)
    except NotFoundError:
        pass
