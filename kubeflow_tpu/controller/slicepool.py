"""SlicePool reconciler: keep warm TPU slices provisioned; serve claims.

TPU-native subsystem with no reference counterpart (the reference's spawn
path is always cold — SURVEY.md §6 records only CI-timeout expectations).
Mechanics per ``kubeflow_tpu.api.slicepool``:

- level-triggered reconcile maintains ``spec.warmReplicas`` placeholder
  StatefulSets per pool (same nodeSelectors/chip resources as a notebook
  slice; workbench image with an idle command, so nodes stay provisioned
  and images stay pulled),
- ``claim_warm_slice`` (called by the Notebook reconciler just before it
  creates a cold slice STS) deletes one all-Ready placeholder, freeing its
  chips on warm nodes for the incoming notebook pods; the pool's next
  reconcile re-creates the placeholder (refill),
- claimed placeholders are named with a monotonic generation counter so a
  refill never races the apiserver's async cascade-delete of the claimed
  StatefulSet's pods.
"""

from __future__ import annotations

import copy
import logging
import secrets
import time
from typing import Callable, Optional

from kubeflow_tpu.api import slicepool as sp
from kubeflow_tpu.api.names import derived_name
from kubeflow_tpu.api.notebook import MAX_NAME_LENGTH
from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.client import Client, retry_on_conflict
from kubeflow_tpu.k8s.errors import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
)
from kubeflow_tpu.k8s.events import EventRecorder
from kubeflow_tpu.k8s.manager import Manager, Reconciler, Request, Result
from kubeflow_tpu.metrics import Metrics
from kubeflow_tpu.tpu.topology import InvalidTopologyError, SliceTopology

log = logging.getLogger(__name__)


# Shipped by config/manager (deploy.manifests.placeholder_priority_class):
# value < 0 so any default-priority notebook pod preempts placeholder pods.
PLACEHOLDER_PRIORITY_CLASS = "tpu-slicepool-placeholder"


def warm_sts_name(pool_name: str, generation: int) -> str:
    return derived_name(pool_name, f"-warm-{generation}", MAX_NAME_LENGTH)


def generate_warm_statefulset(
    pool: sp.SlicePool, topo: SliceTopology, generation: int
) -> dict:
    """Placeholder slice: real chips + nodeSelectors, idle container.

    The container requests the full per-host chip count so the scheduler
    (and GKE autoscaler) treat it exactly like a notebook slice; the idle
    command never opens the notebook port, so routing/culling ignore it.
    """
    name = warm_sts_name(pool.name, generation)
    labels = {
        sp.POOL_LABEL: pool.name,
        sp.STATE_LABEL: sp.STATE_WARM,
        sp.ACCELERATOR_LABEL: topo.accelerator_type,
        sp.TOPOLOGY_LABEL: topo.topology_str,
        "statefulset": name,
    }
    container = {
        "name": "warm-placeholder",
        "image": pool.image,
        # The workbench image's shell idles; the image itself is the point
        # (kubelet keeps it pulled on every slice node).
        "command": ["/bin/sh", "-c", "sleep infinity"],
        "resources": {
            "limits": {"google.com/tpu": str(topo.chips_per_host)},
            "requests": {"google.com/tpu": str(topo.chips_per_host)},
        },
    }
    pod_spec = {
        "containers": [container],
        "nodeSelector": dict(topo.node_selector()),
        "tolerations": [
            {"key": "google.com/tpu", "operator": "Exists", "effect": "NoSchedule"}
        ],
        # Negative-priority pods (config/manager ships the PriorityClass):
        # notebook pods (priority 0) PREEMPT placeholders, so a refill that
        # races the claiming notebook's pods for the just-freed nodes can
        # never win — the scheduler evicts it in the notebook's favor, and
        # the warm handoff holds without any claim/refill ordering.
        "priorityClassName": PLACEHOLDER_PRIORITY_CLASS,
    }
    return {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {
            "name": name,
            "namespace": pool.namespace,
            "labels": dict(labels),
        },
        "spec": {
            "replicas": topo.hosts,
            "podManagementPolicy": "Parallel",
            "selector": {"matchLabels": {"statefulset": name}},
            # Placeholders need no DNS, but apiserver validation requires a
            # non-empty governing service name (it need not exist) on
            # k8s <= 1.31; the STS's own name keeps it unique and obvious.
            "serviceName": name,
            "template": {"metadata": {"labels": labels}, "spec": pod_spec},
        },
    }


def _sts_ready(sts: dict) -> bool:
    status = sts.get("status", {})
    want = sts.get("spec", {}).get("replicas", 0)
    return want > 0 and status.get("readyReplicas", 0) >= want


class ClaimLost(Exception):
    """Another claimant won this placeholder between our read and our
    write. Raised per candidate by the fenced claim; ``claim_warm_slice``
    catches it and walks on to the next candidate, so two racing claimants
    end up on DISTINCT slices (or one takes a clean miss) — never both
    holding the same one."""


def _claim_candidate(client: Client, chosen: dict, claimant: str) -> None:
    """Atomically take ownership of one placeholder, then delete it.

    The fence is an optimistic-concurrency update: we re-read the
    StatefulSet, reject it if another claimant's CLAIMED_BY fence is
    already on it, stamp our own, and write it back carrying the read's
    resourceVersion. The apiserver's conflict check makes that write the
    atomic claim — a bare delete is check-then-act, and two in-flight
    claimants (an autoscaler tick and a migration, say) can both "win" it.
    Raises ClaimLost when anyone else got there first at any point.
    """
    name = obj_util.name_of(chosen)
    namespace = obj_util.namespace_of(chosen)
    try:
        fresh = client.get("StatefulSet", name, namespace)
    except NotFoundError as err:
        raise ClaimLost(f"{name}: placeholder already deleted") from err
    owner = obj_util.annotations_of(fresh).get(sp.CLAIMED_BY)
    if owner and owner != claimant:
        raise ClaimLost(f"{name}: fenced by {owner}")
    obj_util.set_annotation(fresh, sp.CLAIMED_BY, claimant)
    try:
        client.update(fresh)
    except (ConflictError, NotFoundError) as err:
        raise ClaimLost(f"{name}: fence write lost ({err})") from err
    try:
        client.delete("StatefulSet", name, namespace)
    except NotFoundError as err:
        # Deleted despite a won fence (e.g. an out-of-band GC): the slice
        # is gone either way — surface it as a lost claim, not a success.
        raise ClaimLost(f"{name}: deleted after fence") from err


def claim_warm_slice(
    client: Client,
    namespace: str,
    topo: SliceTopology,
    recorder: Optional[EventRecorder] = None,
    notebook: Optional[dict] = None,
    now: Optional[float] = None,
    pools: Optional[list] = None,
    deadline: Optional[float] = None,
    claimant: Optional[str] = None,
) -> Optional[str]:
    """Claim one warm placeholder matching (accelerator, topology).

    Returns the pool name, or None when no matching warm slice exists.
    Prefers an all-Ready placeholder (nodes provisioned AND image pulled);
    falls back to a still-warming one — even a partially-provisioned
    placeholder beats a cold node-pool scale-up. Deleting the StatefulSet
    cascades to its pods, releasing chips for the notebook's pods.

    Each candidate is taken through the CLAIMED_BY fence (see
    ``_claim_candidate``): concurrent claimants — recovery escalation, a
    migration, the fleet autoscaler — conflict-retry onto distinct slices
    instead of double-claiming one. ``claimant`` names this claim in the
    fence annotation; a fresh random identity is minted when omitted.

    ``deadline`` (a ``time.perf_counter()`` instant) bounds the candidate
    walk: a fleet-wide delete-race pileup or a crawling apiserver turns
    into a clean miss instead of wedging the caller — the gateway's
    autoscaler treats that miss as a claim failure and backs off.

    Demand signals for the autoscaler: a successful claim stamps
    LAST_CLAIM on the owning pool; a miss stamps LAST_MISS and increments
    MISS_COUNT on every topology-matching AUTOSCALED pool in the namespace
    (callers pass ``now``, and may pass a prefetched ``pools`` list to
    avoid a second SlicePool list on the spawn path). Fixed-size pools
    never read the signals, so they are never written.
    """
    candidates = client.list(
        "StatefulSet",
        namespace,
        label_selector={
            sp.STATE_LABEL: sp.STATE_WARM,
            sp.ACCELERATOR_LABEL: topo.accelerator_type,
            sp.TOPOLOGY_LABEL: topo.topology_str,
        },
    )
    # Ready placeholders first, then still-warming ones; on a lost claim
    # race (a concurrent claimant's fence or delete got there first) fall
    # through to the next candidate instead of going cold while warm
    # capacity remains.
    ordered = sorted(candidates, key=lambda s: not _sts_ready(s))
    claimant = claimant or f"claim-{secrets.token_hex(4)}"
    for chosen in ordered:
        if deadline is not None and time.perf_counter() >= deadline:
            return None  # bounded claim: a timed-out walk is a miss
        pool_name = obj_util.labels_of(chosen).get(sp.POOL_LABEL, "")
        try:
            _claim_candidate(client, chosen, claimant)
        except ClaimLost as lost:
            log.info("warm-slice claim by %s moved on: %s", claimant, lost)
            continue
        if recorder is not None and notebook is not None:
            recorder.eventf(
                notebook, "Normal", "ClaimedWarmSlice",
                f"Claimed warm slice {obj_util.name_of(chosen)} from pool "
                f"{pool_name} ({topo.accelerator_type})",
            )
        if now is not None and pool_name:
            _stamp(client, namespace, [pool_name], sp.LAST_CLAIM, now)
        return pool_name or None
    if now is not None:
        if pools is None:
            pools = client.list("SlicePool", namespace)
        matching = [
            obj_util.name_of(p)
            for p in pools
            if _pool_matches(p, topo)
        ]
        _stamp(
            client, namespace, matching, sp.LAST_MISS, now,
            count_key=sp.MISS_COUNT,
        )
    return None


def _pool_matches(pool_obj: dict, topo: SliceTopology) -> bool:
    pool = sp.SlicePool(pool_obj)
    if pool.autoscale is None:
        return False  # fixed pools never read demand signals
    try:
        pt = pool.tpu.slice_topology()
    except Exception:
        return False
    return (
        pt.accelerator_type == topo.accelerator_type
        and pt.topology_str == topo.topology_str
    )


def _stamp(
    client: Client, namespace: str, pool_names: list, key: str, now: float,
    count_key: Optional[str] = None,
) -> None:
    """Demand-signal write. Conflicts are RETRIED (the usual conflicting
    writer is the pool reconciler updating status — losing the race must
    not lose the miss/claim signal); only a deleted pool is skipped. The
    claim-side autoscale gate lives in _pool_matches for misses; claims
    stamp only autoscaled pools too. ``count_key`` additionally increments
    a monotonic counter so N concurrent signals count as N."""
    for name in pool_names:

        def write(name=name):
            try:
                pool = client.get("SlicePool", name, namespace)
            except NotFoundError:
                return
            if sp.SlicePool(pool).autoscale is None:
                return  # nothing reads signals on fixed pools
            obj_util.set_annotation(pool, key, str(now))
            if count_key is not None:
                anns = obj_util.annotations_of(pool)
                try:
                    seen = int(anns.get(count_key, "0"))
                except ValueError:
                    seen = 0
                obj_util.set_annotation(pool, count_key, str(seen + 1))
            client.update(pool)

        retry_on_conflict(write)


class SlicePoolReconciler(Reconciler):
    """Maintains each pool's placeholder StatefulSets and status."""

    def __init__(
        self,
        client: Client,
        metrics: Optional[Metrics] = None,
        recorder: Optional[EventRecorder] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.client = client
        self.metrics = metrics
        self.recorder = recorder or EventRecorder(client)
        self.clock = clock or time.time

    def register(self, manager: Manager) -> None:
        manager.register(
            self,
            for_kind="SlicePool",
            owns=("StatefulSet",),
            name="SlicePoolReconciler",
        )

    def reconcile(self, req: Request) -> Result:
        try:
            obj = self.client.get("SlicePool", req.name, req.namespace)
        except NotFoundError:
            self._drop_gauge(req.name)
            return Result()  # placeholders go via ownerReference GC
        if "deletionTimestamp" in obj["metadata"]:
            self._drop_gauge(req.name)
            return Result()
        pool = sp.SlicePool(obj)

        try:
            topo = pool.tpu.slice_topology()
        except InvalidTopologyError as err:
            self.recorder.eventf(obj, "Warning", "InvalidTPUTopology", str(err))
            pool.status["conditions"] = [
                {
                    "type": "TopologyValid",
                    "status": "False",
                    "reason": "InvalidTopology",
                    "message": str(err),
                }
            ]
            self._write_status(obj)
            return Result()

        warm_target, requeue, scale_status = self._warm_target(pool)

        owned = [
            s
            for s in self.client.list(
                "StatefulSet", pool.namespace,
                label_selector={sp.POOL_LABEL: pool.name},
            )
            if obj_util.is_controlled_by(obj, s)
        ]
        # Refill names never reuse a generation — not even a deleted one
        # (on a real apiserver the claimed StatefulSet lingers while its
        # cascade-delete runs; recreating the same name would fail). The
        # high-water mark persists in status.
        next_gen = max(
            int(pool.status.get("generation", 0)),
            1 + max((_generation_of(s) for s in owned), default=-1),
        )
        changed = False
        while len(owned) < warm_target:
            desired = generate_warm_statefulset(pool, topo, next_gen)
            obj_util.set_controller_reference(obj, desired)
            try:
                created = self.client.create(desired)
                owned.append(created)
                changed = True
            except AlreadyExistsError:
                pass  # stale cache; the next event re-reconciles
            next_gen += 1
        # Scale-down: retire the newest (least likely to be fully warm).
        overs = sorted(owned, key=_generation_of)[warm_target:]
        for extra in overs:
            try:
                self.client.delete(
                    "StatefulSet", obj_util.name_of(extra), pool.namespace
                )
                changed = True
            except NotFoundError:
                pass
        kept = sorted(owned, key=_generation_of)[:warm_target]

        ready = sum(1 for s in kept if _sts_ready(s))
        pool.status.update(
            {
                "generation": next_gen,
                "warmReplicas": len(kept),
                "readyReplicas": ready,
                "conditions": [
                    {
                        "type": "TopologyValid",
                        "status": "True",
                        "reason": "Resolved",
                        "message": f"{topo.accelerator_type} ({topo.hosts} hosts)",
                    }
                ],
                **scale_status,
            }
        )
        self._write_status(obj)
        if self.metrics is not None:
            self.metrics.pool_warm_ready.labels(pool.name).set(ready)
        if changed:
            log.info(
                "slicepool %s/%s: %d warm (%d ready)",
                pool.namespace, pool.name, len(kept), ready,
            )
        return Result(requeue_after=requeue)

    def _write_status(self, pool_obj: dict) -> None:
        """Push this reconcile's computed status, retrying conflicts.

        The usual concurrent writer is claim_warm_slice stamping demand
        annotations — a bare update_status here would lose that race with
        a 409 and abort the whole reconcile mid-transition. The computed
        status is authoritative for this reconcile, so a retry re-reads
        only the resourceVersion, not the decision."""
        desired = copy.deepcopy(pool_obj.get("status", {}))
        name = obj_util.name_of(pool_obj)
        namespace = obj_util.namespace_of(pool_obj)

        def write():
            try:
                fresh = self.client.get("SlicePool", name, namespace)
            except NotFoundError:
                return
            fresh["status"] = desired
            self.client.update_status(fresh)

        retry_on_conflict(write)

    def _warm_target(self, pool: sp.SlicePool) -> tuple[int, float, dict]:
        """(warm target, requeue seconds, status fields).

        Fixed pools: spec.warmReplicas, no requeue. Autoscaled pools: the
        target persists in status and moves one step per reconcile — up
        when a miss postdates the last scale event (demand outran the
        pool), down after scaleDownAfterSeconds with no claim/miss (the
        periodic requeue is what notices pure idleness).
        """
        auto = pool.autoscale
        if auto is None:
            # A pool switched back to fixed sizing must not keep exporting
            # (or later resurrect) autoscaler state — including the demand
            # ANNOTATIONS, or a re-enable would read a stale miss counter
            # against a fresh missCountSeen and scale up on dead demand.
            for key in ("autoscaleTarget", "lastScaleTime", "missCountSeen"):
                pool.status.pop(key, None)
            self._clear_demand_annotations(pool)
            return pool.warm_replicas, 0.0, {}
        lo, hi = auto["min"], auto["max"]
        cooldown = auto["scaleDownAfterSeconds"]
        now = self.clock()
        target = int(pool.status.get("autoscaleTarget", lo))
        target = max(lo, min(hi, target))
        last_scale = float(pool.status.get("lastScaleTime", 0))

        def stamp(key):
            value = pool.obj.get("metadata", {}).get("annotations", {}).get(key)
            try:
                return float(value)
            except (TypeError, ValueError):
                return 0.0

        last_miss, last_claim = stamp(sp.LAST_MISS), stamp(sp.LAST_CLAIM)
        # Misses are a COUNTER so N concurrent cold spawns grow the target
        # by N in one reconcile; the timestamps only feed idle detection.
        miss_count = int(stamp(sp.MISS_COUNT))
        seen = int(pool.status.get("missCountSeen", 0))
        fresh_misses = max(0, miss_count - seen)
        if fresh_misses and target < hi:
            target = min(hi, target + fresh_misses)
            last_scale = now
        elif (
            target > lo
            and now - max(last_miss, last_claim, last_scale) >= cooldown
        ):
            target -= 1
            last_scale = now
        return target, float(cooldown), {
            "autoscaleTarget": target,
            "lastScaleTime": last_scale,
            "missCountSeen": miss_count,
        }

    def _clear_demand_annotations(self, pool: sp.SlicePool) -> None:
        keys = (sp.LAST_MISS, sp.LAST_CLAIM, sp.MISS_COUNT)
        anns = pool.obj.get("metadata", {}).get("annotations", {})
        if not any(k in anns for k in keys):
            return

        def write():
            try:
                fresh = self.client.get("SlicePool", pool.name, pool.namespace)
            except NotFoundError:
                return
            removed = [obj_util.remove_annotation(fresh, k) for k in keys]
            if any(removed):  # list, not genexpr: every key must be removed
                self.client.update(fresh)

        retry_on_conflict(write)

    def _drop_gauge(self, pool_name: str) -> None:
        """A deleted pool must not keep exporting its last warm count."""
        if self.metrics is None:
            return
        try:
            self.metrics.pool_warm_ready.remove(pool_name)
        except KeyError:
            pass  # never set for this pool


def _generation_of(sts: dict) -> int:
    name = obj_util.name_of(sts)
    try:
        return int(name.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return 0
