"""kube-rbac-proxy backing resources (controller side of auth mode).

Rebuild of reference components/odh-notebook-controller/controllers/
notebook_kube_rbac_auth.go: per-notebook ServiceAccount (:48-92), the
``{name}-kube-rbac-proxy`` Service on 8443 with OpenShift serving-cert
annotation (:95-159), the SubjectAccessReview ConfigMap (:180-282), and the
ClusterRoleBinding to ``system:auth-delegator`` (:287-342) — CRBs are
cluster-scoped so they cannot be owned and need manual cleanup (:346-368).
"""

from __future__ import annotations

import json

from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.controller import reconcilehelper as helper
from kubeflow_tpu.k8s.client import Client
from kubeflow_tpu.k8s.errors import NotFoundError
from kubeflow_tpu.webhook.auth_sidecar import (
    RBAC_PROXY_PORT,
    rbac_config_map_name,
    service_account_name,
    tls_secret_name,
)


from kubeflow_tpu.api.names import proxy_service_name  # noqa: F401  (shared
# with routes.py so the HTTPRoute backendRef always matches the Service,
# including the long-name hashed fallback)


def crb_name(nb: Notebook) -> str:
    return f"{nb.namespace}-{nb.name}-auth-delegator"


def new_service_account(nb: Notebook) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "ServiceAccount",
        "metadata": {
            "name": service_account_name(nb.name),
            "namespace": nb.namespace,
            "labels": {"notebook-name": nb.name},
        },
    }


def new_proxy_service(nb: Notebook) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": proxy_service_name(nb.name),
            "namespace": nb.namespace,
            "labels": {"notebook-name": nb.name},
            "annotations": {
                # OpenShift mints the TLS pair the sidecar serves with.
                "service.beta.openshift.io/serving-cert-secret-name": tls_secret_name(
                    nb.name
                ),
            },
        },
        "spec": {
            "type": "ClusterIP",
            "selector": {
                "statefulset": nb.name,
                "apps.kubernetes.io/pod-index": "0",
            },
            "ports": [
                {
                    "name": "https",
                    "port": RBAC_PROXY_PORT,
                    "targetPort": RBAC_PROXY_PORT,
                    "protocol": "TCP",
                }
            ],
        },
    }


def new_proxy_config_map(nb: Notebook) -> dict:
    """SubjectAccessReview config: access requires ``get`` on this Notebook
    (reference :180-282)."""
    config = {
        "authorization": {
            "resourceAttributes": {
                "apiGroup": "kubeflow.org",
                "resource": "notebooks",
                "verb": "get",
                "namespace": nb.namespace,
                "name": nb.name,
            }
        }
    }
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": rbac_config_map_name(nb.name),
            "namespace": nb.namespace,
            "labels": {"notebook-name": nb.name},
        },
        "data": {"config-file.yaml": json.dumps(config, indent=2)},
    }


def new_auth_delegator_crb(nb: Notebook) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRoleBinding",
        "metadata": {
            "name": crb_name(nb),
            "labels": {
                "notebook-name": nb.name,
                "notebook-namespace": nb.namespace,
            },
        },
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole",
            "name": "system:auth-delegator",
        },
        "subjects": [
            {
                "kind": "ServiceAccount",
                "name": service_account_name(nb.name),
                "namespace": nb.namespace,
            }
        ],
    }


def reconcile_auth_bundle(client: Client, nb: Notebook) -> None:
    """SA + Service + ConfigMap + CRB for auth mode (webhook injects the
    sidecar itself)."""
    helper.reconcile_child(client, nb.obj, new_service_account(nb))
    helper.reconcile_child(
        client, nb.obj, new_proxy_service(nb), helper.copy_service_fields
    )
    helper.reconcile_child(client, nb.obj, new_proxy_config_map(nb))
    desired_crb = new_auth_delegator_crb(nb)
    # Cluster-scoped: cannot carry a namespaced owner ref (reference :287).
    helper.reconcile_child(client, nb.obj, desired_crb, set_owner=False)


def cleanup_auth_bundle(client: Client, nb: Notebook) -> None:
    """Owned objects GC with the notebook; only the CRB needs manual
    deletion (reference :346-368). Used on both auth-mode-off and deletion."""
    try:
        client.delete("ClusterRoleBinding", crb_name(nb))
    except NotFoundError:
        pass


def cleanup_auth_mode_off(client: Client, nb: Notebook) -> None:
    """Mode switch auth→plain: remove the whole bundle (reference
    notebook_controller.go:479-497)."""
    cleanup_auth_bundle(client, nb)
    for kind, name in (
        ("ServiceAccount", service_account_name(nb.name)),
        ("Service", proxy_service_name(nb.name)),
        ("ConfigMap", rbac_config_map_name(nb.name)),
    ):
        try:
            client.delete(kind, name, nb.namespace)
        except NotFoundError:
            pass
