"""Create-or-update helpers with field-copy diffing.

Counterpart of the reference's shared helper library
(components/common/reconcilehelper/util.go:18-219). One deliberate fix over
the reference: ``copy_statefulset_fields`` there only diffs
labels/annotations/replicas to decide whether to Update but always overwrites
``Template.Spec`` (util.go:107-134, flagged in SURVEY.md §2.3 as a sharp
edge) — meaning template drift alone never triggered an Update. Here the
template participates in the diff, so webhook-injected template changes
actually roll out.
"""

from __future__ import annotations

import copy
from typing import Callable

from kubeflow_tpu.k8s.client import Client, retry_on_conflict
from kubeflow_tpu.k8s.errors import NotFoundError
from kubeflow_tpu.k8s import objects as obj_util


def copy_statefulset_fields(desired: dict, existing: dict) -> bool:
    """Copy reconcile-relevant STS fields onto ``existing``; True if changed."""
    changed = _copy_meta(desired, existing)
    for field in ("replicas", "template", "podManagementPolicy", "serviceName"):
        want = desired.get("spec", {}).get(field)
        have = existing.get("spec", {}).get(field)
        if want != have:
            existing.setdefault("spec", {})[field] = copy.deepcopy(want)
            changed = True
    return changed


def copy_service_fields(desired: dict, existing: dict) -> bool:
    """Copy Service fields, deliberately preserving the allocated ClusterIP
    (reference util.go:166-195)."""
    changed = _copy_meta(desired, existing)
    want_spec = copy.deepcopy(desired.get("spec", {}))
    have_spec = existing.get("spec", {})
    # ClusterIP is allocated by the API server; never copy it.
    want_spec.pop("clusterIP", None)
    comparable_have = {k: v for k, v in have_spec.items() if k != "clusterIP"}
    if want_spec != comparable_have:
        preserved = have_spec.get("clusterIP")
        existing["spec"] = want_spec
        if preserved is not None:
            existing["spec"]["clusterIP"] = preserved
        changed = True
    return changed


def copy_virtual_service_fields(desired: dict, existing: dict) -> bool:
    """Istio VirtualService: meta + whole-spec copy (reference
    CopyVirtualService, util.go:199-219 — nested-map spec compare, update
    when drifted)."""
    changed = _copy_meta(desired, existing)
    want = desired.get("spec")
    if want is not None and existing.get("spec") != want:
        existing["spec"] = copy.deepcopy(want)
        changed = True
    return changed


def copy_generic_fields(desired: dict, existing: dict) -> bool:
    """Labels/annotations + every non-meta top-level field (ConfigMap data,
    NetworkPolicy/HTTPRoute/RoleBinding specs, ...)."""
    changed = _copy_meta(desired, existing)
    for key, value in desired.items():
        if key in ("apiVersion", "kind", "metadata", "status"):
            continue
        if existing.get(key) != value:
            existing[key] = copy.deepcopy(value)
            changed = True
    return changed


def _copy_meta(desired: dict, existing: dict) -> bool:
    changed = False
    for field in ("labels", "annotations"):
        want = desired.get("metadata", {}).get(field)
        if want is not None and existing.get("metadata", {}).get(field) != want:
            existing.setdefault("metadata", {})[field] = copy.deepcopy(want)
            changed = True
    return changed


def reconcile_child(
    client: Client,
    owner: dict,
    desired: dict,
    copy_fields: Callable[[dict, dict], bool] = copy_generic_fields,
    set_owner: bool = True,
) -> dict:
    """Level-triggered create-or-update of one owned child object."""
    if set_owner:
        obj_util.set_controller_reference(owner, desired)
    kind = desired.get("kind", "")
    name = obj_util.name_of(desired)
    namespace = obj_util.namespace_of(desired)
    def write():
        try:
            existing = client.get(kind, name, namespace)
        except NotFoundError:
            return client.create(desired)
        if copy_fields(desired, existing):
            return client.update(existing)
        return existing

    # The conflicting writer is usually a status update racing the spec
    # copy; re-running the whole read-modify-write re-diffs against the
    # fresh object, so the retry cannot clobber the other writer.
    return retry_on_conflict(write)
