"""Slice health / preemption recovery controller.

TPU slices on spot or maintenance-window capacity lose hosts without warning;
the reference has no analogue (SURVEY.md §7 "Hard parts": "Preemption/
maintenance events have no reference analogue; design from scratch against
the event-re-emission + conditions machinery"). Design:

- Watch slice pods. A pod that dies with a DisruptionTarget condition or a
  Preempted/Evicted reason marks the whole Notebook ``SliceInterrupted``
  (condition + annotation + Warning event) — a partial slice is useless, so
  interruption is a slice-level state, not a pod-level one.
- Recovery is level-triggered: the failed pod is deleted so the StatefulSet
  controller (FakeKubelet in tests, kubelet in prod) recreates it; when every
  host is Ready again the interruption clears and a SliceRecovered event is
  emitted. In-notebook state is gone (jax.distributed must re-init) but the
  *capacity* and the user's Jupyter session recover without dashboard action.
"""

from __future__ import annotations

import logging
from typing import Optional

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.client import Client, retry_on_conflict
from kubeflow_tpu.k8s.errors import NotFoundError
from kubeflow_tpu.k8s.events import EventRecorder
from kubeflow_tpu.k8s.manager import Manager, Reconciler, Request, Result
from kubeflow_tpu.metrics import Metrics

log = logging.getLogger(__name__)

_PREEMPTION_REASONS = {"Preempted", "Evicted", "TerminationByKubernetes"}


def _pod_preempted(pod: dict) -> Optional[str]:
    status = pod.get("status", {})
    if status.get("reason") in _PREEMPTION_REASONS:
        return status.get("reason")
    for cond in status.get("conditions", []):
        if cond.get("type") == "DisruptionTarget" and cond.get("status") == "True":
            return cond.get("reason", "DisruptionTarget")
    if status.get("phase") == "Failed":
        return status.get("reason", "PodFailed")
    return None


class SliceHealthReconciler(Reconciler):
    def __init__(
        self,
        client: Client,
        metrics: Optional[Metrics] = None,
        recorder: Optional[EventRecorder] = None,
    ):
        self.client = client
        self.metrics = metrics or Metrics(client)
        self.recorder = recorder or EventRecorder(client, component="slice-health")

    def register(self, manager: Manager) -> None:
        manager.register(
            self,
            for_kind="Notebook",
            watches=[("Pod", _pod_to_notebook)],
            name="SliceHealth",
        )

    def reconcile(self, req: Request) -> Result:
        try:
            obj = self.client.get("Notebook", req.name, req.namespace)
        except NotFoundError:
            return Result()
        nb = Notebook(obj)
        if nb.tpu is None or "deletionTimestamp" in obj["metadata"]:
            return Result()

        pods = self.client.list(
            "Pod", nb.namespace, {ann.NOTEBOOK_NAME_LABEL: nb.name}
        )
        failed = [(p, _pod_preempted(p)) for p in pods]
        failed = [(p, reason) for p, reason in failed if reason]

        if failed:
            for pod, reason in failed:
                self.metrics.slice_preemptions_total.inc()
                self.recorder.eventf(
                    obj, "Warning", "SliceInterrupted",
                    f"Host pod {obj_util.name_of(pod)} lost ({reason}); "
                    "recreating — in-notebook JAX state is gone",
                )
                # Delete so the STS/kubelet recreates the host pod.
                try:
                    self.client.delete("Pod", obj_util.name_of(pod), nb.namespace)
                except NotFoundError:
                    pass
            self._mark_interrupted(nb, failed[0][1])
            return Result()

        # No failed pods: clear interruption once the slice is whole again.
        if ann.TPU_SLICE_INTERRUPTED in nb.annotations:
            try:
                # ALL hosts of ALL slices must be Ready again (a 2-slice
                # notebook has hosts×2 pods; comparing against one slice's
                # host count would leave the interruption set forever).
                hosts = nb.tpu.slice_topology().hosts * nb.tpu.slice_count
            except Exception:
                return Result()
            ready = sum(1 for p in pods if _pod_ready(p))
            if ready == hosts:
                self._clear_interrupted(nb)
                self.recorder.eventf(
                    obj, "Normal", "SliceRecovered",
                    f"All {hosts} slice hosts Ready again",
                )
        return Result()

    def _mark_interrupted(self, nb: Notebook, reason: str) -> None:
        def write():
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
            anns = obj_util.annotations_of(fresh)
            if anns.get(ann.TPU_SLICE_INTERRUPTED) == reason:
                return
            anns[ann.TPU_SLICE_INTERRUPTED] = reason
            self.client.update(fresh)

        retry_on_conflict(write)

    def _clear_interrupted(self, nb: Notebook) -> None:
        def write():
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
            if obj_util.remove_annotation(fresh, ann.TPU_SLICE_INTERRUPTED):
                self.client.update(fresh)

        retry_on_conflict(write)


def _pod_to_notebook(ev) -> list[Request]:
    labels = ev.object.get("metadata", {}).get("labels", {})
    name = labels.get(ann.NOTEBOOK_NAME_LABEL)
    if name:
        return [Request(name, ev.namespace)]
    return []


def _pod_ready(pod: dict) -> bool:
    for cond in pod.get("status", {}).get("conditions", []):
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return False
