"""Slice health / preemption recovery controller.

TPU slices on spot or maintenance-window capacity lose hosts without warning;
the reference has no analogue (SURVEY.md §7 "Hard parts": "Preemption/
maintenance events have no reference analogue; design from scratch against
the event-re-emission + conditions machinery"). Design:

- Watch slice pods. A pod that dies with a DisruptionTarget condition or a
  Preempted/Evicted reason marks the whole Notebook ``SliceInterrupted``
  (condition + annotation + Warning event) — a partial slice is useless, so
  interruption is a slice-level state, not a pod-level one.
- Recovery is level-triggered AND deadline-bounded. The failed pod is
  deleted so the StatefulSet controller (FakeKubelet in tests, kubelet in
  prod) recreates it, and the reconciler polls on a timer (elapsed-based
  backoff, SliceRecoveryProgress events with ready/total host counts)
  instead of waiting for incidental Pod events that may never come.
- Past ``RecoveryConfig.deadline_s`` the controller ESCALATES: claim a warm
  placeholder from a matching SlicePool (frees healthy provisioned nodes for
  the stuck replacement pods), or — no warm capacity — delete the slice
  StatefulSets so the scheduler retries placement from scratch. Each
  escalation re-arms the deadline.
- After ``max_escalations`` the interruption goes TERMINAL: a
  ``SliceRecoveryFailed`` condition + Warning event, then only a long idle
  requeue — a stuck slice must be visible, not silently retried forever,
  and must not burn API calls.
- When every host is Ready again all recovery state clears, a
  ``tpu-last-interruption-duration`` annotation records how long the
  interruption lasted (restore-hint input for runtime/checkpoint.py), the
  recovery-latency histogram observes it, and SliceRecovered is emitted.
  In-notebook state is gone (jax.distributed must re-init) but the
  *capacity* and the user's Jupyter session recover without dashboard
  action.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Optional

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.client import Client, retry_on_conflict
from kubeflow_tpu.k8s.errors import NotFoundError
from kubeflow_tpu.k8s.events import EventRecorder
from kubeflow_tpu.k8s.manager import Manager, Reconciler, Request, Result
from kubeflow_tpu.metrics import Metrics
from kubeflow_tpu.observability import tracing

log = logging.getLogger(__name__)

_PREEMPTION_REASONS = {"Preempted", "Evicted", "TerminationByKubernetes"}

RECOVERY_FAILED_CONDITION = "SliceRecoveryFailed"

# Annotations owned by this controller; cleared together on recovery (and on
# stop — a stopped notebook holds no slice, so interruption state is stale).
_RECOVERY_ANNOTATIONS = (
    ann.TPU_SLICE_INTERRUPTED,
    ann.TPU_RECOVERY_STARTED,
    ann.TPU_RECOVERY_ESCALATIONS,
    ann.TPU_RECOVERY_LAST_ESCALATION,
)


@dataclass
class RecoveryConfig:
    """Env knobs for the recovery escalation state machine, named and
    defaulted like CullerConfig.from_env (culling_controller.go:534-568
    style: one env var per field, safe defaults)."""

    # How long a recovery phase may poll before escalating.
    deadline_s: float = 300.0
    # First poll interval after an interruption (backs off from here).
    poll_initial_s: float = 5.0
    # Poll interval ceiling while waiting within the deadline.
    poll_max_s: float = 60.0
    # Warm-claim / STS-recreate attempts before going terminal.
    max_escalations: int = 2
    # Requeue period once terminal: still level-triggered (capacity coming
    # back recovers the slice), but no longer burning API calls.
    terminal_requeue_s: float = 1800.0
    # Bound on a single warm-pool claim walk during escalation: the ladder
    # must keep moving (to STS recreate) even if the pool listing is slow
    # or every candidate is being fenced away by concurrent claimants.
    claim_deadline_s: float = 5.0

    @classmethod
    def from_env(cls, env: dict) -> "RecoveryConfig":
        return cls(
            deadline_s=float(env.get("SLICE_RECOVERY_DEADLINE_SECONDS", "300")),
            poll_initial_s=float(env.get("SLICE_RECOVERY_POLL_SECONDS", "5")),
            poll_max_s=float(env.get("SLICE_RECOVERY_POLL_MAX_SECONDS", "60")),
            max_escalations=int(env.get("SLICE_RECOVERY_MAX_ESCALATIONS", "2")),
            terminal_requeue_s=float(
                env.get("SLICE_RECOVERY_TERMINAL_REQUEUE_SECONDS", "1800")
            ),
            claim_deadline_s=float(
                env.get("SLICE_RECOVERY_CLAIM_DEADLINE_SECONDS", "5")
            ),
        )


def _pod_preempted(pod: dict) -> Optional[str]:
    status = pod.get("status", {})
    if status.get("reason") in _PREEMPTION_REASONS:
        return status.get("reason")
    for cond in status.get("conditions", []):
        if cond.get("type") == "DisruptionTarget" and cond.get("status") == "True":
            return cond.get("reason", "DisruptionTarget")
    if status.get("phase") == "Failed":
        return status.get("reason", "PodFailed")
    return None


def _parse_float(value) -> Optional[float]:
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


def _parse_int(value, default: int = 0) -> int:
    try:
        return int(value)
    except (TypeError, ValueError):
        return default


def _condition_true(obj: dict, cond_type: str) -> bool:
    for c in obj.get("status", {}).get("conditions", []):
        if c.get("type") == cond_type:
            return c.get("status") == "True"
    return False


class SliceHealthReconciler(Reconciler):
    def __init__(
        self,
        client: Client,
        metrics: Optional[Metrics] = None,
        recorder: Optional[EventRecorder] = None,
        clock: Optional[Callable[[], float]] = None,
        config: Optional[RecoveryConfig] = None,
        migration_trigger: Optional[Callable[[dict, str], None]] = None,
    ):
        self.client = client
        self.metrics = metrics or Metrics(client)
        self.recorder = recorder or EventRecorder(client, component="slice-health")
        self.clock = clock or time.time
        self.config = config or RecoveryConfig()
        # Optional hook into runtime/migration.py: called with (notebook
        # object, trigger name) when a preemption notice lands or the
        # operator stamps tpu-migrate-now. Fire-and-notify — the reactive
        # ladder below proceeds regardless, so a migration that fails (or
        # a hook that raises) costs nothing the ladder wasn't already
        # going to pay. None (the default) keeps recovery purely reactive.
        self.migration_trigger = migration_trigger

    def register(self, manager: Manager) -> None:
        manager.register(
            self,
            for_kind="Notebook",
            watches=[("Pod", _pod_to_notebook)],
            name="SliceHealth",
        )

    def reconcile(self, req: Request) -> Result:
        # One span per health pass; the recovery-ladder steps below add
        # events/child spans, so an outage reads as a single trace:
        # interruption → polls → escalations → recovered/terminal.
        with tracing.get_tracer("controller").start_span(
            "slice_health.reconcile",
            notebook=req.name, namespace=req.namespace,
        ):
            return self._reconcile(req)

    def _reconcile(self, req: Request) -> Result:
        try:
            obj = self.client.get("Notebook", req.name, req.namespace)
        except NotFoundError:
            return Result()
        nb = Notebook(obj)
        if nb.tpu is None or "deletionTimestamp" in obj["metadata"]:
            return Result()
        if nb.stopped:
            # A stopped notebook holds no slice: interruption/recovery state
            # is stale the moment the STS scales to 0 (but keep the last
            # interruption duration — it still describes a real outage).
            if any(k in nb.annotations for k in _RECOVERY_ANNOTATIONS):
                self._clear_recovery_state(nb)
            return Result()

        now = self.clock()
        if ann.TPU_MIGRATE_NOW in nb.annotations:
            # Operator-requested migration: consume the annotation first
            # (clearing it marks the trigger picked up, and makes a retry
            # an explicit re-stamp rather than an accidental loop), then
            # fire the hook.
            self._consume_migrate_annotation(nb)
            self._fire_migration(obj, "operator")
        pods = self.client.list(
            "Pod", nb.namespace, {ann.NOTEBOOK_NAME_LABEL: nb.name}
        )
        failed = [(p, _pod_preempted(p)) for p in pods]
        failed = [(p, reason) for p, reason in failed if reason]

        if failed:
            state_note = _checkpoint_state_note(nb)
            for pod, reason in failed:
                self.metrics.slice_preemptions_total.inc()
                self.recorder.eventf(
                    obj, "Warning", "SliceInterrupted",
                    f"Host pod {obj_util.name_of(pod)} lost ({reason}); "
                    f"recreating — {state_note}",
                )
                # Delete so the STS/kubelet recreates the host pod.
                try:
                    self.client.delete("Pod", obj_util.name_of(pod), nb.namespace)
                except NotFoundError:
                    pass
            self._mark_interrupted(nb, failed[0][1], now)
            tracing.current_span().add_event("slice_interrupted", {
                "reason": failed[0][1], "pods_lost": len(failed),
            })
            # Proactive path first (save → warm-claim → restore → flip),
            # but the reactive poll below is scheduled unconditionally:
            # a migration that falls back leaves the ladder mid-stride,
            # exactly where it would have been without the attempt.
            self._fire_migration(obj, "preemption-notice")
            # Recovery is now OURS to drive: poll on a timer instead of
            # hoping replacement-pod events keep arriving.
            return Result(requeue_after=self.config.poll_initial_s)

        if ann.TPU_SLICE_INTERRUPTED not in nb.annotations:
            return Result()
        try:
            # ALL hosts of ALL slices must be Ready again (a 2-slice
            # notebook has hosts×2 pods; comparing against one slice's
            # host count would leave the interruption set forever).
            hosts = nb.tpu.slice_topology().hosts * nb.tpu.slice_count
        except Exception:
            return Result()
        ready = sum(1 for p in pods if _pod_ready(p))
        if ready == hosts:
            self._complete_recovery(nb, obj, hosts, now)
            return Result()
        return self._poll_or_escalate(nb, obj, ready, hosts, now)

    # -- proactive migration hand-off --------------------------------------

    def _fire_migration(self, obj: dict, trigger: str) -> None:
        if self.migration_trigger is None:
            return
        try:
            self.migration_trigger(obj, trigger)
        except Exception:
            # Migration is an optimization, never a new failure mode: a
            # hook crash must not take the reactive reconcile down with it.
            log.exception(
                "migration trigger (%s) raised; reactive recovery continues",
                trigger,
            )

    def _consume_migrate_annotation(self, nb: Notebook) -> None:
        def write():
            try:
                fresh = self.client.get("Notebook", nb.name, nb.namespace)
            except NotFoundError:
                return
            if obj_util.remove_annotation(fresh, ann.TPU_MIGRATE_NOW):
                self.client.update(fresh)

        retry_on_conflict(write)

    # -- interruption lifecycle --------------------------------------------

    def _mark_interrupted(self, nb: Notebook, reason: str, now: float) -> None:
        def write():
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
            anns = obj_util.annotations_of(fresh)
            changed = False
            if anns.get(ann.TPU_SLICE_INTERRUPTED) != reason:
                anns[ann.TPU_SLICE_INTERRUPTED] = reason
                changed = True
            # First failure of THIS interruption starts the recovery clock;
            # repeated failures while already interrupted keep the original
            # start (the deadline measures the whole outage, not the last
            # pod flap).
            if ann.TPU_RECOVERY_STARTED not in anns:
                anns[ann.TPU_RECOVERY_STARTED] = str(now)
                changed = True
            if changed:
                self.client.update(fresh)

        retry_on_conflict(write)

    def _poll_or_escalate(
        self, nb: Notebook, obj: dict, ready: int, hosts: int, now: float
    ) -> Result:
        cfg = self.config
        anns = nb.annotations
        if _condition_true(obj, RECOVERY_FAILED_CONDITION):
            # Terminal: stay visible (condition + prior Warning event), stop
            # burning API calls — a long idle requeue still notices capacity
            # that comes back on its own (the ready==hosts path clears it).
            return Result(requeue_after=cfg.terminal_requeue_s)

        started = _parse_float(anns.get(ann.TPU_RECOVERY_STARTED))
        if started is None:
            # Interruption marked by an older controller build: adopt the
            # annotation into the state machine starting now.
            started = now
            self._stamp_recovery_started(nb, now)
        escalations = _parse_int(anns.get(ann.TPU_RECOVERY_ESCALATIONS))
        last_escalation = _parse_float(anns.get(ann.TPU_RECOVERY_LAST_ESCALATION))
        phase_start = max(started, last_escalation or 0.0)
        elapsed = max(0.0, now - phase_start)

        # Message deliberately excludes elapsed time: the EventRecorder
        # dedups on (kind/name/reason/message), so identical polls bump one
        # Event's count instead of spamming new objects.
        self.recorder.eventf(
            obj, "Normal", "SliceRecoveryProgress",
            f"Slice recovering: {ready}/{hosts} hosts Ready "
            f"(escalations used: {escalations}/{cfg.max_escalations})",
        )

        if elapsed < cfg.deadline_s:
            # Elapsed-based backoff needs no stored poll counter: wait about
            # as long as this phase has already waited, clamped to
            # [poll_initial, poll_max] and never past the deadline.
            delay = min(
                max(cfg.poll_initial_s, elapsed),
                cfg.poll_max_s,
                cfg.deadline_s - elapsed,
            )
            return Result(requeue_after=max(delay, 0.001))

        if escalations >= cfg.max_escalations:
            return self._go_terminal(nb, obj, ready, hosts)
        self._escalate(nb, obj, escalations, now)
        return Result(requeue_after=cfg.poll_initial_s)

    def _escalate(
        self, nb: Notebook, obj: dict, escalations: int, now: float
    ) -> None:
        """One escalation step: warm-pool claim, else STS recreate."""
        with tracing.get_tracer("controller").start_span(
            "preemption.escalate", attempt=escalations + 1,
        ) as span:
            self._escalate_step(nb, obj, escalations, now, span)

    def _escalate_step(
        self, nb: Notebook, obj: dict, escalations: int, now: float, span
    ) -> None:
        from kubeflow_tpu.controller.notebook import slice_sts_names
        from kubeflow_tpu.controller.slicepool import claim_warm_slice
        from kubeflow_tpu.deploy.manifests import termination_grace_seconds

        attempt = escalations + 1
        topo = nb.tpu.slice_topology()
        pool = claim_warm_slice(
            self.client, nb.namespace, topo,
            recorder=self.recorder, notebook=obj, now=now,
            claimant=f"recovery-{nb.namespace}-{nb.name}",
            deadline=time.perf_counter() + self.config.claim_deadline_s,
        )
        if pool is not None:
            # claim_warm_slice already emitted ClaimedWarmSlice; deleting the
            # placeholder freed provisioned warm nodes, so the stuck
            # replacement pods can bind on the next scheduler retry.
            self.recorder.eventf(
                obj, "Warning", "SliceRecoveryEscalated",
                f"Recovery deadline exceeded; claimed a warm slice from pool "
                f"{pool} to free capacity (escalation {attempt})",
            )
        else:
            names = slice_sts_names(nb.name, nb.tpu.slice_count)
            for name in names:
                try:
                    self.client.delete("StatefulSet", name, nb.namespace)
                except NotFoundError:
                    pass
            # An STS recreate TERMINATES the surviving healthy hosts too:
            # say up front how long the kubelet will wait for their
            # emergency checkpoints, so the event explains the extra
            # teardown latency the ladder just signed up for.
            grace = ann.parse_checkpoint_grace(
                nb.annotations.get(ann.TPU_CHECKPOINT_GRACE)
            )
            grace_note = (
                f"; surviving hosts get {termination_grace_seconds(grace)}s "
                "termination grace for an emergency checkpoint"
                if grace is not None else ""
            )
            self.recorder.eventf(
                obj, "Warning", "SliceRecoveryEscalated",
                "Recovery deadline exceeded and no warm slice available; "
                f"recreating StatefulSet(s) {', '.join(names)} for fresh "
                f"placement (escalation {attempt}){grace_note}",
            )
        self.metrics.slice_recovery_escalations_total.inc()
        span.set_attribute(
            "mode", "warm-claim" if pool else "sts-recreate"
        )
        log.warning(
            "slice %s/%s: recovery escalation %d (%s)",
            nb.namespace, nb.name, attempt,
            "warm-claim" if pool else "sts-recreate",
        )

        def write():
            try:
                fresh = self.client.get("Notebook", nb.name, nb.namespace)
            except NotFoundError:
                return
            anns = obj_util.annotations_of(fresh)
            anns[ann.TPU_RECOVERY_ESCALATIONS] = str(attempt)
            anns[ann.TPU_RECOVERY_LAST_ESCALATION] = str(now)
            self.client.update(fresh)

        retry_on_conflict(write)

    def _go_terminal(self, nb: Notebook, obj: dict, ready: int, hosts: int) -> Result:
        cfg = self.config
        self.metrics.slice_recovery_failed_total.inc()

        def write():
            try:
                fresh = self.client.get("Notebook", nb.name, nb.namespace)
            except NotFoundError:
                return
            obj_util.set_condition(fresh, {
                "type": RECOVERY_FAILED_CONDITION,
                "status": "True",
                "reason": "RecoveryDeadlineExceeded",
                "message": (
                    f"slice stuck at {ready}/{hosts} Ready hosts after "
                    f"{cfg.max_escalations} escalations"
                ),
            })
            self.client.update_status(fresh)

        retry_on_conflict(write)
        self.recorder.eventf(
            obj, "Warning", RECOVERY_FAILED_CONDITION,
            f"Giving up active recovery: {ready}/{hosts} hosts Ready after "
            f"{cfg.max_escalations} escalations; will re-check every "
            f"{int(cfg.terminal_requeue_s)}s",
        )
        log.error(
            "slice %s/%s: recovery FAILED terminally (%d/%d hosts)",
            nb.namespace, nb.name, ready, hosts,
        )
        tracing.current_span().record_error(RuntimeError(
            f"recovery terminal: {ready}/{hosts} hosts Ready"
        ))
        return Result(requeue_after=cfg.terminal_requeue_s)

    def _complete_recovery(
        self, nb: Notebook, obj: dict, hosts: int, now: float
    ) -> None:
        started = _parse_float(nb.annotations.get(ann.TPU_RECOVERY_STARTED))
        duration = max(0.0, now - started) if started is not None else None
        if duration is not None:
            self.metrics.slice_recovery_seconds.observe(duration)
        tracing.current_span().add_event("slice_recovered", {
            "hosts": hosts,
            **({"duration_s": round(duration, 3)}
               if duration is not None else {}),
        })
        self._clear_recovery_state(nb, duration=duration)
        if _condition_true(obj, RECOVERY_FAILED_CONDITION):
            # Capacity came back after we went terminal: flip the condition
            # rather than delete it — the transition itself is signal.
            def write():
                try:
                    fresh = self.client.get("Notebook", nb.name, nb.namespace)
                except NotFoundError:
                    return
                obj_util.set_condition(fresh, {
                    "type": RECOVERY_FAILED_CONDITION,
                    "status": "False",
                    "reason": "Recovered",
                    "message": f"all {hosts} hosts Ready again",
                })
                self.client.update_status(fresh)

            retry_on_conflict(write)
        message = f"All {hosts} slice hosts Ready again"
        if duration is not None:
            message += f" after {duration:.0f}s interruption"
        self.recorder.eventf(obj, "Normal", "SliceRecovered", message)

    def _stamp_recovery_started(self, nb: Notebook, now: float) -> None:
        def write():
            try:
                fresh = self.client.get("Notebook", nb.name, nb.namespace)
            except NotFoundError:
                return
            anns = obj_util.annotations_of(fresh)
            if ann.TPU_RECOVERY_STARTED not in anns:
                anns[ann.TPU_RECOVERY_STARTED] = str(now)
                self.client.update(fresh)

        retry_on_conflict(write)

    def _clear_recovery_state(
        self, nb: Notebook, duration: Optional[float] = None
    ) -> None:
        def write():
            try:
                fresh = self.client.get("Notebook", nb.name, nb.namespace)
            except NotFoundError:
                return
            removed = [
                obj_util.remove_annotation(fresh, key)
                for key in _RECOVERY_ANNOTATIONS
            ]
            changed = any(removed)
            if duration is not None:
                anns = obj_util.annotations_of(fresh)
                stamp = f"{duration:.0f}s"
                if anns.get(ann.TPU_LAST_INTERRUPTION_DURATION) != stamp:
                    anns[ann.TPU_LAST_INTERRUPTION_DURATION] = stamp
                    changed = True
            if changed:
                self.client.update(fresh)

        retry_on_conflict(write)


def _checkpoint_state_note(nb: Notebook) -> str:
    """How much in-notebook state the interruption cost, for the
    SliceInterrupted event: with the checkpoint-grace annotation the pod
    had a SIGTERM emergency-save window (runtime/checkpoint.py), so the
    message points at the resumable checkpoint instead of declaring the
    state gone."""
    grace = ann.parse_checkpoint_grace(
        nb.annotations.get(ann.TPU_CHECKPOINT_GRACE)
    )
    if grace is None:
        return "in-notebook JAX state is gone"
    ckpt_dir = (
        nb.annotations.get(ann.TPU_CHECKPOINT_DIR, "").strip()
        or ann.DEFAULT_CHECKPOINT_DIR
    )
    return (
        f"resume from the emergency checkpoint in {ckpt_dir} "
        f"(pod had {grace}s SIGTERM grace)"
    )


def _pod_to_notebook(ev) -> list[Request]:
    labels = ev.object.get("metadata", {}).get("labels", {})
    name = labels.get(ann.NOTEBOOK_NAME_LABEL)
    if name:
        return [Request(name, ev.namespace)]
    return []


def _pod_ready(pod: dict) -> bool:
    for cond in pod.get("status", {}).get("conditions", []):
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return False
