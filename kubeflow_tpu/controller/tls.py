"""Cluster TLS security profile: fetch + change watcher.

Reference parity: the ODH manager reads the cluster-wide TLS policy from the
OpenShift ``APIServer`` CR named ``cluster`` and configures its webhook/metrics
listeners from it, falling back to a hardened cipher list when the CR is
absent or unreadable (reference components/odh-notebook-controller/
main.go:71-78,183-234). A ``SecurityProfileWatcher`` then watches that CR and
cancels the manager context — i.e. restarts the pod — when the profile
changes, because Go's TLS config cannot be swapped live
(main.go:344-367). Here the restart is modeled as an ``on_change`` callback.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from kubeflow_tpu.k8s.client import Client
from kubeflow_tpu.k8s.manager import Reconciler, Request, Result

# Mozilla "intermediate" profile — the reference's fallback cipher suite set
# (main.go:183-200 hardcodes this list when the APIServer CR can't be read).
INTERMEDIATE_CIPHERS = (
    "TLS_ECDHE_ECDSA_WITH_AES_128_GCM_SHA256",
    "TLS_ECDHE_RSA_WITH_AES_128_GCM_SHA256",
    "TLS_ECDHE_ECDSA_WITH_AES_256_GCM_SHA384",
    "TLS_ECDHE_RSA_WITH_AES_256_GCM_SHA384",
    "TLS_ECDHE_ECDSA_WITH_CHACHA20_POLY1305_SHA256",
    "TLS_ECDHE_RSA_WITH_CHACHA20_POLY1305_SHA256",
)
MODERN_CIPHERS = (
    "TLS_AES_128_GCM_SHA256",
    "TLS_AES_256_GCM_SHA384",
    "TLS_CHACHA20_POLY1305_SHA256",
)
OLD_CIPHERS = INTERMEDIATE_CIPHERS + (
    "TLS_ECDHE_ECDSA_WITH_AES_128_CBC_SHA256",
    "TLS_ECDHE_RSA_WITH_AES_128_CBC_SHA256",
    "TLS_RSA_WITH_AES_128_GCM_SHA256",
    "TLS_RSA_WITH_AES_256_GCM_SHA384",
)


@dataclass(frozen=True)
class TLSProfile:
    profile_type: str  # Old | Intermediate | Modern | Custom
    min_version: str
    ciphers: tuple[str, ...]


INTERMEDIATE = TLSProfile("Intermediate", "VersionTLS12", INTERMEDIATE_CIPHERS)
MODERN = TLSProfile("Modern", "VersionTLS13", MODERN_CIPHERS)
OLD = TLSProfile("Old", "VersionTLS10", OLD_CIPHERS)

_BY_TYPE = {"Old": OLD, "Intermediate": INTERMEDIATE, "Modern": MODERN}


def fetch_tls_profile(client: Client) -> TLSProfile:
    """Read spec.tlsSecurityProfile off the cluster APIServer CR.

    Absent CR, absent profile, or any read error falls back to the hardened
    Intermediate profile — the reference logs and continues rather than
    crash-looping on a missing OpenShift API (main.go:201-210).
    """
    try:
        apiserver = client.get("APIServer", "cluster")
    except Exception:
        return INTERMEDIATE
    profile = apiserver.get("spec", {}).get("tlsSecurityProfile") or {}
    ptype = profile.get("type", "")
    if ptype == "Custom":
        custom = profile.get("custom") or {}
        ciphers = tuple(custom.get("ciphers") or INTERMEDIATE_CIPHERS)
        min_version = custom.get("minTLSVersion", "VersionTLS12")
        return TLSProfile("Custom", min_version, ciphers)
    return _BY_TYPE.get(ptype, INTERMEDIATE)


class SecurityProfileWatcher(Reconciler):
    """Restart-on-TLS-change semantics (reference main.go:344-367).

    Registered against the APIServer kind; when the effective profile
    differs from the one the manager booted with, invokes ``on_change``
    exactly once (the reference cancels the root context, letting the
    kubelet restart the pod with the new profile).
    """

    def __init__(
        self,
        client: Client,
        boot_profile: TLSProfile,
        on_change: Callable[[TLSProfile], None],
    ):
        self.client = client
        self.boot_profile = boot_profile
        self.on_change = on_change
        self.fired = False

    def register(self, manager) -> None:
        manager.register(self, for_kind="APIServer", name="TLSProfileWatcher")

    def reconcile(self, req: Request) -> Result:
        if self.fired:
            return Result()
        current = fetch_tls_profile(self.client)
        if current != self.boot_profile:
            self.fired = True
            self.on_change(current)
        return Result()
