from kubeflow_tpu.controller.notebook import (  # noqa: F401
    NotebookReconciler,
    ControllerConfig,
)
from kubeflow_tpu.controller.culling import CullingReconciler, CullerConfig  # noqa: F401
from kubeflow_tpu.controller.preemption import SliceHealthReconciler  # noqa: F401
from kubeflow_tpu.controller.platform import PlatformReconciler, PlatformConfig  # noqa: F401
