"""Platform reconciler: the orchestrator over routes/auth/netpol/integrations.

Rebuild of the reference's ODH reconciler (reference
components/odh-notebook-controller/controllers/notebook_controller.go:190-523
and its SetupWithManager watch wiring :736-884):

deletion branch (:207-333)  → legacy OAuthClient, central-ns HTTPRoute,
                              ReferenceGrant-if-last, auth CRB, finalizer off
finalizer add (:335-381)    → with requeue
steady state (:388-523)     → CA bundle CM, NetworkPolicies, runtime-images
                              CM, pipeline RBAC (env-gated), Elyra secret
                              (env-gated), ReferenceGrant, auth bundle OR
                              plain HTTPRoute (+ conflict cleanup), MLflow
                              (requeue 30s until ClusterRole), reconciliation
                              -lock removal — the step that finally lets the
                              slice start.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.controller import auth as auth_mod
from kubeflow_tpu.controller import integrations, network, routes
from kubeflow_tpu.k8s.client import Client, retry_on_conflict
from kubeflow_tpu.k8s.errors import NotFoundError
from kubeflow_tpu.k8s.events import EventRecorder
from kubeflow_tpu.k8s.manager import Manager, Reconciler, Request, Result

log = logging.getLogger(__name__)

FINALIZER = ann.PLATFORM_CLEANUP_FINALIZER
# Poll cadence while waiting for the token controller to mint the pod
# ServiceAccount's image-pull secret (reference :155-186 wait step).
PULL_SECRET_REQUEUE_S = 2.0


@dataclass
class PlatformConfig:
    controller_namespace: str = "opendatahub"
    set_pipeline_rbac: bool = False
    set_pipeline_secret: bool = False
    mlflow_enabled: bool = False
    gateway_hostname: str = ""
    routes: routes.RouteConfig = field(default_factory=routes.RouteConfig)

    def __post_init__(self):
        # Single source of truth: the route layer always lives in the same
        # controller namespace as everything else.
        self.routes.controller_namespace = self.controller_namespace

    @classmethod
    def from_env(cls, env: dict) -> "PlatformConfig":
        return cls(
            controller_namespace=env.get("K8S_NAMESPACE", "opendatahub"),
            set_pipeline_rbac=env.get("SET_PIPELINE_RBAC", "false").lower() == "true",
            set_pipeline_secret=env.get("SET_PIPELINE_SECRET", "false").lower()
            == "true",
            mlflow_enabled=env.get("MLFLOW_ENABLED", "false").lower() == "true",
            gateway_hostname=env.get("GATEWAY_URL", "").removeprefix("https://"),
            routes=routes.RouteConfig.from_env(env),
        )


class PlatformReconciler(Reconciler):
    def __init__(
        self,
        client: Client,
        config: Optional[PlatformConfig] = None,
        recorder: Optional[EventRecorder] = None,
    ):
        self.client = client
        self.config = config or PlatformConfig()
        self.recorder = recorder or EventRecorder(client, component="platform")

    def register(self, manager: Manager) -> None:
        manager.register(
            self,
            for_kind="Notebook",
            owns=(
                "ServiceAccount",
                "Service",
                "ConfigMap",
                "Secret",
                "NetworkPolicy",
                "RoleBinding",
            ),
            watches=[
                ("HTTPRoute", _route_to_notebook),
                ("ReferenceGrant", _grant_to_notebooks(self.client)),
            ],
            name="Platform",
        )

    # ------------------------------------------------------------------
    def reconcile(self, req: Request) -> Result:
        try:
            obj = self.client.get("Notebook", req.name, req.namespace)
        except NotFoundError:
            return Result()
        nb = Notebook(obj)

        if "deletionTimestamp" in obj["metadata"]:
            self._handle_deletion(nb)
            return Result()

        # Finalizer add-on-first-sight, with conflict retry (reference
        # :335-381 batches finalizer adds the same way).
        if FINALIZER not in obj["metadata"].get("finalizers", []):
            def add():
                fresh = self.client.get("Notebook", nb.name, nb.namespace)
                fins = fresh["metadata"].setdefault("finalizers", [])
                if FINALIZER not in fins:
                    fins.append(FINALIZER)
                    self.client.update(fresh)

            retry_on_conflict(add)
            return Result(requeue_after=0.0)

        cfg = self.config
        integrations.reconcile_ca_bundle(self.client, nb, cfg.controller_namespace)
        network.reconcile_network_policies(
            self.client, nb, cfg.controller_namespace,
            gateway_namespace=cfg.routes.gateway_namespace,
        )
        integrations.sync_runtime_images_config_map(
            self.client, nb, cfg.controller_namespace
        )
        if cfg.set_pipeline_rbac:
            integrations.reconcile_pipeline_rbac(self.client, nb)
        if cfg.set_pipeline_secret:
            integrations.sync_elyra_runtime_config(
                self.client, nb, cfg.gateway_hostname
            )
        routes.reconcile_reference_grant(self.client, nb, cfg.routes)

        auth_mode = nb.annotations.get(ann.INJECT_AUTH) == "true"
        routes.ensure_conflicting_route_absent(self.client, nb, cfg.routes, auth_mode)
        if auth_mode:
            auth_mod.reconcile_auth_bundle(self.client, nb)
        else:
            auth_mod.cleanup_auth_mode_off(self.client, nb)
        routes.reconcile_httproute(self.client, nb, cfg.routes, auth_mode)

        requeue = 0.0
        if cfg.mlflow_enabled:
            delay = integrations.reconcile_mlflow_rbac(self.client, nb)
            if delay:
                self.recorder.eventf(
                    obj, "Normal", "WaitingForMLflowOperator",
                    f"ClusterRole {integrations.MLFLOW_CLUSTER_ROLE} not found; "
                    "retrying",
                )
                requeue = delay

        if nb.lock_held:
            if not self._pull_secret_ready(nb):
                # The pod would race its registry pull against the
                # token controller minting the SA's pull secret and
                # land in ImagePullBackOff; hold the lock and requeue
                # (reference RemoveReconciliationLock :155-186 waits on
                # the same secret before releasing).
                self.recorder.eventf(
                    nb.obj, "Normal", "WaitingForPullSecret",
                    "ServiceAccount image-pull secret not yet minted; "
                    "holding reconciliation lock",
                )
                return Result(requeue_after=PULL_SECRET_REQUEUE_S)
            self._remove_reconciliation_lock(nb)
        return Result(requeue_after=requeue)

    # ------------------------------------------------------------------
    def _pull_secret_ready(self, nb: Notebook) -> bool:
        """True once the pod's ServiceAccount exists AND carries an
        imagePullSecrets entry. The pod runs as the template's
        serviceAccountName when set (the auth webhook injects one), else
        the namespace "default" SA."""
        sa_name = nb.pod_spec.get("serviceAccountName") or "default"
        try:
            sa = self.client.get("ServiceAccount", sa_name, nb.namespace)
        except NotFoundError:
            return False
        return bool(sa.get("imagePullSecrets"))

    def _remove_reconciliation_lock(self, nb: Notebook) -> None:
        """Everything is in place — release the lock so the slice starts
        (reference RemoveReconciliationLock :155-186, the merge-patch that
        removes the stop annotation)."""

        def release():
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
            anns = fresh.get("metadata", {}).get("annotations", {})
            if anns.get(ann.STOP) == ann.RECONCILIATION_LOCK_VALUE:
                del anns[ann.STOP]
                self.client.update(fresh)

        retry_on_conflict(release)

    def _handle_deletion(self, nb: Notebook) -> None:
        """Reference deletion branch (:207-333), in the same order."""
        if FINALIZER not in nb.obj["metadata"].get("finalizers", []):
            return
        integrations.cleanup_legacy_oauth_client(self.client, nb)
        routes.delete_httproute(self.client, nb, self.config.routes)
        routes.delete_reference_grant_if_last_notebook(
            self.client, nb, self.config.routes
        )
        auth_mod.cleanup_auth_bundle(self.client, nb)

        def remove_finalizer():
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
            fins = fresh["metadata"].get("finalizers", [])
            if FINALIZER in fins:
                fins.remove(FINALIZER)
                self.client.update(fresh)

        retry_on_conflict(remove_finalizer)


# ---------------------------------------------------------------------------
# Watch map functions (reference SetupWithManager :736-884)


def _route_to_notebook(ev) -> list[Request]:
    """Central-ns HTTPRoutes map back to their notebook by labels."""
    labels = ev.object.get("metadata", {}).get("labels", {})
    name = labels.get(routes.NOTEBOOK_NAME_ROUTE_LABEL)
    namespace = labels.get(routes.NOTEBOOK_NS_LABEL)
    if name and namespace:
        return [Request(name, namespace)]
    return []


def _grant_to_notebooks(client: Client):
    """A ReferenceGrant event re-reconciles every notebook in its namespace."""

    def map_fn(ev) -> list[Request]:
        out = []
        for nb in client.list("Notebook", ev.namespace):
            out.append(Request(nb["metadata"]["name"], ev.namespace))
        return out

    return map_fn
