"""NetworkPolicies: notebook ingress lockdown + intra-slice data plane.

Rebuild of reference components/odh-notebook-controller/controllers/
notebook_network.go (:132-174 ``{name}-ctrl-np`` allowing 8888 only from the
controller namespace; :177-211 ``{name}-kube-rbac-proxy-np`` allowing 8443
from anywhere) plus the TPU-native addition from SURVEY.md §7 step 4: an
intra-slice policy so slice host pods can reach each other over the JAX/DCN
coordination ports — without it, a default-deny namespace would wedge
``jax.distributed.initialize`` while 8888 still works, which is miserable to
debug.
"""

from __future__ import annotations

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.names import NOTEBOOK_PORT, RBAC_PROXY_PORT
from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.controller import reconcilehelper as helper
from kubeflow_tpu.k8s.client import Client


def ctrl_np_name(name: str) -> str:
    return f"{name}-ctrl-np"


def proxy_np_name(name: str) -> str:
    return f"{name}-kube-rbac-proxy-np"


def slice_np_name(name: str) -> str:
    return f"{name}-slice-np"


def new_ctrl_policy(
    nb: Notebook, controller_namespace: str, gateway_namespace: str
) -> dict:
    """Allow 8888 from the controller namespace (culler probes) AND the
    gateway namespace — plain-mode HTTPRoutes terminate at the gateway pods,
    whose connections to 8888 must not be dropped by the lockdown."""
    peers = [
        {
            "namespaceSelector": {
                "matchLabels": {"kubernetes.io/metadata.name": controller_namespace}
            }
        }
    ]
    if gateway_namespace and gateway_namespace != controller_namespace:
        peers.append(
            {
                "namespaceSelector": {
                    "matchLabels": {"kubernetes.io/metadata.name": gateway_namespace}
                }
            }
        )
    return {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": {
            "name": ctrl_np_name(nb.name),
            "namespace": nb.namespace,
            "labels": {"notebook-name": nb.name},
        },
        "spec": {
            "podSelector": {"matchLabels": {"statefulset": nb.name}},
            "policyTypes": ["Ingress"],
            "ingress": [
                {
                    "from": peers,
                    "ports": [
                        {"protocol": "TCP", "port": p}
                        for p in _allowed_ports(nb)
                    ],
                }
            ],
        },
    }


def _allowed_ports(nb: Notebook) -> list[int]:
    """8888 always; the profiling-port annotation opens the jax.profiler
    server to the same peers (xprof connects via port-forward/gateway);
    the serving-port annotation opens the HTTP inference endpoint."""
    ports = [NOTEBOOK_PORT]
    for key in (ann.TPU_PROFILING_PORT, ann.TPU_SERVING_PORT):
        port = ann.parse_profiling_port(nb.annotations.get(key))
        if port is not None:
            ports.append(port)
    return ports


def new_proxy_policy(nb: Notebook) -> dict:
    """8443 open to all (the rbac proxy IS the auth boundary)."""
    return {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": {
            "name": proxy_np_name(nb.name),
            "namespace": nb.namespace,
            "labels": {"notebook-name": nb.name},
        },
        "spec": {
            "podSelector": {"matchLabels": {"statefulset": nb.name}},
            "policyTypes": ["Ingress"],
            "ingress": [{"ports": [{"protocol": "TCP", "port": RBAC_PROXY_PORT}]}],
        },
    }


def new_slice_policy(nb: Notebook) -> dict:
    """TPU addition: slice pods talk to each other on every port — JAX
    coordination (8476), per-host debug/profiling servers, and the gRPC
    sidechannels libtpu opens between hosts use ephemeral ports, so the
    peer-selector is the gate, not the port list."""
    peer = {"podSelector": {"matchLabels": {"statefulset": nb.name}}}
    return {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": {
            "name": slice_np_name(nb.name),
            "namespace": nb.namespace,
            "labels": {"notebook-name": nb.name},
        },
        "spec": {
            "podSelector": {"matchLabels": {"statefulset": nb.name}},
            "policyTypes": ["Ingress"],
            "ingress": [{"from": [peer]}],
        },
    }


def reconcile_network_policies(
    client: Client, nb: Notebook, controller_namespace: str,
    gateway_namespace: str = "",
) -> None:
    """Reference ReconcileAllNetworkPolicies (notebook_network.go:44)."""
    helper.reconcile_child(
        client, nb.obj,
        new_ctrl_policy(nb, controller_namespace, gateway_namespace),
    )
    helper.reconcile_child(client, nb.obj, new_proxy_policy(nb))
    multi_host = False
    if nb.tpu is not None:
        try:
            multi_host = nb.tpu.slice_topology().hosts > 1
        except Exception:
            multi_host = False
    if multi_host:
        helper.reconcile_child(client, nb.obj, new_slice_policy(nb))
