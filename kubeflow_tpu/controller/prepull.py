"""Image pre-pull reconciler: keep notebook images pulled on TPU nodes.

TPU-native subsystem with no reference counterpart (the reference's spawn
path pulls images cold inside its CI's 600 s timeout — SURVEY.md §6; GKE
image streaming is a node-pool feature covering only AR/GCR-backed
images). The <90 s p50 spawn budget (BASELINE.md) cannot absorb a
multi-GB workbench image pull on a COLD node, and SlicePool keeps images
warm only on nodes its placeholders hold. This reconciler maintains one
node-pinned pre-pull Pod per TPU node — the DaemonSet controller's exact
mechanics (DaemonSet pods bind via ``spec.nodeName``, not the scheduler)
without requiring DaemonSet semantics of the control plane:

- the image SET is the operator-listed refs in the
  ``notebook-prepull-images`` ConfigMap (controller namespace,
  key → image ref) UNION the images of live TPU notebooks, so a newly
  adopted workbench image starts warming on every TPU node at its first
  use, not at the next operator action;
- each pre-pull Pod pulls every image via initContainers that run
  ``true`` (pull, execute nothing) and completes; it requests NO
  resources, tolerates everything, and carries the SlicePool
  placeholder PriorityClass so it can never displace — or even delay —
  a real workload;
- the pod NAME carries a content hash of the image set: set changes
  roll new pods, stale ones are deleted, Failed ones are deleted and
  re-created next reconcile (pull retry), and pods whose node is gone
  are GC'd. A Succeeded pod is its node's coverage marker for that set
  (node-local image GC can invalidate the marker silently — the same
  honesty tradeoff every DaemonSet pre-puller makes).

Enabled by ``ENABLE_IMAGE_PREPULL=true`` on the core manager (gate
style: reference main.go:111-123 ``ENABLE_CULLING``).
"""

from __future__ import annotations

import hashlib
import logging
from dataclasses import dataclass
from typing import Optional

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.names import derived_name
from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.client import Client
from kubeflow_tpu.k8s.errors import AlreadyExistsError, NotFoundError
from kubeflow_tpu.k8s.manager import Manager, Reconciler, Request, Result
from kubeflow_tpu.controller.slicepool import PLACEHOLDER_PRIORITY_CLASS

log = logging.getLogger(__name__)

PREPULL_CONFIGMAP = "notebook-prepull-images"
PREPULL_LABEL = ann.PREPULL_LABEL
TPU_NODE_LABEL = "cloud.google.com/gke-tpu-accelerator"
# A Failed pre-pull pod (broken ref, registry outage) is retried by
# delete + re-create, but only after this backoff — immediate recreation
# would hammer a broken registry once per watch event.
RETRY_FAILED_AFTER = 60.0

# The shared distroless-safe pull recipe: a pull container must exit 0
# no matter what the target image contains (distroless/scratch ship NO
# binaries), so a static busybox is copied into an emptyDir first and
# every target image runs THAT. One home for the recipe — the static
# DaemonSet sample (deploy.manifests.image_prepuller_daemonset) builds
# from these too, so a busybox bump or argv fix cannot drift.
BUSYBOX_IMAGE = "busybox:1.36"
TOOLS_MOUNT = {"name": "prepull-tools", "mountPath": "/prepull-tools"}
TINY_RESOURCES = {"limits": {"cpu": "100m", "memory": "64Mi"}}


def prepull_init_containers(images, name_prefix: str = "pull") -> list[dict]:
    """copy-busybox + one no-op-run per target image (serial pulls)."""
    return [
        {
            "name": "copy-busybox",
            "image": BUSYBOX_IMAGE,
            # Multicall binary: keep its own name, dispatch via argv —
            # renamed to "noop" it would exit 127 (applet not found).
            "command": ["cp", "/bin/busybox", "/prepull-tools/busybox"],
            "volumeMounts": [dict(TOOLS_MOUNT)],
            "resources": dict(TINY_RESOURCES),
        }
    ] + [
        {
            "name": f"{name_prefix}-{i}",
            "image": img,
            "command": ["/prepull-tools/busybox", "sleep", "0"],
            "volumeMounts": [dict(TOOLS_MOUNT)],
            "resources": dict(TINY_RESOURCES),
        }
        for i, img in enumerate(images)
    ]


def _failure_time(pod: dict) -> Optional[float]:
    """When the pod actually FAILED: the latest terminated finishedAt
    across container statuses, falling back to creationTimestamp. The
    backoff must key off failure, not creation — a pod failing after
    living past the window would otherwise retry with zero backoff."""
    latest = None
    status = pod.get("status") or {}
    for cs in (status.get("containerStatuses") or []) + (
        status.get("initContainerStatuses") or []
    ):
        fin = ((cs.get("state") or {}).get("terminated") or {}).get(
            "finishedAt"
        )
        t = obj_util.parse_timestamp(fin)
        if t is not None and (latest is None or t > latest):
            latest = t
    if latest is not None:
        return latest
    return obj_util.parse_timestamp(
        (pod.get("metadata") or {}).get("creationTimestamp")
    )


@dataclass
class PrePullConfig:
    namespace: str = "kubeflow"
    configmap: str = PREPULL_CONFIGMAP

    @classmethod
    def from_env(cls, env: dict) -> "PrePullConfig":
        return cls(
            namespace=env.get("K8S_NAMESPACE", "kubeflow"),
            configmap=env.get("IMAGE_PREPULL_CONFIGMAP", PREPULL_CONFIGMAP),
        )


def image_set(client: Client, cfg: PrePullConfig) -> list[str]:
    """Sorted union of operator-listed and live-TPU-notebook images."""
    images: set[str] = set()
    try:
        cm = client.get("ConfigMap", cfg.configmap, cfg.namespace)
        images.update(v for v in (cm.get("data") or {}).values() if v)
    except NotFoundError:
        pass
    for nb in client.list("Notebook"):
        if not nb.get("spec", {}).get("tpu"):
            continue
        pod_spec = (
            nb.get("spec", {}).get("template", {}).get("spec", {})
        )
        for c in pod_spec.get("containers", []):
            if c.get("image"):
                images.add(c["image"])
    return sorted(images)


def image_set_digest(images: list[str]) -> str:
    return hashlib.sha1("\n".join(images).encode()).hexdigest()[:10]


def prepull_pod_name(node: str, digest: str) -> str:
    return derived_name(f"prepull-{node}", f"-{digest}")


def generate_prepull_pod(
    cfg: PrePullConfig, node: str, images: list[str], digest: str
) -> dict:
    """Node-pinned run-to-completion pod pulling every image.

    All images ride initContainers (serial pulls — kubelets pull one
    image at a time per pod anyway). A pull container must exit 0 no
    matter what the target image contains — distroless/scratch
    workbench images ship NO binaries — so this uses the same recipe as
    deploy.manifests.image_prepuller_daemonset (the static sample this
    controller supersedes when enabled): copy busybox's static multicall
    binary into an emptyDir first, then run it from every target image's
    filesystem (prepull_init_containers — one home for the recipe). Tiny
    cpu/memory limits bound the (no-op) containers; no ``google.com/tpu``
    request, so the pod never consumes chip capacity the scheduler could
    give a notebook."""
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": prepull_pod_name(node, digest),
            "namespace": cfg.namespace,
            "labels": {PREPULL_LABEL: "true"},
            "annotations": {PREPULL_LABEL + "-node": node},
        },
        "spec": {
            "nodeName": node,
            "restartPolicy": "Never",
            "priorityClassName": PLACEHOLDER_PRIORITY_CLASS,
            "tolerations": [{"operator": "Exists"}],
            "volumes": [{"name": "prepull-tools", "emptyDir": {}}],
            "initContainers": prepull_init_containers(images),
            "containers": [
                {
                    "name": "done",
                    "image": BUSYBOX_IMAGE,
                    "command": ["/bin/busybox", "true"],
                    "resources": dict(TINY_RESOURCES),
                }
            ],
        },
    }


class PrePullReconciler(Reconciler):
    """Singleton reconcile: the whole desired state (image set × TPU
    nodes) is recomputed per wake-up — level-triggered, like every
    controller here. Anchored on the ConfigMap kind; node, notebook, and
    own-pod events map onto the one request."""

    def __init__(self, client: Client, config: Optional[PrePullConfig] = None,
                 metrics=None, clock=None, enabled: bool = True):
        self.client = client
        self.cfg = config or PrePullConfig()
        self.metrics = metrics
        self.clock = clock  # None → Failed pods retry without backoff
        # Disabled mode still registers and reconciles with an EMPTY
        # desired set: flipping ENABLE_IMAGE_PREPULL off must GC the
        # node-pinned pods a previous run created (they carry no
        # ownerReferences — nothing else would ever clean them up).
        self.enabled = enabled

    def register(self, manager: Manager) -> None:
        def singleton(ev) -> list[Request]:
            return [Request(self.cfg.configmap, self.cfg.namespace)]

        def own_pods(ev) -> list[Request]:
            labels = (ev.object.get("metadata") or {}).get("labels") or {}
            return singleton(ev) if PREPULL_LABEL in labels else []

        manager.register(
            self,
            for_kind="ConfigMap",
            watches=[
                ("Node", singleton),
                ("Notebook", singleton),
                ("Pod", own_pods),
            ],
            name="PrePullReconciler",
        )

    def reconcile(self, req: Request) -> Result:
        if req.name != self.cfg.configmap or req.namespace != self.cfg.namespace:
            return Result()  # some other ConfigMap's event
        images = image_set(self.client, self.cfg) if self.enabled else []
        digest = image_set_digest(images)
        nodes = [
            obj_util.name_of(n)
            for n in self.client.list("Node")
            if TPU_NODE_LABEL in ((n.get("metadata") or {}).get("labels") or {})
        ]
        desired = (
            {prepull_pod_name(node, digest): node for node in nodes}
            if images else {}
        )
        covered = 0
        existing = set()
        requeue = 0.0
        for pod in self.client.list("Pod", self.cfg.namespace):
            labels = (pod.get("metadata") or {}).get("labels") or {}
            if PREPULL_LABEL not in labels:
                continue
            name = obj_util.name_of(pod)
            phase = (pod.get("status") or {}).get("phase")
            stale = name not in desired  # old image set or vanished node
            retry = False
            if phase == "Failed" and not stale:
                failed_at = _failure_time(pod)
                age = (
                    self.clock.now() - failed_at
                    if self.clock is not None and failed_at is not None
                    else RETRY_FAILED_AFTER
                )
                if age >= RETRY_FAILED_AFTER:
                    retry = True
                else:
                    # Keep the Failed pod as the backoff marker; come
                    # back when its retry window opens.
                    wait = RETRY_FAILED_AFTER - age
                    requeue = min(requeue, wait) if requeue else wait
                    existing.add(name)
                    continue
            if stale or retry:
                try:
                    self.client.delete("Pod", name, self.cfg.namespace)
                except NotFoundError:
                    pass
                continue
            existing.add(name)
            if phase == "Succeeded":
                covered += 1
        for name, node in desired.items():
            if name in existing:
                continue
            try:
                self.client.create(
                    generate_prepull_pod(self.cfg, node, images, digest)
                )
            except AlreadyExistsError:
                pass  # raced our own cache; the watch will re-trigger
        if self.metrics is not None:
            self.metrics.prepull_nodes_covered.set(covered)
            self.metrics.prepull_nodes_target.set(len(desired))
        return Result(requeue_after=requeue)
