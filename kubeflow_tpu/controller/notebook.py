"""Core Notebook reconciler: Notebook CR → indexed StatefulSet + Services.

TPU-native rebuild of the reference's core loop (reference
components/notebook-controller/controllers/notebook_controller.go:94-294,
generateStatefulSet :433-523, generateService :525-556, status mirroring
:299-374, restart annotation :259-294, event re-emission :99-126), with the
key generalization from SURVEY.md §7 step 2: a notebook is N pods, not 1.

- CPU notebook (no ``spec.tpu``): 1-replica StatefulSet — reference parity.
- TPU notebook: **indexed StatefulSet** with ``replicas == slice hosts``,
  ``podManagementPolicy: Parallel`` (all hosts start together — a partial
  slice is useless and jax.distributed.initialize would hang), a headless
  Service for stable per-host DNS, ``google.com/tpu`` chip limits on the
  primary container, and GKE TPU nodeSelectors + tolerations.
- The stop annotation scales the *whole slice* to 0 atomically; a restart
  annotation deletes *every* host pod (never partial — the slice restarts
  as a unit).
"""

from __future__ import annotations

import copy
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.notebook import MAX_NAME_LENGTH, Notebook
from kubeflow_tpu.controller import reconcilehelper as helper
from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.client import Client, retry_on_conflict
from kubeflow_tpu.k8s.errors import NotFoundError
from kubeflow_tpu.k8s.events import EventRecorder
from kubeflow_tpu.k8s.manager import Manager, Reconciler, Request, Result
from kubeflow_tpu.metrics import Metrics
from kubeflow_tpu.observability import tracing
from kubeflow_tpu.tpu.topology import InvalidTopologyError, SliceTopology

log = logging.getLogger(__name__)

from kubeflow_tpu.api.names import (
    JAX_COORDINATOR_PORT,
    MEGASCALE_PORT,
    NOTEBOOK_PORT,
    derived_name,
    routing_service_name,
)
from kubeflow_tpu.webhook import tpu_env as envc
from kubeflow_tpu.webhook.tpu_env import upsert_env

NOTEBOOK_PORT_NAME = "notebook-port"

# Annotations never copied onto pod templates (reference
# notebook_controller.go:486-491 filters kubectl + lifecycle keys).
_TEMPLATE_ANNOTATION_SKIP = {
    "kubectl.kubernetes.io/last-applied-configuration",
    ann.STOP,
    ann.RESTART,
    ann.LAST_ACTIVITY,
    ann.LAST_ACTIVITY_CHECK,
    ann.UPDATE_PENDING,
    ann.TPU_SLICE_INTERRUPTED,
    # Recovery state machine churns these while the slice is interrupted;
    # copying them into the template would roll the StatefulSet (and restart
    # the very pods recovery is waiting on).
    ann.TPU_RECOVERY_STARTED,
    ann.TPU_RECOVERY_ESCALATIONS,
    ann.TPU_RECOVERY_LAST_ESCALATION,
    ann.TPU_LAST_INTERRUPTION_DURATION,
}

# Dedup-cursor token regimes (compared as STRINGS; '!' < '.' < '0'..'9'):
# the priming floor sorts below everything, timestamp tokens below every
# integer token — so on an integer-rv cluster (etcd) one anomalous
# rv-less Event is merely dropped instead of poisoning the cursor into a
# regime that would suppress all future integer events.
_CURSOR_FLOOR = "!"
_TS_PREFIX = "."


def _event_token(event: dict) -> str:
    """Orderable dedup token for an Event, compared as STRINGS.

    Primary regime: integer resourceVersions (etcd's monotonic revisions —
    the pragmatic ordering informer resume relies on), zero-padded so
    lexicographic order equals numeric order. Fallback regime for
    apiservers whose rvs are genuinely opaque (the API contract allows
    it): the Event's RFC3339 lastTimestamp with the event NAME as a
    tiebreaker — timestamps have 1-second granularity, and two Warnings
    in the same second must not collide into one token (the collision
    would drop the second forever). Residual, documented limitation of
    the opaque regime: an event recorded AFTER the cursor advanced, with
    the same second and a lexically smaller name, is missed — bounded to
    one second of history, versus etcd's unique revisions which never
    collide."""
    meta = event.get("metadata", {})
    rv = meta.get("resourceVersion", "")
    try:
        return f"{int(rv):020d}"
    except (TypeError, ValueError):
        ts = (
            event.get("lastTimestamp")
            or meta.get("creationTimestamp")
            or ""
        )
        return f"{_TS_PREFIX}{ts}/{meta.get('name', '')}"


def _cursor_token(raw: str) -> str:
    """Normalize a stored cursor annotation into token form (upgrades
    cursors written by the older raw-int scheme)."""
    if not raw:
        return ""
    try:
        return f"{int(raw):020d}"
    except (TypeError, ValueError):
        return raw


def _token_regime(tok: str) -> str:
    """Which dedup regime a token belongs to: "int" (zero-padded etcd
    revision), "ts" (timestamp/name fallback), or "" (floor/empty —
    regime not yet pinned)."""
    if not tok or tok == _CURSOR_FLOOR:
        return ""
    return "ts" if tok.startswith(_TS_PREFIX) else "int"


@dataclass
class ControllerConfig:
    """Env-sourced knobs (reference manager.yaml:28-58 ConfigMap wiring)."""

    add_fsgroup: bool = True
    cluster_domain: str = "cluster.local"
    default_working_dir: str = "/home/jovyan"
    # Istio mode (reference notebook_controller.go:238, manager.yaml:28-43):
    # the kubeflow overlay serves notebooks through an Istio
    # VirtualService; standalone/GKE use Gateway-API HTTPRoutes (the
    # platform controller's path).
    use_istio: bool = False
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    istio_host: str = "*"

    @classmethod
    def from_env(cls, env: dict) -> "ControllerConfig":
        return cls(
            add_fsgroup=env.get("ADD_FSGROUP", "true").lower() != "false",
            cluster_domain=env.get("CLUSTER_DOMAIN", "cluster.local"),
            use_istio=env.get("USE_ISTIO", "").lower() == "true",
            istio_gateway=env.get("ISTIO_GATEWAY") or "kubeflow/kubeflow-gateway",
            istio_host=env.get("ISTIO_HOST") or "*",
        )


def headless_service_name(notebook_name: str) -> str:
    # Service names get the full 63-char DNS label budget.
    return derived_name(notebook_name, "-hosts", 63)


def virtual_service_name(notebook_name: str, namespace: str) -> str:
    """Reference virtualServiceName (notebook_controller.go:554-556)."""
    return f"notebook-{namespace}-{notebook_name}"


def generate_virtual_service(nb: Notebook, config: ControllerConfig) -> dict:
    """Istio VirtualService routing ``/notebook/{ns}/{name}/`` to the
    notebook Service (reference generateVirtualService,
    notebook_controller.go:558-658; apiVersion upgraded v1alpha3 →
    v1beta1, same schema for these fields).

    Annotation overrides, as the reference: ``http-rewrite-uri`` replaces
    the rewrite target; ``http-headers-request-set`` is a JSON object of
    request headers to set (malformed JSON degrades to no headers rather
    than failing the reconcile)."""
    import json

    prefix = f"/notebook/{nb.namespace}/{nb.name}/"
    rewrite = nb.annotations.get(ann.REWRITE_URI) or prefix
    headers = {}
    raw = nb.annotations.get(ann.HEADERS_REQUEST_SET)
    if raw:
        try:
            parsed = json.loads(raw)
            if isinstance(parsed, dict):
                headers = {str(k): str(v) for k, v in parsed.items()}
        except ValueError:
            headers = {}
    # The ROUTING SERVICE's name, not the raw notebook name: names over
    # the 63-char Service budget get the deterministic hashed fallback
    # (api.names.derived_name), and a mismatch here would 503 every
    # long-named notebook through Istio while all children look healthy.
    service = (
        f"{routing_service_name(nb.name)}.{nb.namespace}"
        f".svc.{config.cluster_domain}"
    )
    return {
        "apiVersion": "networking.istio.io/v1beta1",
        "kind": "VirtualService",
        "metadata": {
            "name": virtual_service_name(nb.name, nb.namespace),
            "namespace": nb.namespace,
        },
        "spec": {
            "hosts": [config.istio_host],
            "gateways": [config.istio_gateway],
            "http": [
                {
                    "headers": {"request": {"set": headers}},
                    "match": [{"uri": {"prefix": prefix}}],
                    "rewrite": {"uri": rewrite},
                    "route": [
                        {
                            "destination": {
                                "host": service,
                                "port": {"number": 80},
                            }
                        }
                    ],
                }
            ],
        },
    }


def slice_sts_name(notebook_name: str, slice_id: int) -> str:
    """StatefulSet name for one slice of a (possibly multislice) notebook.

    Slice 0 keeps the bare notebook name whenever it fits — single-slice
    notebooks (the overwhelmingly common case) are byte-identical to the
    pre-multislice layout, and pod-0 DNS/routing ({name}-0) stays stable.
    Names that would overflow the 52-char StatefulSet budget fall back to
    the deterministic hashed form from ``api.names.derived_name`` instead
    of being rejected (reference GenerateName fallback,
    notebook_controller.go:145-149).
    """
    suffix = "" if slice_id == 0 else f"-s{slice_id}"
    return derived_name(notebook_name, suffix, MAX_NAME_LENGTH)


def slice_sts_names(notebook_name: str, slice_count: int) -> list[str]:
    return [slice_sts_name(notebook_name, j) for j in range(slice_count)]


class NotebookReconciler(Reconciler):
    def __init__(
        self,
        client: Client,
        config: Optional[ControllerConfig] = None,
        metrics: Optional[Metrics] = None,
        recorder: Optional[EventRecorder] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.client = client
        self.config = config or ControllerConfig()
        self.metrics = metrics or Metrics(client)
        self.recorder = recorder or EventRecorder(client)
        self.clock = clock or time.time
        # Notebooks whose slice-ready latency was already observed.
        self._ready_observed: set[tuple[str, str]] = set()

    def register(self, manager: Manager) -> None:
        manager.register(
            self,
            for_kind="Notebook",
            owns=("StatefulSet", "Service"),
            watches=[
                ("Pod", _pod_to_notebook),
                ("Event", _event_to_notebook),
            ],
            name="NotebookReconciler",
        )

    # ------------------------------------------------------------------
    def reconcile(self, req: Request) -> Result:
        # Root span per reconcile pass; the phase methods below hang
        # child spans off it (StatefulSet apply, Services/routes, status
        # mirroring) so a slow reconcile decomposes in the trace export.
        with tracing.get_tracer("controller").start_span(
            "reconcile", notebook=req.name, namespace=req.namespace,
        ):
            return self._reconcile(req)

    def _reconcile(self, req: Request) -> Result:
        try:
            obj = self.client.get("Notebook", req.name, req.namespace)
        except NotFoundError:
            return Result()
        if "deletionTimestamp" in obj["metadata"]:
            # Deletion cleanup is finalizer-driven (platform controller);
            # child objects go via ownerReference GC.
            return Result()
        nb = Notebook(obj)


        # Resolve TPU topology up front; an invalid spec must never produce
        # a half-scheduled slice.
        slice_topo: Optional[SliceTopology] = None
        if nb.tpu is not None:
            try:
                slice_topo = nb.tpu.slice_topology()
            except InvalidTopologyError as err:
                self.recorder.eventf(obj, "Warning", "InvalidTPUTopology", str(err))
                self._set_condition(
                    nb, "TPUTopologyValid", "False", "InvalidTopology", str(err)
                )
                return Result()
            self._set_condition(
                nb, "TPUTopologyValid", "True", "Resolved",
                f"{slice_topo.accelerator_type} ({slice_topo.hosts} hosts)",
            )

        slice_count = nb.tpu.slice_count if nb.tpu is not None else 1
        with tracing.get_tracer("controller").start_span(
            "reconcile.statefulsets", slices=slice_count,
        ):
            self._reconcile_slices(obj, nb, slice_topo, slice_count)
        if nb.stopped:
            self._clear_claim_annotations(obj, nb)

        with tracing.get_tracer("controller").start_span(
            "reconcile.services",
        ):
            service = generate_service(nb)
            helper.reconcile_child(
                self.client, obj, service, helper.copy_service_fields
            )
            if slice_topo is not None:
                headless = generate_headless_service(nb, slice_topo)
                helper.reconcile_child(
                    self.client, obj, headless, helper.copy_service_fields
                )
            if self.config.use_istio:
                helper.reconcile_child(
                    self.client, obj,
                    generate_virtual_service(nb, self.config),
                    helper.copy_virtual_service_fields,
                )

        with tracing.get_tracer("controller").start_span(
            "reconcile.status",
        ):
            self._reemit_pod_events(nb, slice_topo)
            self._update_status(nb, slice_topo)
            self._handle_restart(nb, slice_topo)
        return Result()

    def _reconcile_slices(self, obj: dict, nb: Notebook,
                          slice_topo, slice_count: int) -> None:
        """The StatefulSet-apply phase of one reconcile pass (its own
        child span): per-slice generate/diff/apply plus warm-pool claims
        and stale-slice pruning."""
        created_any = False
        for slice_id in range(slice_count):
            sts = generate_statefulset(
                nb, slice_topo, self.config,
                slice_id=slice_id, slice_count=slice_count,
            )
            try:
                existing = self.client.get(
                    "StatefulSet", obj_util.name_of(sts), nb.namespace
                )
            except NotFoundError:
                existing = None
            # Claim a warm slice on every 0→N replica transition, not just
            # first creation: the webhook's reconciliation lock means the
            # STS is born at replicas 0 and scales up only after the
            # platform reconciler releases the lock (the production path),
            # and a culled notebook's RESUME re-acquires capacity too. The
            # ownership check mirrors _reconcile_statefulset's no-adopt
            # guard — a name-collision STS stuck at 0 replicas must not
            # drain the pool on every reconcile.
            scaling_up = slice_topo is not None and not nb.stopped and (
                existing is None
                or (
                    existing.get("spec", {}).get("replicas", 0) == 0
                    and obj_util.is_controlled_by(obj, existing)
                )
            )
            if scaling_up:
                self._maybe_claim_warm_slice(obj, nb, slice_topo, slice_id)
            created_any |= self._reconcile_statefulset(obj, sts, existing)
        if created_any:
            self.metrics.create_total.inc()
            # Long names fall back to deterministic hashed StatefulSet
            # names (reference GenerateName fallback,
            # notebook_controller.go:145-149) instead of a silently-never-
            # scheduled notebook; surface the substitution on creation so
            # the user can find their pods (not every reconcile — eventf
            # costs API round-trips).
            fallback_names = [
                n for j in range(slice_count)
                if (n := slice_sts_name(nb.name, j))
                != (nb.name if j == 0 else f"{nb.name}-s{j}")
            ]
            if fallback_names:
                self.recorder.eventf(
                    obj, "Normal", "LongNameFallback",
                    f"Notebook name exceeds {MAX_NAME_LENGTH} characters "
                    f"for its slice layout; using generated StatefulSet "
                    f"name(s) {', '.join(fallback_names)}",
                )
        self._prune_stale_slice_sts(nb, slice_count)

    # ------------------------------------------------------------------
    @staticmethod
    def _claim_marker_key(slice_id: int) -> str:
        """Claim-intent marker, keyed PER SLICE: each slice of a
        multislice notebook claims its own placeholder, so slice 0's
        marker must not suppress slice 1's claim. Slice 0 keeps the bare
        CLAIMED_FROM name (the single-slice contract tests/users see)."""
        from kubeflow_tpu.api.slicepool import CLAIMED_FROM

        return CLAIMED_FROM if slice_id == 0 else f"{CLAIMED_FROM}.{slice_id}"

    def _maybe_claim_warm_slice(
        self, obj: dict, nb: Notebook, topo, slice_id: int = 0
    ) -> None:
        """Claim a warm SlicePool placeholder BEFORE the slice scales up,
        so the freed chips/warm nodes are available when the slice pods
        first schedule (kubeflow_tpu.controller.slicepool). The caller only
        invokes this on a 0→N replica transition (creation with no lock,
        lock release, or resume) — never the steady-state reconcile path."""
        from kubeflow_tpu.controller.slicepool import claim_warm_slice

        marker = self._claim_marker_key(slice_id)
        pools = self.client.list("SlicePool", nb.namespace)
        if not pools:
            # Namespace doesn't use pools (the common case): return before
            # the idempotence GET below — an extra read per scale-up
            # reconcile on the no-pool spawn path is measurable wire
            # latency for nothing. Keep metrics quiet too.
            return
        # One transition, one claim per slice: a prior pass may have
        # claimed but its replica update is not visible yet (stale cache
        # read, or the STS write failed after the claim) — the claim
        # marker on a FRESH read is the intent record that stops a second
        # placeholder being drained for the same scale-up. Markers are
        # cleared whenever the notebook is stopped
        # (_clear_claim_annotations), so a resume claims again.
        try:
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
        except NotFoundError:
            return
        if marker in obj_util.annotations_of(fresh):
            return
        pool = claim_warm_slice(
            self.client, nb.namespace, topo, recorder=self.recorder,
            notebook=obj, now=self.clock(), pools=pools,
            # Bound the fenced candidate walk: this runs inside the
            # single-threaded reconcile loop, and a claim stampede must
            # cost one scale-up its placeholder, not wedge every queued
            # reconcile behind the walk.
            deadline=time.perf_counter() + 5.0,
        )
        if not pool:
            self.metrics.pool_claim_misses_total.inc()
            return
        self.metrics.pool_claims_total.inc()

        def record():
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
            if obj_util.annotations_of(fresh).get(marker) != pool:
                obj_util.set_annotation(fresh, marker, pool)
                self.client.update(fresh)

        retry_on_conflict(record)

    def _clear_claim_annotations(self, obj: dict, nb: Notebook) -> None:
        """A stopped notebook holds no slice capacity: drop the
        claimed-from-pool markers (every per-slice key) so the next 0→N
        transition (resume) claims fresh warm slices, while repeated
        reconciles of the SAME transition stay idempotent
        (_maybe_claim_warm_slice skips on the marker)."""
        from kubeflow_tpu.api.slicepool import CLAIMED_FROM

        def markers(o) -> list[str]:
            return [
                k for k in obj_util.annotations_of(o)
                if k == CLAIMED_FROM or k.startswith(f"{CLAIMED_FROM}.")
            ]

        # Steady-state cheapness: most stopped notebooks carry no marker;
        # decide on the already-fetched object before paying a fresh GET.
        if not markers(obj):
            return

        def clear():
            try:
                fresh = self.client.get("Notebook", nb.name, nb.namespace)
            except NotFoundError:
                return
            found = markers(fresh)
            if found:
                for k in found:
                    obj_util.remove_annotation(fresh, k)
                self.client.update(fresh)

        retry_on_conflict(clear)

    # ------------------------------------------------------------------
    def _reconcile_statefulset(
        self, owner: dict, desired: dict, existing: Optional[dict]
    ) -> bool:
        """Create-or-update (``existing`` prefetched by the caller — one
        GET serves both the claim probe and this); True when created."""
        name = obj_util.name_of(desired)
        if existing is None:
            obj_util.set_controller_reference(owner, desired)
            try:
                self.client.create(desired)
            except Exception:
                self.metrics.create_failed_total.inc()
                raise
            return True
        if not obj_util.is_controlled_by(owner, existing):
            # E.g. notebook "foo" (sliceCount 2) vs a sibling notebook
            # literally named "foo-s1": both would claim STS "foo-s1".
            # Never adopt — two reconcilers would fight over one object.
            self.recorder.eventf(
                owner, "Warning", "StatefulSetConflict",
                f"StatefulSet {name} exists but is not controlled by this "
                "Notebook; refusing to adopt it (name collision?)",
            )
            return False
        if helper.copy_statefulset_fields(desired, existing):
            # Conflict-retried: aborting here after a warm-slice claim
            # would re-enter the 0→N transition next reconcile and consume
            # a SECOND placeholder for the same scale-up.
            def write():
                fresh = self.client.get(
                    "StatefulSet", name, obj_util.namespace_of(desired)
                )
                if helper.copy_statefulset_fields(desired, fresh):
                    self.client.update(fresh)

            retry_on_conflict(write)
        return False

    def _prune_stale_slice_sts(self, nb: Notebook, slice_count: int) -> None:
        """Delete per-slice StatefulSets beyond the current sliceCount (a
        shrink while stopped; the validating webhook blocks live changes)."""
        expected = set(slice_sts_names(nb.name, slice_count))
        for sts in self.client.list(
            "StatefulSet", nb.namespace, {ann.NOTEBOOK_NAME_LABEL: nb.name}
        ):
            name = obj_util.name_of(sts)
            if name in expected:
                continue
            if not obj_util.is_controlled_by(nb.obj, sts):
                # Mirror _reconcile_statefulset's adoption guard: a
                # user-created STS that merely carries our name label must
                # not be deleted out from under its owner.
                self.recorder.eventf(
                    nb.obj, "Warning", "StatefulSetConflict",
                    f"StatefulSet {name} carries label "
                    f"{ann.NOTEBOOK_NAME_LABEL}={nb.name} but is not "
                    "controlled by this Notebook; refusing to prune it",
                )
                continue
            try:
                self.client.delete("StatefulSet", name, nb.namespace)
            except NotFoundError:
                pass

    # ------------------------------------------------------------------
    def _slice_pods(self, nb: Notebook) -> list[dict]:
        # Server-side label selection: this runs in every reconcile, and a
        # full-namespace pod list would be O(namespace) on a real apiserver.
        pods = self.client.list(
            "Pod", nb.namespace, {ann.NOTEBOOK_NAME_LABEL: nb.name}
        )
        return sorted(pods, key=obj_util.name_of)

    def _update_status(self, nb: Notebook, slice_topo: Optional[SliceTopology]) -> None:
        """Mirror pod state onto the Notebook (reference
        createNotebookStatus :315-374), extended with slice-level TPU status."""
        pods = self._slice_pods(nb)
        pod0 = next((p for p in pods if obj_util.name_of(p).endswith("-0")), None)

        status: dict = {}
        ready_hosts = 0
        for pod in pods:
            if _pod_ready(pod):
                ready_hosts += 1
        status["readyReplicas"] = ready_hosts

        pod_conditions: list = []
        if pod0 is not None:
            # Mirror pod-0 conditions (the reference mirrors its single pod).
            pod_conditions = pod0.get("status", {}).get("conditions", [])
            for cs in pod0.get("status", {}).get("containerStatuses", []):
                if cs.get("name") == nb.name:
                    status["containerState"] = cs.get("state", {})
                    break

        if slice_topo is not None:
            slice_count = nb.tpu.slice_count if nb.tpu is not None else 1
            hosts = slice_topo.hosts * slice_count  # total pods
            interrupted = any(
                p.get("status", {}).get("phase") == "Failed" for p in pods
            ) or ann.TPU_SLICE_INTERRUPTED in nb.annotations
            if nb.stopped:
                health = "Stopped"
            elif interrupted:
                health = "Interrupted"
            elif ready_hosts == hosts:
                health = "Healthy"
            else:
                health = "Forming"
            status["tpu"] = {
                "hosts": hosts,
                "readyHosts": ready_hosts,
                "sliceHealth": health,
                "acceleratorType": slice_topo.accelerator_type,
            }
            if slice_count > 1:
                status["tpu"]["slices"] = slice_count
                status["tpu"]["hostsPerSlice"] = slice_topo.hosts
            if hosts > 1:
                status["tpu"]["jaxCoordinator"] = (
                    f"{slice_sts_name(nb.name, 0)}-0."
                    f"{headless_service_name(nb.name)}."
                    f"{nb.namespace}.svc.{self.config.cluster_domain}"
                    f":{JAX_COORDINATOR_PORT}"
                )
            prof = ann.parse_profiling_port(
                nb.annotations.get(ann.TPU_PROFILING_PORT)
            )
            if prof is not None:
                # Worker 0 runs jax.profiler.start_server on this port
                # (runtime.bootstrap consumes the webhook-injected env).
                status["tpu"]["profilingServer"] = (
                    f"{slice_sts_name(nb.name, 0)}-0."
                    f"{headless_service_name(nb.name)}."
                    f"{nb.namespace}.svc.{self.config.cluster_domain}:{prof}"
                )
            serving = ann.parse_profiling_port(
                nb.annotations.get(ann.TPU_SERVING_PORT)
            )
            if serving is not None:
                # Worker 0 binds the HTTP inference endpoint on this port
                # (models/server.py serving_port_from_env).
                status["tpu"]["servingEndpoint"] = (
                    f"{slice_sts_name(nb.name, 0)}-0."
                    f"{headless_service_name(nb.name)}."
                    f"{nb.namespace}.svc.{self.config.cluster_domain}"
                    f":{serving}"
                )
            if health == "Healthy":
                self._observe_slice_ready(nb)

        def write():
            # Merge against the FRESH object's conditions: a condition set
            # earlier in this reconcile (e.g. TPUTopologyValid) must survive
            # the status rewrite, or the two writers oscillate forever.
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
            merged = dict(status)
            merged["conditions"] = _merge_pod_conditions(
                fresh.get("status", {}).get("conditions", []), pod_conditions
            )
            if fresh.get("status", {}) == merged:
                return
            fresh["status"] = merged
            self.client.update_status(fresh)

        retry_on_conflict(write)

    def _observe_slice_ready(self, nb: Notebook) -> None:
        key = (nb.namespace, nb.name)
        if key in self._ready_observed:
            return
        self._ready_observed.add(key)
        created = nb.obj.get("metadata", {}).get("creationTimestamp", "")
        created_s = obj_util.parse_timestamp(created)
        if created_s is None:
            return
        elapsed = max(0.0, self.clock() - created_s)
        self.metrics.slice_ready_seconds.observe(elapsed)

    # ------------------------------------------------------------------
    def _handle_restart(self, nb: Notebook, slice_topo: Optional[SliceTopology]) -> None:
        """Restart annotation → delete every slice pod, then clear it
        (reference :259-294 deletes the single pod; a TPU slice restarts
        as a unit — deleting only one host would wedge jax.distributed)."""
        if nb.annotations.get(ann.RESTART) != "true":
            return
        deleted = 0
        for pod in self._slice_pods(nb):
            try:
                self.client.delete("Pod", obj_util.name_of(pod), nb.namespace)
                deleted += 1
            except NotFoundError:
                pass

        def clear():
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
            if obj_util.remove_annotation(fresh, ann.RESTART):
                self.client.update(fresh)

        retry_on_conflict(clear)
        self.recorder.eventf(
            nb.obj, "Normal", "NotebookRestarted",
            f"All {max(1, deleted)} slice pod(s) deleted for restart",
        )

    # ------------------------------------------------------------------
    def _reemit_pod_events(self, nb: Notebook, slice_topo: Optional[SliceTopology]) -> None:
        """Surface Warning events from slice pods on the Notebook itself
        (reference :99-126 re-emits via nbNameFromInvolvedObject).

        Dedup is a lastSeen CURSOR on the Notebook (the newest Event
        resourceVersion already processed): ONE field-indexed Event read
        per reconcile and zero writes to Event objects — writing dedup
        marks onto Events (the previous design) raced apiserver Event
        TTL/series aggregation and cost one update per surfaced event.
        The cursor lives on the Notebook, so a restarted controller
        resumes where it left off instead of re-emitting history.
        """
        slice_count = nb.tpu.slice_count if nb.tpu is not None else 1
        pod_names = {
            f"{sts}-{i}"
            for sts in slice_sts_names(nb.name, slice_count)
            for i in range(slice_topo.hosts if slice_topo else 1)
        }
        raw_cursor = nb.annotations.get(ann.LAST_SEEN_EVENT_RV, "")
        cursor = _cursor_token(raw_cursor)
        events = self.client.list(
            "Event", nb.namespace,
            field_selector={"involvedObject.kind": "Pod"},
        )
        # Floor token: sorts below BOTH regimes, so priming with no events
        # still writes a non-empty annotation (its presence IS the primed
        # marker) without blocking either regime's first real event.
        max_seen = cursor or _CURSOR_FLOOR
        emitted = False
        priming = not raw_cursor
        # Sticky regime: once the cursor holds an int (etcd) or ts
        # (opaque-rv fallback) token, events from the OTHER regime are
        # skipped symmetrically — string order must never promote the
        # cursor across regimes. Without this, ONE opaque rv that happens
        # to parse as an integer would lift the cursor into the int regime
        # (ints sort above every '.'-prefixed ts token) and permanently
        # suppress all subsequent timestamp-token events. An unpinned
        # cursor (fresh/floor) pins to the MAJORITY regime of the visible
        # events, so the same single anomaly cannot pin the wrong regime
        # at priming either.
        regime = _token_regime(cursor)
        if not regime and events:
            votes = {"int": 0, "ts": 0}
            for e in events:
                votes[_token_regime(_event_token(e))] += 1
            regime = "int" if votes["int"] >= votes["ts"] else "ts"
        for event in sorted(events, key=_event_token):
            rv = _event_token(event)
            if regime and _token_regime(rv) != regime:
                continue
            if rv <= cursor:
                continue
            max_seen = max(max_seen, rv)
            if priming:
                # First sight of this notebook (fresh create OR controller
                # upgraded from the old per-Event-mark dedup): prime the
                # cursor past existing history instead of re-emitting it —
                # the notebook's own pods cannot have pre-creation events
                # worth surfacing, and upgrades must not replay the fleet's
                # retained Warning history as a duplicate burst.
                continue
            inv = event.get("involvedObject", {})
            if event.get("type") != "Warning" or inv.get("name") not in pod_names:
                continue
            self.recorder.eventf(
                nb.obj, "Warning", event.get("reason", "PodEvent"),
                f"[{inv.get('name')}] {event.get('message', '')}",
            )
            emitted = True
        # Persist the cursor when something was surfaced, or once to prime
        # (even at 0 — the annotation's presence IS the primed marker).
        # Otherwise skip the write: unrelated namespace events are cheap to
        # re-filter next reconcile, and writing would make N notebooks each
        # update themselves whenever ANY pod in the namespace logs an event.
        if priming or (emitted and max_seen > cursor):
            def advance():
                try:
                    fresh = self.client.get("Notebook", nb.name, nb.namespace)
                except NotFoundError:
                    return  # deleted mid-reconcile — nothing to advance
                # Monotonic merge: another worker may have advanced further.
                fresh_raw = obj_util.annotations_of(fresh).get(
                    ann.LAST_SEEN_EVENT_RV, ""
                )
                if fresh_raw and _cursor_token(fresh_raw) >= max_seen:
                    return
                obj_util.set_annotation(
                    fresh, ann.LAST_SEEN_EVENT_RV, max_seen
                )
                self.client.update(fresh)

            retry_on_conflict(advance)

    def _set_condition(
        self, nb: Notebook, ctype: str, cstatus: str, reason: str, message: str
    ) -> None:
        def write():
            fresh = self.client.get("Notebook", nb.name, nb.namespace)
            obj_util.set_condition(
                fresh,
                {"type": ctype, "status": cstatus, "reason": reason, "message": message},
            )
            self.client.update_status(fresh)

        retry_on_conflict(write)


# ---------------------------------------------------------------------------
# Spec generation (pure functions — the unit-test surface, SURVEY.md §4a)


def generate_statefulset(
    nb: Notebook,
    slice_topo: Optional[SliceTopology],
    config: ControllerConfig,
    slice_id: int = 0,
    slice_count: int = 1,
) -> dict:
    """Notebook CR → StatefulSet spec (reference generateStatefulSet :433-523,
    TPU-generalized).

    Multislice (slice_count > 1): ONE StatefulSet PER SLICE, so each pod's
    index label is its slice-LOCAL ordinal — TPU_WORKER_ID stays a plain
    downward-API projection and libtpu sees per-slice worker ids, exactly
    as GKE Multislice structures its JobSets. Slice-varying env
    (TPU_WORKER_HOSTNAMES, MEGASCALE_*) is injected here; slice-invariant
    env comes from the webhook.
    """
    hosts = slice_topo.hosts if slice_topo else 1
    replicas = 0 if nb.stopped else hosts
    sts_name = slice_sts_name(nb.name, slice_id)

    template_labels = {
        "statefulset": sts_name,
        ann.NOTEBOOK_NAME_LABEL: nb.name,
    }
    for key, value in nb.labels.items():
        template_labels.setdefault(key, value)
    template_annotations = {
        k: v
        for k, v in nb.annotations.items()
        if k not in _TEMPLATE_ANNOTATION_SKIP
    }

    pod_spec = copy.deepcopy(nb.pod_spec)
    containers = pod_spec.setdefault("containers", [])
    for container in containers:
        if container.get("name") == nb.name:
            _apply_container_defaults(container, nb, config)
            if slice_topo is not None:
                resources = container.setdefault("resources", {})
                chips = str(slice_topo.chips_per_host)
                resources.setdefault("limits", {})["google.com/tpu"] = chips
                resources.setdefault("requests", {})["google.com/tpu"] = chips
                if slice_count > 1:
                    _apply_multislice_env(
                        container, nb, slice_topo, config, slice_id, slice_count
                    )
            break

    if config.add_fsgroup:
        pod_spec.setdefault("securityContext", {}).setdefault("fsGroup", 100)

    if slice_topo is not None:
        selector = pod_spec.setdefault("nodeSelector", {})
        selector.update(slice_topo.node_selector())
        if nb.tpu is not None and nb.tpu.spot:
            selector["cloud.google.com/gke-spot"] = "true"
        tolerations = pod_spec.setdefault("tolerations", [])
        if not any(t.get("key") == "google.com/tpu" for t in tolerations):
            tolerations.append(
                {"key": "google.com/tpu", "operator": "Exists", "effect": "NoSchedule"}
            )

    sts = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {
            "name": sts_name,
            "namespace": nb.namespace,
            "labels": dict(template_labels),
        },
        "spec": {
            "replicas": replicas,
            # Selector keys on the PER-SLICE name: two slices' StatefulSets
            # must never adopt each other's pods.
            "selector": {"matchLabels": {"statefulset": sts_name}},
            "serviceName": headless_service_name(nb.name)
            if slice_topo is not None
            else routing_service_name(nb.name),
            "template": {
                "metadata": {
                    "labels": template_labels,
                    "annotations": template_annotations,
                },
                "spec": pod_spec,
            },
        },
    }
    if slice_topo is not None:
        # All hosts must come up together; OrderedReady would serialize the
        # slice and blow the <90s spawn budget.
        sts["spec"]["podManagementPolicy"] = "Parallel"
    return sts


def _apply_multislice_env(
    container: dict,
    nb: Notebook,
    slice_topo: SliceTopology,
    config: ControllerConfig,
    slice_id: int,
    slice_count: int,
) -> None:
    """Slice-varying env for multislice notebooks.

    Overrides the webhook's single-slice values where they differ:
    TPU_WORKER_HOSTNAMES lists THIS slice's hosts (libtpu is per-slice);
    JAX_* spans every host of every slice (jax.distributed runs one global
    process group over DCN); MEGASCALE_* carries the slice topology
    (SURVEY.md §5: "MEGASCALE_*/JAX_COORDINATOR style env when spanning
    slices").
    """
    headless = headless_service_name(nb.name)
    sts_name = slice_sts_name(nb.name, slice_id)
    hostnames = slice_topo.worker_hostnames(
        sts_name, headless, nb.namespace, config.cluster_domain
    )
    # Slice 0 / host 0 coordinates both planes (jax.distributed and
    # megascale); slice_sts_name(…, 0) keeps the long-name fallback
    # consistent with the actual pod hostname.
    head = (
        f"{slice_sts_name(nb.name, 0)}-0.{headless}."
        f"{nb.namespace}.svc.{config.cluster_domain}"
    )
    upsert_env(
        container,
        [
            {"name": envc.TPU_WORKER_HOSTNAMES, "value": ",".join(hostnames)},
            {"name": envc.TPU_HOSTS_PER_SLICE, "value": str(slice_topo.hosts)},
            {"name": envc.MEGASCALE_NUM_SLICES, "value": str(slice_count)},
            {"name": envc.MEGASCALE_SLICE_ID, "value": str(slice_id)},
            {
                "name": envc.MEGASCALE_COORDINATOR_ADDRESS,
                "value": f"{head}:{MEGASCALE_PORT}",
            },
            {
                "name": envc.JAX_COORDINATOR_ADDRESS,
                "value": f"{head}:{JAX_COORDINATOR_PORT}",
            },
            {
                "name": envc.JAX_NUM_PROCESSES,
                "value": str(slice_topo.hosts * slice_count),
            },
        ],
    )


def _apply_container_defaults(
    container: dict, nb: Notebook, config: ControllerConfig
) -> None:
    """Reference defaults (notebook_controller.go:493-508)."""
    container.setdefault("workingDir", config.default_working_dir)
    ports = container.setdefault("ports", [])
    if not any(p.get("containerPort") == NOTEBOOK_PORT for p in ports):
        ports.append(
            {"containerPort": NOTEBOOK_PORT, "name": NOTEBOOK_PORT_NAME, "protocol": "TCP"}
        )
    env = container.setdefault("env", [])
    if not any(e.get("name") == "NB_PREFIX" for e in env):
        env.append(
            {"name": "NB_PREFIX", "value": f"/notebook/{nb.namespace}/{nb.name}"}
        )


def generate_service(nb: Notebook) -> dict:
    """Routing Service: port 80 → 8888 on pod 0 (reference generateService
    :525-556; Jupyter runs on worker 0 of a slice). Selector and port name
    go through the same long-name derivation as the StatefulSet — a
    mismatch would leave a running slice unreachable."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": routing_service_name(nb.name),
            "namespace": nb.namespace,
            "labels": {ann.NOTEBOOK_NAME_LABEL: nb.name},
        },
        "spec": {
            "type": "ClusterIP",
            "selector": {
                "statefulset": slice_sts_name(nb.name, 0),
                "apps.kubernetes.io/pod-index": "0",
            },
            "ports": [
                {
                    "name": derived_name("http-" + nb.name, "", 63),
                    "port": 80,
                    "targetPort": NOTEBOOK_PORT,
                    "protocol": "TCP",
                }
            ],
        },
    }


def generate_headless_service(nb: Notebook, slice_topo: SliceTopology) -> dict:
    """Headless Service giving every slice host a stable DNS identity —
    the backbone of TPU_WORKER_HOSTNAMES and jax.distributed bootstrap."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": headless_service_name(nb.name),
            "namespace": nb.namespace,
            "labels": {ann.NOTEBOOK_NAME_LABEL: nb.name},
        },
        "spec": {
            "clusterIP": "None",
            # Selects by NOTEBOOK label, not per-slice statefulset label:
            # every slice's pods share this subdomain so cross-slice DCN
            # (megascale, jax.distributed) resolves one flat DNS space.
            "selector": {ann.NOTEBOOK_NAME_LABEL: nb.name},
            "publishNotReadyAddresses": True,  # hosts must resolve during formation
            "ports": [
                {"name": "jax-coordinator", "port": JAX_COORDINATOR_PORT, "protocol": "TCP"},
                {"name": "notebook", "port": NOTEBOOK_PORT, "protocol": "TCP"},
            ],
        },
    }


# ---------------------------------------------------------------------------
# Watch map functions


def _pod_to_notebook(ev) -> list[Request]:
    labels = ev.object.get("metadata", {}).get("labels", {})
    name = labels.get(ann.NOTEBOOK_NAME_LABEL)
    if name:
        return [Request(name, ev.namespace)]
    return []


def _event_to_notebook(ev) -> list[Request]:
    """Map pod Events to their Notebook: pod "{nb}-{ordinal}" → nb
    (reference nbNameFromInvolvedObject :705)."""
    inv = ev.object.get("involvedObject", {})
    if inv.get("kind") != "Pod":
        return []
    name = inv.get("name", "")
    base, _, ordinal = name.rpartition("-")
    if base and ordinal.isdigit():
        requests = [Request(base, ev.namespace)]
        # Multislice pods are "{nb}-s{j}-{i}"; a notebook literally named
        # "{nb}-s{j}" is also possible, so requeue BOTH candidates (a
        # nonexistent name reconciles to a no-op).
        head, _, tail = base.rpartition("-")
        if head and len(tail) > 1 and tail[0] == "s" and tail[1:].isdigit():
            requests.append(Request(head, ev.namespace))
        return requests
    return []


def _pod_ready(pod: dict) -> bool:
    for cond in pod.get("status", {}).get("conditions", []):
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return False


def _merge_pod_conditions(existing: list, pod_conditions: list) -> list:
    """Mirror pod conditions by type (reference PodCondToNotebookCond :376)."""
    merged = {c.get("type"): c for c in existing}
    for cond in pod_conditions:
        merged[cond.get("type")] = {
            "type": cond.get("type"),
            "status": cond.get("status"),
            **({"reason": cond["reason"]} if cond.get("reason") else {}),
            **({"message": cond["message"]} if cond.get("message") else {}),
            **(
                {"lastTransitionTime": cond["lastTransitionTime"]}
                if cond.get("lastTransitionTime")
                else {}
            ),
        }
    return list(merged.values())
