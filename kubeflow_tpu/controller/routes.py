"""Gateway-API routing: HTTPRoutes in the central namespace + ReferenceGrants.

Rebuild of the reference's route layer (reference
components/odh-notebook-controller/controllers/notebook_route.go:51-325 and
notebook_referencegrant.go:39-184):

- The HTTPRoute ``nb-{ns}-{name}`` lives in the **controller (central)
  namespace** and carries a cross-namespace backendRef to the notebook's
  Service. Cross-namespace owner references are impossible, so routes are
  found by labels and cleaned up by the deletion finalizer (:173-193).
- Each user namespace gets one ``notebook-httproute-access`` ReferenceGrant
  permitting central-namespace HTTPRoutes → Services; it is deleted only
  when the namespace's last notebook goes away (:130-162).
- Auth mode swaps the backend to the kube-rbac-proxy service on 8443; the
  conflicting other-mode route is removed on mode switches (:270-325).

On a TPU slice the route always lands on pod 0 (Jupyter runs on worker 0).
"""

from __future__ import annotations

from dataclasses import dataclass

from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.controller import reconcilehelper as helper
from kubeflow_tpu.k8s.client import Client
from kubeflow_tpu.k8s.errors import NotFoundError

HTTPROUTE_API = "gateway.networking.k8s.io/v1"
REFERENCEGRANT_API = "gateway.networking.k8s.io/v1beta1"
REFERENCE_GRANT_NAME = "notebook-httproute-access"

NOTEBOOK_NS_LABEL = "notebook-namespace"
NOTEBOOK_NAME_ROUTE_LABEL = "notebook-name"
ROUTE_MODE_LABEL = "notebook-route-mode"  # plain | auth


@dataclass
class RouteConfig:
    controller_namespace: str = "opendatahub"
    gateway_name: str = "data-science-gateway"
    gateway_namespace: str = "openshift-ingress"

    @classmethod
    def from_env(cls, env: dict) -> "RouteConfig":
        return cls(
            controller_namespace=env.get("K8S_NAMESPACE", "opendatahub"),
            gateway_name=env.get("NOTEBOOK_GATEWAY_NAME", "data-science-gateway"),
            gateway_namespace=env.get("NOTEBOOK_GATEWAY_NAMESPACE", "openshift-ingress"),
        )


def route_name(nb: Notebook) -> str:
    return f"nb-{nb.namespace}-{nb.name}"


def new_httproute(nb: Notebook, cfg: RouteConfig, auth: bool) -> dict:
    """Build the HTTPRoute (reference NewNotebookHTTPRoute :51-132)."""
    from kubeflow_tpu.api.names import proxy_service_name, routing_service_name

    if auth:
        backend = {
            "name": proxy_service_name(nb.name),
            "namespace": nb.namespace,
            "port": 8443,
        }
    else:
        backend = {
            "name": routing_service_name(nb.name),
            "namespace": nb.namespace,
            "port": 80,
        }
    return {
        "apiVersion": HTTPROUTE_API,
        "kind": "HTTPRoute",
        "metadata": {
            "name": route_name(nb),
            "namespace": cfg.controller_namespace,
            "labels": {
                NOTEBOOK_NAME_ROUTE_LABEL: nb.name,
                NOTEBOOK_NS_LABEL: nb.namespace,
                ROUTE_MODE_LABEL: "auth" if auth else "plain",
            },
        },
        "spec": {
            "parentRefs": [
                {"name": cfg.gateway_name, "namespace": cfg.gateway_namespace}
            ],
            "rules": [
                {
                    "matches": [
                        {
                            "path": {
                                "type": "PathPrefix",
                                "value": f"/notebook/{nb.namespace}/{nb.name}",
                            }
                        }
                    ],
                    "backendRefs": [backend],
                }
            ],
        },
    }


def reconcile_httproute(client: Client, nb: Notebook, cfg: RouteConfig, auth: bool) -> None:
    desired = new_httproute(nb, cfg, auth)
    # Cross-namespace: no owner reference possible (reference :173-193).
    helper.reconcile_child(client, nb.obj, desired, set_owner=False)


def ensure_conflicting_route_absent(
    client: Client, nb: Notebook, cfg: RouteConfig, auth: bool
) -> None:
    """On auth-mode switches the old-mode route must go (reference :270-325).
    Route names collide by design, so a mode mismatch means delete+recreate."""
    try:
        existing = client.get("HTTPRoute", route_name(nb), cfg.controller_namespace)
    except NotFoundError:
        return
    mode = existing.get("metadata", {}).get("labels", {}).get(ROUTE_MODE_LABEL)
    want = "auth" if auth else "plain"
    if mode != want:
        client.delete("HTTPRoute", route_name(nb), cfg.controller_namespace)


def delete_httproute(client: Client, nb: Notebook, cfg: RouteConfig) -> None:
    """Finalizer-driven cleanup (reference DeleteHTTPRouteForNotebook :230-266)."""
    try:
        client.delete("HTTPRoute", route_name(nb), cfg.controller_namespace)
    except NotFoundError:
        pass


# ---------------------------------------------------------------------------
# ReferenceGrant


def new_reference_grant(namespace: str, cfg: RouteConfig) -> dict:
    """Reference NewNotebookReferenceGrant :39-69."""
    return {
        "apiVersion": REFERENCEGRANT_API,
        "kind": "ReferenceGrant",
        "metadata": {"name": REFERENCE_GRANT_NAME, "namespace": namespace},
        "spec": {
            "from": [
                {
                    "group": "gateway.networking.k8s.io",
                    "kind": "HTTPRoute",
                    "namespace": cfg.controller_namespace,
                }
            ],
            "to": [{"group": "", "kind": "Service"}],
        },
    }


def reconcile_reference_grant(client: Client, nb: Notebook, cfg: RouteConfig) -> None:
    desired = new_reference_grant(nb.namespace, cfg)
    # Namespace-scoped shared resource: not owned by any single notebook.
    helper.reconcile_child(client, nb.obj, desired, set_owner=False)


def delete_reference_grant_if_last_notebook(
    client: Client, nb: Notebook, cfg: RouteConfig
) -> None:
    """Reference DeleteReferenceGrantIfLastNotebook :130-162."""
    for other in client.list("Notebook", nb.namespace):
        if other.get("metadata", {}).get("name") == nb.name:
            continue
        if "deletionTimestamp" not in other.get("metadata", {}):
            return  # another live notebook still needs the grant
    try:
        client.delete("ReferenceGrant", REFERENCE_GRANT_NAME, nb.namespace)
    except NotFoundError:
        pass
