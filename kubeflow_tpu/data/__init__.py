"""Input pipeline: prefetching token loaders (native C++ + Python fallback)."""

from kubeflow_tpu.data.loader import (  # noqa: F401
    TokenLoader,
    device_put_global,
    sharded_loader,
    write_token_file,
)
