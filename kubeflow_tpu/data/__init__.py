"""Input pipeline: prefetching token loaders (native C++ + Python fallback)."""

from kubeflow_tpu.data.loader import TokenLoader, write_token_file  # noqa: F401
