"""Token batch loader over the native prefetcher, with a Python fallback.

The C++ loader (native/dataloader.cpp) mmaps a uint32 token corpus and
assembles random (batch, seq) windows on a producer thread — batch assembly
overlaps device compute so the TPU never waits on the host. The Python
fallback implements the identical sampling (same xorshift64* stream) on
np.memmap; both are pure functions of (corpus, batch, seq, seed), which the
tests use to cross-check them bit-for-bit.

The shared library is built on demand with g++ and cached next to the
source; environments without a toolchain silently use the fallback.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import weakref
from pathlib import Path
from typing import Iterator, Optional

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_SRC = _NATIVE_DIR / "dataloader.cpp"

_MASK = (1 << 64) - 1


# Bumped with every C ABI change; dl_abi_version() in the .so must match
# or the library is rebuilt (an mtime check alone lets a stale binary
# with a preserved timestamp silently drop new trailing arguments — on
# x86-64 a 5-arg dl_open called with 6 declared args just ignores
# start_batch, resurrecting the resume re-read bug with no error).
_ABI_VERSION = 2

# The ABI version is part of the filename so processes running different
# package versions never fight over one cache path, and an old binary can
# never be picked up by its name alone.
_LIB = _NATIVE_DIR / f"libkftpu_dataloader.v{_ABI_VERSION}.so"


def _build_native(force: bool = False) -> Optional[Path]:
    if not force and _LIB.exists() and (
        not _SRC.exists() or _LIB.stat().st_mtime >= _SRC.stat().st_mtime
    ):
        return _LIB
    if not _SRC.exists():
        return None
    # Compile to a pid-suffixed temp path and rename into place: writing
    # the cache path directly would truncate a .so another process may
    # have mapped (SIGBUS there); rename keeps the old inode alive for
    # existing mappings.
    tmp = _LIB.with_name(f".{_LIB.name}.{os.getpid()}.tmp")
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
             str(_SRC), "-o", str(tmp)],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _LIB)
        return _LIB
    except (OSError, subprocess.SubprocessError):
        try:
            tmp.unlink()
        except OSError:
            pass
        return None


def _load_native() -> Optional[ctypes.CDLL]:
    lib_path = _build_native()
    if lib_path is None:
        return None
    try:
        lib = ctypes.CDLL(str(lib_path))
    except OSError:
        return None
    if (getattr(lib, "dl_abi_version", None) is None
            or lib.dl_abi_version() != _ABI_VERSION):
        # Stale binary (pre-version or other version): rebuild once.
        lib_path = _build_native(force=True)
        if lib_path is None:
            return None
        # dlopen caches handles per pathname, so CDLLing the rebuilt file
        # at the same path would hand back the already-mapped STALE
        # library; load it through a one-shot alias path instead — the
        # mapping survives the unlink.
        alias = lib_path.with_name(f".{lib_path.name}.{os.getpid()}.fresh")
        try:
            shutil.copy2(lib_path, alias)
            lib = ctypes.CDLL(str(alias))
        except OSError:
            return None
        finally:
            try:
                alias.unlink()
            except OSError:
                pass
        if (getattr(lib, "dl_abi_version", None) is None
                or lib.dl_abi_version() != _ABI_VERSION):
            return None
    lib.dl_open.restype = ctypes.c_void_p
    lib.dl_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_uint64, ctypes.c_int, ctypes.c_uint64,
    ]
    lib.dl_num_tokens.restype = ctypes.c_long
    lib.dl_num_tokens.argtypes = [ctypes.c_void_p]
    lib.dl_next.restype = ctypes.c_int
    lib.dl_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)]
    lib.dl_close.restype = None
    lib.dl_close.argtypes = [ctypes.c_void_p]
    return lib


def write_token_file(path: str | Path, tokens: np.ndarray) -> Path:
    """Persist a token corpus in the loader's format (flat uint32 LE)."""
    path = Path(path)
    np.asarray(tokens, dtype=np.uint32).tofile(path)
    return path


def _xorshift_matrix() -> "np.ndarray":
    """The xorshift64 state transition (x^=x>>12; x^=x<<25; x^=x>>27) as
    a 64×64 GF(2) matrix acting on bit-column vectors (bit i = 2**i)."""
    m = np.eye(64, dtype=np.uint8)

    def shift_xor(mat, k):
        # x ^= x >> k  (bit j of result has bit j+k mixed in)   [k > 0]
        # x ^= x << k  (bit j has bit j-k mixed in)              [k < 0]
        out = mat.copy()
        if k > 0:
            out[: 64 - k] ^= mat[k:]
        else:
            out[-k:] ^= mat[: 64 + k]
        return out

    for k in (12, -25, 27):
        m = shift_xor(m, k)
    return m


def _xorshift_skip(state: int, n: int) -> int:
    """Advance the xorshift64 state by ``n`` transitions in O(log n):
    square-and-multiply over the GF(2) transition matrix. Bit-identical
    to n sequential transitions (tests cross-check)."""
    if n <= 0:
        return state
    vec = np.array([(state >> i) & 1 for i in range(64)], dtype=np.uint8)
    m = _xorshift_matrix()
    while n:
        if n & 1:
            vec = (m @ vec) & 1
        m = (m @ m) & 1
        n >>= 1
    return int(sum(int(b) << i for i, b in enumerate(vec)))


class _PyState:
    """Python mirror of the C++ sampler (same xorshift64* stream)."""

    def __init__(self, path: Path, batch: int, seq: int, seed: int,
                 start_batch: int = 0):
        self.tokens = np.memmap(path, dtype=np.uint32, mode="r")
        self.batch = batch
        self.seq = seq
        self.rng = seed if seed else 0x9E3779B97F4A7C15
        # Resume skip (mirrors dl_open): the output multiply does not
        # feed the state, so only the xorshift transition matters — and
        # it is linear over GF(2), so deep skips jump in O(log n) 64×64
        # bit-matrix squarings instead of an O(n) Python loop (resuming
        # at step 1e6 × batch 1024 would otherwise stall for minutes on
        # toolchain-less hosts where this fallback is the only option).
        self.rng = _xorshift_skip(self.rng, start_batch * batch)

    def _next_rand(self) -> int:
        x = self.rng
        x ^= (x >> 12)
        x = (x ^ (x << 25)) & _MASK
        x ^= (x >> 27)
        self.rng = x
        return (x * 0x2545F4914F6CDD1D) & _MASK

    def next(self) -> np.ndarray:
        max_start = self.tokens.shape[0] - self.seq
        out = np.empty((self.batch, self.seq), np.int32)
        for b in range(self.batch):
            start = self._next_rand() % (max_start + 1)
            out[b] = self.tokens[start : start + self.seq].astype(np.int32)
        return out


class TokenLoader:
    """Iterator of (batch, seq) int32 arrays sampled from a token file."""

    def __init__(
        self,
        path: str | Path,
        batch: int,
        seq: int,
        seed: int = 1,
        prefetch: int = 4,
        force_python: bool = False,
        start_batch: int = 0,  # checkpoint resume: skip consumed batches
    ):
        self.path = Path(path)
        if not self.path.exists():
            raise FileNotFoundError(self.path)
        if start_batch < 0:
            # Must be rejected BEFORE reaching either backend: ctypes
            # would wrap a negative into c_uint64 (~2**64 — the native
            # skip then never terminates) while the Python fallback
            # silently treats it as 0; neither is an acceptable answer
            # to a corrupted resume offset.
            raise ValueError(f"start_batch must be >= 0, got {start_batch}")
        self.batch = batch
        self.seq = seq
        n_tokens = self.path.stat().st_size // 4
        if n_tokens < seq:
            raise ValueError(f"corpus has {n_tokens} tokens < seq={seq}")
        self.n_tokens = n_tokens

        self._lib = None if force_python else _load_native()
        self._handle = None
        if self._lib is not None:
            self._handle = self._lib.dl_open(
                str(self.path).encode(), batch, seq, seed, prefetch,
                start_batch,
            )
            if not self._handle:
                self._lib = None
        if self._lib is None:
            self._py = _PyState(self.path, batch, seq, seed,
                                start_batch=start_batch)
        else:
            # Reclaim the producer thread + mmap even if the user never
            # calls close() (abandoned loaders in re-run notebook cells).
            self._finalizer = weakref.finalize(
                self, self._lib.dl_close, self._handle
            )

    @property
    def native(self) -> bool:
        return self._lib is not None

    def next(self) -> np.ndarray:
        if self._lib is not None:
            out = np.empty((self.batch, self.seq), np.int32)
            rc = self._lib.dl_next(
                self._handle, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
            )
            if rc != 0:
                raise RuntimeError("native loader failed")
            return out
        return self._py.next()

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next()

    def batches(self, n: int) -> Iterator[np.ndarray]:
        for _ in range(n):
            yield self.next()

    def close(self) -> None:
        if self._lib is not None and self._handle:
            self._finalizer.detach()
            self._lib.dl_close(self._handle)
            self._handle = None

    def __enter__(self) -> "TokenLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Multi-host input pipeline


def sharded_loader(
    path: str | Path,
    global_batch: int,
    seq: int,
    seed: int = 1,
    process_id: Optional[int] = None,
    num_processes: Optional[int] = None,
    start_batch: int = 0,
    **kwargs,
) -> TokenLoader:
    """Per-host loader for multi-host training: each process loads ONLY
    its global_batch/num_processes rows, from a process-disjoint random
    stream (seed is splitmix-style mixed with the process id so streams
    never collide even for adjacent seeds).

    Pair with :func:`device_put_global` to assemble the per-host batches
    into one global jax.Array laid out over the mesh — the host never
    materializes (and DCN never moves) the full global batch.

    ``start_batch`` makes checkpoint resume EXACT: pass
    ``runtime.checkpoint.resume_start_batch(ckpt, at)`` after
    ``restore_latest`` and every host skips precisely the batches the
    lost run consumed (the cursor is a GLOBAL batch index — each host's
    xorshift stream advances by the same count, so per-host streams stay
    aligned and cross-host rows stay disjoint; nothing is replayed or
    skipped).
    """
    import jax

    pid = jax.process_index() if process_id is None else process_id
    num = jax.process_count() if num_processes is None else num_processes
    if global_batch % num != 0:
        raise ValueError(
            f"global_batch {global_batch} not divisible by "
            f"{num} processes"
        )
    mixed = (seed * 0x9E3779B97F4A7C15 + pid * 0xBF58476D1CE4E5B9) & _MASK
    # Keep the mixed seed nonzero (xorshift fixed point) and in int range.
    mixed = (mixed % ((1 << 63) - 1)) or 1
    return TokenLoader(
        path, global_batch // num, seq, seed=mixed,
        start_batch=start_batch, **kwargs
    )


def device_put_global(local_batch: "np.ndarray", mesh, spec):
    """Per-host (local_batch, seq) numpy → GLOBAL jax.Array over ``mesh``
    with PartitionSpec ``spec`` (e.g. the MeshPlan batch spec). Each host
    contributes only its own rows; jax assembles the global view."""
    import jax
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), local_batch
    )
