"""Notebook CRD: typed accessors, versions, and conversion.

Mirrors the reference API surface (reference
components/notebook-controller/api/v1beta1/notebook_types.go:27-75 —
``NotebookSpec{Template.Spec: corev1.PodSpec}`` passthrough plus
``NotebookStatus{Conditions, ReadyReplicas, ContainerState}``) with the
TPU-native addition of ``spec.tpu`` and ``status.tpu``:

    spec:
      template:
        spec: <PodSpec passthrough, exactly as in the reference>
      tpu:              # new, optional — absent means a plain CPU notebook
        accelerator: v5e | v5p | v4 | v6e (+aliases)
        topology: "4x4"
        runtimeVersion: optional libtpu/runtime hint
        spot: bool
    status:
      conditions: [...]            # mirrored pod conditions, as in reference
      readyReplicas: int
      containerState: {...}        # state of the container named like the CR
      tpu:
        hosts: int
        readyHosts: int
        sliceHealth: Healthy | Forming | Interrupted | Stopped
        jaxCoordinator: host:port of worker 0

Version scheme follows the reference: three served versions with identical
shape, v1beta1 as the conversion hub (reference
api/v1beta1/notebook_conversion.go:19, api/v1/notebook_conversion.go:25-69).
Because the shapes are identical, conversion rewrites apiVersion and
validates the tpu block.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Any, Optional

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.tpu.topology import (
    InvalidTopologyError,
    SliceTopology,
    slice_from_spec,
)

GROUP = "kubeflow.org"
KIND = "Notebook"
HUB_VERSION = "v1beta1"
VERSIONS = ("v1alpha1", "v1beta1", "v1")

# StatefulSet names above this length break the controller-generated pod
# hostnames (reference notebook_controller.go:59 MaxStatefulSetNameLength).
MAX_NAME_LENGTH = 52


@dataclass(frozen=True)
class TPUSpec:
    accelerator: str
    topology: str
    runtime_version: str = ""
    spot: bool = False
    # Multislice: N identical slices form one notebook (GKE Multislice /
    # MEGASCALE — DCN between slices, ICI within). 1 = plain single slice.
    slice_count: int = 1

    def slice_topology(self) -> SliceTopology:
        """Resolve and validate; raises InvalidTopologyError on bad input."""
        if self.slice_count < 1:
            raise InvalidTopologyError(
                f"sliceCount must be >= 1, got {self.slice_count}"
            )
        return slice_from_spec(self.accelerator, self.topology)

    @classmethod
    def from_dict(cls, d: dict) -> "TPUSpec":
        return cls(
            accelerator=d.get("accelerator", ""),
            topology=d.get("topology", ""),
            runtime_version=d.get("runtimeVersion", ""),
            spot=bool(d.get("spot", False)),
            slice_count=int(d.get("sliceCount", 1)),
        )

    def to_dict(self) -> dict:
        out: dict[str, Any] = {"accelerator": self.accelerator, "topology": self.topology}
        if self.runtime_version:
            out["runtimeVersion"] = self.runtime_version
        if self.spot:
            out["spot"] = True
        if self.slice_count != 1:
            out["sliceCount"] = self.slice_count
        return out


class Notebook:
    """Typed view over a dict-shaped Notebook object (shared storage)."""

    def __init__(self, obj: dict):
        self.obj = obj

    # -- metadata ----------------------------------------------------------
    @property
    def name(self) -> str:
        return obj_util.name_of(self.obj)

    @property
    def namespace(self) -> str:
        return obj_util.namespace_of(self.obj)

    @property
    def annotations(self) -> dict:
        return obj_util.annotations_of(self.obj)

    @property
    def labels(self) -> dict:
        return obj_util.labels_of(self.obj)

    # -- spec --------------------------------------------------------------
    @property
    def pod_spec(self) -> dict:
        return (
            self.obj.setdefault("spec", {})
            .setdefault("template", {})
            .setdefault("spec", {})
        )

    @property
    def containers(self) -> list[dict]:
        return self.pod_spec.setdefault("containers", [])

    def primary_container(self) -> Optional[dict]:
        """The notebook container: the one named like the CR (reference
        notebook_controller.go:350-360 mirrors exactly this container)."""
        for c in self.containers:
            if c.get("name") == self.name:
                return c
        return self.containers[0] if self.containers else None

    @property
    def tpu(self) -> Optional[TPUSpec]:
        d = self.obj.get("spec", {}).get("tpu")
        return TPUSpec.from_dict(d) if d else None

    # -- lifecycle annotations --------------------------------------------
    @property
    def stopped(self) -> bool:
        return ann.STOP in self.obj.get("metadata", {}).get("annotations", {})

    @property
    def lock_held(self) -> bool:
        return (
            self.obj.get("metadata", {}).get("annotations", {}).get(ann.STOP)
            == ann.RECONCILIATION_LOCK_VALUE
        )

    # -- status ------------------------------------------------------------
    @property
    def status(self) -> dict:
        return self.obj.setdefault("status", {})


def new_notebook(
    name: str,
    namespace: str,
    image: str = "jupyter-minimal:latest",
    tpu: Optional[TPUSpec] = None,
    version: str = "v1",
    annotations: Optional[dict] = None,
    labels: Optional[dict] = None,
    container_overrides: Optional[dict] = None,
) -> dict:
    """Build a Notebook object the way a dashboard/user would."""
    container = {
        "name": name,
        "image": image,
        "resources": {"requests": {"cpu": "500m", "memory": "1Gi"}},
    }
    if container_overrides:
        container.update(container_overrides)
    obj = obj_util.new_object(
        f"{GROUP}/{version}", KIND, name, namespace,
        labels=labels, annotations=annotations,
    )
    obj["spec"] = {"template": {"spec": {"containers": [container]}}}
    if tpu:
        obj["spec"]["tpu"] = tpu.to_dict()
    return obj


def convert(obj: dict, to_version: str) -> dict:
    """Convert a Notebook between served versions through the hub.

    All versions share one shape (as in the reference, where ConvertTo /
    ConvertFrom copy fields 1:1 — reference api/v1/notebook_conversion.go:
    25-69), so conversion is an apiVersion rewrite with validation.
    """
    if to_version not in VERSIONS:
        raise ValueError(f"unknown Notebook version {to_version!r}; known {VERSIONS}")
    current = obj.get("apiVersion", "")
    if current.split("/")[0] not in (GROUP,):
        raise ValueError(f"not a {GROUP} object: apiVersion={current!r}")
    out = copy.deepcopy(obj)
    out["apiVersion"] = f"{GROUP}/{to_version}"
    return out
